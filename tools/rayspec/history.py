"""rayspec history recorder: concurrent invocation/response capture.

The product's decision cores report operation boundaries through the
``sanitize_hooks.spec_op`` seam (``spec.<core>.<op>`` points, two
phases: ``call`` on entry, ``ret`` on return). A :class:`Recorder`
installed into that seam turns them into a **history** — the standard
linearizability object: a sequence of invocation and response events,
each op carrying the argument/result views its call site passed.

Recording discipline:

- one global, lock-protected sequence counter orders invocations and
  responses across threads (a single process-wide total order is
  exactly what the checker's happens-before relation needs);
- call/ret pairing is per (thread, point, instance): an op that raised
  instead of returning leaves its invocation **pending** — the checker
  treats pending invocations as may-or-may-not-have-taken-effect,
  which is also the honest reading of an op that died mid-flight;
- events are bounded (``max_events``); overflow stops recording and is
  flagged rather than silently wedging the process being observed;
- instances are tracked by ``id(obj)``, and the recorder PINS a strong
  reference to every instance it has seen: CPython reuses freed
  addresses routinely, and two unrelated cores merged under one
  recycled id would concatenate into a single bogus history (phantom
  violations). Pinning bounds the extension to the recorder's own
  lifetime — one CLI run or one raymc execution.

The raw payloads are whatever cheap views the product taps passed;
per-spec adapters (:mod:`.specs`) normalize them into the op alphabet
and tokenize run-specific identifiers so logically-identical histories
from different runs canonicalize identically (the conformance cache
keys on that).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Optional, Tuple

from ray_tpu._private import sanitize_hooks


@dataclasses.dataclass
class RawEvent:
    """One completed-or-pending operation as recorded (unadapted)."""

    point: str                 # "spec.<core>.<op>"
    instance: int              # id() of the core instance
    call_payload: object
    ret_payload: object
    invoked: int               # global sequence number of the call
    returned: Optional[int]    # ... of the return; None = pending
    thread: str

    @property
    def core(self) -> str:
        return self.point.split(".")[1]

    @property
    def op(self) -> str:
        return self.point.split(".")[2]


@dataclasses.dataclass
class OpEvent:
    """One adapted operation: the spec-alphabet form the checker eats."""

    point: str
    op: str
    args: tuple
    result: object
    invoked: int
    returned: Optional[int]
    thread: str

    @property
    def pending(self) -> bool:
        return self.returned is None


class Tokens:
    """Run-specific identifier canonicalization: maps object identities
    (``for_obj``) and hashable values (``for_val``) to dense ``"t<n>"``
    tokens in first-appearance order, so two runs producing the same
    logical history adapt to byte-identical canonical forms."""

    def __init__(self):
        self._by_id: Dict[int, str] = {}
        self._by_val: Dict[object, str] = {}
        self._n = 0
        # Adapter scratch space (e.g. the dep-table's item->key map):
        # lives with the token table so incremental adaptation keeps
        # its cross-event context.
        self.aux: Dict[str, dict] = {}

    def _mint(self) -> str:
        tok = f"t{self._n}"
        self._n += 1
        return tok

    def for_obj(self, obj) -> str:
        tok = self._by_id.get(id(obj))
        if tok is None:
            tok = self._by_id[id(obj)] = self._mint()
        return tok

    def for_val(self, value) -> str:
        tok = self._by_val.get(value)
        if tok is None:
            tok = self._by_val[value] = self._mint()
        return tok

    def peek_obj(self, obj) -> Optional[str]:
        """Token for an already-seen object; None for a stranger (a
        live-state row the history never touched — a conformance
        mismatch by construction, surfaced instead of minted over)."""
        return self._by_id.get(id(obj))

    def peek_val(self, value) -> Optional[str]:
        return self._by_val.get(value)


class Recorder:
    """Process-wide spec-op history recorder (context manager).

    ::

        with Recorder() as rec:
            ...drive the cores...
        for (core, instance), events in rec.histories().items():
            ...check...

    Chains with a previously-installed hook (raymc's conformance mode
    nests a per-execution recorder under whatever the session has
    installed).
    """

    def __init__(self, max_events: int = 200_000):
        self._lock = threading.Lock()
        self._seq = 0
        self._events: List[RawEvent] = []
        self._by_instance: Dict[int, List[RawEvent]] = {}
        # id -> the instance itself: pinned so the id cannot be
        # recycled under us (see module docstring).
        self._pinned: Dict[int, object] = {}
        # (thread ident, point, instance) -> stack of open RawEvents.
        self._open: Dict[Tuple[int, str, int], List[RawEvent]] = {}
        self.max_events = max_events
        self.overflowed = False
        self._prev = None
        self._installed = False

    # -- installation ------------------------------------------------------

    def __enter__(self) -> "Recorder":
        self._prev = sanitize_hooks._spec_op
        sanitize_hooks.install_spec_op(self._record)
        self._installed = True
        return self

    def __exit__(self, *exc) -> None:
        sanitize_hooks.install_spec_op(self._prev)
        self._installed = False

    # -- the installed hook ------------------------------------------------

    def _record(self, point: str, phase: str, obj: object,
                payload: object) -> None:
        prev = self._prev
        if prev is not None:
            prev(point, phase, obj, payload)
        ident = threading.get_ident()
        with self._lock:
            if self.overflowed:
                return
            if len(self._events) >= self.max_events:
                self.overflowed = True
                return
            self._seq += 1
            key = (ident, point, id(obj))
            if phase == "call":
                ev = RawEvent(point=point, instance=id(obj),
                              call_payload=payload, ret_payload=None,
                              invoked=self._seq, returned=None,
                              thread=threading.current_thread().name)
                self._open.setdefault(key, []).append(ev)
                self._events.append(ev)
                iid = id(obj)
                self._pinned.setdefault(iid, obj)
                self._by_instance.setdefault(iid, []).append(ev)
            else:
                stack = self._open.get(key)
                if not stack:
                    return  # ret with no recorded call (install raced)
                ev = stack.pop()
                if not stack:
                    del self._open[key]
                ev.ret_payload = payload
                ev.returned = self._seq

    # -- results -----------------------------------------------------------

    def events(self) -> List[RawEvent]:
        with self._lock:
            return list(self._events)

    def histories(self) -> Dict[Tuple[str, int], List[RawEvent]]:
        """Raw events grouped per (core, instance), invocation order.
        One core instance = one linearizability object (two ledgers
        never form one history)."""
        out: Dict[Tuple[str, int], List[RawEvent]] = {}
        for ev in self.events():
            out.setdefault((ev.core, ev.instance), []).append(ev)
        return out

    def events_for(self, obj) -> List[RawEvent]:
        """This instance's raw events (conformance filters by the live
        core it is about to compare against)."""
        with self._lock:
            return list(self._by_instance.get(id(obj), ()))

    def count_for(self, obj) -> int:
        """Cheap per-instance event count: lets a conformance session
        skip quiescent states where no op touched its core (state
        provably unchanged — every mutator is tapped)."""
        with self._lock:
            return len(self._by_instance.get(id(obj), ()))
