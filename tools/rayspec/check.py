"""Wing & Gong-style linearizability checking over recorded histories.

The algorithm is the classic one: search for a total order of the
history's operations that (a) respects real-time order — an operation
whose response precedes another's invocation must linearize first —
and (b) replays legally through the sequential spec, each completed
operation's recorded result matching the spec's. Pending invocations
(ops that never returned: a thread parked mid-op, an op that raised)
may linearize at any legal point or not at all.

Engineering notes:

- **Partition-by-key compositionality**: linearizability is
  compositional over independent objects, so specs that declare
  ``partition = True`` (per-actor gate state, per-key table cells,
  per-call exactly-once registers) are checked one key-subhistory at a
  time — turning one big search into many trivial ones. A violation
  is still a violation of the whole history (the failing key's
  sub-history is reported).
- **Mostly-sequential fast path**: recorded histories from real runs
  are long but thinly overlapped. Candidates at each step are found by
  scanning the invocation-ordered suffix up to the earliest
  outstanding response — O(window), not O(n) — and the memo key
  compresses the linearized set as (sequential prefix, small overflow
  set).
- **Bounded-search fallback**: the search is budgeted
  (``max_configs`` visited configurations). A blown budget returns
  ``undecided`` — never a false verdict — and is reported as such.
- On violation the failing sub-history is **ddmin-shrunk** (the raymc
  delta-debugging machinery) to a 1-minimal non-linearizable
  sub-history, re-verified, and emitted as a raysan ``Schedule``
  script over the ``spec.*`` points for deterministic replay.
"""

from __future__ import annotations

import dataclasses
import sys
from typing import Dict, List, Optional, Tuple

from tools.rayspec.history import OpEvent
from tools.rayspec.specs import Spec


class _Budget(Exception):
    """Internal: the configuration budget tripped (→ undecided)."""


@dataclasses.dataclass
class CheckOutcome:
    """Verdict for one (sub-)history.

    ``status``: ``ok`` (linearizable; and live state reachable when a
    conformance target was given), ``violation`` (not linearizable),
    ``divergence`` (linearizable, but no linearization reaches the
    live core's observable state — a conformance failure),
    ``undecided`` (search budget exhausted).
    """

    status: str
    spec: str
    key: Optional[object] = None
    explored: int = 0
    events: int = 0
    message: str = ""
    minimal: List[OpEvent] = dataclasses.field(default_factory=list)
    schedule_order: List[str] = dataclasses.field(default_factory=list)
    minimal_verified: bool = False

    @property
    def ok(self) -> bool:
        return self.status == "ok"

    def to_dict(self) -> dict:
        return {
            "status": self.status,
            "spec": self.spec,
            "key": repr(self.key) if self.key is not None else None,
            "explored": self.explored,
            "events": self.events,
            "message": self.message,
            "minimal": [
                {"point": e.point, "op": e.op, "args": repr(e.args),
                 "result": repr(e.result), "thread": e.thread,
                 "pending": e.pending}
                for e in self.minimal],
            "schedule_order": self.schedule_order,
            "minimal_verified": self.minimal_verified,
        }

    @classmethod
    def from_dict(cls, data: dict) -> "CheckOutcome":
        out = cls(status=data["status"], spec=data["spec"],
                  key=data.get("key"), explored=data.get("explored", 0),
                  events=data.get("events", 0),
                  message=data.get("message", ""),
                  schedule_order=list(data.get("schedule_order", ())),
                  minimal_verified=bool(
                      data.get("minimal_verified", False)))
        return out


def linearize(events: List[OpEvent], spec: Spec,
              max_configs: int = 100_000,
              target=None, init_state=None) -> Tuple[str, int]:
    """Core search. Returns (status, configurations explored); status
    as in :class:`CheckOutcome`. ``target`` (optional) is an
    observable the final spec state must reach for ``ok`` —
    conformance mode's refinement question."""
    events = sorted(events, key=lambda e: e.invoked)
    n = len(events)
    state0 = spec.init_state() if init_state is None else init_state
    if n == 0:
        if target is not None and spec.observable(state0) != target:
            return "divergence", 0
        return "ok", 0

    # Ascending (response, index) over completed ops: the real-time
    # constraint source. resp_order[resp_lo:] skips linearized ones.
    resp_order = sorted(
        (e.returned, i) for i, e in enumerate(events)
        if e.returned is not None)
    lin = [False] * n
    explored = [0]
    found_full = [False]
    memo = set()
    completed_left = [len(resp_order)]

    def first_unlin_resp(skip: int, start: int) -> Tuple[Optional[int],
                                                         int]:
        """(response, holder) of the earliest unlinearized completed op
        (excluding ``skip``), scanning from resp_order[start:]."""
        for j in range(start, len(resp_order)):
            resp, idx = resp_order[j]
            if not lin[idx] and idx != skip:
                return resp, idx
        return None, -1

    def search(state, lo: int, resp_lo: int) -> bool:
        explored[0] += 1
        if explored[0] > max_configs:
            raise _Budget
        # Advance the sequential-prefix pointers past linearized ops.
        while lo < n and lin[lo]:
            lo += 1
        while resp_lo < len(resp_order) and lin[resp_order[resp_lo][1]]:
            resp_lo += 1
        if completed_left[0] == 0:
            found_full[0] = True
            if target is None or spec.observable(state) == target:
                return True
            # Keep searching: a pending op's effect may be what the
            # live state reflects.
        key = (lo, frozenset(i for i in range(lo, n) if lin[i]),
               spec.state_key(state))
        if key in memo:
            return False
        memo.add(key)
        bound, holder = first_unlin_resp(-1, resp_lo)
        i = lo
        while i < n:
            e = events[i]
            if lin[i]:
                i += 1
                continue
            # Real-time rule: e may go next only if no OTHER
            # unlinearized completed op responded before e invoked.
            limit = bound
            if i == holder:
                limit, _ = first_unlin_resp(i, resp_lo)
            if limit is not None and e.invoked >= limit:
                break  # invocation-ordered: later events only later
            for new_state, res in spec.apply(state, e.op, e.args):
                if e.returned is not None and \
                        not spec.match(e.op, e.args, res, e.result):
                    continue
                lin[i] = True
                if e.returned is not None:
                    completed_left[0] -= 1
                try:
                    if search(new_state, lo, resp_lo):
                        return True
                finally:
                    lin[i] = False
                    if e.returned is not None:
                        completed_left[0] += 1
            i += 1
        return False

    old_limit = sys.getrecursionlimit()
    sys.setrecursionlimit(max(old_limit, n * 2 + 200))
    try:
        ok = search(state0, 0, 0)
    except _Budget:
        return "undecided", explored[0]
    finally:
        sys.setrecursionlimit(old_limit)
    if ok:
        return "ok", explored[0]
    if target is not None and found_full[0]:
        return "divergence", explored[0]
    return "violation", explored[0]


def _partitions(events: List[OpEvent],
                spec: Spec) -> Dict[object, List[OpEvent]]:
    if not spec.partition:
        return {None: events}
    out: Dict[object, List[OpEvent]] = {}
    for e in events:
        out.setdefault(spec.key_of(e.op, e.args), []).append(e)
    return out


def schedule_script(events: List[OpEvent]) -> List[str]:
    """A raysan ``Schedule(order=[...])`` script over the sub-history's
    spec points, invocation order, global occurrence keys. Replaying:
    install a rayspec Recorder (spec taps gate only while one is
    installed), then run the component drive under the Schedule — the
    script pins the op-entry order that produced the violation."""
    counts: Dict[str, int] = {}
    out = []
    for e in sorted(events, key=lambda ev: ev.invoked):
        occ = counts.get(e.point, 0) + 1
        counts[e.point] = occ
        out.append(e.point if occ == 1 else f"{e.point}#{occ}")
    return out


def minimize_violation(events: List[OpEvent], spec: Spec,
                       max_configs: int,
                       max_probes: int = 64) -> Tuple[List[OpEvent],
                                                      bool]:
    """ddmin the non-linearizable sub-history to 1-minimality (every
    probe is a full re-check; the raymc delta-debugging engine drives
    the chunking), then re-verify the result still fails."""
    from tools.raymc.minimize import ddmin

    def fails(candidate: List[OpEvent]) -> bool:
        status, _ = linearize(candidate, spec, max_configs)
        return status == "violation"

    minimal = ddmin(fails, list(events), max_probes=max_probes)
    verified = fails(minimal)
    return minimal, verified


def check_events(events: List[OpEvent], spec: Spec,
                 max_configs: int = 100_000,
                 minimize: bool = True) -> List[CheckOutcome]:
    """Linearizability verdicts for a history (one outcome per
    partition key for partitioned specs)."""
    out = []
    for key, group in sorted(_partitions(events, spec).items(),
                             key=lambda kv: repr(kv[0])):
        status, explored = linearize(group, spec, max_configs)
        outcome = CheckOutcome(status=status, spec=spec.name, key=key,
                               explored=explored, events=len(group))
        if status == "violation":
            minimal = group
            verified = True
            if minimize:
                minimal, verified = minimize_violation(
                    group, spec, max_configs)
            outcome.minimal = minimal
            outcome.minimal_verified = verified
            outcome.schedule_order = schedule_script(minimal)
            outcome.message = (
                f"history of {len(group)} op(s) is not linearizable "
                f"w.r.t. {spec.name}"
                + (f" (key {key!r})" if key is not None else "")
                + f"; minimal sub-history: "
                + ", ".join(f"{e.op}{e.args}->{e.result!r}"
                            for e in minimal))
        elif status == "undecided":
            outcome.message = (
                f"search budget ({max_configs} configurations) "
                f"exhausted on {len(group)} op(s) — no verdict")
        out.append(outcome)
    return out
