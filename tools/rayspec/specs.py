"""Executable sequential specifications for the decision cores.

Each spec is a small pure model of one core's *sequential* contract:
an explicit operation alphabet, an initial state, and an ``apply``
step function. The concurrent implementation is correct when every
recorded concurrent history is **linearizable** with respect to this
model (check.py), and — in raymc conformance mode — when the live
core's observable state is reachable by *some* linearization of the
recorded history (conformance.py): refinement, not a property list.

Spec design rules:

- ``apply(state, op, args)`` returns a list of ``(new_state, result)``
  candidates — usually one; more when the sequential contract itself is
  nondeterministic (the WFQ pick among tied virtual times). An empty
  list means the op is *illegal* in that state (a double release, a
  dequeue with nothing queued): no linearization may pass through it.
- States are never mutated — every step builds a new value — so the
  checker can memoize on ``state_key``.
- ``adapt`` turns the raw recorded payloads into the op alphabet and
  tokenizes run-specific identifiers (object ids, random task/actor
  ids) in first-appearance order, so logically identical histories
  from different runs canonicalize identically.
- ``ANY`` as a spec result matches every recorded result (used where
  the implementation's answer depends on an argument the cheap tap
  deliberately does not capture, e.g. ``dict.get``'s default).

``SPEC_CATALOG`` maps each registered product core to its spec;
raylint R9 holds catalog, ``sanitize_hooks.SPEC_POINTS`` registry, and
product tap sites to each other. ``FIXTURE_SPECS`` are checker
self-test models (atomic register, FIFO queue) — not product cores.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Tuple

from tools.rayspec.history import OpEvent, RawEvent, Tokens

# Matches any recorded result (see module docstring).
ANY = "<any>"

_UNSEEN = "?unseen"


def _freeze(value):
    """Canonical hashable form of a state component."""
    if isinstance(value, dict):
        return tuple(sorted((k, _freeze(v)) for k, v in value.items()))
    if isinstance(value, (list, tuple)):
        return tuple(_freeze(v) for v in value)
    if isinstance(value, (set, frozenset)):
        return frozenset(_freeze(v) for v in value)
    return value


def _tok(tokens: Tokens, value) -> str:
    """Token for an identifier that is *usually* a hashable value
    (bytes ids) but may be an arbitrary object."""
    try:
        return tokens.for_val(value)
    except TypeError:
        return tokens.for_obj(value)


def _peek(tokens: Tokens, value) -> str:
    try:
        out = tokens.peek_val(value)
    except TypeError:
        out = tokens.peek_obj(value)
    return _UNSEEN if out is None else out


class Spec:
    """Base sequential specification. Subclasses define the alphabet."""

    name = "unnamed"
    description = ""
    product = ""          # dotted product path ("module.Class"), docs/R9
    prefix = ""           # "spec.<core>." point prefix
    partition = False     # check per key (compositional linearizability)
    ops: Tuple[str, ...] = ()
    supports_conformance = True

    # -- model -------------------------------------------------------------

    def init_state(self):
        raise NotImplementedError

    def apply(self, state, op: str, args: tuple) -> List[tuple]:
        """[(new_state, result), ...]; [] = illegal here."""
        raise NotImplementedError

    def match(self, op: str, args: tuple, spec_result, actual) -> bool:
        if spec_result is ANY:
            return True
        if spec_result is actual:
            return True
        try:
            return bool(spec_result == actual)
        except Exception:
            return False

    def state_key(self, state):
        return _freeze(state)

    def observable(self, state):
        """The refinement-visible projection conformance compares; by
        default the whole state."""
        return self.state_key(state)

    def key_of(self, op: str, args: tuple):
        """Partition key (first arg by convention)."""
        return args[0]

    # -- bridges to the implementation -------------------------------------

    def adapt(self, raw: List[RawEvent],
              tokens: Optional[Tokens] = None) \
            -> Tuple[List[OpEvent], Tokens]:
        """Raw recorded events -> (alphabet events, token table)."""
        tokens = tokens or Tokens()
        out = []
        for ev in raw:
            adapted = self.adapt_event(ev, tokens)
            if adapted is not None:
                out.append(adapted)
        return out, tokens

    def adapt_event(self, ev: RawEvent,
                    tokens: Tokens) -> Optional[OpEvent]:
        args, result = self.adapt_payloads(
            ev.op, ev.call_payload,
            ev.ret_payload if ev.returned is not None else None, tokens)
        return OpEvent(point=ev.point, op=ev.op, args=args,
                       result=result, invoked=ev.invoked,
                       returned=ev.returned, thread=ev.thread)

    def adapt_payloads(self, op: str, call, ret, tokens: Tokens):
        """(args, result) from the raw payloads; default passthrough."""
        if isinstance(call, tuple):
            return call, ret
        return ((() if call is None else (call,)), ret)

    def observe(self, core, tokens: Tokens):
        """Live-core observable (same token space as the adapted
        history). Partitioned specs return {key: observable}."""
        raise NotImplementedError

    def bind(self, core) -> None:
        """Adopt per-instance model parameters from the live core
        before conformance checking (e.g. the WFQ's weight map — the
        catalog factory cannot know them). Default: nothing."""

    def params_key(self):
        """Hashable fingerprint of bound model parameters — part of
        the conformance verdict cache key (two differently-bound
        sessions must never share verdicts). Default: None."""
        return None


# -- fixture specs (checker self-tests) --------------------------------------


class AtomicRegisterSpec(Spec):
    name = "atomic_register"
    description = "single atomic register: read/write"
    ops = ("read", "write")
    supports_conformance = False

    def init_state(self):
        return None

    def apply(self, state, op, args):
        if op == "write":
            return [(args[0], None)]
        if op == "read":
            return [(state, state)]
        return []


class FifoQueueSpec(Spec):
    name = "fifo_queue"
    description = "FIFO queue: enq/deq (deq on empty returns None)"
    ops = ("enq", "deq")
    supports_conformance = False

    def init_state(self):
        return ()

    def apply(self, state, op, args):
        if op == "enq":
            return [(state + (args[0],), None)]
        if op == "deq":
            if not state:
                return [(state, None)]
            return [(state[1:], state[0])]
        return []


# -- QuotaLedger -------------------------------------------------------------


class QuotaLedgerSpec(Spec):
    """Charge/release/ceiling-check law of the tenancy ledger: usage
    counters never exceed the ceiling passed to the op, never go
    negative (a release without a matching charge is ILLEGAL — the
    double-release class of bug), and the drainer's batched charges
    obey the same ceiling one at a time."""

    name = "quota_ledger"
    description = "per-job CPU/queued/lease quota accounting"
    product = "ray_tpu._private.tenancy.QuotaLedger"
    prefix = "spec.quota."
    ops = ("admit", "dequeue", "charge", "release", "drain",
           "lease_acquire", "lease_release")

    def init_state(self):
        return {"cpu": {}, "queued": {}, "leases": {}}

    @staticmethod
    def _bump(table: dict, key, delta: int) -> dict:
        out = dict(table)
        left = out.get(key, 0) + delta
        if left > 0:
            out[key] = left
        else:
            out.pop(key, None)
        return out

    def apply(self, state, op, args):
        cpu, queued, leases = (state["cpu"], state["queued"],
                               state["leases"])
        if op == "charge":
            job, milli, cap = args
            ok = cpu.get(job, 0) + milli <= cap
            if not ok:
                return [(state, False)]
            return [({**state, "cpu": self._bump(cpu, job, milli)},
                     True)]
        if op == "release":
            job, milli = args
            if cpu.get(job, 0) < milli:
                return []  # released more than was ever charged
            return [({**state, "cpu": self._bump(cpu, job, -milli)},
                     None)]
        if op == "admit":
            job, ceiling = args
            ok = queued.get(job, 0) < ceiling
            if not ok:
                return [(state, False)]
            return [({**state, "queued": self._bump(queued, job, 1)},
                     True)]
        if op == "dequeue":
            job, = args
            if queued.get(job, 0) < 1:
                return []  # dequeue without an admission
            return [({**state, "queued": self._bump(queued, job, -1)},
                     None)]
        if op == "drain":
            charges, = args
            new_cpu = cpu
            for job, milli, cap in charges:
                if new_cpu.get(job, 0) + milli > cap:
                    return []  # the drainer charged past the ceiling
                new_cpu = self._bump(new_cpu, job, milli)
            return [({**state, "cpu": new_cpu}, None)]
        if op == "lease_acquire":
            job, cap = args
            ok = leases.get(job, 0) < cap
            if not ok:
                return [(state, False)]
            return [({**state, "leases": self._bump(leases, job, 1)},
                     True)]
        if op == "lease_release":
            job, = args
            # Lenient by design: lease release sites are not
            # token-guarded and the implementation clamps at zero.
            return [({**state, "leases": self._bump(leases, job, -1)},
                     None)]
        return []

    def adapt_payloads(self, op, call, ret, tokens):
        if op == "drain":
            return ((tuple(ret or ()),), None)
        if op in ("dequeue", "lease_release"):
            return ((call,), None)
        return call, ret

    def observe(self, core, tokens):
        with core._lock:
            return self.observable({"cpu": dict(core._cpu),
                                    "queued": dict(core._queued),
                                    "leases": dict(core._leases)})


# -- DepTable ----------------------------------------------------------------


class DepTableSpec(Spec):
    """Exactly-once handoff law of the dep-park table: every parked
    item is claimed by the ready path XOR a sweep — a sweep claiming an
    already-handed-out item is illegal, and a ready claim must return
    exactly the items whose last dependency fired."""

    name = "dep_table"
    description = "dependency-parked work, exactly-once claims"
    product = "ray_tpu._private.sched_state.DepTable"
    prefix = "spec.dep."
    ops = ("park", "ready", "sweep")

    def init_state(self):
        return {"counts": {}, "by_dep": {}}

    def apply(self, state, op, args):
        counts, by_dep = state["counts"], state["by_dep"]
        if op == "park":
            key, deps = args
            new_by = dict(by_dep)
            for dep in deps:
                new_by[dep] = new_by.get(dep, ()) + (key,)
            return [({"counts": {**counts, key: len(deps)},
                      "by_dep": new_by}, None)]
        if op == "ready":
            dep, = args
            claimed = []
            new_counts = dict(counts)
            new_by = dict(by_dep)
            for key in new_by.pop(dep, ()):
                left = new_counts.get(key)
                if left is None:
                    continue  # already claimed elsewhere: stale entry
                if left > 1:
                    new_counts[key] = left - 1
                else:
                    del new_counts[key]
                    claimed.append(key)
            return [({"counts": new_counts, "by_dep": new_by},
                     frozenset(claimed))]
        if op == "sweep":
            claimed, = args
            new_counts = dict(counts)
            for key in claimed:
                if key not in new_counts:
                    return []  # claimed an item it never owned
                del new_counts[key]
            return [({"counts": new_counts, "by_dep": by_dep}, None)]
        return []

    def adapt_event(self, ev: RawEvent,
                    tokens: Tokens) -> Optional[OpEvent]:
        # The item->key map rides the token table: incremental
        # adaptation (conformance sessions) must resolve a ready/sweep
        # result against parks adapted in earlier batches.
        item_keys = tokens.aux.setdefault("dep_item_keys", {})
        if ev.op == "park":
            key, item, deps = ev.call_payload
            ktok = _tok(tokens, key)
            item_keys[id(item)] = ktok
            args = (ktok, tuple(_tok(tokens, d) for d in deps))
            result = None
        elif ev.op == "ready":
            args = (_tok(tokens, ev.call_payload),)
            result = None if ev.returned is None else frozenset(
                item_keys.get(id(item), _UNSEEN)
                for item in ev.ret_payload)
        else:  # sweep: the claim set rides the result payload
            claimed = () if ev.returned is None else tuple(
                item_keys.get(id(item), _UNSEEN)
                for item in ev.ret_payload)
            args = (frozenset(claimed),)
            result = None
        return OpEvent(point=ev.point, op=ev.op, args=args,
                       result=result, invoked=ev.invoked,
                       returned=ev.returned, thread=ev.thread)

    def observable(self, state):
        return _freeze(state["counts"])  # by_dep staleness is internal

    def observe(self, core, tokens):
        with core._lock:
            counts = {_peek(tokens, k): v
                      for k, v in core._counts.items()}
        return _freeze(counts)


# -- ActorRestartGate --------------------------------------------------------


class ActorGateSpec(Spec):
    """The restart FSM + per-call decision law, per actor (partition
    by actor id): budgets only ever decrease, DEAD is terminal, and
    route/replay verdicts follow the documented replay-or-reject
    contract."""

    name = "actor_gate"
    description = "actor restart FSM and replay-or-reject decisions"
    product = "ray_tpu._private.actor_gate.ActorRestartGate"
    prefix = "spec.actor."
    partition = True
    ops = ("register", "restart", "ready", "rollback", "dead",
           "route", "replay")

    ALIVE, RESTARTING, DEAD = "ALIVE", "RESTARTING", "DEAD"

    def init_state(self):
        return None  # unregistered

    def apply(self, state, op, args):
        if op == "register":
            _aid, mx, used = args
            if state is not None:
                return [(state, None)]  # idempotent
            budget = mx
            if mx >= 0 and used > 0:
                budget = max(0, mx - used)
            return [((self.ALIVE, budget, mx), None)]
        if op == "restart":
            if state is None:
                return [((self.DEAD, 0, 0), False)]
            st, budget, mx = state
            if st == self.DEAD:
                return [(state, False)]
            if budget == 0:
                return [((self.DEAD, 0, mx), False)]
            left = budget - 1 if budget > 0 else budget
            return [((self.RESTARTING, left, mx), True)]
        if op == "ready":
            if state is not None and state[0] == self.RESTARTING:
                return [((self.ALIVE,) + state[1:], None)]
            return [(state, None)]
        if op == "rollback":
            if state is not None and state[0] == self.ALIVE:
                return [((self.RESTARTING,) + state[1:], None)]
            return [(state, None)]
        if op == "dead":
            if state is None:
                return [((self.DEAD, 0, 0), None)]
            return [((self.DEAD,) + state[1:], None)]
        if op == "route":
            _aid, max_retries, attempt = args
            st = state[0] if state is not None else None
            if st == self.DEAD:
                return [(state, "dead")]
            if st == self.RESTARTING and max_retries == 0 \
                    and attempt == 0:
                return [(state, "reject")]
            return [(state, "park")]
        if op == "replay":
            _aid, max_retries = args
            st = state[0] if state is not None else None
            if st == self.DEAD:
                return [(state, "dead")]
            if max_retries == 0:
                return [(state, "reject")]
            return [(state, "resubmit")]
        return []

    def adapt_payloads(self, op, call, ret, tokens):
        if op == "register":
            aid, mx, used = call
            return (_tok(tokens, aid), mx, used), None
        if op in ("ready", "rollback", "dead"):
            return (_tok(tokens, call),), None
        if op == "restart":
            result = None if ret is None else ret[1]
            return (_tok(tokens, call),), result
        if op in ("route", "replay"):
            args = (_tok(tokens, call[0]),) + tuple(call[1:])
            result = None if ret is None else ret[1]
            return args, result
        return call, ret

    def observable(self, state):
        if state is None:
            return None
        return (state[0], state[1])  # FSM state + remaining budget

    def observe(self, core, tokens):
        with core._lock:
            return {_peek(tokens, aid): (st, core._budget.get(aid, 0))
                    for aid, st in core._state.items()}


# -- ShardedTable ------------------------------------------------------------


class ShardedTableSpec(Spec):
    """Refinement of ONE flat dict, per key (the showcase of
    partition-by-key compositionality: each key's subhistory must
    independently linearize against a single-cell map). Results whose
    value depends on an uncaptured caller default (a ``get``/``pop``
    miss) match anything — the refinement bite is on present keys."""

    name = "sharded_table"
    description = "lock-partitioned map refines one flat dict"
    product = "ray_tpu._private.sched_state.ShardedTable"
    prefix = "spec.table."
    partition = True
    ops = ("get", "set", "pop", "contains", "setdefault")

    ABSENT = ("absent",)

    def init_state(self):
        return self.ABSENT

    def apply(self, state, op, args):
        present = state is not self.ABSENT and state[0] == "present"
        if op == "set":
            return [(("present", args[1]), None)]
        if op == "get":
            return [(state, state[1] if present else ANY)]
        if op == "contains":
            return [(state, present)]
        if op == "pop":
            if present:
                return [(self.ABSENT, state[1])]
            return [(state, ANY)]
        if op == "setdefault":
            if present:
                return [(state, state[1])]
            return [(("present", args[1]), args[1])]
        return []

    def adapt_payloads(self, op, call, ret, tokens):
        if op in ("set", "setdefault"):
            key, value = call
            args = (_tok(tokens, key), self._val_tok(tokens, value))
        else:
            args = (_tok(tokens, call),)
        if ret is None:
            return args, None
        _key, out = ret
        if op in ("get", "pop", "setdefault"):
            return args, self._val_tok(tokens, out)
        if op == "contains":
            return args, out
        return args, None

    @staticmethod
    def _val_tok(tokens, value):
        return None if value is None else _tok(tokens, value)

    def observable(self, state):
        return state

    def observe(self, core, tokens):
        out = {}
        for i, shard in enumerate(core._shards):
            with core._locks[i]:
                snap = dict(shard)
            for key, value in snap.items():
                out[_peek(tokens, key)] = (
                    "present",
                    None if value is None else _peek(tokens, value))
        return out


# -- FairTaskQueue -----------------------------------------------------------


class FairTaskQueueSpec(Spec):
    """The virtual-time WFQ law: a pick serves the head of a class
    whose virtual time is minimal among backlogged classes (ties may
    break either way — the spec is deliberately nondeterministic
    there), each serve advances the class's clock by 1/weight, and a
    rejoining class starts at the global virtual time. With one class
    (enforcement off) this degenerates to exactly a FIFO queue."""

    name = "fair_task_queue"
    description = "virtual-time weighted fair queuing law"
    product = "ray_tpu._private.tenancy.FairTaskQueue"
    prefix = "spec.wfq."
    ops = ("put", "pop")

    def __init__(self, weights: Optional[Dict[str, float]] = None,
                 default_weight: float = 1.0):
        self.weights = weights or {}
        self.default_weight = default_weight

    def _weight(self, job: str) -> float:
        return self.weights.get(job) or self.default_weight

    def init_state(self):
        return {"classes": {}, "vt": {}, "gvt": 0.0}

    def apply(self, state, op, args):
        classes, vt, gvt = state["classes"], state["vt"], state["gvt"]
        if op == "put":
            job, item = args
            q = classes.get(job, ())
            new_vt = vt
            if not q:
                new_vt = {**vt, job: max(vt.get(job, 0.0), gvt)}
            return [({"classes": {**classes, job: q + (item,)},
                      "vt": new_vt, "gvt": gvt}, None)]
        if op == "pop":
            backlogged = [j for j, q in classes.items() if q]
            if not backlogged:
                return [(state, None)]
            best_vt = min(vt.get(j, 0.0) for j in backlogged)
            out = []
            for job in backlogged:
                if vt.get(job, 0.0) != best_vt:
                    continue
                q = classes[job]
                new_classes = dict(classes)
                if len(q) > 1:
                    new_classes[job] = q[1:]
                else:
                    del new_classes[job]
                out.append((
                    {"classes": new_classes,
                     "vt": {**vt,
                            job: best_vt + 1.0 / self._weight(job)},
                     "gvt": best_vt}, q[0]))
            return out
        return []

    def adapt_payloads(self, op, call, ret, tokens):
        if op == "put":
            job, item = call
            return (job, tokens.for_obj(item)), None
        # pop: result is the served item (None = empty beat)
        result = None if ret is None else tokens.for_obj(ret)
        return (), result

    def observable(self, state):
        return _freeze(state["classes"])  # clocks are internal pacing

    def observe(self, core, tokens):
        with core._lock:
            classes = {job: tuple(tokens.peek_obj(item) or _UNSEEN
                                  for item in q)
                       for job, q in core._classes.items() if q}
        return _freeze(classes)

    def bind(self, core) -> None:
        """Adopt the live queue's weight map (the virtual-time law is
        weight-parameterized; a mismatched model would flag correct
        picks). A config-driven queue (weights=None) binds the current
        cached parse + default weight, mirroring FairTaskQueue._weight."""
        weights = getattr(core, "_weights", None)
        if weights is not None:
            self.weights = dict(weights)
            return
        from ray_tpu._private.config import ray_config
        from ray_tpu._private.tenancy import cached_job_weights

        self.weights = dict(cached_job_weights())
        self.default_weight = max(
            float(ray_config.job_default_weight), 1e-6)

    def params_key(self):
        return (_freeze(self.weights), self.default_weight)


# -- actor-call exactly-once protocol ----------------------------------------


class ExactlyOnceCallSpec(Spec):
    """Exactly-once register over actor calls, per task id: a call's
    output REPORT may be *applied* at most once. The recorded apply tap
    always observes "applied" (the implementation cannot see its own
    duplicate), so a history in which one call's effect lands twice
    has NO linearization — the FT-gap-(a) double execution, flagged
    mechanically (ROADMAP FT gap a)."""

    name = "exactly_once_call"
    description = "actor-call output applied at most once per task"
    product = "ray_tpu.cluster_utils.ClusterHead"
    prefix = "spec.call."
    partition = True
    ops = ("invoke", "apply")
    supports_conformance = False  # protocol spec: no single live core

    def init_state(self):
        return ("idle", 0)  # (phase, invocations)

    def apply(self, state, op, args):
        phase, n = state
        if op == "invoke":
            return [((phase, n + 1), None)]
        if op == "apply":
            if phase == "applied":
                return [(state, "duplicate")]
            return [(("applied", n), "applied")]
        return []

    def adapt_payloads(self, op, call, ret, tokens):
        if op == "invoke":
            tid, attempt = call
            return (_tok(tokens, tid), attempt), None
        # apply
        result = None if ret is None else ret[1]
        return (_tok(tokens, call),), result

    def observe(self, core, tokens):
        raise NotImplementedError


# -- PrefixCache (LLM KV cache) ----------------------------------------------


class KvCacheSpec(Spec):
    """Safety law of the LLM prefix/KV cache: a pinned (refs>0) block
    is never evicted, refcounts never go negative (a release or pin
    without a matching hold is ILLEGAL), admission only creates blocks
    that are absent and only evicts unpinned ones, resident bytes stay
    under the bound capacity, and the per-tenant charge map equals the
    bytes of each job's resident blocks (conservation — checked by
    refinement: ``observe`` reads the live charge map separately from
    the block table, so drift diverges)."""

    name = "kv_cache"
    description = "prefix/KV block pinning, LRU eviction, tenant charge"
    product = "ray_tpu._private.kv_cache.PrefixCache"
    prefix = "spec.kv."
    ops = ("lookup", "pin", "release", "admit", "evict")

    def __init__(self):
        self._capacity = None  # bound from the live core

    def init_state(self):
        return {"blocks": {}}  # key -> (job, nbytes, refs)

    def _bytes(self, blocks: dict) -> int:
        return sum(nb for _job, nb, _refs in blocks.values())

    def apply(self, state, op, args):
        blocks = state["blocks"]
        if op == "lookup":
            chain, = args
            new_blocks = dict(blocks)
            matched = 0
            for key in chain:
                entry = new_blocks.get(key)
                if entry is None:
                    break
                job, nb, refs = entry
                new_blocks[key] = (job, nb, refs + 1)
                matched += 1
            return [({"blocks": new_blocks}, matched)]
        if op == "pin":
            keys, = args
            new_blocks = dict(blocks)
            for key in keys:
                entry = new_blocks.get(key)
                if entry is None or entry[2] < 1:
                    return []  # pin of a block the caller cannot hold
                new_blocks[key] = (entry[0], entry[1], entry[2] + 1)
            return [({"blocks": new_blocks}, None)]
        if op == "release":
            keys, = args
            new_blocks = dict(blocks)
            for key in keys:
                entry = new_blocks.get(key)
                if entry is None or entry[2] < 1:
                    return []  # release past zero: double-release bug
                new_blocks[key] = (entry[0], entry[1], entry[2] - 1)
            return [({"blocks": new_blocks}, None)]
        if op == "admit":
            chain, job, nbytes, created, evicted = args
            new_blocks = dict(blocks)
            for key in evicted:
                entry = new_blocks.get(key)
                if entry is None or entry[2] != 0:
                    return []  # evicted a pinned (or absent) block
                del new_blocks[key]
            for key in created:
                if key in new_blocks or key not in chain:
                    return []  # created a duplicate / unasked block
                new_blocks[key] = (job, nbytes, 1)
            if self._capacity is not None \
                    and self._bytes(new_blocks) > self._capacity:
                return []  # admitted past the capacity bound
            return [({"blocks": new_blocks}, None)]
        if op == "evict":
            _nbytes, evicted = args
            new_blocks = dict(blocks)
            for key in evicted:
                entry = new_blocks.get(key)
                if entry is None or entry[2] != 0:
                    return []  # evicted a pinned (or absent) block
                del new_blocks[key]
            return [({"blocks": new_blocks}, None)]
        return []

    def adapt_payloads(self, op, call, ret, tokens):
        # admit/evict: the created/evicted key sets ride the RESULT
        # payload into args (the DepTable sweep pattern) so ``apply``
        # validates their legality deterministically.
        if op == "lookup":
            chain, = call
            return ((tuple(_tok(tokens, k) for k in chain),), ret)
        if op in ("pin", "release"):
            keys, = call
            return ((tuple(_tok(tokens, k) for k in keys),), None)
        if op == "admit":
            chain, job, nbytes = call
            created, evicted = ((), ()) if ret is None else ret
            args = (tuple(_tok(tokens, k) for k in chain), job, nbytes,
                    tuple(_tok(tokens, k) for k in created),
                    tuple(_tok(tokens, k) for k in evicted))
            return args, None
        if op == "evict":
            nbytes, = call
            evicted = () if ret is None else ret[0]
            return ((nbytes,
                     tuple(_tok(tokens, k) for k in evicted)), None)
        return call, ret

    def bind(self, core) -> None:
        self._capacity = core.capacity_bytes

    def params_key(self):
        return self._capacity

    def observable(self, state):
        blocks = state["blocks"]
        charge: Dict[str, int] = {}
        for job, nb, _refs in blocks.values():
            charge[job] = charge.get(job, 0) + nb
        return (_freeze(blocks), _freeze(charge))

    def observe(self, core, tokens):
        with core._lock:
            blocks = {_peek(tokens, k): (b.job, b.nbytes, b.refs)
                      for k, b in core._blocks.items()}
            charge = dict(core._charge)
        return (_freeze(blocks), _freeze(charge))


# -- the registry ------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class CatalogEntry:
    name: str
    factory: Callable[[], Spec]
    product: str
    prefix: str
    description: str
    supports_conformance: bool = True


def _entry(factory: Callable[[], Spec]) -> CatalogEntry:
    probe = factory()
    return CatalogEntry(name=probe.name, factory=factory,
                        product=probe.product, prefix=probe.prefix,
                        description=probe.description,
                        supports_conformance=probe.supports_conformance)


SPEC_CATALOG: Dict[str, CatalogEntry] = {
    entry.name: entry for entry in (
        _entry(QuotaLedgerSpec),
        _entry(DepTableSpec),
        _entry(ActorGateSpec),
        _entry(ShardedTableSpec),
        _entry(FairTaskQueueSpec),
        _entry(ExactlyOnceCallSpec),
        _entry(KvCacheSpec),
    )
}

FIXTURE_SPECS: Dict[str, Callable[[], Spec]] = {
    "atomic_register": AtomicRegisterSpec,
    "fifo_queue": FifoQueueSpec,
}


def entry_for_core(core: str) -> Optional[CatalogEntry]:
    """Catalog entry for a recorded point's core segment ("quota" from
    "spec.quota.charge")."""
    want = f"spec.{core}."
    for entry in SPEC_CATALOG.values():
        if entry.prefix == want:
            return entry
    return None
