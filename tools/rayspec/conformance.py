"""Conformance (refinement) checking: live core vs. spec-reachable states.

Linearizability answers "could this history have happened against the
sequential spec?". Conformance asks one question more: "and is the
core's CURRENT state one the spec can reach via some linearization of
that history?" — i.e. the concurrent implementation *refines* the
sequential model, not just its answers but its state. raymc calls into
this at every quiescent state of an explored scenario, turning each
existing scenario into a refinement proof.

The search is the checker's (:func:`tools.rayspec.check.linearize`
with a ``target`` observable). Two layers keep the cost compatible
with raymc's thousands of replayed executions:

- a :class:`ConformanceSession` adapts the recorder's raw events
  **incrementally** (the adapters' token tables live on the session's
  ``Tokens``), maintaining one canonical tuple per event instead of
  re-canonicalizing the whole history at every quiescent state;
- verdicts are cached process-wide keyed on (spec, canonical history,
  target): a DFS re-execution of the same logical prefix hits the
  cache instead of re-searching. Canonical forms use the recorder's
  per-execution sequence numbers — identical replayed prefixes produce
  identical sequences — plus adapter-tokenized identifiers.

Verdict mapping: ``violation`` (history itself non-linearizable) and
``divergence`` (linearizable, but the live state is not reachable)
both return a message — a finding. ``undecided`` (budget) returns
None: a bounded-search miss must not fabricate a finding; the caller
counts checks so a silent wash-out is visible in the stats.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from tools.rayspec.check import linearize
from tools.rayspec.history import RawEvent, Tokens
from tools.rayspec.specs import CatalogEntry, Spec, _freeze

# (spec name, canonical history, target) -> status. Bounded: cleared
# wholesale at the cap (simplicity over LRU; one raymc scenario's
# distinct prefixes sit far below it).
_CACHE: Dict[tuple, str] = {}
_CACHE_CAP = 500_000


def _canonical_item(e) -> tuple:
    return (e.point, _freeze(e.args), _freeze(e.result), e.invoked,
            e.returned, e.thread)


def _cached_linearize(events, items, spec: Spec, target,
                      max_configs: int) -> str:
    # `target` is already canonical/hashable (every observe()/
    # observable() returns frozen forms) — re-freezing it dominated
    # the profile at raymc's check rates. params_key covers bound
    # model parameters (WFQ weights): differently-bound sessions must
    # never share verdicts.
    key = (spec.name, spec.params_key(), items, target)
    status = _CACHE.get(key)
    if status is None:
        status, _explored = linearize(events, spec, max_configs,
                                      target=target)
        if len(_CACHE) >= _CACHE_CAP:
            _CACHE.clear()
        _CACHE[key] = status
    return status


class ConformanceSession:
    """Incremental adapter + checker for ONE (core, spec) binding over
    a growing recorded history (one raymc execution)."""

    def __init__(self, entry: CatalogEntry,
                 max_configs: int = 50_000):
        self.entry = entry
        self.spec = entry.factory()
        self.tokens = Tokens()
        self.max_configs = max_configs
        self._adapted: List = []
        self._items: List[tuple] = []
        self._consumed = 0
        # (index, raw event) adapted while still pending: re-adapted
        # once the recorder completes them in place.
        self._open: List[Tuple[int, RawEvent]] = []
        self._last: Optional[str] = None
        self._checked = False
        self._bound = False

    def _refresh(self, raw: List[RawEvent]) -> None:
        still_open = []
        for ix, ev in self._open:
            if ev.returned is not None:
                adapted = self.spec.adapt_event(ev, self.tokens)
                self._adapted[ix] = adapted
                self._items[ix] = _canonical_item(adapted)
            else:
                still_open.append((ix, ev))
        self._open = still_open
        for ix in range(self._consumed, len(raw)):
            ev = raw[ix]
            adapted = self.spec.adapt_event(ev, self.tokens)
            self._adapted.append(adapted)
            self._items.append(_canonical_item(adapted))
            if ev.returned is None:
                self._open.append((ix, ev))
        self._consumed = len(raw)

    def check(self, recorder, core) -> Optional[str]:
        """Recorder-driven form with the unchanged-state skip: every
        mutator of a catalog core is tapped, so a quiescent state with
        no new events (and no pending op completed) cannot have
        changed the core — the previous verdict stands."""
        if self._checked \
                and recorder.count_for(core) == self._consumed and \
                not any(ev.returned is not None
                        for _ix, ev in self._open):
            return self._last
        self._checked = True
        self._last = self.check_raw(recorder.events_for(core), core)
        return self._last

    def check_raw(self, raw: List[RawEvent], core) -> Optional[str]:
        """None when ``core`` conforms (or the budget washed out);
        else a violation message naming the failing key and kind."""
        if not self._bound:
            self.spec.bind(core)
            self._bound = True
        self._refresh(raw)
        spec = self.spec
        events = self._adapted
        if not spec.partition:
            target = spec.observe(core, self.tokens)
            status = _cached_linearize(events, tuple(self._items),
                                       spec, target, self.max_configs)
            return _verdict(status, spec.name, None, len(events))
        groups: Dict[object, list] = {}
        group_items: Dict[object, list] = {}
        for e, item in zip(events, self._items):
            key = spec.key_of(e.op, e.args)
            groups.setdefault(key, []).append(e)
            group_items.setdefault(key, []).append(item)
        live = spec.observe(core, self.tokens)
        init_obs = spec.observable(spec.init_state())
        for key in sorted(set(groups) | set(live), key=repr):
            target = live.get(key, init_obs)
            status = _cached_linearize(
                groups.get(key, []),
                tuple(group_items.get(key, ())), spec, target,
                self.max_configs)
            msg = _verdict(status, spec.name, key,
                           len(groups.get(key, ())))
            if msg is not None:
                return msg
        return None


def check_conformance(raw_events: List[RawEvent], entry: CatalogEntry,
                      core,
                      max_configs: int = 100_000) -> Optional[str]:
    """One-shot form (tests, ad-hoc triage): adapt the whole history
    and check ``core`` against it."""
    return ConformanceSession(entry, max_configs).check_raw(raw_events,
                                                            core)


def _verdict(status: str, spec_name: str, key,
             events: int) -> Optional[str]:
    where = f" (key {key!r})" if key is not None else ""
    if status == "violation":
        return (f"{spec_name}{where}: recorded history of {events} "
                f"op(s) is not linearizable w.r.t. the sequential "
                f"spec")
    if status == "divergence":
        return (f"{spec_name}{where}: live core state is not "
                f"reachable by any linearization of the recorded "
                f"{events}-op history (refinement violation)")
    return None  # ok, or undecided (bounded search washed out)


def conformance_cache_info() -> Tuple[int, int]:
    return len(_CACHE), _CACHE_CAP
