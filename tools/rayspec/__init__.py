"""rayspec: executable sequential specifications + linearizability
checking for the runtime's pure decision cores.

The analysis ladder so far proves structure (raylint), replays one
schedule (raysan), and exhausts bounded interleavings against
hand-written per-scenario properties (raymc). rayspec adds the missing
rung: each registered decision core (``QuotaLedger``, ``FairTaskQueue``,
``DepTable``, ``ActorRestartGate``, ``ShardedTable``, plus the actor-call
exactly-once protocol) gets a small *executable sequential
specification* — a pure Python model with an explicit operation
alphabet — and the tooling to hold the concurrent implementation to it:

- a **history recorder** (:mod:`.history`) riding the
  ``sanitize_hooks.spec_op`` seam captures concurrent
  invocation/response histories from real runs at near-zero uninstalled
  cost;
- a **Wing & Gong-style linearizability checker** (:mod:`.check`) with
  partition-by-key compositionality and a bounded-search fallback; on
  violation it ddmin-shrinks to the minimal non-linearizable
  sub-history and emits a raysan ``Schedule`` script for replay;
- a **conformance mode** (:mod:`.conformance`) cross-checks a live core
  against the spec's reachable state set — wired into raymc so every
  quiescent state of an explored scenario becomes a refinement check.

``SPEC_CATALOG`` in :mod:`.specs` is the registry; raylint R9 holds the
product taps, the ``sanitize_hooks.SPEC_POINTS`` registry, and the
catalog to each other.
"""

from tools.rayspec.check import CheckOutcome, check_events  # noqa: F401
from tools.rayspec.conformance import check_conformance  # noqa: F401
from tools.rayspec.history import OpEvent, RawEvent, Recorder  # noqa: F401
from tools.rayspec.specs import (FIXTURE_SPECS, SPEC_CATALOG,  # noqa: F401
                                 Spec)
