"""CLI: ``python -m tools.rayspec [paths] [--report json] ...``

Runs the given test paths under a process-wide history recorder, then
checks every recorded decision-core history against its executable
sequential specification — the form CI archives as
``RAYSPEC_REPORT.json`` (deterministic artifact; volatile counters go
to the ``.timing.json`` sidecar).

Exit-code contract (raylint's, extended over test outcomes):
  0  tests passed, every checked history linearizable
  1  test failures and/or linearizability violations
  2  usage error (bad path)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

DEFAULT_PATHS = ("tests/core/test_fault_semantics.py",
                 "tests/core/test_sched_scale.py",
                 "tests/core/test_kv_cache.py")

# Run-to-run volatile report fields (timings, id-/timing-dependent
# counters): normalized out of the committed artifact.
VOLATILE_FIELDS = ("elapsed_s", "events", "instances", "explored",
                   "checked_keys", "recorded_events")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.rayspec",
        description="executable-spec linearizability checking for "
                    "ray_tpu decision cores")
    parser.add_argument(
        "paths", nargs="*", default=list(DEFAULT_PATHS),
        help="test files/directories to record and check (default: "
             f"the decision-core suites {', '.join(DEFAULT_PATHS)})")
    parser.add_argument("--report", choices=("json", "pretty"),
                        default="pretty")
    parser.add_argument("--report-file", default="", metavar="PATH",
                        help="also write the JSON report artifact to "
                             "PATH (plus PATH.timing.json sidecar)")
    parser.add_argument("--pytest-args", default="-q", metavar="ARGS",
                        help="extra arguments handed to pytest "
                             "(default: -q)")
    parser.add_argument("--include-slow", action="store_true",
                        help="do not add '-m not slow' to the pytest "
                             "run")
    parser.add_argument("--max-events", type=int, default=200_000,
                        help="recorder event cap (overflow stops "
                             "recording, flagged in the report)")
    parser.add_argument("--max-configs", type=int, default=200_000,
                        help="per-history linearization search budget "
                             "(exhausted -> 'undecided', never a "
                             "false verdict)")
    args = parser.parse_args(argv)

    for path in args.paths:
        if not os.path.exists(path):
            print(f"rayspec: no such path: {path}", file=sys.stderr)
            return 2

    import pytest

    from tools.rayspec.check import check_events
    from tools.rayspec.history import Recorder
    from tools.rayspec.specs import entry_for_core

    t0 = time.monotonic()
    pytest_argv = list(args.paths) + args.pytest_args.split()
    if not args.include_slow:
        pytest_argv += ["-m", "not slow"]
    pytest_argv += ["-p", "no:cacheprovider"]
    recorder = Recorder(max_events=args.max_events)
    with recorder:
        rc = pytest.main(pytest_argv)

    cores: dict = {}
    violations_total = 0
    undecided_total = 0
    for (core, _instance), raw in sorted(recorder.histories().items(),
                                         key=lambda kv: kv[0]):
        entry = entry_for_core(core)
        if entry is None:
            continue  # a tap with no registered spec: R9's business
        spec = entry.factory()
        events, _tokens = spec.adapt(raw)
        row = cores.setdefault(entry.name, {
            "instances": 0, "recorded_events": 0, "checked_keys": 0,
            "undecided": 0, "violations": []})
        row["instances"] += 1
        row["recorded_events"] += len(events)
        for outcome in check_events(events, spec,
                                    max_configs=args.max_configs):
            row["checked_keys"] += 1
            if outcome.status == "violation":
                violations_total += 1
                row["violations"].append(outcome.to_dict())
            elif outcome.status == "undecided":
                undecided_total += 1
                row["undecided"] += 1

    report = {
        "schema_version": 1,
        "harness": "python -m tools.rayspec",
        "pytest_exit": int(rc),
        "recorder_overflowed": recorder.overflowed,
        "cores": cores,
        "undecided": undecided_total,
        "pass": violations_total == 0 and int(rc) == 0,
        "elapsed_s": round(time.monotonic() - t0, 3),
    }

    if args.report == "json":
        print(json.dumps(report, indent=2))
    else:
        for name, row in sorted(cores.items()):
            print(f"rayspec[{name}]: {row['instances']} instance(s), "
                  f"{row['recorded_events']} op(s), "
                  f"{row['checked_keys']} checked key(s), "
                  f"{len(row['violations'])} violation(s), "
                  f"{row['undecided']} undecided")
            for v in row["violations"]:
                print(f"  VIOLATION {v['message']}")
                print(f"    replay: Schedule(order="
                      f"{v['schedule_order']})")
        print(f"rayspec: {'PASS' if report['pass'] else 'FAIL'} "
              f"(pytest exit {rc}, {violations_total} violation(s), "
              f"{undecided_total} undecided, "
              f"{report['elapsed_s']:.2f}s)")

    if args.report_file:
        from tools.reporting import write_report_artifact

        write_report_artifact(args.report_file, report,
                              volatile=VOLATILE_FIELDS)

    if int(rc) == 4:  # pytest usage error
        return 2
    return 0 if report["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
