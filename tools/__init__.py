"""Developer tooling for the ray_tpu repo (not shipped with the runtime)."""
