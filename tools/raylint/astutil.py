"""Shared AST helpers: dotted-name resolution and the blocking-call
predicate that R1 (async-blocking) and R2 (lock discipline) both use.

"Blocking" here means: may park the calling thread for an unbounded or
operator-visible time — sleeps, socket/file I/O, subprocess, sync RPC
(``RpcClient.call`` / ``PipelinedClient.send`` / framed ``send_msg`` /
``recv_msg``), untimed ``Condition.wait`` / ``Thread.join``, sync
ObjectRef resolution (``ray_tpu.get`` / ``ray_tpu.wait`` with a nonzero
timeout, ``ray_tpu.kill``), ``Future.result``, and the actor-backed
``util.queue.Queue`` methods (each is a round-trip through an actor).
"""

from __future__ import annotations

import ast
import re
from typing import Optional, Tuple

QUEUE_RECEIVER = re.compile(r"^(q|queue|.*_q|.*_queue)$")
THREAD_RECEIVER = re.compile(r"^(t|th|thread|proc|process|worker"
                             r"|.*_thread|.*_proc(ess)?|flusher|reaper"
                             r"|reporter|pump)$")
CALLBACK_NAME = re.compile(r"^(cb|callback|callbacks?|fn|func|handler"
                           r"|hook|listener|on_[a-z_]+|user_[a-z_]+)$")

# Dotted calls that block wherever they appear.
BLOCKING_DOTTED = {
    "time.sleep",
    "socket.create_connection",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "subprocess.Popen",
    "os.system", "os.waitpid",
    "ray_tpu.kill",
}

# Attribute suffixes that block regardless of receiver name: socket and
# framed-RPC primitives.
BLOCKING_SUFFIXES = {
    "recv", "recv_into", "accept", "sendall", "connect",
    "call", "call_with_rid",
}

# Module-level helper names (the rpc.py framing primitives).
BLOCKING_BARE = {"send_msg", "recv_msg"}


def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def receiver_name(func: ast.AST) -> Optional[str]:
    """For a call ``recv.attr(...)``, the final receiver segment name
    ('queue' for ``self.queue.get``), else None."""
    if not isinstance(func, ast.Attribute):
        return None
    value = func.value
    if isinstance(value, ast.Name):
        return value.id
    if isinstance(value, ast.Attribute):
        return value.attr
    return None


def call_kwarg(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def is_zero_or_false(node: Optional[ast.AST]) -> bool:
    return isinstance(node, ast.Constant) and (
        node.value == 0 or node.value is False)


def has_timeout(call: ast.Call, positional_index: Optional[int] = None) \
        -> bool:
    """True when the call passes any timeout-like bound (kwarg
    ``timeout``/``timeout_s``, or a positional arg at ``positional_index``)."""
    if call_kwarg(call, "timeout") is not None:
        return True
    if call_kwarg(call, "timeout_s") is not None:
        return True
    if positional_index is not None and len(call.args) > positional_index:
        return True
    return False


def classify_blocking(call: ast.Call) -> Optional[Tuple[str, str]]:
    """(kind, detail) when this call can block the calling thread, else
    None. ``kind`` distinguishes rpc/sleep/io/sync-get/... for rule
    messages."""
    func = call.func
    dotted = dotted_name(func)
    if dotted in BLOCKING_DOTTED:
        kind = "sleep" if dotted == "time.sleep" else (
            "sync-get" if dotted == "ray_tpu.kill" else "io")
        return kind, dotted
    if isinstance(func, ast.Name):
        if func.id in BLOCKING_BARE:
            return "rpc", func.id
        if func.id == "open":
            return "io", "open"
        return None
    if not isinstance(func, ast.Attribute):
        return None
    attr = func.attr
    recv = receiver_name(func) or ""

    if dotted in ("ray_tpu.get", "ray_tpu.wait") or (
            attr in ("get", "wait") and recv in ("ray_tpu", "worker")
            and dotted in ("worker.get", "worker.wait",
                           "ray_tpu.get", "ray_tpu.wait")):
        if is_zero_or_false(call_kwarg(call, "timeout")):
            return None  # poll, not a wait
        return "sync-get", dotted or attr
    if attr in BLOCKING_SUFFIXES:
        return "rpc" if attr in ("call", "call_with_rid") else "io", \
            f"{recv}.{attr}" if recv else attr
    if attr == "acquire":
        if is_zero_or_false(call_kwarg(call, "blocking")):
            return None
        return "lock", f"{recv}.acquire" if recv else "acquire"
    if attr in ("wait", "wait_for"):
        # Condition/Event wait. A timeout bounds it but it still parks
        # the thread — callers decide per-rule how strict to be; we
        # report untimed waits as blocking, timed waits as "timed-wait".
        pos = 1 if attr == "wait_for" else 0
        if has_timeout(call, positional_index=pos):
            return "timed-wait", f"{recv}.{attr}" if recv else attr
        return "untimed-wait", f"{recv}.{attr}" if recv else attr
    if attr == "join" and THREAD_RECEIVER.match(recv):
        if has_timeout(call, positional_index=0):
            return "timed-wait", f"{recv}.join"
        return "untimed-wait", f"{recv}.join"
    if attr == "result":
        return "sync-get", f"{recv}.result" if recv else "result"
    if QUEUE_RECEIVER.match(recv):
        if attr in ("get", "put", "shutdown"):
            if is_zero_or_false(call_kwarg(call, "block")):
                return None  # explicit non-blocking variant
            # util.queue.Queue: an actor round-trip; stdlib Queue: may
            # park on capacity/emptiness.
            return "sync-get", f"{recv}.{attr}"
        if attr in ("qsize", "empty", "full"):
            # Never parks on a stdlib queue; on the actor-backed Queue
            # it is still an RPC round-trip — only the event-loop rule
            # (R1) treats it as blocking.
            return "queue-stat", f"{recv}.{attr}"
    return None


def iter_calls_outside_nested_defs(fn: ast.AST):
    """Yield every Call node in ``fn``'s body, not descending into
    nested function/class definitions (their bodies run elsewhere)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        if isinstance(node, ast.Call):
            yield node
        stack.extend(ast.iter_child_nodes(node))
