"""R2 lock-discipline: lock ordering and what may run under a lock.

Builds an inter-procedural lock-acquisition graph from ``with
self._lock`` / ``.acquire()`` patterns across the analyzed tree and
reports:

- **lock-order cycles**: lock A is taken while B is held on one path
  and B while A is held on another (classic AB/BA deadlock). Locks are
  identified per class attribute (``module.Class._lock``) or per
  assigned name, with ``threading.Condition(existing_lock)`` aliased to
  its underlying lock;
- **blocking under a lock**: a lock held across a sleep, socket/RPC
  send (``send_msg``/``recv``/``.call``), sync ObjectRef resolution,
  ``.remote()`` submission (can stall on batcher backpressure), an
  untimed ``Condition.wait`` on a *different* lock's condition, or a
  ``Thread.join`` — directly or via a same-class method call
  (transitive, fixpoint);
- **user callbacks under a lock**: invoking a callback-shaped value
  (``cb``/``callback``/``handler``/``on_*``/``fn``) while holding a
  lock hands your lock to arbitrary user code (re-entrancy deadlock).

Waiting on a condition **whose own lock is the only one held** is the
normal condvar protocol and is never flagged.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.raylint.astutil import (
    CALLBACK_NAME,
    classify_blocking,
    dotted_name,
)
from tools.raylint.core import FileInfo, Project, Rule

_LOCK_FACTORIES = {"Lock", "RLock", "Condition"}


def _lock_factory(value: ast.AST) -> Optional[Tuple[str, Optional[str]]]:
    """(factory, wrapped_attr_or_name) when ``value`` constructs a
    threading lock/condition; wrapped is the Condition's lock arg."""
    if not isinstance(value, ast.Call):
        return None
    dn = dotted_name(value.func)
    if dn is None:
        return None
    last = dn.rsplit(".", 1)[-1]
    if last not in _LOCK_FACTORIES:
        return None
    if not (dn.startswith("threading.") or dn == last):
        return None
    wrapped = None
    if last == "Condition" and value.args:
        wrapped = dotted_name(value.args[0])
    return last, wrapped


@dataclasses.dataclass
class _FnSummary:
    key: str                      # "module.Class.method" or "module.fn"
    cls: Optional[str]
    acquires: Set[str] = dataclasses.field(default_factory=set)
    # (held_lock, message, line) — direct violations
    direct: List[Tuple[str, str, int]] = dataclasses.field(
        default_factory=list)
    # (held_tuple, callee_bare_name, line) — unresolved until fixpoint
    calls_under_lock: List[Tuple[Tuple[str, ...], str, int]] = \
        dataclasses.field(default_factory=list)
    callees: Set[str] = dataclasses.field(default_factory=set)
    blocks: Optional[str] = None   # human label of first blocking site
    # (outer, inner, line) lock-order edges observed in this body
    edges: List[Tuple[str, str, int]] = dataclasses.field(
        default_factory=list)


class _ClassLocks:
    def __init__(self):
        self.attrs: Dict[str, str] = {}    # attr -> canonical lock id
        self.alias: Dict[str, str] = {}    # condition attr -> lock attr


class LockDisciplineRule(Rule):
    id = "R2"
    name = "lock-discipline"
    description = ("lock-order cycles; blocking calls, RPC sends, "
                   "submissions, or user callbacks while holding a lock")

    # -- collection -------------------------------------------------------

    def finalize(self, project: Project) \
            -> Iterable[Tuple[FileInfo, int, str]]:
        summaries: Dict[str, _FnSummary] = {}
        per_file_fns: Dict[str, List[str]] = {}
        fn_sites: Dict[str, Tuple[FileInfo, int]] = {}

        for fi in project.files:
            keys = []
            class_locks = self._collect_class_locks(fi)
            global_locks = self._collect_global_locks(fi)
            for cls_name, fn in self._iter_functions(fi.tree):
                key = f"{fi.module}.{cls_name + '.' if cls_name else ''}" \
                      f"{fn.name}"
                summary = self._summarize(
                    fi, fn, cls_name, class_locks, global_locks, key)
                summaries[key] = summary
                fn_sites[key] = (fi, fn.lineno)
                keys.append(key)
            per_file_fns[fi.module] = keys

        self._propagate(summaries)

        violations: List[Tuple[FileInfo, int, str]] = []
        edges: Dict[Tuple[str, str], Tuple[FileInfo, int]] = {}

        for key, s in summaries.items():
            fi, _ = fn_sites[key]
            for _, message, line in s.direct:
                violations.append((fi, line, message))
            for held, callee, line in s.calls_under_lock:
                callee_key = self._resolve_callee(
                    key, callee, s.cls, summaries)
                if callee_key is None:
                    continue
                cs = summaries[callee_key]
                if cs.blocks is not None:
                    violations.append((
                        fi, line,
                        f"lock(s) {', '.join(sorted(held))} held across "
                        f"call to `{callee}` which blocks "
                        f"({cs.blocks})"))
                for inner in cs.acquires:
                    for outer in held:
                        if inner != outer:
                            edges.setdefault((outer, inner), (fi, line))
            for outer, inner, line in s.edges:
                edges.setdefault((outer, inner), (fi, line))

        violations.extend(self._find_cycles(edges))
        return violations

    # -- helpers ----------------------------------------------------------

    def _collect_class_locks(self, fi: FileInfo) -> Dict[str, _ClassLocks]:
        out: Dict[str, _ClassLocks] = {}
        for node in fi.nodes():
            if not isinstance(node, ast.ClassDef):
                continue
            locks = _ClassLocks()
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Assign) or len(sub.targets) != 1:
                    continue
                target = sub.targets[0]
                if not (isinstance(target, ast.Attribute)
                        and isinstance(target.value, ast.Name)
                        and target.value.id == "self"):
                    continue
                fac = _lock_factory(sub.value)
                if fac is None:
                    continue
                factory, wrapped = fac
                attr = target.attr
                if factory == "Condition" and wrapped \
                        and wrapped.startswith("self."):
                    locks.alias[attr] = wrapped.split(".", 1)[1]
                locks.attrs[attr] = f"{fi.module}.{node.name}.{attr}"
            if locks.attrs:
                out[node.name] = locks
        return out

    def _collect_global_locks(self, fi: FileInfo) -> Dict[str, str]:
        """Any ``name = threading.Lock()``-style assignment in the file
        (module level or closure-local) — closures share them across
        nested functions, so resolve by bare name file-wide."""
        out: Dict[str, str] = {}
        for node in fi.nodes():
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and _lock_factory(node.value) is not None:
                name = node.targets[0].id
                out[name] = f"{fi.module}.{name}"
        return out

    def _iter_functions(self, tree: ast.AST):
        """(class_name_or_None, fn) for every def/async def, nested ones
        included (each is summarized independently)."""
        def walk(node, cls):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    yield from walk(child, child.name)
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    yield cls, child
                    yield from walk(child, cls)
                else:
                    yield from walk(child, cls)
        yield from walk(tree, None)

    def _lock_id(self, expr: ast.AST, cls: Optional[str],
                 class_locks: Dict[str, _ClassLocks],
                 global_locks: Dict[str, str]) -> Optional[str]:
        dn = dotted_name(expr)
        if dn is None:
            return None
        if dn.startswith("self.") and cls and cls in class_locks:
            attr = dn.split(".", 1)[1]
            locks = class_locks[cls]
            attr = locks.alias.get(attr, attr)
            return locks.attrs.get(attr)
        return global_locks.get(dn)

    # -- per-function summarization ---------------------------------------

    def _summarize(self, fi: FileInfo, fn, cls: Optional[str],
                   class_locks: Dict[str, _ClassLocks],
                   global_locks: Dict[str, str], key: str) -> _FnSummary:
        s = _FnSummary(key=key, cls=cls)

        def lock_of(expr):
            return self._lock_id(expr, cls, class_locks, global_locks)

        def visit_call(call: ast.Call, held: Tuple[str, ...]):
            func = call.func
            dn = dotted_name(func)
            # .acquire() outside a with: function-scoped acquisition.
            if isinstance(func, ast.Attribute) and func.attr == "acquire":
                lid = lock_of(func.value)
                if lid is not None:
                    s.acquires.add(lid)
                    for outer in held:
                        if outer != lid:
                            s.edges.append((outer, lid, call.lineno))
                return (lid,) if lid is not None else ()
            if not held:
                if s.blocks is None:
                    hit = classify_blocking(call)
                    if hit is not None and hit[0] not in (
                            "timed-wait", "queue-stat"):
                        s.blocks = f"{hit[1]}:{call.lineno}"
                    elif isinstance(func, ast.Attribute) \
                            and func.attr in ("remote", "remote_async"):
                        s.blocks = f"{dn or func.attr}:{call.lineno} " \
                                   f"(.remote submission)"
                # Still record callees for transitive acquire edges.
                self._note_callee(s, func, dn, call, held)
                return ()
            # -- a lock is held --
            if isinstance(func, ast.Attribute) \
                    and func.attr in ("wait", "wait_for"):
                cond_lock = lock_of(func.value)
                if cond_lock is not None and cond_lock in held:
                    others = [h for h in held if h != cond_lock]
                    if others:
                        s.direct.append((
                            others[0],
                            f"lock(s) {', '.join(others)} held across "
                            f"`{dn}` (condvar wait releases only its "
                            f"own lock)", call.lineno))
                    return ()
            hit = classify_blocking(call)
            if hit is not None:
                kind, detail = hit
                if kind not in ("lock", "queue-stat"):
                    s.direct.append((
                        held[0],
                        f"lock(s) {', '.join(held)} held across "
                        f"blocking call `{detail}` ({kind})",
                        call.lineno))
                return ()
            if isinstance(func, ast.Attribute) \
                    and func.attr in ("remote", "remote_async"):
                s.direct.append((
                    held[0],
                    f"lock(s) {', '.join(held)} held across `.{func.attr}"
                    f"()` submission (RPC; can stall on batcher "
                    f"backpressure)", call.lineno))
                return ()
            cb_name = None
            if isinstance(func, ast.Name) and CALLBACK_NAME.match(func.id):
                cb_name = func.id
            elif isinstance(func, ast.Attribute) \
                    and CALLBACK_NAME.match(func.attr) \
                    and not dn.startswith(("self.", "cls.")):
                cb_name = dn
            if cb_name is not None:
                s.direct.append((
                    held[0],
                    f"lock(s) {', '.join(held)} held while invoking "
                    f"user callback `{cb_name}`", call.lineno))
                return ()
            self._note_callee(s, func, dn, call, held)
            return ()

        def walk(node, held: Tuple[str, ...]):
            acquired_here: Tuple[str, ...] = ()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.Lambda)):
                return  # separate summaries / deferred execution
            if isinstance(node, (ast.With, ast.AsyncWith)):
                new = list(held)
                for item in node.items:
                    expr = item.context_expr
                    lid = lock_of(expr) if not isinstance(expr, ast.Call) \
                        else None
                    if lid is not None:
                        s.acquires.add(lid)
                        for outer in new:
                            if outer != lid:
                                s.edges.append(
                                    (outer, lid, node.lineno))
                        if lid not in new:
                            new.append(lid)
                for child in node.body:
                    walk(child, tuple(new))
                return
            if isinstance(node, ast.Call):
                acquired_here = visit_call(node, held)
            new_held = held + tuple(
                l for l in acquired_here if l not in held)
            for child in ast.iter_child_nodes(node):
                walk(child, new_held)

        for child in ast.iter_child_nodes(fn):
            walk(child, ())
        return s

    def _note_callee(self, s: _FnSummary, func, dn: Optional[str],
                     call: ast.Call, held: Tuple[str, ...]):
        name = None
        if dn and dn.startswith("self."):
            rest = dn.split(".", 1)[1]
            if "." not in rest:
                name = rest
        elif isinstance(func, ast.Name):
            name = func.id
        if name is None:
            return
        s.callees.add(name)
        if held:
            s.calls_under_lock.append((held, name, call.lineno))

    def _resolve_callee(self, caller_key: str, callee: str,
                        cls: Optional[str],
                        summaries: Dict[str, _FnSummary]) -> Optional[str]:
        module = caller_key.rsplit(".", 2 if cls else 1)[0]
        if cls:
            key = f"{module}.{cls}.{callee}"
            if key in summaries:
                return key
        key = f"{module}.{callee}"
        return key if key in summaries else None

    # -- fixpoint + cycles -------------------------------------------------

    def _propagate(self, summaries: Dict[str, _FnSummary]):
        """Transitive closure of "blocks" and "acquires" through
        same-module/class bare and self calls."""
        changed = True
        while changed:
            changed = False
            for key, s in summaries.items():
                for callee in s.callees:
                    ck = self._resolve_callee(key, callee, s.cls,
                                              summaries)
                    if ck is None or ck == key:
                        continue
                    cs = summaries[ck]
                    if cs.blocks is not None and s.blocks is None:
                        s.blocks = f"via {callee}: {cs.blocks}"
                        changed = True
                    before = len(s.acquires)
                    s.acquires |= cs.acquires
                    if len(s.acquires) != before:
                        changed = True

    def _find_cycles(self, edges) -> List[Tuple[FileInfo, int, str]]:
        graph: Dict[str, Set[str]] = {}
        for (a, b) in edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        # Tarjan SCC; any SCC with >1 node is a lock-order cycle.
        index_counter = [0]
        stack: List[str] = []
        lowlink: Dict[str, int] = {}
        index: Dict[str, int] = {}
        on_stack: Dict[str, bool] = {}
        sccs: List[List[str]] = []

        def strongconnect(v):
            worklist = [(v, iter(sorted(graph[v])))]
            index[v] = lowlink[v] = index_counter[0]
            index_counter[0] += 1
            stack.append(v)
            on_stack[v] = True
            while worklist:
                node, it = worklist[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = lowlink[w] = index_counter[0]
                        index_counter[0] += 1
                        stack.append(w)
                        on_stack[w] = True
                        worklist.append((w, iter(sorted(graph[w]))))
                        advanced = True
                        break
                    elif on_stack.get(w):
                        lowlink[node] = min(lowlink[node], index[w])
                if advanced:
                    continue
                worklist.pop()
                if worklist:
                    parent = worklist[-1][0]
                    lowlink[parent] = min(lowlink[parent], lowlink[node])
                if lowlink[node] == index[node]:
                    comp = []
                    while True:
                        w = stack.pop()
                        on_stack[w] = False
                        comp.append(w)
                        if w == node:
                            break
                    sccs.append(comp)

        for v in sorted(graph):
            if v not in index:
                strongconnect(v)

        out = []
        for comp in sccs:
            if len(comp) < 2:
                continue
            comp_set = set(comp)
            sites = sorted(
                (fi.relpath, line, a, b)
                for (a, b), (fi, line) in edges.items()
                if a in comp_set and b in comp_set)
            site_desc = "; ".join(
                f"{a}->{b} at {p}:{ln}" for p, ln, a, b in sites)
            anchor = None
            for (a, b), (fi, line) in sorted(
                    edges.items(), key=lambda kv: (kv[1][0].relpath,
                                                   kv[1][1])):
                if a in comp_set and b in comp_set:
                    anchor = (fi, line)
                    break
            fi, line = anchor
            out.append((fi, line,
                        f"lock-order cycle among "
                        f"{{{', '.join(sorted(comp_set))}}}: {site_desc}"))
        return out
