"""Rule registry: one place the CLI, tests, and tier-1 gate agree on."""

from __future__ import annotations

from typing import Dict, List, Optional

from tools.raylint.core import Rule
from tools.raylint.rules.r1_async_blocking import AsyncBlockingRule
from tools.raylint.rules.r2_lock_discipline import LockDisciplineRule
from tools.raylint.rules.r3_layering import LayeringRule
from tools.raylint.rules.r4_lifecycle import ResourceLifecycleRule
from tools.raylint.rules.r5_wire_hygiene import WireHygieneRule
from tools.raylint.rules.r6_hygiene import HygieneRule
from tools.raylint.rules.r7_ambient import AmbientStateRule
from tools.raylint.rules.r8_yield_points import YieldPointHygieneRule
from tools.raylint.rules.r9_spec_coverage import SpecCoverageRule
from tools.raylint.rules.r10_length_alloc import LengthAllocationRule

_RULE_CLASSES = (
    AsyncBlockingRule,
    LockDisciplineRule,
    LayeringRule,
    ResourceLifecycleRule,
    WireHygieneRule,
    HygieneRule,
    AmbientStateRule,
    YieldPointHygieneRule,
    SpecCoverageRule,
    LengthAllocationRule,
)


def all_rules() -> List[Rule]:
    return [cls() for cls in _RULE_CLASSES]


def rules_by_id() -> Dict[str, type]:
    return {cls.id: cls for cls in _RULE_CLASSES}


def select_rules(ids: Optional[List[str]]) -> List[Rule]:
    """Instantiate the requested rule ids (case-insensitive), or all."""
    if not ids:
        return all_rules()
    table = rules_by_id()
    out = []
    for rid in ids:
        rid = rid.strip().upper()
        if rid not in table:
            raise KeyError(
                f"unknown rule {rid!r}; known: {', '.join(sorted(table))}")
        out.append(table[rid]())
    return out
