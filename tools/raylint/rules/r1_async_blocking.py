"""R1 async-blocking: no synchronous blocking work on an event loop.

Flags, inside ``async def`` bodies (nested sync ``def``/``lambda``
bodies excluded — they run elsewhere), any non-awaited call that can
park the loop thread: ``time.sleep``, sync ``ObjectRef`` resolution
(``ray_tpu.get``/``ray_tpu.wait``/``ray_tpu.kill``/``worker.wait``),
``Future.result``, ``Lock.acquire`` / ``with <lock>``, ``Condition`` /
``Event`` waits (timed or not — a timed wait still stalls every other
coroutine), file/socket I/O (``open``, ``recv``, ``sendall``,
``accept``, ``connect``, ``socket.create_connection``), ``subprocess``,
and the actor-backed ``util.queue.Queue`` methods (each is a blocking
actor round-trip; use the ``*_async`` variants or an executor).

Targets: ``serve/_private/``, ``serve/streaming.py``,
``serve/batching.py``, ``util/queue.py`` — any module that runs
coroutines on the ingress/replica loops.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, Tuple

from tools.raylint.astutil import (
    classify_blocking,
    dotted_name,
    receiver_name,
)
from tools.raylint.core import FileInfo, Rule

# `with self._lock:` inside a coroutine acquires a *threading* lock on
# the loop thread. Matched by attribute naming convention.
LOCKISH = re.compile(r"(^_?(lock|mutex|cond|condition)$)"
                     r"|(_lock$)|(_mutex$)|(_cond$)|(_condition$)")

_KIND_HINT = {
    "sleep": "use `await asyncio.sleep(...)`",
    "sync-get": "await the async variant or run it in an executor",
    "rpc": "move the RPC off the loop (executor/thread)",
    "io": "use loop-native I/O or an executor",
    "lock": "keep loop code lock-free or use asyncio primitives",
    "untimed-wait": "never park the loop on a thread primitive",
    "timed-wait": "a timed wait still stalls every coroutine",
    "queue-stat": "an actor-queue stat is an RPC round-trip",
}


def _awaited_calls(fn: ast.AST) -> set:
    out = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Await) and isinstance(node.value, ast.Call):
            out.add(id(node.value))
    return out


def _walk_async_body(fn: ast.AST):
    """Nodes of ``fn``'s body that execute on the coroutine itself
    (nested defs/lambdas/classes excluded)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


class AsyncBlockingRule(Rule):
    id = "R1"
    name = "async-blocking"
    description = ("synchronous blocking call inside an `async def` "
                   "body (event-loop stall)")

    def check_file(self, fi: FileInfo) -> Iterable[Tuple[int, str]]:
        for node in fi.nodes():
            if not isinstance(node, ast.AsyncFunctionDef):
                continue
            yield from self._check_coroutine(node)

    def _check_coroutine(self, fn: ast.AsyncFunctionDef):
        awaited = _awaited_calls(fn)
        for node in _walk_async_body(fn):
            if isinstance(node, (ast.With,)):
                for item in node.items:
                    expr = item.context_expr
                    target = expr.func if isinstance(expr, ast.Call) \
                        else expr
                    dn = dotted_name(target)
                    last = dn.rsplit(".", 1)[-1] if dn else ""
                    if last and LOCKISH.match(last):
                        yield (node.lineno,
                               f"`with {dn}` acquires a threading lock "
                               f"inside `async def {fn.name}` — "
                               f"{_KIND_HINT['lock']}")
                continue
            if not isinstance(node, ast.Call) or id(node) in awaited:
                continue
            recv = receiver_name(node.func) or ""
            if recv == "asyncio":
                continue  # asyncio.* primitives are loop-native
            hit = classify_blocking(node)
            if hit is None:
                continue
            kind, detail = hit
            yield (node.lineno,
                   f"blocking call `{detail}` ({kind}) inside "
                   f"`async def {fn.name}` — {_KIND_HINT[kind]}")
