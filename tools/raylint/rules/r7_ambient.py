"""R7 ambient-state hygiene: thread-local tags and global registries.

The order-dependent flakes PR 6 documented all reduced to two shapes
of ambient state escaping its scope, so both are now machine-checked:

- **Ambient setter without token/try-finally reset.** The thread-local
  ambient setters (``set_ambient_job_id``, ``set_ambient_trace_parent``)
  return the previous value precisely so callers can restore it; a
  call that discards that token, or captures it but never restores in
  a ``finally``, leaves the tag on the calling thread — and executor
  threads are pooled, so the residue silently tags unrelated work.
  The sanctioned shape is::

      prev = set_ambient_job_id(job)
      try:
          ...
      finally:
          set_ambient_job_id(prev)

- **Grow-only module-level mutable registry.** A module-level dict/
  list/set that functions in the module only ever ADD to, with no
  removal path and no reset-capable API (a ``reset``/``restore``/
  ``clear``/``remove``-style function referencing it), is state no
  test can isolate and no long-lived process can bound. Either give it
  a reset/removal API (what ``perf_stats.reset`` and
  ``health.remove_loop_lag_component`` do) or justify-suppress why
  append-only is the contract (e.g. the wire message catalog).

The runtime counterpart is raysan's ambient sanitizer
(``tools/raysan/ambient.py``): R7 proves the reset path exists,
the sanitizer proves it ran.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List, Optional, Set, Tuple

from tools.raylint.astutil import dotted_name
from tools.raylint.core import FileInfo, Rule

_AMBIENT_SETTERS = ("set_ambient_job_id", "set_ambient_trace_parent")

# Mutations that only ever ADD entries...
_GROW_METHODS = {"append", "add", "update", "setdefault", "extend",
                 "insert", "appendleft"}
# ...vs. ones that remove/reset (their presence anywhere in the module
# means a bounded-lifetime path exists).
_SHRINK_METHODS = {"pop", "popitem", "clear", "remove", "discard",
                   "popleft"}
_RESET_FN_RE = re.compile(
    r"reset|restore|clear|remove|retire|purge|evict|delete|uninstall"
    r"|invalidate|close|shutdown|stop|teardown")
_REGISTRY_FACTORIES = {"dict", "list", "set", "OrderedDict",
                       "defaultdict", "deque", "WeakValueDictionary"}


def _setter_name(call: ast.Call) -> Optional[str]:
    dn = dotted_name(call.func)
    if dn is None:
        return None
    last = dn.rsplit(".", 1)[-1]
    return last if last in _AMBIENT_SETTERS else None


class AmbientStateRule(Rule):
    id = "R7"
    name = "ambient-hygiene"
    description = ("ambient thread-local setters without token/"
                   "try-finally reset; grow-only module-level mutable "
                   "registries without a reset-capable API")

    def check_file(self, fi: FileInfo) -> Iterable[Tuple[int, str]]:
        out: List[Tuple[int, str]] = []
        for fn in self._functions(fi):
            out.extend(self._check_ambient_fn(fn))
        out.extend(self._check_registries(fi))
        return out

    # -- ambient setters ---------------------------------------------------

    def _functions(self, fi: FileInfo):
        return [n for n in fi.nodes()
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]

    def _check_ambient_fn(self, fn) -> List[Tuple[int, str]]:
        sets: List[Tuple[ast.Call, str, bool]] = []  # (call, setter, captured)
        restores: Set[str] = set()

        def scan(node, in_finally: bool, captured: frozenset):
            """Recursive descent over fn's own statements (nested defs
            are their own functions) tracking finally containment —
            ``ast.walk`` would flatten a nested try/finally's restore
            calls into the surrounding context."""
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return
            if isinstance(node, (ast.Assign, ast.AnnAssign,
                                 ast.NamedExpr)) \
                    and node.value is not None:
                captured = captured | {
                    id(sub) for sub in ast.walk(node.value)
                    if isinstance(sub, ast.Call)
                    and _setter_name(sub) is not None}
            if isinstance(node, ast.Call):
                setter = _setter_name(node)
                if setter is not None:
                    if in_finally:
                        restores.add(setter)
                    else:
                        sets.append((node, setter, id(node) in captured))
            if isinstance(node, ast.Try):
                for child in node.body + node.handlers + node.orelse:
                    scan(child, in_finally, captured)
                for child in node.finalbody:
                    scan(child, True, captured)
                return
            for child in ast.iter_child_nodes(node):
                scan(child, in_finally, captured)

        for child in fn.body:
            scan(child, False, frozenset())

        out: List[Tuple[int, str]] = []
        for call, setter, captured in sets:
            if not captured:
                out.append((
                    call.lineno,
                    f"`{setter}(...)` discards the restore token — "
                    f"capture it and restore in a finally: "
                    f"`prev = {setter}(x) ... finally: {setter}(prev)`"))
            elif setter not in restores:
                out.append((
                    call.lineno,
                    f"`{setter}(...)` token captured but never restored "
                    f"in a `finally` in this function — the ambient tag "
                    f"outlives its scope on a pooled thread"))
        return out

    # -- module-level registries -------------------------------------------

    def _check_registries(self, fi: FileInfo) -> List[Tuple[int, str]]:
        candidates = {}  # name -> (lineno, is_mapping)
        for node in ast.iter_child_nodes(fi.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                target, value = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name) \
                    and node.value is not None:
                target, value = node.target, node.value
            else:
                continue
            is_registry = isinstance(value, (ast.Dict, ast.List, ast.Set))
            is_mapping = isinstance(value, ast.Dict)
            if isinstance(value, ast.Call):
                dn = dotted_name(value.func)
                last = dn.rsplit(".", 1)[-1] if dn else ""
                if last in _REGISTRY_FACTORIES and not value.args \
                        and not value.keywords:
                    is_registry = True
                    is_mapping = last in ("dict", "OrderedDict",
                                          "defaultdict",
                                          "WeakValueDictionary")
            if is_registry:
                candidates[target.id] = (node.lineno, is_mapping)
        if not candidates:
            return []

        grows: Set[str] = set()
        shrinks: Set[str] = set()

        def ref_name(expr) -> Optional[str]:
            return expr.id if isinstance(expr, ast.Name) else None

        # Only RUNTIME mutations count — import-time construction of a
        # memo table (e.g. a CRC table filled by a module-level loop)
        # is a constant, not unbounded ambient state — so the scan
        # covers function bodies only.
        fn_nodes = [sub for fn in self._functions(fi)
                    for sub in ast.walk(fn)]
        for node in fn_nodes:
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    if isinstance(t, ast.Subscript):
                        name = ref_name(t.value)
                        # Subscript store only GROWS a mapping; on a
                        # list/box it replaces an existing slot.
                        if name in candidates and candidates[name][1]:
                            grows.add(name)
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    if isinstance(t, ast.Subscript):
                        name = ref_name(t.value)
                        if name in candidates:
                            shrinks.add(name)
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute):
                name = ref_name(node.func.value)
                if name in candidates:
                    if node.func.attr in _GROW_METHODS:
                        grows.add(name)
                    elif node.func.attr in _SHRINK_METHODS:
                        shrinks.add(name)

        # A reset-named function that references the registry is a
        # reset-capable API even when it mutates entries in place
        # (perf_stats.reset zeroes stat objects without touching the
        # dict). Function-level reassignment (`name = {}` under a
        # `global` decl) counts the same way.
        for fn in self._functions(fi):
            body_names = {n.id for n in ast.walk(fn)
                          if isinstance(n, ast.Name)}
            if not body_names & set(candidates):
                continue
            if _RESET_FN_RE.search(fn.name):
                shrinks.update(body_names & set(candidates))
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Assign):
                    for t in sub.targets:
                        if isinstance(t, ast.Name) \
                                and t.id in candidates:
                            shrinks.add(t.id)

        out = []
        for name, (lineno, _) in sorted(candidates.items(),
                                        key=lambda kv: kv[1][0]):
            if name in grows and name not in shrinks:
                out.append((
                    lineno,
                    f"module-level registry `{name}` only ever grows — "
                    f"add a reset-capable API (reset/clear/removal "
                    f"path) so tests can isolate it and long-lived "
                    f"processes can bound it, or justify-suppress"))
        return out
