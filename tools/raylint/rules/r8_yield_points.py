"""R8 yield-point hygiene: product crossings use registered literals.

``sanitize_hooks.sched_point`` / ``crash_point`` call sites in product
code are the contract surface three tools share: raysan schedules gate
them, raymc's explorer seizes them, and the catalog in
``sanitize_hooks.SCHED_POINTS``/``CRASH_POINTS`` is how those tools
know what exists. A typo'd or unregistered name silently never gates —
the schedule that should have caught a regression just passes through —
and a dynamically-built name can't be gated deterministically at all.

So, for every call site inside ``ray_tpu/``:

- the point name must be a LITERAL string (no f-strings, no variables);
- the literal must be registered in the catalog, in the set matching
  the call (``sched_point`` ↔ ``SCHED_POINTS``, ``crash_point`` ↔
  ``CRASH_POINTS``).

Tooling and tests are exempt (they're the scheduler, not the
scheduled): the rule only fires on files under the ``ray_tpu``
package. The defining module itself is exempt too.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional, Tuple

from tools.raylint.core import FileInfo, Rule

_HOOK_FNS = ("sched_point", "crash_point")


def _default_catalogs():
    from ray_tpu._private.sanitize_hooks import CRASH_POINTS, SCHED_POINTS

    return {"sched_point": frozenset(SCHED_POINTS),
            "crash_point": frozenset(CRASH_POINTS)}


class YieldPointHygieneRule(Rule):
    id = "R8"
    name = "yield-point-hygiene"
    description = ("sanitize_hooks crossings must use literal, "
                   "registered point names")

    def __init__(self, catalogs: Optional[dict] = None):
        # Injectable for fixture tests; defaults to the live registry
        # so the rule can never drift from the code.
        self._catalogs = catalogs

    def _catalog(self, fn: str) -> frozenset:
        if self._catalogs is None:
            self._catalogs = _default_catalogs()
        return self._catalogs.get(fn, frozenset())

    def check_file(self, fi: FileInfo) -> Iterable[Tuple[int, str]]:
        if fi.package is None:
            return  # tooling/tests: the scheduler side of the seam
        if fi.relpath.endswith("_private/sanitize_hooks.py"):
            return  # the registry itself
        module_aliases, fn_aliases = self._import_aliases(fi)
        for node in fi.nodes():
            if not isinstance(node, ast.Call):
                continue
            fn_name = self._hook_call_name(node.func, module_aliases,
                                           fn_aliases)
            if fn_name is None:
                continue
            if not node.args:
                yield (node.lineno,
                       f"`{fn_name}()` called without a point name")
                continue
            arg = node.args[0]
            if not (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)):
                yield (node.lineno,
                       f"`{fn_name}` point name must be a literal "
                       f"string (a computed name cannot be gated "
                       f"deterministically or registered)")
                continue
            if arg.value not in self._catalog(fn_name):
                other = [f for f in _HOOK_FNS if f != fn_name][0]
                hint = ""
                if arg.value in self._catalog(other):
                    hint = (f" (it is registered for `{other}` — "
                            f"wrong hook?)")
                yield (node.lineno,
                       f"`{fn_name}({arg.value!r})` is not in the "
                       f"registered point catalog "
                       f"(sanitize_hooks.{'SCHED' if fn_name == 'sched_point' else 'CRASH'}"
                       f"_POINTS){hint} — a typo'd name silently "
                       f"never gates")

    @staticmethod
    def _import_aliases(fi: FileInfo):
        """Names this file binds to the sanitize_hooks module (incl.
        `as` renames) and to the hook functions themselves — an aliased
        import must not smuggle a typo'd point past the rule."""
        module_aliases = {"sanitize_hooks"}
        fn_aliases = {}
        for node in fi.nodes():
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name.endswith("sanitize_hooks"):
                        module_aliases.add(
                            alias.asname or alias.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                for alias in node.names:
                    if alias.name == "sanitize_hooks":
                        module_aliases.add(alias.asname or alias.name)
                    elif mod.endswith("sanitize_hooks") \
                            and alias.name in _HOOK_FNS:
                        fn_aliases[alias.asname or alias.name] = \
                            alias.name
        return module_aliases, fn_aliases

    @staticmethod
    def _hook_call_name(func, module_aliases,
                        fn_aliases) -> Optional[str]:
        """"<sanitize_hooks-alias>.sched_point" / bare (possibly
        renamed) imported-name call shapes; None for anything else."""
        if isinstance(func, ast.Attribute) and func.attr in _HOOK_FNS:
            root = func.value
            if isinstance(root, ast.Name) and root.id in module_aliases:
                return func.attr
            # dotted module path ray_tpu._private.sanitize_hooks.X
            if isinstance(root, ast.Attribute) \
                    and root.attr == "sanitize_hooks":
                return func.attr
            return None
        if isinstance(func, ast.Name):
            if func.id in fn_aliases:
                return fn_aliases[func.id]
            if func.id in _HOOK_FNS:
                return func.id
        return None
