"""R4 resource-lifecycle: created resources need a reachable teardown.

Checks, per creation site:

- ``threading.Thread(...)`` assigned to an attribute: the owning class
  must expose a teardown method (``close``/``shutdown``/``stop``/
  ``join``/``__exit__``/``__del__``) — a runtime full of unjoinable
  threads cannot drain on failover. Fire-and-forget threads (started
  inline, never stored) must at least be ``daemon=True`` so they can't
  wedge interpreter exit.
- ``socket.socket(...)`` / ``socket.create_connection(...)``: the
  socket must be closed in the creating function (``with`` /
  ``.close()`` on the variable), stored on ``self`` in a class with a
  teardown method, or returned (ownership transfer).
- ``sqlite3.connect(...)``: same containment contract as sockets.
- **group-commit writers** (the ``gcs_storage.py`` pattern): a class
  that defines both ``flush`` and a teardown method must make its
  accepted writes durable on the way out — the teardown must reference
  ``flush``/``commit``; otherwise buffered writes die with the process
  at exactly the shutdown/failover boundary flush exists for.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Tuple

from tools.raylint.astutil import dotted_name
from tools.raylint.core import FileInfo, Rule

TEARDOWN_NAMES = {"close", "shutdown", "stop", "join", "wait",
                  "__exit__", "__del__", "release", "disconnect"}


def _is_teardown_name(name: str) -> bool:
    return name in TEARDOWN_NAMES or any(
        part in name for part in ("shutdown", "teardown", "close"))


def _creation_kind(call: ast.Call) -> Optional[str]:
    dn = dotted_name(call.func)
    if dn in ("threading.Thread", "Thread"):
        return "thread"
    if dn in ("socket.socket", "socket.create_connection"):
        return "socket"
    if dn in ("sqlite3.connect",):
        return "sqlite"
    return None


def _kwarg(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _is_daemon(call: ast.Call) -> bool:
    v = _kwarg(call, "daemon")
    return isinstance(v, ast.Constant) and v.value is True


def _assigned_target(node: ast.AST) -> Tuple[Optional[str], Optional[str]]:
    """(self_attr, local_name) the statement assigns to, if any."""
    if isinstance(node, ast.Assign) and len(node.targets) == 1:
        t = node.targets[0]
        if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
                and t.value.id == "self":
            return t.attr, None
        if isinstance(t, ast.Name):
            return None, t.id
    return None, None


def _fn_closes_name(fn: ast.AST, name: str) -> bool:
    """Does ``fn`` call ``name.close()`` anywhere, use ``with name``-
    style management, or return ``name`` (ownership transfer)?"""
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("close", "shutdown", "detach") \
                and dotted_name(node.func.value) == name:
            return True
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                dn = dotted_name(item.context_expr)
                if dn == name:
                    return True
                if isinstance(item.context_expr, ast.Call):
                    for arg in item.context_expr.args:
                        if dotted_name(arg) == name:
                            return True  # contextlib.closing(name)
        if isinstance(node, ast.Return) and node.value is not None:
            if dotted_name(node.value) == name:
                return True
            for sub in ast.walk(node.value):
                if dotted_name(sub) == name:
                    return True
        if isinstance(node, ast.Call):
            # handed to another function/constructor: ownership transfer
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if dotted_name(arg) == name:
                    return True
    return False


class ResourceLifecycleRule(Rule):
    id = "R4"
    name = "resource-lifecycle"
    description = ("threads/sockets/sqlite connections need a reachable "
                   "shutdown/close path; group-commit writers must "
                   "flush at teardown")

    def check_file(self, fi: FileInfo) -> Iterable[Tuple[int, str]]:
        yield from self._check_classes(fi)
        yield from self._check_functions(fi)

    # -- class-scoped resources -------------------------------------------

    def _check_classes(self, fi: FileInfo):
        for node in fi.nodes():
            if not isinstance(node, ast.ClassDef):
                continue
            methods = {
                c.name for c in node.body
                if isinstance(c, (ast.FunctionDef, ast.AsyncFunctionDef))}
            has_teardown = any(_is_teardown_name(m) for m in methods)
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Assign):
                    continue
                attr, _ = _assigned_target(sub)
                if attr is None or not isinstance(sub.value, ast.Call):
                    continue
                kind = _creation_kind(sub.value)
                if kind is None:
                    continue
                if not has_teardown:
                    yield (sub.lineno,
                           f"class `{node.name}` stores a {kind} in "
                           f"`self.{attr}` but defines no teardown "
                           f"method ({'/'.join(sorted(TEARDOWN_NAMES))})")
            yield from self._check_group_commit(fi, node, methods)

    def _check_group_commit(self, fi: FileInfo, node: ast.ClassDef,
                            methods: set):
        if "flush" not in methods:
            return
        teardowns = [
            c for c in node.body
            if isinstance(c, (ast.FunctionDef, ast.AsyncFunctionDef))
            and c.name in ("close", "shutdown", "stop", "__exit__")]
        for td in teardowns:
            refs = {
                n.attr for n in ast.walk(td)
                if isinstance(n, ast.Attribute)}
            names = {
                n.id for n in ast.walk(td) if isinstance(n, ast.Name)}
            if not ({"flush", "commit"} & (refs | names)):
                yield (td.lineno,
                       f"group-commit writer `{node.name}.{td.name}` "
                       f"tears down without flush()/commit() — buffered "
                       f"writes are lost at the shutdown/failover "
                       f"boundary")

    # -- function-scoped resources ----------------------------------------

    def _check_functions(self, fi: FileInfo):
        for fn in fi.nodes():
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for node in ast.walk(fn):
                if not isinstance(node, (ast.Assign, ast.Expr)):
                    continue
                value = node.value
                # Inline fire-and-forget: threading.Thread(...).start()
                if isinstance(value, ast.Call) \
                        and isinstance(value.func, ast.Attribute) \
                        and value.func.attr == "start" \
                        and isinstance(value.func.value, ast.Call) \
                        and _creation_kind(value.func.value) == "thread":
                    if not _is_daemon(value.func.value):
                        yield (value.lineno,
                               "non-daemon fire-and-forget Thread: pass "
                               "daemon=True or store and join it")
                    continue
                if not isinstance(value, ast.Call):
                    continue
                kind = _creation_kind(value)
                if kind is None:
                    continue
                attr, local = _assigned_target(node)
                if kind == "thread":
                    # Stored on self: class-scoped pass. Local-var
                    # thread: must be daemon or joined somewhere here.
                    if attr is None and not _is_daemon(value) \
                            and local is not None \
                            and not self._fn_joins(fn, local):
                        yield (value.lineno,
                               f"non-daemon Thread `{local}` is never "
                               f"joined in `{fn.name}`")
                    continue
                if attr is not None:
                    continue  # handled by the class-scoped pass
                if local is None:
                    if isinstance(node, ast.Expr):
                        yield (value.lineno,
                               f"{kind} created and dropped without a "
                               f"close path")
                    continue
                if not _fn_closes_name(fn, local):
                    yield (value.lineno,
                           f"{kind} `{local}` is never closed/returned "
                           f"in `{fn.name}` — close it in a "
                           f"finally/with or transfer ownership")

    @staticmethod
    def _fn_joins(fn: ast.AST, name: str) -> bool:
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "join" \
                    and dotted_name(node.func.value) == name:
                return True
        return False
