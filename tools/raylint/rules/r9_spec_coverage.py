"""R9 spec-coverage: decision-core taps, registry, and catalog agree.

rayspec's correctness story has three legs that can silently drift
apart: the ``sanitize_hooks.SPEC_POINTS`` registry (what exists), the
``spec_op`` call sites in the decision cores (what is actually
recorded), and ``tools.rayspec.specs.SPEC_CATALOG`` (what has an
executable sequential specification). A tap with a typo'd name records
nothing; a tapped core with no spec records history nobody checks; a
catalog entry whose core lost its taps "passes" every check vacuously.

So, for every ``spec_op`` call site inside ``ray_tpu/``:

- the point name must be a LITERAL string registered in
  ``SPEC_POINTS`` (same contract as R8 for sched/crash points);
- the phase must be the literal ``"call"`` or ``"ret"`` (a computed
  phase breaks the recorder's invocation/response pairing silently);
- the point's ``spec.<core>.`` prefix must belong to a catalog entry
  (recorded history nobody can check is a lie of omission).

And cross-file, when the registry module itself is in the linted set
(the tier-1 sweep over all of ``ray_tpu/``):

- every catalog entry's prefix must be crossed by at least one product
  call site (a spec with no taps proves nothing);
- every registered SPEC_POINTS name must be crossed somewhere in
  product code (a dead registry entry is a point the tools believe in
  that can never fire).

The other half of the contract — every catalog entry has a
conformance test — is enforced by construction in
``tests/core/test_rayspec.py``: its per-core suites parametrize over
``SPEC_CATALOG`` itself.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional, Tuple

from tools.raylint.core import FileInfo, Project, Rule

_PHASES = ("call", "ret")


def _default_registry():
    from ray_tpu._private.sanitize_hooks import SPEC_POINTS

    return frozenset(SPEC_POINTS)


def _default_prefixes():
    from tools.rayspec.specs import SPEC_CATALOG

    return {entry.prefix: name
            for name, entry in SPEC_CATALOG.items()}


class SpecCoverageRule(Rule):
    id = "R9"
    name = "spec-coverage"
    description = ("spec_op taps literal+registered; taps, SPEC_POINTS "
                   "and the rayspec catalog cover each other")

    def __init__(self, registry: Optional[frozenset] = None,
                 prefixes: Optional[dict] = None):
        # Injectable for fixture tests; defaults to the live registry
        # and catalog so the rule can never drift from the code.
        self._registry = registry
        self._prefixes = prefixes

    def _points(self) -> frozenset:
        if self._registry is None:
            self._registry = _default_registry()
        return self._registry

    def _catalog_prefixes(self) -> dict:
        if self._prefixes is None:
            self._prefixes = _default_prefixes()
        return self._prefixes

    def check_file(self, fi: FileInfo) -> Iterable[Tuple[int, str]]:
        if fi.package is None:
            return  # tooling/tests are the recorder, not the recorded
        if fi.relpath.endswith("_private/sanitize_hooks.py"):
            return  # the registry itself
        for node in fi.nodes():
            if not isinstance(node, ast.Call):
                continue
            if not self._is_spec_op(node.func):
                continue
            if len(node.args) < 2:
                yield (node.lineno,
                       "`spec_op()` needs (point, phase, obj[, "
                       "payload])")
                continue
            point_arg, phase_arg = node.args[0], node.args[1]
            if not (isinstance(point_arg, ast.Constant)
                    and isinstance(point_arg.value, str)):
                yield (node.lineno,
                       "`spec_op` point name must be a literal string "
                       "(a computed name cannot be registered or "
                       "gated)")
                continue
            point = point_arg.value
            if point not in self._points():
                yield (node.lineno,
                       f"`spec_op({point!r})` is not in "
                       f"sanitize_hooks.SPEC_POINTS — an unregistered "
                       f"tap silently records nothing the tools know "
                       f"about")
            else:
                prefix = ".".join(point.split(".")[:2]) + "."
                if prefix not in self._catalog_prefixes():
                    yield (node.lineno,
                           f"`spec_op({point!r})`: no rayspec "
                           f"SPEC_CATALOG entry owns prefix "
                           f"{prefix!r} — recorded history nobody "
                           f"checks")
            if not (isinstance(phase_arg, ast.Constant)
                    and phase_arg.value in _PHASES):
                yield (node.lineno,
                       "`spec_op` phase must be the literal \"call\" "
                       "or \"ret\" (a computed phase breaks "
                       "invocation/response pairing silently)")

    def finalize(self, project: Project) \
            -> Iterable[Tuple[FileInfo, int, str]]:
        registry_fi = None
        crossed = set()
        for fi in project.files:
            if fi.package is None:
                continue
            if fi.relpath.endswith("_private/sanitize_hooks.py"):
                registry_fi = fi
                continue
            for node in fi.nodes():
                if isinstance(node, ast.Call) \
                        and self._is_spec_op(node.func) and node.args:
                    arg = node.args[0]
                    if isinstance(arg, ast.Constant) \
                            and isinstance(arg.value, str):
                        crossed.add(arg.value)
        if registry_fi is None:
            # Partial lint (fixtures, single files): the cross-file
            # coverage half only makes sense over the whole package.
            return
        for prefix, name in sorted(self._catalog_prefixes().items()):
            if not any(p.startswith(prefix) for p in crossed):
                yield (registry_fi, 1,
                       f"rayspec catalog entry {name!r} (prefix "
                       f"{prefix!r}) has no product spec_op tap — its "
                       f"spec can never check a recorded history")
        for point in sorted(self._points() - crossed):
            yield (registry_fi, 1,
                   f"SPEC_POINTS entry {point!r} is never crossed by "
                   f"product code — dead registry entry")

    @staticmethod
    def _is_spec_op(func) -> bool:
        if isinstance(func, ast.Attribute) and func.attr == "spec_op":
            root = func.value
            if isinstance(root, ast.Name) \
                    and root.id == "sanitize_hooks":
                return True
            if isinstance(root, ast.Attribute) \
                    and root.attr == "sanitize_hooks":
                return True
            return False
        return isinstance(func, ast.Name) and func.id == "spec_op"
