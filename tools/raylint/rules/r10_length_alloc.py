"""R10 length-before-allocation: a length decoded off the wire must be
bounds-checked before it sizes an allocation or a blocking read.

The shape behind every allocation-bomb: a u32/u64 comes out of
``struct.unpack`` (or ``int.from_bytes``), and the very next thing the
code does is ``_recv_exact(sock, n)`` / ``f.read(n)`` /
``bytearray(n)`` — handing a remote peer the right to demand a 4 GiB
allocation with a 4-byte header. The rpc framing layer had exactly this
hole (`recv_msg` pre-allocated whatever the prefix claimed) until the
raywire rung added ``rpc_max_frame_bytes``; this rule keeps the next
length-prefixed reader honest.

Taint model, deliberately function-local and syntactic:

- **source** — a variable bound (directly or by tuple-unpacking) from
  ``<anything>.unpack(...)`` / ``.unpack_from(...)`` or
  ``int.from_bytes(...)``;
- **sink** — that variable sizing an allocation before any check:
  an ``*exact``-style read call (``_recv_exact``/``recv_exact``/
  ``read_exact``), ``.recv(n)``/``.read(n)``/``.recvfrom(n)``,
  ``bytes(n)``/``bytearray(n)``, or a multiplication (``b"x" * n``);
- **guard** — ANY comparison mentioning the variable between the
  source and the sink (``if n > cap``, ``if n <= limit``, ``min(n,
  cap)`` does not count — an explicit comparison is the audit point).

A genuinely-bounded length (trusted file, checked upstream) is a
``# raylint: disable=R10 -- why`` with the bound named in the why.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Tuple

from tools.raylint.core import FileInfo, Rule

_EXACT_READ_FNS = {"_recv_exact", "recv_exact", "read_exact",
                   "readexactly"}
_SIZED_METHODS = {"recv", "read", "recvfrom", "recv_into"}
_SIZED_BUILTINS = {"bytes", "bytearray"}


def _is_length_source(node: ast.AST) -> bool:
    """``X.unpack(...)`` / ``X.unpack_from(...)`` /
    ``int.from_bytes(...)``, bare or behind an index
    (``struct.unpack("!I", hdr)[0]`` is the canonical shape)."""
    if isinstance(node, ast.Subscript):
        node = node.value
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    if isinstance(fn, ast.Attribute):
        if fn.attr in ("unpack", "unpack_from"):
            return True
        if fn.attr == "from_bytes" and isinstance(fn.value, ast.Name) \
                and fn.value.id == "int":
            return True
    return False


def _bound_names(target: ast.AST) -> List[str]:
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        out = []
        for el in target.elts:
            out.extend(_bound_names(el))
        return out
    return []


def _names_in(node: ast.AST) -> set:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


class LengthAllocationRule(Rule):
    id = "R10"
    name = "length-before-allocation"
    description = ("a wire-decoded length must be compared against a "
                   "bound before it sizes a read or allocation")

    def check_file(self, fi: FileInfo) -> Iterable[Tuple[int, str]]:
        if fi.package is None:      # product code only
            return
        for node in fi.nodes():
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_function(node)

    def _check_function(self, fn: ast.AST):
        # source var -> line it was decoded on
        tainted: Dict[str, int] = {}
        # var -> lines of comparisons mentioning it
        guards: Dict[str, List[int]] = {}
        sinks: List[Tuple[int, str, str]] = []   # (line, var, what)

        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) \
                    and _is_length_source(node.value):
                for tgt in node.targets:
                    for name in _bound_names(tgt):
                        tainted.setdefault(name, node.lineno)
            elif isinstance(node, ast.Compare):
                for name in _names_in(node):
                    guards.setdefault(name, []).append(node.lineno)
            elif isinstance(node, ast.BinOp) \
                    and isinstance(node.op, ast.Mult):
                for side in (node.left, node.right):
                    if isinstance(side, ast.Name):
                        sinks.append((node.lineno, side.id,
                                      "a multiplied allocation size"))
            elif isinstance(node, ast.Call):
                callee = node.func
                cname = callee.id if isinstance(callee, ast.Name) \
                    else (callee.attr
                          if isinstance(callee, ast.Attribute)
                          else "")
                sized = (cname in _EXACT_READ_FNS
                         or cname in _SIZED_BUILTINS
                         or (isinstance(callee, ast.Attribute)
                             and cname in _SIZED_METHODS))
                if not sized:
                    continue
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        sinks.append((node.lineno, arg.id,
                                      f"`{cname}()`"))

        for line, var, what in sorted(sinks):
            src_line = tainted.get(var)
            if src_line is None or line < src_line:
                continue
            if any(src_line <= g <= line
                   for g in guards.get(var, ())):
                continue
            yield (line,
                   f"`{var}` was decoded off the wire at line "
                   f"{src_line} and sizes {what} with no bounds "
                   f"check in between — a peer controls this "
                   f"allocation; compare it against a cap first")
