"""R3 layering: dependency direction and private-surface hygiene.

Two checks, both purely from imports/attribute syntax:

1. **No upward imports from the core**: modules under
   ``ray_tpu/_private/`` (and ``ray_tpu/util/``) are the substrate the
   libraries build on; importing ``serve``/``tune``/``data``/``rl``/
   ``train`` from there inverts the layering and creates import cycles
   the next refactor trips over.

2. **No cross-package private reach-ins**: importing another package's
   ``_private``/``_internal`` modules, or reading a ``_underscore``
   attribute off a module imported from another package, couples a
   consumer to internals that carry no compatibility promise (the
   PR 3 ``TaskEventBuffer.snapshot()`` cleanup, generalized). A
   package's own code may of course use its own internals.
"""

from __future__ import annotations

import ast
from typing import Iterable, Tuple

from tools.raylint.core import FileInfo, Rule

LIBRARY_PACKAGES = ("serve", "tune", "data", "rl", "train")
CORE_PACKAGES = ("_private", "util")


def _imported_ray_module(node) -> Iterable[Tuple[str, str, int]]:
    """(alias_name, imported_module_path, line) for ray_tpu imports."""
    if isinstance(node, ast.Import):
        for alias in node.names:
            if alias.name.startswith("ray_tpu"):
                bound = alias.asname or alias.name.split(".")[0]
                yield bound, alias.name, node.lineno
    elif isinstance(node, ast.ImportFrom) and node.module \
            and node.module.startswith("ray_tpu") and node.level == 0:
        for alias in node.names:
            full = f"{node.module}.{alias.name}"
            yield alias.asname or alias.name, full, node.lineno


class LayeringRule(Rule):
    id = "R3"
    name = "layering"
    description = ("core packages must not import libraries; no "
                   "cross-package private imports or underscore "
                   "attribute reads")

    def check_file(self, fi: FileInfo) -> Iterable[Tuple[int, str]]:
        my_pkg = fi.package
        if my_pkg is None:
            return
        module_aliases = {}
        for node in fi.nodes():
            if not isinstance(node, (ast.Import, ast.ImportFrom)):
                continue
            for bound, target, line in _imported_ray_module(node):
                parts = target.split(".")
                target_pkg = parts[1] if len(parts) > 1 else ""
                # 1. core -> library imports
                if my_pkg in CORE_PACKAGES \
                        and target_pkg in LIBRARY_PACKAGES:
                    yield (line,
                           f"core module `{fi.module}` imports library "
                           f"package `ray_tpu.{target_pkg}` — invert "
                           f"the dependency (register a hook/provider "
                           f"from the library side)")
                # 2. cross-package private imports
                private_hops = [
                    p for p in parts[2:]
                    if p.startswith("_") and not p.startswith("__")]
                if private_hops and target_pkg != my_pkg:
                    yield (line,
                           f"`{fi.module}` (package "
                           f"`{my_pkg or 'ray_tpu'}`) imports "
                           f"`{target}` through another package's "
                           f"private namespace "
                           f"(`{'.'.join(private_hops)}`)")
                if target_pkg != my_pkg:
                    module_aliases[bound] = target_pkg

        # 3. underscore attribute reads on cross-package module aliases
        for node in fi.nodes():
            if not isinstance(node, ast.Attribute):
                continue
            if not (node.attr.startswith("_")
                    and not node.attr.startswith("__")):
                continue
            if isinstance(node.value, ast.Name) \
                    and node.value.id in module_aliases:
                pkg = module_aliases[node.value.id]
                yield (node.lineno,
                       f"reads private attribute "
                       f"`{node.value.id}.{node.attr}` of package "
                       f"`ray_tpu.{pkg}` from package "
                       f"`{my_pkg or 'ray_tpu'}` — use/introduce a "
                       f"public accessor")
