"""R6 hygiene: unused module-scope imports.

The mechanical-debt rule: an import bound at module scope that no code
in the file references is dead weight (and often a stale layering edge
R3 can no longer see). ``__init__.py`` files are skipped — their
imports ARE the public surface — and so are lines carrying a ``noqa``
marker (the established re-export convention in this repo).

Name-usage detection is conservative: a name counts as used if it
appears as any ``Name`` load, as the root of an attribute chain, in
``__all__``, or anywhere in a docstring-free string annotation.
"""

from __future__ import annotations

import ast
from typing import Iterable, Tuple

from tools.raylint.core import FileInfo, Rule


def _used_names(nodes) -> set:
    used = set()
    for node in nodes:
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            root = node
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name):
                used.add(root.id)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            # quoted annotations / __all__ entries
            token = node.value.strip()
            if token.isidentifier():
                used.add(token)
            elif "." in token and token.split(".")[0].isidentifier():
                used.add(token.split(".")[0])
    return used


class HygieneRule(Rule):
    id = "R6"
    name = "unused-import"
    description = "module-scope import never referenced in the file"

    def check_file(self, fi: FileInfo) -> Iterable[Tuple[int, str]]:
        if fi.relpath.endswith("__init__.py"):
            return
        used = _used_names(fi.nodes())
        for node in fi.tree.body:
            if isinstance(node, ast.Try):
                stmts = node.body + [
                    s for h in node.handlers for s in h.body]
            elif isinstance(node, ast.If):
                stmts = node.body + node.orelse
            else:
                stmts = [node]
            for stmt in stmts:
                if not isinstance(stmt, (ast.Import, ast.ImportFrom)):
                    continue
                if stmt.lineno in fi.noqa_lines:
                    continue
                if isinstance(stmt, ast.ImportFrom) \
                        and stmt.module == "__future__":
                    continue
                for alias in stmt.names:
                    if alias.name == "*":
                        continue
                    bound = alias.asname or alias.name.split(".")[0]
                    if bound not in used:
                        yield (stmt.lineno,
                               f"`{bound}` (from `import "
                               f"{alias.name}`) is never used")
