"""R5 wire-hygiene: every control-plane frame is declared, registered,
and round-trippable.

The typed wire layer (``_private/wire.py``) is the one place the
cluster's processes agree on byte formats; a frame that drifts out of
the contract fails at the worst possible time (cross-version decode on
a live cluster). Checks:

- every class with annotated fields defined in a ``wire`` module must
  be registered for dispatch with the ``@message("Name", version=N)``
  decorator (a bare dataclass silently falls back to opaque pickle);
- wire names must be unique within the module;
- ``version`` must be a literal int >= 1 (the breaking-change gate has
  to be diffable);
- every declared field's annotation must be a wire-supported type
  (``int``/``float``/``str``/``bytes``/``bool``/``dict``/``list``/
  ``tuple``/``Any``) — anything richer must travel as an explicit
  ``Opaque`` field typed ``Any``;
- codebase-wide: a class defining ``to_dict`` must define ``from_dict``
  and vice versa (one-way serialization can be shipped but never
  received — the ``TaskEvent`` shipping contract, generalized), and
  ``from_dict`` must be a classmethod/staticmethod.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional, Tuple

from tools.raylint.core import FileInfo, Rule

SUPPORTED_FIELD_TYPES = {
    "int", "float", "str", "bytes", "bool", "dict", "list", "tuple",
    "Any", "typing.Any",
}


def _message_decorator(dec: ast.AST) -> Optional[Tuple[Optional[str],
                                                       Optional[ast.AST]]]:
    """(wire_name, version_node) when ``dec`` is ``message(...)`` or
    ``wire.message(...)``."""
    if not isinstance(dec, ast.Call):
        return None
    fn = dec.func
    name = fn.id if isinstance(fn, ast.Name) else (
        fn.attr if isinstance(fn, ast.Attribute) else None)
    if name != "message":
        return None
    wire_name = None
    if dec.args and isinstance(dec.args[0], ast.Constant) \
            and isinstance(dec.args[0].value, str):
        wire_name = dec.args[0].value
    version = None
    for kw in dec.keywords:
        if kw.arg == "version":
            version = kw.value
    if version is None and len(dec.args) > 1:
        version = dec.args[1]
    return wire_name, version


def _annotation_name(node: ast.AST) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _annotation_name(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Subscript):
        return _annotation_name(node.value)
    return ast.dump(node)


class WireHygieneRule(Rule):
    id = "R5"
    name = "wire-hygiene"
    description = ("wire frames must be @message-registered with "
                   "literal versions and supported field types; "
                   "to_dict/from_dict must come in matched pairs")

    def check_file(self, fi: FileInfo) -> Iterable[Tuple[int, str]]:
        if fi.module.rsplit(".", 1)[-1] == "wire":
            yield from self._check_wire_module(fi)
        yield from self._check_dict_pairs(fi)

    def _check_wire_module(self, fi: FileInfo):
        seen_names = {}
        for node in fi.tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            fields = [
                c for c in node.body if isinstance(c, ast.AnnAssign)]
            registrations = [
                m for m in (
                    _message_decorator(d) for d in node.decorator_list)
                if m is not None]
            if not registrations:
                if fields:
                    yield (node.lineno,
                           f"frame class `{node.name}` declares fields "
                           f"but is not registered with @message(...) "
                           f"— it would ship as opaque pickle, not a "
                           f"typed frame")
                continue
            wire_name, version = registrations[0]
            if wire_name is None:
                yield (node.lineno,
                       f"`{node.name}`: @message name must be a string "
                       f"literal")
            elif wire_name in seen_names:
                yield (node.lineno,
                       f"duplicate wire name {wire_name!r} (also "
                       f"registered at line {seen_names[wire_name]}) — "
                       f"the registry keeps only one")
            else:
                seen_names[wire_name] = node.lineno
            if version is not None and not (
                    isinstance(version, ast.Constant)
                    and isinstance(version.value, int)
                    and version.value >= 1):
                yield (node.lineno,
                       f"`{node.name}`: @message version must be a "
                       f"literal int >= 1")
            for field in fields:
                ann = _annotation_name(field.annotation)
                if ann not in SUPPORTED_FIELD_TYPES:
                    target = field.target.id \
                        if isinstance(field.target, ast.Name) else "?"
                    yield (field.lineno,
                           f"`{node.name}.{target}`: unsupported wire "
                           f"field type `{ann}` — use a wire scalar/"
                           f"container or `Any` (explicit Opaque)")

    def _check_dict_pairs(self, fi: FileInfo):
        for node in fi.nodes():
            if not isinstance(node, ast.ClassDef):
                continue
            defs = {
                c.name: c for c in node.body
                if isinstance(c, (ast.FunctionDef, ast.AsyncFunctionDef))}
            has_to, has_from = "to_dict" in defs, "from_dict" in defs
            if has_to and not has_from:
                yield (defs["to_dict"].lineno,
                       f"`{node.name}` defines to_dict without "
                       f"from_dict — one-way wire serialization")
            if has_from and not has_to:
                yield (defs["from_dict"].lineno,
                       f"`{node.name}` defines from_dict without "
                       f"to_dict — one-way wire serialization")
            if has_from:
                fd = defs["from_dict"]
                decs = {
                    d.id for d in fd.decorator_list
                    if isinstance(d, ast.Name)}
                if not ({"classmethod", "staticmethod"} & decs):
                    yield (fd.lineno,
                           f"`{node.name}.from_dict` must be a "
                           f"classmethod/staticmethod (decoders have "
                           f"no instance yet)")
