"""raylint core: the visitor framework the rules plug into.

The runtime is deeply concurrent (locks in ``_private/rpc.py``, shared
wait-groups in ``memory_store.py``, a single-threaded asyncio ingress in
``serve/_private/http_proxy.py``) and every invariant those layers
introduced used to live only in reviewers' heads. raylint turns them
into machine-checked rules:

- each ``Rule`` sees every file (``check_file``) and, after the whole
  tree has been collected, the cross-file picture (``finalize``) — the
  lock-order graph and layering checks are inter-file by nature;
- violations anchor to a (path, line) and can be suppressed inline with
  ``# raylint: disable=<rule> -- <justification>`` on the flagged line;
  the justification is REQUIRED — a bare disable is itself a violation
  (rule R0) that cannot be suppressed;
- reporters render pretty (human) or JSON (tooling) output; exit code 1
  means unsuppressed violations exist, 0 means clean, 2 means usage or
  internal error.

The tier-1 test ``tests/core/test_raylint.py`` runs this over all of
``ray_tpu/`` and asserts an empty baseline, so every future PR is
checked with no extra CI infrastructure.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
import time
from typing import Dict, Iterable, List, Optional, Tuple

SUPPRESS_RE = re.compile(
    r"#\s*raylint:\s*disable=([A-Za-z0-9_,\s-]+?)\s*"
    r"(?:--\s*(?P<why>.+?)\s*)?$")

# Rule R0 is the meta-rule: suppressions themselves must carry a
# justification. It is not suppressible.
META_RULE = "R0"


@dataclasses.dataclass
class Violation:
    rule: str              # "R1"
    name: str              # "async-blocking"
    path: str              # repo-relative posix path
    line: int
    message: str
    suppressed: bool = False
    justification: str = ""

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return f"{self.path}:{self.line}: {self.rule} " \
               f"[{self.name}]{tag} {self.message}"


@dataclasses.dataclass
class Suppression:
    line: int
    rules: Tuple[str, ...]
    justification: str


class FileInfo:
    """One parsed source file plus its inline suppressions."""

    def __init__(self, path: str, relpath: str, module: str, source: str):
        self.path = path
        self.relpath = relpath
        self.module = module            # e.g. "ray_tpu.serve.streaming"
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self._nodes: Optional[list] = None
        self.suppressions: Dict[int, Suppression] = {}
        self.noqa_lines: set = set()
        for lineno, text in enumerate(source.splitlines(), start=1):
            if "#" not in text:
                continue
            if "noqa" in text:
                self.noqa_lines.add(lineno)
            m = SUPPRESS_RE.search(text)
            if m:
                rules = tuple(
                    r.strip().upper() for r in m.group(1).split(",")
                    if r.strip())
                self.suppressions[lineno] = Suppression(
                    lineno, rules, (m.group("why") or "").strip())

    def nodes(self) -> list:
        """Every node in the module, flat, computed once. Most rules
        scan the whole tree; with ~8 rules re-walking each file,
        ``ast.walk``'s generator machinery was the analyzer's single
        biggest cost — a cached list turns all but the first scan into
        plain list iteration (the <10s tier-1 pin depends on it)."""
        cached = self._nodes
        if cached is None:
            cached = self._nodes = list(ast.walk(self.tree))
        return cached

    @property
    def package(self) -> Optional[str]:
        """Top-level package inside ray_tpu ("" for ray_tpu/*.py files,
        "serve" for anything under ray_tpu/serve/, None for files
        outside ray_tpu entirely). Computed from the file path, so a
        package ``__init__.py`` belongs to its own package."""
        parts = self.relpath.split("/")
        if parts[0] != "ray_tpu":
            return None
        return parts[1] if len(parts) > 2 else ""

    def suppression_for(self, rule: str, line: int) -> Optional[Suppression]:
        sup = self.suppressions.get(line)
        if sup is not None and rule in sup.rules:
            return sup
        return None


class Rule:
    """Base class. ``check_file`` yields (line, message) per file;
    ``finalize`` yields (fileinfo, line, message) once all files have
    been seen — the hook for cross-file analyses."""

    id = "R?"
    name = "unnamed"
    description = ""

    def check_file(self, fi: FileInfo) -> Iterable[Tuple[int, str]]:
        return ()

    def finalize(self, project: "Project") \
            -> Iterable[Tuple[FileInfo, int, str]]:
        return ()


class Project:
    """All parsed files plus a scratch space rules share across the
    per-file and finalize phases (keyed by rule id)."""

    def __init__(self, files: List[FileInfo]):
        self.files = files
        self.scratch: Dict[str, dict] = {}

    def scratch_for(self, rule_id: str) -> dict:
        return self.scratch.setdefault(rule_id, {})


def _module_name(relpath: str) -> str:
    mod = relpath[:-3] if relpath.endswith(".py") else relpath
    mod = mod.replace(os.sep, ".").replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


def collect_files(paths: List[str], root: Optional[str] = None) \
        -> List[FileInfo]:
    """Parse every .py file under ``paths`` (skipping caches/build
    output). ``root`` anchors repo-relative names; defaults to cwd."""
    root = os.path.abspath(root or os.getcwd())
    seen = set()
    out: List[FileInfo] = []
    for path in paths:
        path = os.path.abspath(path)
        if os.path.isfile(path):
            candidates = [path]
        else:
            candidates = []
            for dirpath, dirnames, filenames in os.walk(path):
                dirnames[:] = [
                    d for d in dirnames
                    if d not in ("__pycache__", ".git", "build", ".eggs")]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        candidates.append(os.path.join(dirpath, fn))
        for cand in candidates:
            if cand in seen:
                continue
            seen.add(cand)
            rel = os.path.relpath(cand, root).replace(os.sep, "/")
            with open(cand, "r", encoding="utf-8") as f:
                source = f.read()
            out.append(FileInfo(cand, rel, _module_name(rel), source))
    return out


def run_rules(files: List[FileInfo], rules: List[Rule]) -> List[Violation]:
    """Run every rule over every file, then finalize; returns ALL
    violations (suppressed ones included, marked) plus R0 meta
    violations for unjustified or unused-looking suppressions."""
    project = Project(files)
    raw: List[Tuple[FileInfo, Rule, int, str]] = []
    for rule in rules:
        for fi in files:
            for line, message in rule.check_file(fi) or ():
                raw.append((fi, rule, line, message))
    for rule in rules:
        for fi, line, message in rule.finalize(project) or ():
            raw.append((fi, rule, line, message))

    out: List[Violation] = []
    emitted = set()
    for fi, rule, line, message in raw:
        key = (rule.id, fi.relpath, line, message)
        if key in emitted:
            continue  # nested-scope walks can visit a site twice
        emitted.add(key)
        sup = fi.suppression_for(rule.id, line)
        out.append(Violation(
            rule=rule.id, name=rule.name, path=fi.relpath, line=line,
            message=message,
            suppressed=sup is not None and bool(sup.justification),
            justification=sup.justification if sup else ""))

    # Meta pass: every suppression must carry a justification. (An
    # unjustified suppression also fails to suppress, above.)
    for fi in files:
        for sup in fi.suppressions.values():
            if not sup.justification:
                out.append(Violation(
                    rule=META_RULE, name="unjustified-suppression",
                    path=fi.relpath, line=sup.line,
                    message="suppression without a justification: use "
                            "`# raylint: disable=<rule> -- <reason>`"))
    out.sort(key=lambda v: (v.path, v.line, v.rule))
    return out


def stale_suppressions(files: List[FileInfo],
                       violations: List[Violation]) -> List[Violation]:
    """Suppression comments whose line no longer triggers the named
    rule — dead weight that silently re-opens the hole if the code
    regresses later (the suppression would mask the NEW violation).
    One entry per (suppression line, named rule) that matched nothing;
    reported via ``--show-suppressed`` and gated stale-free by tier-1.

    A rule the analyzer wasn't asked to run cannot prove its
    suppressions stale, so callers running a rule subset must filter —
    :func:`analyze` handles that."""
    fired = {(v.path, v.line, v.rule) for v in violations}
    out: List[Violation] = []
    for fi in files:
        for sup in fi.suppressions.values():
            for rule_id in sup.rules:
                if (fi.relpath, sup.line, rule_id) not in fired:
                    out.append(Violation(
                        rule=rule_id, name="stale-suppression",
                        path=fi.relpath, line=sup.line,
                        message=f"suppression for {rule_id} is stale: "
                                f"the rule no longer fires on this "
                                f"line — drop the disable comment",
                        justification=sup.justification))
    out.sort(key=lambda v: (v.path, v.line, v.rule))
    return out


@dataclasses.dataclass
class Report:
    violations: List[Violation]
    files_checked: int
    elapsed_s: float
    stale: List[Violation] = dataclasses.field(default_factory=list)

    @property
    def active(self) -> List[Violation]:
        return [v for v in self.violations if not v.suppressed]

    @property
    def suppressed(self) -> List[Violation]:
        return [v for v in self.violations if v.suppressed]

    def to_json(self) -> str:
        return json.dumps({
            "files_checked": self.files_checked,
            "elapsed_s": round(self.elapsed_s, 3),
            "violations": [v.to_dict() for v in self.active],
            "suppressed": [v.to_dict() for v in self.suppressed],
            "stale_suppressions": [v.to_dict() for v in self.stale],
        }, indent=2)

    def render_pretty(self) -> str:
        lines = [v.render() for v in self.active]
        lines.append(
            f"raylint: {self.files_checked} files, "
            f"{len(self.active)} violation(s), "
            f"{len(self.suppressed)} suppressed, "
            f"{self.elapsed_s:.2f}s")
        return "\n".join(lines)


def analyze(paths: List[str], rules: Optional[List[Rule]] = None,
            root: Optional[str] = None) -> Report:
    from tools.raylint.rules import all_rules

    import gc

    t0 = time.monotonic()
    # Bulk ast.parse allocates millions of container objects; with the
    # cyclic GC live, every gen2 pass rescans the host interpreter's
    # whole heap (inside a loaded test run that's 3-4x the standalone
    # wall time). Nothing here creates reference cycles worth chasing
    # mid-run — pause collection for the batch, restore after.
    gc_was_enabled = gc.isenabled()
    gc.disable()
    try:
        files = collect_files(paths, root=root)
        active_rules = rules if rules is not None else all_rules()
        violations = run_rules(files, active_rules)
        ran = {r.id for r in active_rules}
        stale = [v for v in stale_suppressions(files, violations)
                 if v.rule in ran]
    finally:
        if gc_was_enabled:
            gc.enable()
    return Report(violations=violations, files_checked=len(files),
                  elapsed_s=time.monotonic() - t0, stale=stale)


def analyze_source(source: str, rules: List[Rule],
                   module: str = "fixture_mod",
                   relpath: Optional[str] = None) -> List[Violation]:
    """Test/fixture entry point: lint one in-memory snippet."""
    rel = relpath or module.replace(".", "/") + ".py"
    fi = FileInfo(path=rel, relpath=rel, module=module, source=source)
    return run_rules([fi], rules)
