"""raylint: concurrency- and invariant-checking static analysis for the
ray_tpu codebase. Run ``python -m tools.raylint ray_tpu/`` or see the
"Static analysis" section of the README for the rule catalog."""

from tools.raylint.core import (  # noqa: F401
    FileInfo,
    Report,
    Rule,
    Violation,
    analyze,
    analyze_source,
    collect_files,
    run_rules,
)
