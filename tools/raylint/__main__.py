"""CLI: ``python -m tools.raylint [paths] [--json] [--rule R1,R2] ...``

Exit-code contract (stable; the tier-1 test and any CI hook rely on it):
  0  no unsuppressed violations
  1  unsuppressed violations found
  2  usage error / analysis crash (bad path, unknown rule, parse error)
"""

from __future__ import annotations

import argparse
import os
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.raylint",
        description="AST-based concurrency/invariant linter for ray_tpu")
    parser.add_argument(
        "paths", nargs="*", default=["ray_tpu"],
        help="files or directories to lint (default: ray_tpu)")
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="machine-readable JSON report on stdout")
    parser.add_argument(
        "--rule", default=None,
        help="comma-separated rule ids to run (e.g. R1,R3); default all")
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog and exit")
    parser.add_argument(
        "--show-suppressed", action="store_true",
        help="also print suppressed violations, flagging STALE "
             "suppressions whose line no longer triggers the named "
             "rule (pretty mode)")
    args = parser.parse_args(argv)

    from tools.raylint.core import analyze
    from tools.raylint.rules import rules_by_id, select_rules

    if args.list_rules:
        for rid, cls in sorted(rules_by_id().items()):
            print(f"{rid}  {cls.name:<18} {cls.description}")
        return 0

    try:
        rules = select_rules(
            args.rule.split(",") if args.rule else None)
    except KeyError as e:
        print(f"raylint: {e.args[0]}", file=sys.stderr)
        return 2

    for path in args.paths:
        if not os.path.exists(path):
            print(f"raylint: no such path: {path}", file=sys.stderr)
            return 2

    try:
        report = analyze(args.paths, rules=rules)
    except SyntaxError as e:
        print(f"raylint: parse error: {e}", file=sys.stderr)
        return 2

    if args.as_json:
        print(report.to_json())
    else:
        if args.show_suppressed:
            for v in report.suppressed:
                print(v.render())
            for v in report.stale:
                print(f"{v.path}:{v.line}: {v.rule} STALE suppression "
                      f"(rule no longer fires here; drop it)")
        print(report.render_pretty())
    return 1 if report.active else 0


if __name__ == "__main__":
    sys.exit(main())
