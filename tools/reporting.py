"""Deterministic CI report artifacts (shared by raymc/raysan/rayspec).

The analysis CLIs archive JSON reports at the repo root
(``RAYMC_REPORT.json`` & friends). Those files are committed, so two
back-to-back identical runs must produce byte-identical artifacts —
otherwise every CI run double-touches them with timing noise and the
diffs bury real changes. The fix: **volatile** fields (wall-clock
timings, host-dependent counters) are split out of the artifact into a
``<artifact>.timing.json`` sidecar (gitignored) and normalized to a
fixed placeholder in the artifact itself; everything else is written
with sorted keys and a trailing newline so serialization is canonical.

``volatile`` names are matched by dict key at any nesting depth. The
sidecar mirrors the nesting (`"scenarios[3].elapsed_s"`-style flat
paths) so the real numbers stay inspectable per run.
"""

from __future__ import annotations

import json
import sys
from typing import Dict, Tuple

VOLATILE_PLACEHOLDER = 0

TIMING_SIDECAR_SUFFIX = ".timing.json"


def split_volatile(report, volatile: Tuple[str, ...],
                   _path: str = "") -> Tuple[object, Dict[str, object]]:
    """(normalized report, {flat path: real value}) — pure."""
    timings: Dict[str, object] = {}
    if isinstance(report, dict):
        out = {}
        for key, value in report.items():
            child_path = f"{_path}.{key}" if _path else str(key)
            if key in volatile:
                timings[child_path] = value
                out[key] = VOLATILE_PLACEHOLDER
            else:
                norm, sub = split_volatile(value, volatile, child_path)
                out[key] = norm
                timings.update(sub)
        return out, timings
    if isinstance(report, list):
        out_list = []
        for i, value in enumerate(report):
            norm, sub = split_volatile(value, volatile,
                                       f"{_path}[{i}]")
            out_list.append(norm)
            timings.update(sub)
        return out_list, timings
    return report, timings


def render_deterministic(report: dict,
                         volatile: Tuple[str, ...]) -> str:
    normalized, _ = split_volatile(report, volatile)
    return json.dumps(normalized, indent=2, sort_keys=True) + "\n"


def write_report_artifact(path: str, report: dict,
                          volatile: Tuple[str, ...] = ("elapsed_s",)) \
        -> bool:
    """Write the canonical artifact at ``path`` and the real volatile
    values at ``path + ".timing.json"`` (gitignored). Returns False
    (with a stderr note) instead of raising on I/O errors — report
    writing must never fail the analysis run itself."""
    normalized, timings = split_volatile(report, volatile)
    try:
        with open(path, "w", encoding="utf-8") as f:
            f.write(json.dumps(normalized, indent=2, sort_keys=True)
                    + "\n")
        with open(path + TIMING_SIDECAR_SUFFIX, "w",
                  encoding="utf-8") as f:
            f.write(json.dumps(timings, indent=2, sort_keys=True)
                    + "\n")
        return True
    except OSError as e:
        print(f"reporting: could not write report artifact {path}: {e}",
              file=sys.stderr)
        return False
