"""raymc check driver: DFS over schedules with sleep-set pruning.

``check(scenario_factory)`` owns the exploration loop: run one
execution, harvest backtrack points from every decision whose enabled
set had unchosen alternatives, push them (with sleep sets), pop and
replay until the stack drains or a budget trips. See explorer.py for
the execution machinery and the exhaustiveness contract.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, List, Optional, Tuple

from tools.raymc.explorer import (Decision, Execution, ExecutionResult,
                                  ExplorerConfig)
from tools.raymc.minimize import _prop_names, build_counterexample
from tools.raymc.props import Finding
from tools.raymc.scenario import Scenario


@dataclasses.dataclass
class CheckResult:
    scenario: str
    executions: int = 0
    steps_total: int = 0
    pruned: int = 0
    truncated: int = 0
    divergences: int = 0
    exhausted: bool = False
    elapsed_s: float = 0.0
    conformance_checks: int = 0
    findings: List[Finding] = dataclasses.field(default_factory=list)
    # Union of every point name any execution crossed (pre-filter) —
    # the seam-coverage audit diffs this against the full catalog.
    points_crossed: List[str] = dataclasses.field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_dict(self) -> dict:
        return {
            "scenario": self.scenario,
            "executions": self.executions,
            "steps_total": self.steps_total,
            "pruned": self.pruned,
            "truncated": self.truncated,
            "divergences": self.divergences,
            "exhausted": self.exhausted,
            "elapsed_s": round(self.elapsed_s, 3),
            "conformance_checks": self.conformance_checks,
            "findings": [f.to_dict() for f in self.findings],
            "points_crossed": list(self.points_crossed),
        }

    @classmethod
    def from_dict(cls, data: dict) -> "CheckResult":
        fields = {k: v for k, v in data.items() if k != "findings"}
        return cls(findings=[Finding.from_dict(f)
                             for f in data.get("findings", [])],
                   **fields)


def _independent(scn: Scenario, a: Decision, b: Decision) -> bool:
    """Independence is the scenario's call (see
    ``Scenario.independent``); a relation that lies loses soundness,
    so any doubt must answer "dependent"."""
    try:
        return bool(scn.independent(a, b))
    except Exception:
        return False


def check(scenario_factory: Callable[[], Scenario],
          cfg: Optional[ExplorerConfig] = None) -> CheckResult:
    cfg = cfg or ExplorerConfig()
    probe = scenario_factory()
    result = CheckResult(scenario=probe.name)
    # A scenario may declare the budget its exhaustive sweep needs
    # (scenario.max_schedules); the wall-clock budget still binds.
    schedule_cap = max(cfg.max_schedules,
                       getattr(probe, "max_schedules", 0) or 0)
    t0 = time.monotonic()
    deadline = t0 + cfg.time_budget_s

    # (prefix, sleep set at the state the prefix reaches)
    stack: List[Tuple[List[Decision], frozenset]] = [([], frozenset())]
    budget_hit = False

    while stack:
        if result.executions >= schedule_cap \
                or time.monotonic() > deadline:
            budget_hit = True
            break
        prefix, sleep = stack.pop()
        scn = scenario_factory()
        res = Execution(scn, list(prefix), cfg, sleep=sleep).run()
        result.executions += 1
        result.steps_total += len(res.steps)
        result.pruned += res.sleep_leaves
        result.conformance_checks += res.conformance_checks
        result.points_crossed = sorted(
            set(result.points_crossed) | set(res.points_seen))
        if res.truncated:
            result.truncated += 1
        if res.status == "divergence":
            result.divergences += 1
            continue
        if res.status == "timeout":
            result.findings.append(Finding(
                scenario=scn.name, prop="execution-timeout",
                kind="deadlock",
                message=("an explored schedule wedged past the "
                         f"{cfg.exec_timeout_s:.0f}s execution bound; "
                         f"errors: {res.errors}")))
            if cfg.stop_on_first:
                break
            continue
        if res.status in ("violation", "deadlock") or res.errors:
            result.findings.extend(
                _findings_for(scenario_factory, cfg, prefix, res, scn))
            if cfg.stop_on_first:
                break
            continue

        _push_alternatives(stack, scn, cfg, prefix, sleep, res, result)

    result.elapsed_s = time.monotonic() - t0
    # Exhaustive = the DFS tree was fully drained with every execution
    # run to completion under full control and replayed faithfully.
    result.exhausted = (not stack and not budget_hit
                        and result.truncated == 0
                        and result.divergences == 0
                        and not result.findings)
    return result


def _push_alternatives(stack, scn: Scenario, cfg: ExplorerConfig,
                       prefix: List[Decision], sleep: frozenset,
                       res: ExecutionResult, result: CheckResult) -> None:
    """Backtrack points from one clean execution. Alternatives are
    pushed shallow-first so the LIFO stack explores deep branches (the
    chosen transition's subtree) before a sibling — the order sleep-set
    soundness assumes."""
    decisions = [s.chosen for s in res.steps]
    # `sleep` is the sleep set AT THE STATE THE PREFIX REACHES (it was
    # computed against the prefix's own last decision at push time) —
    # updating starts where the prefix ends.
    live = set(sleep)
    for i, step in enumerate(res.steps):
        if i < len(prefix):
            continue
        explored = [step.chosen]
        for alt in step.enabled:
            if alt == step.chosen:
                continue
            if cfg.dpor and alt in live:
                result.pruned += 1
                continue
            # Godefroid sleep sets: the child's sleep is everything
            # already explored from this state (plus the inherited
            # sleep) that commutes with the alternative being taken.
            child_sleep = frozenset(
                t for t in (set(live) | set(explored))
                if _independent(scn, t, alt))
            stack.append((decisions[:i] + [alt], child_sleep))
            explored.append(alt)
        live = {t for t in live if _independent(scn, t, step.chosen)}


def _findings_for(scenario_factory, cfg, prefix, res: ExecutionResult,
                  scn: Scenario) -> List[Finding]:
    decisions = [s.chosen for s in res.steps]
    out: List[Finding] = []
    if res.status == "deadlock":
        targets = {"deadlock"}
        ce = build_counterexample(scenario_factory, cfg, decisions,
                                  res, targets)
        out.append(Finding(
            scenario=scn.name, prop="no-deadlock", kind="deadlock",
            message=("explored schedule reached a state where no "
                     "thread could proceed"),
            counterexample=ce))
        return out
    if res.violations:
        targets = _prop_names(res.violations)
        ce = build_counterexample(scenario_factory, cfg, decisions,
                                  res, targets)
        kind = "invariant"
        for v in res.violations:
            prop = v.split(":", 1)[0]
            for live in scn.liveness():
                if live.name == prop:
                    kind = "liveness"
            out.append(Finding(
                scenario=scn.name, prop=prop, kind=kind,
                message=v.split(":", 1)[1].strip() if ":" in v else v,
                counterexample=ce))
    for err in res.errors:
        out.append(Finding(
            scenario=scn.name, prop="no-unhandled-exception",
            kind="exception", message=err,
            counterexample=None))
    return out
