"""raymc built-in scenarios: the checked protocol property catalog.

Each scenario drives REAL product objects; fakes are limited to the
environment around them (a replica that records dispatches, a
controller handle that dies on demand) — the same stand-ins the
concurrency regression tests use. Properties:

================== ==========================================================
scenario           property
================== ==========================================================
router_cap         a replica never holds more outstanding dispatches than
                   ``max_concurrent_queries`` (reserved-slot handoff)
pipelined_close    a clean ``PipelinedClient.close(flush_timeout=...)``
                   never orphan-sweeps an about-to-be-acked request
gcs_durability     sqlite group commit: acked (flushed) writes survive a
                   crash at either commit boundary; writes no COMMIT ever
                   covered never resurrect after restart
exactly_once       a submit frame resubmitted under its rid after a
                   connection death executes exactly once (server dedupe)
longpoll_recovery  long-poll membership converges after a controller
                   kill/restart with listeners parked mid-poll
================== ==========================================================
"""

from __future__ import annotations

import os
import socket
import tempfile
import threading
import time
from types import SimpleNamespace
from typing import List, Tuple

from ray_tpu._private import sanitize_hooks

from tools.raymc.props import Invariant, Liveness
from tools.raymc.scenario import Scenario


# -- shared fakes ------------------------------------------------------------


class _FakeMethod:
    def __init__(self, fn):
        self._fn = fn

    def remote(self, *args, **kwargs):
        return self._fn(*args, **kwargs)


class _Replica:
    """Hashable (router keys replicas into dicts) dispatch recorder."""

    def __init__(self, fn):
        self.handle_request = _FakeMethod(fn)


class _FakeController:
    """Enough controller surface for a Router: metrics reports are
    swallowed, long-poll listens fail fast (no membership churn in the
    scenario — the replica set is pinned at setup)."""

    def __init__(self):
        self.listen = _FakeMethod(self._listen)
        self.record_handle_metrics = _FakeMethod(lambda dep, total: None)

    def _listen(self, *a, **k):
        raise RuntimeError("no controller in this scenario")


def _pending_ref():
    """An ObjectRef that never resolves, so dispatched requests stay
    in-flight for the whole execution and an oversubscription cannot
    self-heal before the invariant looks."""
    from ray_tpu._private.ids import ObjectID
    from ray_tpu.object_ref import ObjectRef

    return ObjectRef(ObjectID.from_random(), _register=False)


# -- router reserved-slot cap ------------------------------------------------


class RouterCapScenario(Scenario):
    name = "router_cap"
    description = ("concurrent dispatchers against a cap-1 replica: "
                   "outstanding dispatches never exceed the cap")
    points = ("router.handoff", "router.buggy_gap")
    max_steps = 16
    needs_ray = True

    def __init__(self, dispatchers: int = 2, cap: int = 1):
        self.n_dispatchers = dispatchers
        self.cap = cap

    def setup(self) -> None:
        from ray_tpu.serve._private.router import Router

        self.dispatched = 0
        self._dlock = threading.Lock()

        def handle(method, args, kwargs):
            with self._dlock:
                self.dispatched += 1
            return _pending_ref()

        self.replica = _Replica(handle)
        self.router = Router(_FakeController(), "dep",
                             max_concurrent_queries=self.cap)
        self.router._update_replicas([self.replica])
        self.results: List = []

    def actions(self):
        def dispatch():
            self.results.append(
                self.router.try_assign_request("__call__", (), {}))
        return [(f"dispatch-{chr(ord('a') + i)}", dispatch)
                for i in range(self.n_dispatchers)]

    def invariants(self):
        return [Invariant(
            "router-cap",
            lambda s: (s.dispatched <= s.cap
                       or f"{s.dispatched} requests dispatched to a "
                          f"cap-{s.cap} replica"),
            description="per-replica in-flight cap holds mid-handoff")]

    def teardown(self) -> None:
        self.router.shutdown()


# -- pipelined close vs reader sweep ----------------------------------------


class PipelinedCloseScenario(Scenario):
    name = "pipelined_close"
    description = ("clean close with an in-flight, about-to-be-acked "
                   "request: the reader must never orphan-sweep it")
    points = ("rpc.pipeline.reader_edge", "rpc.pipeline.reply_handled",
              "rpc.pipeline.closed_set")
    max_steps = 24
    block_grace_s = 0.04

    def setup(self) -> None:
        from ray_tpu._private.rpc import PipelinedClient, RpcServer

        self.release = threading.Event()
        self.errors: List[Tuple] = []

        def fast(**kwargs):
            return "ok"

        def slow(**kwargs):
            self.release.wait(5.0)
            return "ok"

        self.server = RpcServer({"fast": fast, "slow": slow})
        self.client = PipelinedClient(
            self.server.address,
            on_error=lambda tag, msg, rid, lost: self.errors.append(
                (tag, lost)))

    def actions(self):
        def driver():
            self.client.send("fast", tag="req1")
            self.client.flush(3.0)
            self.client.send("slow", tag="req2")
            self.release.set()  # the peer acks while close() flushes
            self.client.close(flush_timeout=3.0)
        return [("driver", driver)]

    def invariants(self):
        return [Invariant(
            "close-no-orphan",
            lambda s: (not s.errors
                       or f"clean close produced orphan errors: "
                          f"{s.errors}"),
            description="close(flush_timeout) never sweeps an "
                        "about-to-be-acked request into the orphan "
                        "path")]

    def liveness(self):
        return [Liveness(
            "close-acks-all",
            lambda s: s.client._acked == 2, timeout_s=3.0,
            description="both requests acknowledged by close")]

    def teardown(self) -> None:
        self.release.set()
        try:
            self.client.close()
        except Exception:
            pass
        self.server.shutdown()


# -- sqlite group-commit durability under crash ------------------------------


class GroupCommitDurabilityScenario(Scenario):
    name = "gcs_durability"
    description = ("writers vs group commit vs injected crash: acked "
                   "writes survive, uncommitted writes never resurrect")
    points = ("gcs.put",)
    crash_points = ("gcs.commit.before", "gcs.commit.after")
    crash_budget = 1
    max_steps = 24
    # Writers block on the store lock whenever the committer is parked
    # inside the commit window — a certain, immediate block, so a
    # short grace keeps per-step cost down.
    block_grace_s = 0.02

    def __init__(self, writers: int = 1):
        # One writer is the exhaustive small scope (the property is
        # about put-vs-commit-vs-crash ordering, which one writer
        # fully exercises across the two commit windows); more writers
        # widen coverage but grow the space factorially — use bounded
        # budgets there.
        self.n_writers = writers

    def setup(self) -> None:
        from ray_tpu._private.gcs_storage import SqliteStoreClient

        fd, self.path = tempfile.mkstemp(prefix="raymc-gcs-",
                                         suffix=".db")
        os.close(fd)
        os.unlink(self.path)
        # Group-commit mode WITHOUT the background flusher: construct
        # synchronous (interval 0 starts no thread), then widen the
        # interval so puts defer their COMMIT to the scenario's
        # explicit committer action — the checker owns every commit
        # boundary instead of racing a timer.
        self.store = SqliteStoreClient(self.path, commit_interval_s=0)
        self.store._interval = 3600.0
        self.accepted: List[bytes] = []
        self.acked: set = set()
        self.durable: set = set()
        self.present: set = set()
        self.crashed: str = ""

    def actions(self):
        def writer(key):
            def body():
                try:
                    self.store.put("t", key, b"v")
                except Exception:
                    return  # store died under us: the write never took
                self.accepted.append(key)
            return body

        def committer():
            # TWO commit windows: a crash inside the first flush can
            # only lose never-acked writes (vacuous for durability —
            # flush() hasn't returned, nothing was promised). The
            # placement that bites is a death AFTER a completed, acked
            # flush: the second window provides it, with writers free
            # to interleave around both.
            for window in range(2):
                snap = list(self.accepted)
                self.store.flush()
                self.acked.update(snap)
                if window == 0:
                    # Sync gate OUTSIDE the store lock: without it the
                    # committer can barge straight from window 1 into
                    # window 2's lock hold, and whether a lock-blocked
                    # writer squeezes through between the windows is OS
                    # lock-queue luck — exactly the sub-yield-point
                    # nondeterminism that makes explorations diverge.
                    # Parked here, the lock handoff is a decision.
                    sanitize_hooks.sched_point("mc.sync.commit_gap")

        acts = [(f"writer-{chr(ord('a') + i)}",
                 writer(b"k%d" % i)) for i in range(self.n_writers)]
        acts.append(("committer", committer))
        return acts

    def independent(self, a, b) -> bool:
        # Scenario-specific structure that makes the two-writer config
        # tractable to exhaust (argued from the code, not vibes):
        # - two writers' puts commute: each writes its OWN key;
        # - a writer's start transition is PURE — the segment between
        #   its start gate and its put gate executes nothing (the
        #   gcs.put crossing is the first statement of put()) — so it
        #   commutes with every other thread's transition. The
        #   committer's start is NOT pure (it snapshots `accepted`)
        #   and keeps full conflicts.
        if a[0] == b[0] or a[3] or b[3]:
            return False
        if a[1] == "gcs.put" and b[1] == "gcs.put":
            return True
        if a[1].startswith("mc.start.writer") \
                or b[1].startswith("mc.start.writer"):
            return True
        return super().independent(a, b)

    def on_point(self, point: str, role: str) -> None:
        if point == "gcs.commit.after":
            # Crossed INSIDE the store lock right after COMMIT: exactly
            # the accepted-so-far writes are durable now (a writer
            # mid-put is blocked on the same lock and not yet in
            # `accepted`).
            self.durable.update(self.accepted)

    def on_crash(self, point: str) -> None:
        from ray_tpu._private.gcs_storage import SqliteStoreClient

        try:
            # Process death: the connection drops with the pending
            # transaction uncommitted (sqlite rolls it back). UNDER the
            # store lock: closing a sqlite connection while another
            # thread is inside conn.execute() on it is a C-level
            # use-after-free (segfaulted under full-suite load when a
            # lock-blocked writer woke the instant the crashing flush
            # released the lock). The lock sequences the close after
            # any in-flight statement; later puts hit a clean
            # ProgrammingError on the closed connection, which the
            # writer action treats as "the store died under us".
            with self.store._lock:
                self.store._conn.close()
        except Exception:
            pass
        survivor = SqliteStoreClient(self.path, commit_interval_s=0)
        try:
            self.present = {k for k, _ in survivor.get_all("t")}
        finally:
            survivor.close()
        self.crashed = point  # LAST: invariants key off it

    def invariants(self):
        def durability(s):
            if not s.crashed:
                return True
            lost = s.acked - s.present
            return (not lost
                    or f"acked writes lost across crash at "
                       f"{s.crashed}: {sorted(lost)}")

        def no_resurrection(s):
            if not s.crashed:
                return True
            ghosts = s.present - s.durable
            return (not ghosts
                    or f"uncommitted writes resurrected after crash "
                       f"at {s.crashed}: {sorted(ghosts)}")

        return [
            Invariant("gcs-durability", durability,
                      description="flushed writes survive crash"),
            Invariant("gcs-no-resurrection", no_resurrection,
                      description="unflushed writes stay dead"),
        ]

    def teardown(self) -> None:
        try:
            if not self.crashed:
                self.store.close()
        except Exception:
            pass
        for suffix in ("", "-wal", "-shm"):
            try:
                os.unlink(self.path + suffix)
            except OSError:
                pass


# -- multi-process head: cross-shard routing + per-shard commit windows ------


class CrossShardScenario(Scenario):
    name = "cross_shard"
    description = ("two head shards, writers on both key ranges, a "
                   "committer flushing per-shard windows, one shard "
                   "crashing at a commit boundary: rows never land on "
                   "a foreign shard, a cap-1 lease key is never "
                   "double-granted (even from the other shard's "
                   "writer), and a neighbor's acked rows survive the "
                   "victim's crash")
    # Route crossings only: the apply body runs under the shard lock
    # right after its route decision, so route-level interleavings
    # already cover every observable order while keeping the space
    # drainable inside the tier-1 leg.
    points = ("headshard.route",)
    # Per-shard group commit reuses the store's commit crossings: a
    # crash there is one shard PROCESS dying mid-window, the other
    # shard's window untouched.
    crash_points = ("gcs.commit.before", "gcs.commit.after")
    crash_budget = 1
    max_steps = 30
    # Measured exhaustive sweep: 463 schedules (~5.5s standalone); the
    # floor leaves headroom so the tier-1 `exhausted` claim stays
    # honest.
    max_schedules = 2000
    block_grace_s = 0.02

    def setup(self) -> None:
        from ray_tpu._private.gcs_storage import SqliteStoreClient
        from ray_tpu._private.head_shards import (HeadShardState,
                                                  InprocRouter, shard_of)

        self._store_cls = SqliteStoreClient
        self.paths = []
        states = []
        for i in range(2):
            fd, path = tempfile.mkstemp(prefix=f"raymc-shard{i}-",
                                        suffix=".db")
            os.close(fd)
            os.unlink(path)
            self.paths.append(path)
            state = HeadShardState(i, 2, db_path=path,
                                   commit_interval_s=0)
            # Group-commit mode without the background flusher: the
            # committer ACTION owns every commit boundary (same trick
            # as gcs_durability).
            state.store._interval = 3600.0
            states.append(state)
        self.router = InprocRouter(2, states=states)

        def key_for(shard: int, prefix: bytes) -> bytes:
            i = 0
            while True:
                k = prefix + b"-%d" % i
                if shard_of(k, 2) == shard:
                    return k
                i += 1

        self.obj_key = {i: key_for(i, b"obj") for i in range(2)}
        self.lease_key = key_for(0, b"lease")  # shard 0 owns the cap
        self.accepted = {0: [], 1: []}
        self.acked = {0: set(), 1: set()}
        self.durable = {0: set(), 1: set()}
        self.present = {0: set(), 1: set()}
        self.grant_results: List[bool] = []
        self.flushing = -1
        self.crashed = ""
        self.victim = -1
        # Both directory rows are seeded here, NOT concurrently: the
        # put-vs-commit interleaving is gcs_durability's (per-store)
        # property, already exhausted there — re-exploring it per shard
        # multiplies this space past the tier-1 budget. What THIS
        # scenario owns is the cross-shard surface: the routed cap-1
        # grant race and a crash placement inside either shard's commit
        # window while the neighbor's rows sit acked or open.
        for i in range(2):
            self.router.put("objects", self.obj_key[i],
                            ("10.0.0.%d" % i, i))
            self.accepted[i].append(self.obj_key[i])
        # The NEIGHBOR's window commits deterministically up front: its
        # row is acked before any explored crash, which is exactly the
        # precondition the neighbor-durability invariant needs. Only
        # the victim shard's window stays open for the explorer.
        self.flushing = 1
        self.router.shards[1].store.flush()
        self.acked[1].update(self.accepted[1])
        self.flushing = -1

    def actions(self):
        def grantor(node):
            # Writers on BOTH shards' ranges contend for the SAME cap-1
            # key: writer-b's attempt must cross shards to shard 0's
            # single authority — the admission decision the tentpole
            # moved OUT of the coordinator's memory.
            def body():
                try:
                    ok = self.router.lease_register(self.lease_key,
                                                    node, cap=1)
                except Exception:
                    ok = False
                self.grant_results.append(ok)
            return body

        def committer():
            # The victim shard's group-commit window: a crash at either
            # commit crossing is shard 0's process dying mid-window —
            # shard 1's acked rows (committed in setup) must survive it.
            self.flushing = 0
            snap = list(self.accepted[0])
            try:
                self.router.shards[0].store.flush()
            except Exception:
                return  # the shard crashed mid-window
            self.acked[0].update(snap)

        return [("writer-a", grantor("node-a")),
                ("writer-b", grantor("node-b")),
                ("committer", committer)]

    def independent(self, a, b) -> bool:
        if a[0] == b[0] or a[3] or b[3]:
            return False
        # A writer's start transition is PURE — the segment before its
        # first route crossing executes nothing (router.put's crossing
        # is its first statement), so it commutes with every other
        # thread (same argument as gcs_durability's writers). Route
        # crossings themselves keep full conflicts: the two writers
        # share shard 0's lease authority.
        if a[1].startswith("mc.start.writer") \
                or b[1].startswith("mc.start.writer"):
            return True
        return super().independent(a, b)

    def on_point(self, point: str, role: str) -> None:
        if point == "gcs.commit.after" and self.flushing >= 0:
            self.durable[self.flushing] = set(
                self.accepted[self.flushing])

    def on_crash(self, point: str) -> None:
        victim = self.flushing if self.flushing >= 0 else 0
        store = self.router.shards[victim].store
        try:
            # One shard process dies: its connection drops with the
            # window open (sqlite rolls back). Under the store lock —
            # same use-after-free discipline as gcs_durability.
            with store._lock:
                store._conn.close()
        except Exception:
            pass
        # Read BOTH shards' dbs through fresh connections: what a
        # restarted shard (and the untouched neighbor) would reload.
        for i in range(2):
            survivor = self._store_cls(self.paths[i],
                                       commit_interval_s=0)
            try:
                self.present[i] = {k for k, _ in
                                   survivor.get_all("objects")}
            finally:
                survivor.close()
        self.victim = victim
        self.crashed = point  # LAST: invariants key off it

    def invariants(self):
        def ownership(s):
            for state in s.router.shards:
                for table in ("objects", "lease"):
                    for key in state.tables[table]:
                        if not state.owns(key):
                            return (f"shard {state.index} holds "
                                    f"foreign key {key!r} in {table}")
            return True

        def single_grant(s):
            wins = sum(1 for ok in s.grant_results if ok)
            if wins > 1:
                return (f"cap-1 lease key granted {wins} times across "
                        f"shards")
            if not s.crashed:
                grants = [n for state in s.router.shards
                          for n in state.tables["lease"].get(
                              s.lease_key, ())]
                if len(grants) > 1:
                    return f"duplicate grant rows: {grants}"
            return True

        def neighbor_durability(s):
            if not s.crashed:
                return True
            other = 1 - s.victim
            lost = s.acked[other] - s.present[other]
            return (not lost
                    or f"neighbor shard {other} lost acked rows "
                       f"{sorted(lost)} to shard {s.victim}'s crash")

        def victim_loss_bound(s):
            if not s.crashed:
                return True
            lost = s.acked[s.victim] - s.present[s.victim]
            ghosts = s.present[s.victim] - s.durable[s.victim]
            if lost:
                return (f"victim shard {s.victim} lost ACKED rows "
                        f"{sorted(lost)} (loss must stay inside the "
                        f"open window)")
            return (not ghosts
                    or f"unflushed rows resurrected on shard "
                       f"{s.victim}: {sorted(ghosts)}")

        return [
            Invariant("shard-single-ownership", ownership,
                      description="rows live only on the owning shard"),
            Invariant("shard-single-grant", single_grant,
                      description="cap-1 key never double-granted "
                                  "across shards"),
            Invariant("shard-neighbor-durability", neighbor_durability,
                      description="one shard's crash never loses a "
                                  "neighbor's acked rows"),
            Invariant("shard-victim-loss-bound", victim_loss_bound,
                      description="victim loses at most its open "
                                  "commit window, nothing acked"),
        ]

    def teardown(self) -> None:
        try:
            for i, state in enumerate(self.router.shards):
                if not (self.crashed and i == self.victim):
                    state.close()
        except Exception:
            pass
        for path in self.paths:
            for suffix in ("", "-wal", "-shm"):
                try:
                    os.unlink(path + suffix)
                except OSError:
                    pass


# -- exactly-once resubmit across connection death ---------------------------


class ExactlyOnceResubmitScenario(Scenario):
    name = "exactly_once"
    description = ("connection killed around a submit frame: the rid "
                   "resubmit (cluster_utils lost-frame path) executes "
                   "the frame exactly once")
    points = ("rpc.pipeline.send", "rpc.pipeline.reader_edge",
              "rpc.server.dispatch", "rpc.server.reply")
    crash_points = ("mc.env.conn_kill",)
    crash_budget = 1
    max_steps = 24
    block_grace_s = 0.04

    def setup(self) -> None:
        from ray_tpu._private.rpc import PipelinedClient, RpcServer

        self.executed = {}
        self._xlock = threading.Lock()
        self.resubmits = 0
        self.tids = ["t1"]
        self.server = RpcServer({"apply": self._apply},
                                dedupe_methods=frozenset({"apply"}))
        self.client = PipelinedClient(self.server.address,
                                      on_error=self._pipe_error)

    def _apply(self, task_ids=()):
        with self._xlock:
            for t in task_ids:
                self.executed[t] = self.executed.get(t, 0) + 1
        return True

    def _pipe_error(self, tag, message, rid, lost):
        """The driver-side recovery contract, verbatim from
        ``cluster_utils._batch_pipe_error``'s lost branch: a frame that
        died un-acked is resubmitted under the SAME request id so the
        node's dedupe cache makes it exactly-once."""
        if not lost:
            return
        from ray_tpu._private.rpc import RpcClient

        self.resubmits += 1
        try:
            RpcClient.to(self.server.address).call_with_rid(
                rid, "apply", task_ids=self.tids)
        except Exception:
            pass  # node truly dead → the death-sweep path owns recovery

    def actions(self):
        def driver():
            self.rid = self.client.send("apply", tag="frame",
                                        task_ids=self.tids)
            # The injected fault: the checker may kill the submit
            # connection at any point relative to the server's
            # dispatch/reply and the reader's drain.
            sanitize_hooks.crash_point("mc.env.conn_kill")

        def awaiter():
            # Keeps the execution (and so the explorer's control over
            # server/reader crossings) alive until the protocol
            # settles; must finish well inside the explorer's
            # blocked-threads grace (_wait_for_park) so a settled-but-
            # polling awaiter is never mistaken for a deadlock.
            deadline = time.monotonic() + 2.5
            while time.monotonic() < deadline:
                with self._xlock:
                    done = self.executed.get("t1", 0) >= 1
                if done and self.client.in_flight == 0:
                    return
                time.sleep(0.01)

        return [("driver", driver), ("awaiter", awaiter)]

    def on_crash(self, point: str) -> None:
        sock = self.client._sock
        if sock is not None:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass

    def invariants(self):
        return [Invariant(
            "exactly-once",
            lambda s: (s.executed.get("t1", 0) <= 1
                       or f"frame executed "
                          f"{s.executed['t1']} times"),
            description="a resubmitted frame never double-executes")]

    def liveness(self):
        return [Liveness(
            "frame-executes",
            lambda s: s.executed.get("t1", 0) == 1, timeout_s=4.0,
            description="the frame executes despite the kill")]

    def teardown(self) -> None:
        from ray_tpu._private.rpc import RpcClient

        try:
            self.client.close()
        except Exception:
            pass
        self.server.shutdown()
        addr = tuple(self.server.address)
        with RpcClient._pools_lock:
            pooled = RpcClient._pools.pop(addr, None)
        if pooled is not None:
            pooled.close()


# -- long-poll convergence across controller restart -------------------------


class LongPollRecoveryScenario(Scenario):
    name = "longpoll_recovery"
    description = ("controller killed with a listener parked mid-poll: "
                   "membership converges after the restart")
    points = ("longpoll.listen", "longpoll.notify",
              "longpoll.client.loop")
    crash_points = ("mc.env.controller_kill",)
    crash_budget = 1
    # The product client polls in an unbounded loop, so executions
    # truncate at the step bound by design: this scenario is a bounded
    # heuristic check, never an exhaustive one.
    max_steps = 18
    needs_ray = True
    block_grace_s = 0.06

    def setup(self) -> None:
        from ray_tpu.serve._private.long_poll import (LongPollClient,
                                                      LongPollHost)

        self.key = "replicas::dep"
        self.gen = 0
        self.host = LongPollHost()
        self.host.notify_changed(self.key, ("r1",))
        self.observed: List = []
        self.client = LongPollClient(
            self._make_handle(), self.key,
            lambda snap: self.observed.append(tuple(snap or ())),
            reresolve=self._make_handle)

    def _make_handle(self):
        """A controller handle bound to the CURRENT incarnation: calls
        against a superseded one raise ActorDiedError, exactly like a
        handle to a killed actor."""
        import ray_tpu
        from ray_tpu.exceptions import ActorDiedError

        scenario = self
        gen = self.gen

        def listen(key, known):
            if scenario.gen != gen:
                raise ActorDiedError("controller incarnation "
                                     f"{gen} is dead")
            result = scenario.host.listen(key, known, timeout=0.4)
            if scenario.gen != gen:
                # Died while we were parked: the poisoned answer of a
                # dead controller surfaces as the actor-death the real
                # transport would raise.
                raise ActorDiedError("controller died mid-listen")
            return ray_tpu.put(result)

        return SimpleNamespace(listen=_FakeMethod(listen))

    def actions(self):
        def env():
            self.host.notify_changed(self.key, ("r1", "r2"))
            sanitize_hooks.crash_point("mc.env.controller_kill")
        return [("env", env)]

    def on_crash(self, point: str) -> None:
        from ray_tpu.serve._private.long_poll import LongPollHost

        old = self.host
        replacement = LongPollHost()
        # The recovered controller re-broadcasts its checkpointed
        # state; clients resume from version -1 via reresolve.
        replacement.notify_changed(self.key, ("r1", "r2"))
        self.gen += 1
        self.host = replacement
        old.shutdown()  # poison: parked listeners wake NOW

    def invariants(self):
        valid = {("r1",), ("r1", "r2")}
        return [Invariant(
            "membership-sane",
            lambda s: (all(o in valid for o in s.observed)
                       or f"client observed garbage membership: "
                          f"{s.observed}"),
            description="observed snapshots are real memberships")]

    def liveness(self):
        return [Liveness(
            "membership-converges",
            lambda s: bool(s.observed)
            and s.observed[-1] == ("r1", "r2"),
            timeout_s=5.0,
            description="client converges to the post-restart "
                        "membership")]

    def teardown(self) -> None:
        self.client.stop()
        self.host.shutdown()
        self.client._thread.join(2.0)


# -- spill pipeline vs ref release vs restore --------------------------------


class SpillRaceScenario(Scenario):
    name = "spill_race"
    description = ("disk spill racing ref release and transparent "
                   "restore: an acked object is never lost, a freed "
                   "object never resurrects")
    points = ("spill.mark", "spill.restore")
    crash_points = ("spill.write.after",)
    crash_budget = 1
    max_steps = 24
    # Exhaustive sweep of this space is ~1.5k schedules (≈2s): above
    # the CLI default cap, well inside the tier-1 wall budget.
    max_schedules = 2500
    block_grace_s = 0.04

    def setup(self) -> None:
        from ray_tpu._private.config import ray_config
        from ray_tpu._private.ids import ObjectID
        from ray_tpu._private.memory_store import MemoryStore
        from ray_tpu._private.spilling import SpillManager

        # Small objects must be spill-eligible for the race to be
        # reachable at model-checking scale; restored in teardown.
        self._saved_min = ray_config.min_spilling_size_bytes
        ray_config.min_spilling_size_bytes = 1
        self.store = MemoryStore()
        # Seed while the budget is huge (no spill during setup) …
        self.manager = self.store.spill_manager = SpillManager(
            self.store, budget_bytes=10 ** 12)
        self.a_oid = ObjectID.from_random()
        self.b_oid = ObjectID.from_random()
        self.a_value = b"A" * 4096
        self.store.put(self.a_oid, self.a_value)
        self.store.put(self.b_oid, b"B" * 4096)
        # … then shrink it so the spiller action must sweep both.
        self.manager.budget = 1
        self.b_freed = False
        self.crashed = None
        self.spill_done = False
        self.a_reads: List = []

    def actions(self):
        def spiller():
            self.manager.maybe_spill()
            self.spill_done = True

        def releaser():
            self.store.free([self.b_oid])
            self.b_freed = True

        def reader():
            ready, value, error = self.store.peek(self.a_oid)
            self.a_reads.append((ready, bytes(value) if value else None,
                                 error))

        return [("spiller", spiller), ("releaser", releaser),
                ("reader", reader)]

    def _spill_path(self, url) -> str:
        return url[len("file://"):] if url else ""

    def invariants(self):
        def a_never_lost(s):
            entry = s.store._entries.get(s.a_oid)
            if entry is None or not entry.ready or entry.error is not None:
                return "acked object A lost its store entry"
            if entry.value is not None:
                return True
            path = s._spill_path(entry.spilled_url)
            return (path and os.path.exists(path)) or \
                "A is value-less with no durable spilled copy"

        def b_never_resurrects(s):
            if not s.b_freed:
                return True
            entry = s.store._entries.get(s.b_oid)
            if entry is None or entry.error is None or \
                    entry.value is not None:
                return "freed object B resurrected with a live value"
            if entry.spilled_url is not None:
                return ("freed object B still carries a restorable "
                        f"spill URL: {entry.spilled_url}")
            if s.crashed or not s.spill_done:
                # A crashed spiller may orphan its in-flight file —
                # disk garbage a dead process's storage dir reclaims,
                # unreachable by any entry; and a mid-sweep file (write
                # done, mark/delete pending) is legal in-flight state.
                return True
            # Once the sweep completed crash-free, the mark-fails→
            # delete path must have left no ghost copy behind (spill
            # files are <oid.hex()>-<token>, unique per write).
            try:
                ghosts = [n for n in os.listdir(
                    s.manager.storage.directory)
                    if n.startswith(s.b_oid.hex())]
            except OSError:
                ghosts = []
            return (not ghosts) or \
                f"freed object B left readable spill ghost(s): {ghosts}"

        return [
            Invariant("spill-no-loss", a_never_lost,
                      description="an acked object survives spill/"
                                  "restore/crash interleavings"),
            Invariant("spill-no-resurrection", b_never_resurrects,
                      description="a freed object never comes back"),
        ]

    def liveness(self):
        def a_reads_correct(s):
            # The reader ran to completion in every non-crashed
            # execution; whatever it observed must be A's real bytes.
            return all(ready and err is None and value == s.a_value
                       for ready, value, err in s.a_reads)

        return [Liveness("reader-sees-acked-value", a_reads_correct,
                         timeout_s=1.0,
                         description="peek(A) returns the acked bytes "
                                     "through any spill state")]

    def on_crash(self, point: str) -> None:
        self.crashed = point  # the spiller thread dies; nothing to kill

    def teardown(self) -> None:
        from ray_tpu._private.config import ray_config

        ray_config.min_spilling_size_bytes = self._saved_min
        try:
            self.manager.storage.destroy()
        except Exception:
            pass


# -- lineage reconstruction vs node death ------------------------------------


class LineageReconstructionScenario(Scenario):
    name = "lineage_reconstruction"
    description = ("node crash between publish and consume: a get on a "
                   "lost object returns the re-executed (or spill-"
                   "restored) value or a bounded error — never a hang, "
                   "never a stale/partial value")
    points = ("recon.request", "recon.resubmit", "recon.restore",
              "store.put", "mc.sync.get_loop")
    max_steps = 40
    # The getter's bounded poll loop widens the space past the CLI
    # default; the exhaustive sweep is still small (two threads).
    max_schedules = 6000
    block_grace_s = 0.04

    def setup(self) -> None:
        from types import SimpleNamespace

        from ray_tpu._private.config import ray_config
        from ray_tpu._private.ids import TaskID
        from ray_tpu._private.memory_store import MemoryStore
        from ray_tpu._private.spilling import FileSystemStorage
        from ray_tpu._private.task_spec import TaskKind, TaskSpec
        from ray_tpu.cluster_utils import ClusterHead, _NodeRecord

        # No health-checker thread: node liveness is scenario-driven.
        self._saved_period = ray_config.health_check_period_s
        ray_config.health_check_period_s = 0
        self.reexec = {"x": 0, "y": 0}
        worker = SimpleNamespace(memory_store=MemoryStore(),
                                 shm_plane=None, gcs=None, backend=None)
        self.head = head = ClusterHead(worker, start_server=False)
        self.store = worker.memory_store

        def execute(spec):
            # The re-execution environment: runs the creating task on
            # the head and reports the output — the real node-side
            # store_task_outputs/report path condensed to its effect.
            key = spec.name
            self.reexec[key] += 1
            value = spec.func()
            self.store.put(spec.return_ids[0], value)
            head._report_objects([spec.return_ids[0].binary()],
                                 head.server.address)

        worker.backend = SimpleNamespace(submit=execute)
        self.node_addr = ("127.0.0.1", 7091)
        head.nodes["n1"] = _NodeRecord("n1", self.node_addr, {"CPU": 1})
        # X: lost copy must be re-created by re-executing its task.
        spec_x = TaskSpec(task_id=TaskID.from_random(),
                          kind=TaskKind.NORMAL_TASK,
                          func=lambda: 42, args=(), kwargs={}, name="x")
        spec_x.assign_return_ids()
        self.x = spec_x.return_ids[0]
        head.record_lineage(spec_x)
        head._report_objects([self.x.binary()], self.node_addr,
                             sizes=[8])
        # Y: lost copy has a surviving spilled payload — restore must
        # win over re-execution (reexec["y"] stays 0).
        spec_y = TaskSpec(task_id=TaskID.from_random(),
                          kind=TaskKind.NORMAL_TASK,
                          func=lambda: "never", args=(), kwargs={},
                          name="y")
        spec_y.assign_return_ids()
        self.y = spec_y.return_ids[0]
        head.record_lineage(spec_y)
        head._report_objects([self.y.binary()], self.node_addr,
                             sizes=[16])
        self.spill_store = FileSystemStorage()
        import cloudpickle as _cp

        url = self.spill_store.spill(self.y, _cp.dumps("from-disk"))
        head._report_spilled([self.y.binary()], [url], node_id="n1")
        self.results = {}

    def _bounded_get(self, key, oid):
        head, store = self.head, self.store
        for _ in range(8):
            sanitize_hooks.sched_point("mc.sync.get_loop")
            ready, value, error = store.peek(oid)
            if ready:
                self.results[key] = ("err", error) if error else value
                return
            info = head._locate2(oid.binary())
            if info is not None:
                record = head.nodes.get("n1")
                if tuple(info["address"]) == self.node_addr:
                    # Remote fetch from the owner: succeeds only while
                    # the owner process is alive (env-controlled).
                    if record is not None and record.alive:
                        self.results[key] = \
                            42 if key == "x" else "from-disk"
                        return
                    # process gone mid-fetch: retry (next locate sees
                    # the dropped location and reconstructs)
        self.results[key] = ("err", "fetch deadline")

    def actions(self):
        def getter():
            self._bounded_get("x", self.x)
            self._bounded_get("y", self.y)

        def env():
            self.head.mark_node_dead("n1", reason="chaos kill")

        return [("getter", getter), ("env", env)]

    def invariants(self):
        def values_sane(s):
            for key, want in (("x", 42), ("y", "from-disk")):
                got = s.results.get(key, "<pending>")
                if got not in (want, "<pending>") and \
                        not (isinstance(got, tuple) and got[0] == "err"):
                    return (f"get({key}) returned stale/partial "
                            f"{got!r} (want {want!r} or bounded error)")
            return True

        def attempts_bounded(s):
            from ray_tpu._private.config import ray_config

            cap = ray_config.max_reconstruction_attempts
            over = {k.hex()[:8]: v
                    for k, v in s.head._recon_attempts.items()
                    if v > cap}
            return (not over) or f"reconstruction attempts over " \
                                 f"max_reconstruction_attempts: {over}"

        def spill_wins(s):
            return s.reexec["y"] == 0 or (
                f"spill-backed object re-executed its task "
                f"{s.reexec['y']} times instead of restoring")

        return [
            Invariant("recon-no-stale-value", values_sane,
                      description="a get never observes a wrong value"),
            Invariant("recon-attempts-bounded", attempts_bounded,
                      description="per-object attempt charge holds"),
            Invariant("recon-spill-short-circuit", spill_wins,
                      description="a durable spilled copy restores "
                                  "instead of re-executing"),
        ]

    def liveness(self):
        def completes_correctly(s):
            # The getter is a bounded loop (never hangs, by
            # construction); with reconstruction enabled it must also
            # CONVERGE: both gets return the real values.
            return s.results.get("x") == 42 and \
                s.results.get("y") == "from-disk"

        return [Liveness(
            "recon-converges", completes_correctly, timeout_s=3.0,
            description="gets on lost objects return the re-executed/"
                        "restored values, not errors")]

    def conformance(self):
        # rayspec refinement over the head's lock-partitioned object
        # directory (ShardedTable): under the publish/death/
        # reconstruct churn, the directory must stay a refinement of
        # ONE flat dict per key — the catalog's sharded_table spec
        # checked against a REAL head under exploration.
        return [("sharded_table", lambda: self.head.object_locations)]

    def teardown(self) -> None:
        from ray_tpu._private.config import ray_config

        ray_config.health_check_period_s = self._saved_period
        self.head.stop()
        try:
            self.spill_store.destroy()
        except Exception:
            pass


# -- actor restart: replay-or-reject over every death placement --------------


class ActorRestartScenario(Scenario):
    name = "actor_restart"
    description = ("node death across mailbox-submit/dispatch/restart: "
                   "<=1 execution per call always, exactly-1 for calls "
                   "with retry budget, rejects name the budget")
    points = ("actor.route", "actor.replay", "actor.restart.begin",
              "actor.restart.ready", "mc.sync.exec1")
    max_steps = 36
    # Measured exhaustive sweep: ~17.3k schedules (~17s on a 1-core
    # box); the floor leaves headroom so the tier-1 `exhausted` claim
    # stays honest.
    max_schedules = 25000
    block_grace_s = 0.04

    # The model around the REAL ActorRestartGate mirrors the head's
    # choreography (ClusterBackendMixin.submit / ClusterHead.
    # mark_node_dead) the way exactly_once mirrors _batch_pipe_error:
    # dispatch appends to the hosting node's mailbox (+ the inflight
    # table), node death sweeps the inflight snapshot through
    # gate.recover_call, and the restarted actor's location release
    # drains parked calls. Execution and its inflight-clear are one
    # atomic segment — the model's analog of the output report; the
    # report-in-flight window is out of scope here (closed by the
    # caller-side dedupe in ClusterHead.recover_actor_call — ROADMAP
    # FT gap (a) — with the rayspec exactly_once_call spec as the
    # mechanical witness, see test_rayspec.py's pre-fix history test).

    def setup(self) -> None:
        from types import SimpleNamespace

        from ray_tpu._private.actor_gate import (ActorRestartGate,
                                                 ActorRestartState)
        self._alive = ActorRestartState.ALIVE
        self._restarting = ActorRestartState.RESTARTING
        self._dead = ActorRestartState.DEAD
        self.aid = b"actor-1"

        aid = self.aid

        class _Call:  # hashable (rides set-typed inflight tables)
            def __init__(self, name, retries):
                self.name = name
                self.max_retries = retries
                self.actor_id = SimpleNamespace(
                    binary=lambda: aid, hex=lambda: "61637430")

            def describe(self):
                return self.name

        def call(name, retries):
            return _Call(name, retries)

        self.gate = ActorRestartGate()
        self.gate.register(self.aid, 1)
        self.c_r = call("r", 1)   # rides max_task_retries=1
        self.c_n = call("n", 0)   # no retry budget
        self.node1 = {"alive": True, "mailbox": []}
        self.actor_node = "n1"
        # Insertion-ordered (a set of id-hashed objects iterates in a
        # different order per process run — divergence under replay).
        self.inflight = []
        self.parked = []
        self.executions = {"r": 0, "n": 0}
        self.rejected = {}
        self._lock = threading.Lock()
        # c_r is already dispatched and in flight when the fault hits.
        self.inflight.append(self.c_r)
        self.node1["mailbox"].append(self.c_r)

    # -- model effects (the head's wiring, condensed) --------------------

    def _reject(self, spec, msg, dead):
        self.rejected[spec.name] = (msg, dead)

    def _exec_on_n2(self, spec):
        # The replacement node is warm and healthy: dispatch-to-exec is
        # synchronous in the model (the races under proof are around
        # the death, not the healthy node's queueing).
        with self._lock:
            self.executions[spec.name] += 1
        if spec in self.inflight:
            self.inflight.remove(spec)

    def _drain_parked(self):
        while self.parked:
            self._submit(self.parked.pop(0))

    def _park(self, spec):
        self.parked.append(spec)
        # Model of the park-waiter thread: an actor already ALIVE again
        # releases immediately.
        if self.gate.state(self.aid) == self._alive and \
                self.actor_node is not None:
            self._drain_parked()

    def _submit(self, spec):
        node = self.actor_node
        if node == "n1" and self.node1["alive"]:
            self.inflight.append(spec)
            self.node1["mailbox"].append(spec)
            return
        if node == "n2":
            self.inflight.append(spec)
            self._exec_on_n2(spec)
            return
        state = self.gate.state(self.aid)
        if state == self._dead:
            self._reject(spec, self.gate.death_cause(self.aid), True)
            return
        self.gate.route_call(spec, dispatch=None, park=self._park,
                             fail=self._reject)

    # -- actions ---------------------------------------------------------

    def actions(self):
        def caller():
            # Submitted at an arbitrary point relative to the death:
            # may execute on n1, reject mid-restart (naming the
            # budget), or run on the replacement.
            self._submit(self.c_n)

        def node1():
            # Two service beats: c_r is pre-queued, c_n may land during
            # the loop — both can execute pre-death; a third beat only
            # re-observes an empty mailbox (space, no coverage).
            for _ in range(2):
                sanitize_hooks.sched_point("mc.sync.exec1")
                if not self.node1["alive"]:
                    return
                if self.node1["mailbox"]:
                    spec = self.node1["mailbox"].pop(0)
                    sanitize_hooks.sched_point("mc.sync.exec1")
                    if not self.node1["alive"]:
                        return  # died mid-call: spec stays in flight
                    with self._lock:
                        self.executions[spec.name] += 1
                    if spec in self.inflight:
                        self.inflight.remove(spec)

        def env_kill():
            # mark_node_dead condensed: kill, restart decision,
            # replay-or-reject every in-flight call via the REAL gate,
            # then the creation resubmit completing (set_actor_node →
            # ready → parked calls drain). The sweep-vs-ready thread
            # race is pinned separately by a deterministic unit test
            # (test_fault_semantics) — a fourth event-blocked thread
            # here costs exhaustiveness.
            self.node1["alive"] = False
            self.actor_node = None
            restarted = self.gate.begin_restart(self.aid,
                                                "its node n1 died")
            for spec in list(self.inflight):
                self.inflight.remove(spec)
                self.gate.recover_call(spec, resubmit=self._submit,
                                       fail=self._reject)
            if not restarted:
                # tombstoned: parked calls fail fast
                for spec in list(self.parked):
                    self.parked.remove(spec)
                    self._reject(spec,
                                 self.gate.death_cause(self.aid), True)
                return
            self.actor_node = "n2"
            self.gate.ready(self.aid)
            self._drain_parked()

        return [("caller", caller), ("node1", node1),
                ("env_kill", env_kill)]

    # -- properties ------------------------------------------------------

    def invariants(self):
        def at_most_once(s):
            over = {k: v for k, v in s.executions.items() if v > 1}
            return (not over) or f"calls executed more than once: {over}"

        def no_double_outcome(s):
            both = [k for k in s.executions
                    if s.executions[k] >= 1 and k in s.rejected]
            return (not both) or \
                f"calls both executed AND rejected: {both}"

        def rejects_name_budget(s):
            bad = [
                (k, msg) for k, (msg, _dead) in s.rejected.items()
                if "max_task_retries" not in msg
                and "max_restarts" not in msg
            ]
            return (not bad) or \
                f"rejection errors do not name the budget: {bad}"

        return [
            Invariant("actor-at-most-once", at_most_once,
                      description="<=1 execution per call, always"),
            Invariant("actor-single-outcome", no_double_outcome,
                      description="a call resolves exactly one way"),
            Invariant("actor-reject-names-budget", rejects_name_budget,
                      description="rejects name restart/retry budgets"),
        ]

    def liveness(self):
        def budget_call_exactly_once(s):
            return s.executions["r"] == 1

        def no_budget_call_resolves(s):
            return (s.executions["n"] + (1 if "n" in s.rejected
                                         else 0)) == 1

        return [
            Liveness("actor-retry-exactly-once",
                     budget_call_exactly_once, timeout_s=3.0,
                     description="a call with retry budget executes "
                                 "exactly once despite the death"),
            Liveness("actor-zero-budget-resolves",
                     no_budget_call_resolves, timeout_s=3.0,
                     description="a call without budget either ran "
                                 "pre-death or was rejected — exactly "
                                 "one of the two"),
        ]

    def conformance(self):
        # rayspec refinement: the REAL gate's FSM state and remaining
        # budget must match a linearization of the recorded
        # register/restart/ready/route/replay history at every
        # quiescent state of every death placement.
        return [("actor_gate", lambda: self.gate)]

    def teardown(self) -> None:
        pass


# -- tenancy: quota admission + WFQ delivery under concurrency ---------------


class QuotaAdmissionScenario(Scenario):
    name = "quota_admission"
    description = ("concurrent submits + a release racing a grant "
                   "against a cpus:1/queued:1 quota, WFQ puts racing "
                   "pops: grants never exceed the quota, admissions "
                   "never exceed the ceiling, the fair queue neither "
                   "loses nor duplicates items, and no backlogged "
                   "class is bypassed past the WFQ bound")
    # The WFQ edges gate scenario-side (mc.sync.wfq.*): a product
    # crossing inside FairTaskQueue.get would fire on every idle
    # dispatch-loop poll of the runtime's own queue and the explorer
    # would adopt the raylet dispatcher into this exploration.
    points = ("tenancy.acquire", "tenancy.release", "mc.sync.wfq.put",
              "mc.sync.wfq.pop")
    max_steps = 40
    # Measured exhaustive sweep: 7122 schedules (~9s standalone); the
    # floor leaves headroom so the tier-1 `exhausted` claim stays
    # honest.
    max_schedules = 12000
    block_grace_s = 0.04

    # The REAL decision cores (QuotaLedger, FairTaskQueue) under a
    # condensed model of the product wiring: submitters are the
    # cluster mixin's admission+charge path, the releaser is a
    # finishing task's release (the moment parked work may dispatch),
    # and the consumer is the dispatch loop serving the runnable WFQ.

    def setup(self) -> None:
        from types import SimpleNamespace

        from ray_tpu._private.config import ray_config
        from ray_tpu._private.tenancy import FairTaskQueue, QuotaLedger

        self._old_enf = ray_config.tenancy_enforcement
        self._old_quotas = ray_config.job_quotas
        ray_config.tenancy_enforcement = True
        ray_config.job_quotas = "a=cpus:1,queued:1"
        self.ledger = QuotaLedger()

        def spec(name):
            return SimpleNamespace(job_id="a", resources={"CPU": 1.0},
                                   attempt=0, name=name)

        # One slot already held when the race begins (the setup grant
        # the releaser will free mid-flight).
        self.s0 = spec("s0")
        assert self.ledger.try_acquire_cpu(self.s0)
        self.s1, self.s2 = spec("s1"), spec("s2")
        self.admits: List = []   # note_queued outcomes (None = admitted)
        self.grants: List = []   # try_acquire_cpu outcomes
        self.released = False
        # Weighted fair queue: class "a" (the quota'd job) vs class "b"
        # — explicit weights force fair mode independent of config.
        self.wfq = FairTaskQueue(weights={"a": 1.0, "b": 1.0})
        self.put_items: List = []
        self.inflight_puts: set = set()
        self.popped: List = []
        self._wlock = threading.Lock()
        # Class "a" is already backlogged when the race begins (seeded
        # here, not concurrently — a third concurrent put multiplies
        # the space past the tier-1 budget): the explored pop always
        # has two classes competing, so the bypass bookkeeping — the
        # non-starvation witness — is live in every interleaving where
        # b1's put lands first.
        self._put("a", "a0")

    def _put(self, job, tag) -> None:
        from types import SimpleNamespace

        item = SimpleNamespace(job_id=job, tag=tag)
        # The put's crossing sits BEFORE the enqueue, so a quiescent
        # state can observe the put started-but-not-landed: track the
        # window explicitly and let the conservation invariant allow
        # an in-flight item on either side.
        with self._wlock:
            self.inflight_puts.add(tag)
        sanitize_hooks.sched_point("mc.sync.wfq.put")
        self.wfq.put(item)
        with self._wlock:
            self.inflight_puts.discard(tag)
            self.put_items.append(tag)

    def actions(self):
        import queue as _queue

        def pop_one():
            # One dispatch beat: whatever is enqueued serves in WFQ
            # order; an empty beat is a recorded miss, never a hang.
            sanitize_hooks.sched_point("mc.sync.wfq.pop")
            try:
                item = self.wfq.get_nowait()
            except _queue.Empty:
                return
            with self._wlock:
                self.popped.append(item.tag)

        def sub1():
            self.admits.append(self.ledger.note_queued(self.s1))
            self.grants.append(self.ledger.try_acquire_cpu(self.s1))

        def sub2():
            # Second racing submitter doubles as the dispatch-loop
            # beat serving the runnable WFQ (a fourth action thread
            # multiplies the space past the tier-1 budget).
            self.admits.append(self.ledger.note_queued(self.s2))
            self.grants.append(self.ledger.try_acquire_cpu(self.s2))
            pop_one()

        def releaser():
            # The setup grant completes: its CPU charge frees (racing
            # both submitters' acquires), then class b's item arrives.
            self.ledger.release_cpu(self.s0)
            self.released = True
            self._put("b", "b1")

        return [("sub1", sub1), ("sub2", sub2), ("rel", releaser)]

    # -- properties ------------------------------------------------------

    def invariants(self):
        def quota_never_exceeded(s):
            peak = s.ledger.usage("a")["peak_cpu_milli"]
            return peak <= 1000 or \
                f"peak running milli-CPU {peak} over the cpus:1 quota"

        def conservation(s):
            held = (0 if s.released else 1) \
                + sum(1 for g in s.grants if g)
            used = s.ledger.usage("a")["cpu_milli"]
            return used == held * 1000 or \
                f"ledger says {used} milli held, model says {held} slots"

        def ceiling_respected(s):
            admitted = sum(1 for a in s.admits if a is None)
            return admitted <= 1 or \
                f"{admitted} submits admitted past queued:1"

        def wfq_no_loss_no_dup(s):
            with s._wlock:
                popped = list(s.popped)
                put = set(s.put_items)
                inflight = set(s.inflight_puts)
            if len(popped) != len(set(popped)):
                return f"duplicate delivery: {popped}"
            remaining = [item.tag for q in s.wfq._classes.values()
                         for item in q]
            seen = set(popped) | set(remaining)
            if len(popped) + len(remaining) != len(seen):
                return (f"item both popped and queued: "
                        f"popped={popped} remaining={remaining}")
            lost = put - seen  # a COMPLETED put must be somewhere
            forged = seen - put - inflight
            if lost or forged:
                return (f"lost={sorted(lost)} forged={sorted(forged)} "
                        f"(put={sorted(put)} popped={popped} "
                        f"remaining={remaining} "
                        f"inflight={sorted(inflight)})")
            return True

        def wfq_non_starvation(s):
            # Equal weights: a backlogged class is served at least
            # every other pop — a bypass streak past 2 means the
            # virtual-time law broke and a class can starve.
            return s.wfq.max_bypass <= 2 or \
                f"a backlogged class was bypassed " \
                f"{s.wfq.max_bypass} consecutive times"

        return [
            Invariant("quota-never-exceeded", quota_never_exceeded,
                      description="grants never exceed the CPU quota, "
                                  "across every submit/release race"),
            Invariant("quota-conservation", conservation,
                      description="ledger usage equals model holds"),
            Invariant("queued-ceiling", ceiling_respected,
                      description="admissions never exceed queued:1"),
            Invariant("wfq-exactly-once", wfq_no_loss_no_dup,
                      description="the fair queue neither loses nor "
                                  "duplicates items"),
            Invariant("wfq-non-starvation", wfq_non_starvation,
                      description="no backlogged nonzero-weight class "
                                  "is bypassed past the WFQ bound"),
        ]

    def liveness(self):
        def all_resolved(s):
            # Every submitter observed a definite admission AND grant
            # outcome; with the release in flight at least one of the
            # racers (or the freed slot itself) must land a grant.
            return len(s.admits) == 2 and len(s.grants) == 2

        return [Liveness("submits-resolve", all_resolved,
                         timeout_s=2.0,
                         description="every racing submit resolves to "
                                     "a definite grant/deny outcome")]

    def conformance(self):
        # rayspec refinement: at every quiescent state, the REAL
        # ledger and fair queue must sit in a state some linearization
        # of the recorded charge/release/admit (resp. put/pop) history
        # reaches — the scenario's invariants prove the properties,
        # the conformance pass proves the state.
        return [("quota_ledger", lambda: self.ledger),
                ("fair_task_queue", lambda: self.wfq)]

    def conflict_key(self, point: str):
        # The ledger (quota counters + model grant/release lists) and
        # the fair queue (items + put/pop model lists) are DISJOINT
        # state: their crossings commute, and declaring so is what
        # keeps the exhaustive sweep inside the tier-1 budget. Model
        # bookkeeping respects the split — ledger ops touch only
        # admits/grants/released, wfq ops only put_items/popped.
        if point.startswith("mc.sync.wfq"):
            return "tenancy-wfq"
        if point.startswith("tenancy."):
            return "tenancy-ledger"
        return super().conflict_key(point)

    def teardown(self) -> None:
        from ray_tpu._private.config import ray_config

        ray_config.tenancy_enforcement = self._old_enf
        ray_config.job_quotas = self._old_quotas


# -- scheduler dep-park table: death sweep vs dep-ready claims ---------------


class ReplicaDirectScenario(Scenario):
    name = "replica_direct"
    description = ("serve replica-direct dispatch racing a long-poll "
                   "membership removal: no slot claim ever lands on a "
                   "replica whose removal committed before the claim "
                   "started, per-replica slots never exceed the cap "
                   "or go negative, and every claim releases")
    points = ("serve.direct.acquire", "serve.direct.update")
    max_steps = 24
    # Three single-crossing actions (dep_sweep's shape): the
    # exhaustive sweep is small; the floor leaves headroom so
    # `exhausted` stays honest. Release is deliberately NOT a gated
    # point here — the acquire crossing sits INSIDE the product's
    # snapshot→claim race window (the interleaving that matters), and
    # release-after-removal is reached via the pre-held rB token that
    # disp-a releases after the updater may have committed.
    max_schedules = 6000
    block_grace_s = 0.02

    # The REAL ReplicaDirectTable (the proxy fleet's steady-state fast
    # path) under a condensed model of the wiring: two dispatchers are
    # concurrent proxy requests claiming slots, the updater is the
    # shared membership watch committing a snapshot that REMOVES
    # replica rB (a scale-down / death broadcast). The property is the
    # data plane's cache-invalidation contract: once the removal
    # commits, no acquire returns rB — a request is never dispatched
    # to a replica after its removal committed to long-poll state.

    def setup(self) -> None:
        from ray_tpu.serve._private.membership import ReplicaDirectTable

        self.table = ReplicaDirectTable(cap=1)
        self.table.update(1, ["rA", "rB"])
        # Pre-hold rB's only slot (round-robin: first acquire claims
        # rA — returned immediately — second claims rB): disp-a
        # releases it mid-run, so schedules where the updater's
        # removal commits FIRST exercise release-after-removal.
        first = self.table.acquire()
        self.held_rb = self.table.acquire()
        self.table.release(first)
        assert self.held_rb is not None and self.held_rb.replica == "rB"
        # version -> committed membership (the updater bumps
        # `committed` AFTER its update returns — commit is a return
        # edge).
        self.members = {1: {"rA", "rB"}, 2: {"rA"}}
        self.committed = 1
        self._wlock = threading.Lock()
        self.claims: List[Tuple[str, int, int]] = []

    def actions(self):
        def dispatcher(release_held):
            def body():
                pre = self.committed  # committed BEFORE this acquire
                token = self.table.acquire()
                if token is not None:
                    with self._wlock:
                        self.claims.append(
                            (token.replica, token.version, pre))
                    self.table.release(token)
                if release_held:
                    # Possibly AFTER rB's removal committed: the slot
                    # row is gone and the release must drop into the
                    # void, never corrupt the replacement accounting.
                    self.table.release(self.held_rb)
            return body

        return [("disp-a", dispatcher(True)),
                ("disp-b", dispatcher(False)),
                ("updater", self._update)]

    def _update(self):
        self.table.update(2, ["rA"])
        self.committed = 2

    def invariants(self):
        def no_stale_claim(s):
            with s._wlock:
                claims = list(s.claims)
            for replica, version, pre in claims:
                legal = s.members.get(version)
                if legal is None or replica not in legal:
                    return (f"claim on {replica!r} under version "
                            f"{version}, whose membership is {legal}")
                if pre >= 2 and replica == "rB":
                    return ("acquire started after rB's removal "
                            "committed yet returned rB")
            return True

        def slots_exact(s):
            with s.table._lock:
                slots = dict(s.table._slots)
            for replica, held in slots.items():
                if held < 0:
                    return f"slot count for {replica!r} is {held} (<0)"
                if held > s.table.cap:
                    return (f"slot count for {replica!r} is {held}, "
                            f"over cap {s.table.cap}")
            return True

        return [
            Invariant("no-stale-claim", no_stale_claim,
                      description="a request is never dispatched to a "
                                  "replica after its removal committed "
                                  "to long-poll state"),
            Invariant("slots-exact", slots_exact,
                      description="per-replica in-flight slots stay "
                                  "within [0, cap] at every quiescent "
                                  "state"),
        ]

    def liveness(self):
        return [Liveness(
            "slots-drain",
            lambda s: sum(s.table._slots.values()) == 0,
            timeout_s=2.0,
            description="every claimed slot is released (tokens for "
                        "since-removed replicas included)")]


class DepSweepScenario(Scenario):
    name = "dep_sweep"
    description = ("the scheduler's dep-park table under a racing "
                   "death sweep (ROADMAP FT gap d): two dep-ready "
                   "claims race one sweep over items parked on one and "
                   "two dependencies — every item is handed to exactly "
                   "one owner (ready path XOR sweep), nothing leaks a "
                   "per-dep entry, and every item resolves")
    points = ("sched.dep_ready", "sched.dep_sweep")
    max_steps = 24
    # Measured exhaustive sweep is tiny (3 single-crossing actions);
    # the floor leaves headroom so `exhausted` stays honest.
    max_schedules = 2000
    block_grace_s = 0.02

    # The REAL DepTable (the core LocalBackend parks dep-blocked work
    # in) under a condensed model of the product wiring: ready1/ready2
    # are _on_dep_ready for two objects landing concurrently, the
    # sweeper is _on_actor_death's claim over a dying actor's parked
    # specs. Item A parks on {d1}, item B on {d1, d2} — the multi-dep
    # item is what makes stale-entry purging and double-claim windows
    # reachable.

    def setup(self) -> None:
        from ray_tpu._private.sched_state import DepTable

        self.table = DepTable()
        self.item_a = SimpleNamespace(name="A")
        self.item_b = SimpleNamespace(name="B")
        self.table.park(b"A", self.item_a, ["d1"])
        self.table.park(b"B", self.item_b, ["d1", "d2"])
        self._wlock = threading.Lock()
        self.dispatched: List[str] = []
        self.failed: List[str] = []

    def actions(self):
        def claim(out, items):
            with self._wlock:
                out.extend(item.name for item in items)

        def ready1():
            claim(self.dispatched, self.table.dep_ready("d1"))

        def ready2():
            claim(self.dispatched, self.table.dep_ready("d2"))

        def sweeper():
            claim(self.failed,
                  self.table.sweep(lambda item: True))

        return [("ready1", ready1), ("ready2", ready2),
                ("sweeper", sweeper)]

    def invariants(self):
        def exactly_once(s):
            with s._wlock:
                dispatched = list(s.dispatched)
                failed = list(s.failed)
            both = set(dispatched) & set(failed)
            if both:
                return (f"items claimed by BOTH ready and sweep: "
                        f"{sorted(both)}")
            if len(dispatched) != len(set(dispatched)) or \
                    len(failed) != len(set(failed)):
                return (f"duplicate claim: dispatched={dispatched} "
                        f"failed={failed}")
            return True

        def conservation(s):
            with s._wlock:
                claimed = set(s.dispatched) | set(s.failed)
            waiting = s.table.waiting_count()
            if len(claimed) + waiting != 2:
                return (f"items lost or forged: claimed="
                        f"{sorted(claimed)} waiting={waiting}")
            return True

        def no_entry_leak(s):
            # A claimed item must not pin per-dep list entries: a dep
            # that never fires would hold them (and their args)
            # forever. Entries may only remain for UNCLAIMED items.
            waiting = s.table.waiting_count()
            entries = s.table.parked_entries()
            if waiting == 0 and entries != 0:
                return (f"{entries} stale per-dep entries with no "
                        f"unclaimed items")
            return True

        return [
            Invariant("dep-exactly-once-handoff", exactly_once,
                      description="each parked item is claimed by the "
                                  "ready path XOR the sweep, once"),
            Invariant("dep-conservation", conservation,
                      description="claimed + still-waiting == parked"),
            Invariant("dep-no-entry-leak", no_entry_leak,
                      description="claimed items leave no per-dep "
                                  "entries behind"),
        ]

    def liveness(self):
        def all_resolved(s):
            # The sweep matches everything, so by quiescence every
            # item has exactly one owner (sweep-first executions fail
            # both; ready-first dispatch some and sweep the rest).
            with s._wlock:
                return len(set(s.dispatched) | set(s.failed)) == 2

        return [Liveness("dep-items-resolve", all_resolved,
                         timeout_s=2.0,
                         description="every parked item ends owned by "
                                     "the ready path or the sweep")]

    def conformance(self):
        # rayspec refinement: the live DepTable's remaining-count rows
        # must match a linearization of the park/ready/sweep history
        # at every quiescent state (FT gap (d)'s exactly-once handoff,
        # now also proven as a refinement of the sequential model).
        return [("dep_table", lambda: self.table)]

    def teardown(self) -> None:
        pass


class KvCacheReuseScenario(Scenario):
    name = "kv_cache_reuse"
    description = ("LLM prefix/KV cache: a lookup hit racing block "
                   "admission and pressure eviction — a hit never "
                   "yields stale/freed KV bytes (pinned blocks are "
                   "never evicted), per-tenant charge is conserved, "
                   "and resident bytes stay under capacity")
    # Release is deliberately NOT gated (replica_direct's shape): the
    # race that matters is admit/evict landing between a lookup's pin
    # and the payload read — pinned by mc.sync.kv.read below.
    points = ("llm.kv.lookup", "llm.kv.admit", "llm.kv.evict",
              "mc.sync.kv.read")
    max_steps = 24
    # Three actions, 1-2 gated crossings each: the exhaustive sweep is
    # small; the floor leaves headroom so `exhausted` stays honest.
    max_schedules = 6000
    block_grace_s = 0.02

    # The REAL PrefixCache (the LLM engine's prefix-reuse decision
    # core) under a condensed model of the wiring: the reader is a
    # prefill hitting the shared prompt head and copying matched KV
    # payloads into its slot, the writer is another request admitting
    # a different prompt's blocks (capacity forces LRU eviction), the
    # evictor is arena-pressure reclaim. ``payloads`` stands in for
    # the host-side KV byte store: an evicted block's payload is
    # freed, so a hit observing a missing payload IS the
    # read-after-free the pinning protocol must make impossible.

    def setup(self) -> None:
        from ray_tpu._private.kv_cache import PrefixCache, chain_keys

        self.cache = PrefixCache(capacity_bytes=250, block_tokens=4)
        self.chain_p = chain_keys(list(range(8)), 4, "m")
        self.chain_q = chain_keys(list(range(100, 108)), 4, "m")
        self._wlock = threading.Lock()
        self.payloads: dict = {}
        self.stale: List[str] = []
        created, _ev = self.cache.admit(self.chain_p, "a", 100)
        assert len(created) == 2
        for h in created:
            self.payloads[h.block_id] = b"P"
        self.cache.release(created)

    def actions(self):
        def reader():
            hit = self.cache.lookup(self.chain_p, "a")
            # The pin-to-read window: admit/evict may be granted here.
            sanitize_hooks.sched_point("mc.sync.kv.read")
            with self._wlock:
                for h in hit:
                    if self.payloads.get(h.block_id) is None:
                        self.stale.append(h.key)
            self.cache.release(hit)

        def writer():
            created, evicted = self.cache.admit(self.chain_q, "b", 100)
            with self._wlock:
                for e in evicted:
                    self.payloads.pop(e.block_id, None)  # the free
                for h in created:
                    self.payloads[h.block_id] = b"Q"
            self.cache.release(created)

        def evictor():
            for e in self.cache.evict(100):
                with self._wlock:
                    self.payloads.pop(e.block_id, None)

        return [("reader", reader), ("writer", writer),
                ("evictor", evictor)]

    def invariants(self):
        def no_stale_hit(s):
            with s._wlock:
                stale = list(s.stale)
            if stale:
                return (f"lookup hit observed freed KV bytes for "
                        f"blocks {stale} — evicted while pinned")
            return True

        def charge_conserved(s):
            with s.cache._lock:
                derived: dict = {}
                total = 0
                for b in s.cache._blocks.values():
                    derived[b.job] = derived.get(b.job, 0) + b.nbytes
                    total += b.nbytes
                charge = dict(s.cache._charge)
                resident = s.cache._bytes
            if charge != derived:
                return (f"per-tenant charge {charge} != resident "
                        f"blocks' bytes {derived}")
            if resident != total:
                return f"byte counter {resident} != blocks {total}"
            if resident > s.cache.capacity_bytes:
                return (f"resident {resident} bytes over capacity "
                        f"{s.cache.capacity_bytes}")
            return True

        def refs_sane(s):
            with s.cache._lock:
                bad = {b.key: b.refs for b in s.cache._blocks.values()
                       if b.refs < 0}
            if bad:
                return f"negative refcounts: {bad}"
            return True

        return [
            Invariant("kv-no-stale-hit", no_stale_hit,
                      description="a prefix hit never reads bytes an "
                                  "eviction already freed"),
            Invariant("kv-charge-conserved", charge_conserved,
                      description="tenant charge == resident bytes per "
                                  "job; total within capacity"),
            Invariant("kv-refs-nonnegative", refs_sane,
                      description="block refcounts never go negative"),
        ]

    def liveness(self):
        def pins_drain(s):
            with s.cache._lock:
                return all(b.refs == 0
                           for b in s.cache._blocks.values())

        return [Liveness("kv-pins-drain", pins_drain, timeout_s=2.0,
                         description="every lookup/admit pin is "
                                     "released by quiescence")]

    def conformance(self):
        # rayspec refinement: the live block table + charge map must
        # match a linearization of the lookup/admit/release/evict
        # history at every quiescent state.
        return [("kv_cache", lambda: self.cache)]

    def teardown(self) -> None:
        pass


# -- head hard-crash: durability + node re-registration convergence ----------


class HeadCrashRecoveryScenario(Scenario):
    name = "head_crash_recovery"
    description = ("head killed at the commit boundary with a parked "
                   "submitter and a live node: acked-durable rows "
                   "survive, un-acked writes never resurrect, the node "
                   "re-registers through the report-returns-False path")
    # head.node_report / head.register are crossed by the node beats
    # but left UNGATED: registration orderings touch none of the
    # checked properties (the store and the node table are disjoint),
    # and gating them multiplies the space ~30x past the tier-1
    # budget. The convergence property is still driven through the
    # real handlers at EVERY crash placement (see on_crash).
    points = ("gcs.put",)
    crash_points = ("gcs.commit.before", "gcs.commit.after")
    crash_budget = 1
    max_steps = 26
    max_schedules = 4000
    block_grace_s = 0.02

    def setup(self) -> None:
        from types import SimpleNamespace

        from ray_tpu._private.config import ray_config
        from ray_tpu._private.gcs_storage import SqliteStoreClient
        from ray_tpu._private.memory_store import MemoryStore
        from ray_tpu.cluster_utils import ClusterHead

        self._saved_period = ray_config.health_check_period_s
        ray_config.health_check_period_s = 0
        fd, self.path = tempfile.mkstemp(prefix="raymc-headcrash-",
                                         suffix=".db")
        os.close(fd)
        os.unlink(self.path)
        # Group-commit mode, committer-driven (see gcs_durability).
        self.store = SqliteStoreClient(self.path, commit_interval_s=0)
        self.store._interval = 3600.0

        def make_head():
            worker = SimpleNamespace(memory_store=MemoryStore(),
                                     shm_plane=None, gcs=None,
                                     backend=None)
            return ClusterHead(worker, start_server=False)

        self._make_head = make_head
        self.head = make_head()
        self.node_addr = ("127.0.0.1", 7093)
        self.head._register_node("n1", self.node_addr, {"CPU": 1})
        self.accepted: List[bytes] = []
        self.acked: set = set()
        self.durable: set = set()
        self.present: set = set()
        self.crashed: str = ""
        self._post_crash = False
        self.converged_after_crash = False

    def actions(self):
        def writer():
            try:
                self.store.put("t", b"k1", b"v")
            except Exception:
                return  # store died under us: the write never took
            self.accepted.append(b"k1")

        def committer():
            for window in range(2):
                snap = list(self.accepted)
                self.store.flush()
                self.acked.update(snap)
                if window == 0:
                    sanitize_hooks.sched_point("mc.sync.commit_gap")

        # No node ACTION thread: even a gate-only third thread
        # multiplies the interleaving space ~70x past the tier-1
        # budget, and the node's pre-crash report beats touch nothing
        # the properties read. Its post-crash convergence handshake is
        # driven through the REAL head handlers inside on_crash, at
        # every explored crash placement.
        return [("writer", writer), ("committer", committer)]

    def _node_converge_step(self) -> bool:
        """One report-loop beat against the CURRENT head; True once
        convergence post-crash is established. Keys off the internal
        _post_crash flag — the public ``crashed`` field is set LAST in
        on_crash so mid-crash invariant evaluations stay vacuous."""
        head = self.head
        ok = head._report_resources("n1", {"CPU": 1})
        if ok:
            if self._post_crash:
                self.converged_after_crash = True
                return True
            return False
        head._register_node("n1", self.node_addr, {"CPU": 1})
        return False

    def on_point(self, point: str, role: str) -> None:
        if point == "gcs.commit.after":
            self.durable.update(self.accepted)

    def on_crash(self, point: str) -> None:
        from ray_tpu._private.gcs_storage import SqliteStoreClient

        # The head process dies: connection drops (open window rolls
        # back) and every in-memory table is gone. crash() takes the
        # store lock, sequencing the close after any in-flight
        # statement (a lock-blocked writer then sees a clean
        # ProgrammingError).
        self.store.crash()
        survivor = SqliteStoreClient(self.path, commit_interval_s=0)
        try:
            self.present = {k for k, _ in survivor.get_all("t")}
        finally:
            survivor.close()
        old = self.head
        self.head = self._make_head()  # fresh head, EMPTY node table
        old.stop()
        self._post_crash = True
        # The node's report loop keeps beating after the failover (in
        # product it is an infinite timer loop; the bounded action
        # thread may already have drained its iterations). Driving the
        # remaining beats here keeps every crash placement's
        # convergence CHECKED without an unbounded action: report →
        # False → re-register → report → True, all real head handlers.
        for _ in range(3):
            if self._node_converge_step():
                break
        self.crashed = point  # LAST: invariants key off it

    def invariants(self):
        def durability(s):
            if not s.crashed:
                return True
            lost = s.acked - s.present
            return (not lost
                    or f"acked-durable rows lost across head crash at "
                       f"{s.crashed}: {sorted(lost)}")

        def no_resurrection(s):
            if not s.crashed:
                return True
            ghosts = s.present - s.durable
            return (not ghosts
                    or f"un-acked writes resurrected after head crash "
                       f"at {s.crashed}: {sorted(ghosts)}")

        def reregistered(s):
            # Evaluated at end-state: by then on_crash has driven the
            # node's remaining report beats, so a crash execution that
            # did NOT converge is a real protocol failure, not a
            # bounded-thread artifact. (An invariant, not a Liveness:
            # the state is final when the actions drain — polling
            # would only burn the budget.)
            if not s.crashed:
                return True
            record = s.head.nodes.get("n1")
            if record is None or not record.alive:
                return ("node n1 never re-registered with the "
                        "post-crash head")
            return s.converged_after_crash or \
                "node n1 re-registered but never reconverged (no " \
                "True report after the crash)"

        return [
            Invariant("head-crash-durability", durability,
                      description="acked table rows survive the crash"),
            Invariant("head-crash-no-resurrection", no_resurrection,
                      description="window-riding writes stay dead"),
            Invariant("head-crash-node-converges", reregistered,
                      description="the live node re-registers through "
                                  "report-returns-False, no driver "
                                  "intervention"),
        ]

    def teardown(self) -> None:
        from ray_tpu._private.config import ray_config

        ray_config.health_check_period_s = self._saved_period
        try:
            self.head.stop()
        except Exception:
            pass
        try:
            if not self.crashed:
                self.store.close()
        except Exception:
            pass
        for suffix in ("", "-wal", "-shm"):
            try:
                os.unlink(self.path + suffix)
            except OSError:
                pass


SCENARIOS = {
    cls.name: cls
    for cls in (RouterCapScenario, PipelinedCloseScenario,
                GroupCommitDurabilityScenario, CrossShardScenario,
                ExactlyOnceResubmitScenario, LongPollRecoveryScenario,
                SpillRaceScenario, LineageReconstructionScenario,
                ActorRestartScenario, HeadCrashRecoveryScenario,
                QuotaAdmissionScenario, DepSweepScenario,
                ReplicaDirectScenario, KvCacheReuseScenario)
}

# The bounded tier-1 leg: real code, small configs, exhaustive where
# the scenario supports it (see test_raymc_ci_leg.py).
# dep_sweep and quota_admission run FIRST: they are the scenarios that
# never need the ray_tpu runtime, and explorer executions are an order
# of magnitude cheaper before a needs_ray scenario brings the runtime
# (and its background threads, which every quiescence settle must
# scan) up for the rest of the leg (run order matters — cheap
# scenarios first).
DEFAULT_SCENARIOS = ("dep_sweep", "kv_cache_reuse", "quota_admission",
                     "cross_shard", "replica_direct", "router_cap",
                     "gcs_durability", "pipelined_close", "spill_race",
                     "lineage_reconstruction", "actor_restart",
                     "head_crash_recovery")
