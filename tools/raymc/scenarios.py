"""raymc built-in scenarios: the checked protocol property catalog.

Each scenario drives REAL product objects; fakes are limited to the
environment around them (a replica that records dispatches, a
controller handle that dies on demand) — the same stand-ins the
concurrency regression tests use. Properties:

================== ==========================================================
scenario           property
================== ==========================================================
router_cap         a replica never holds more outstanding dispatches than
                   ``max_concurrent_queries`` (reserved-slot handoff)
pipelined_close    a clean ``PipelinedClient.close(flush_timeout=...)``
                   never orphan-sweeps an about-to-be-acked request
gcs_durability     sqlite group commit: acked (flushed) writes survive a
                   crash at either commit boundary; writes no COMMIT ever
                   covered never resurrect after restart
exactly_once       a submit frame resubmitted under its rid after a
                   connection death executes exactly once (server dedupe)
longpoll_recovery  long-poll membership converges after a controller
                   kill/restart with listeners parked mid-poll
================== ==========================================================
"""

from __future__ import annotations

import os
import socket
import tempfile
import threading
import time
from types import SimpleNamespace
from typing import List, Tuple

from ray_tpu._private import sanitize_hooks

from tools.raymc.props import Invariant, Liveness
from tools.raymc.scenario import Scenario


# -- shared fakes ------------------------------------------------------------


class _FakeMethod:
    def __init__(self, fn):
        self._fn = fn

    def remote(self, *args, **kwargs):
        return self._fn(*args, **kwargs)


class _Replica:
    """Hashable (router keys replicas into dicts) dispatch recorder."""

    def __init__(self, fn):
        self.handle_request = _FakeMethod(fn)


class _FakeController:
    """Enough controller surface for a Router: metrics reports are
    swallowed, long-poll listens fail fast (no membership churn in the
    scenario — the replica set is pinned at setup)."""

    def __init__(self):
        self.listen = _FakeMethod(self._listen)
        self.record_handle_metrics = _FakeMethod(lambda dep, total: None)

    def _listen(self, *a, **k):
        raise RuntimeError("no controller in this scenario")


def _pending_ref():
    """An ObjectRef that never resolves, so dispatched requests stay
    in-flight for the whole execution and an oversubscription cannot
    self-heal before the invariant looks."""
    from ray_tpu._private.ids import ObjectID
    from ray_tpu.object_ref import ObjectRef

    return ObjectRef(ObjectID.from_random(), _register=False)


# -- router reserved-slot cap ------------------------------------------------


class RouterCapScenario(Scenario):
    name = "router_cap"
    description = ("concurrent dispatchers against a cap-1 replica: "
                   "outstanding dispatches never exceed the cap")
    points = ("router.handoff", "router.buggy_gap")
    max_steps = 16
    needs_ray = True

    def __init__(self, dispatchers: int = 2, cap: int = 1):
        self.n_dispatchers = dispatchers
        self.cap = cap

    def setup(self) -> None:
        from ray_tpu.serve._private.router import Router

        self.dispatched = 0
        self._dlock = threading.Lock()

        def handle(method, args, kwargs):
            with self._dlock:
                self.dispatched += 1
            return _pending_ref()

        self.replica = _Replica(handle)
        self.router = Router(_FakeController(), "dep",
                             max_concurrent_queries=self.cap)
        self.router._update_replicas([self.replica])
        self.results: List = []

    def actions(self):
        def dispatch():
            self.results.append(
                self.router.try_assign_request("__call__", (), {}))
        return [(f"dispatch-{chr(ord('a') + i)}", dispatch)
                for i in range(self.n_dispatchers)]

    def invariants(self):
        return [Invariant(
            "router-cap",
            lambda s: (s.dispatched <= s.cap
                       or f"{s.dispatched} requests dispatched to a "
                          f"cap-{s.cap} replica"),
            description="per-replica in-flight cap holds mid-handoff")]

    def teardown(self) -> None:
        self.router.shutdown()


# -- pipelined close vs reader sweep ----------------------------------------


class PipelinedCloseScenario(Scenario):
    name = "pipelined_close"
    description = ("clean close with an in-flight, about-to-be-acked "
                   "request: the reader must never orphan-sweep it")
    points = ("rpc.pipeline.reader_edge", "rpc.pipeline.reply_handled",
              "rpc.pipeline.closed_set")
    max_steps = 24
    block_grace_s = 0.04

    def setup(self) -> None:
        from ray_tpu._private.rpc import PipelinedClient, RpcServer

        self.release = threading.Event()
        self.errors: List[Tuple] = []

        def fast(**kwargs):
            return "ok"

        def slow(**kwargs):
            self.release.wait(5.0)
            return "ok"

        self.server = RpcServer({"fast": fast, "slow": slow})
        self.client = PipelinedClient(
            self.server.address,
            on_error=lambda tag, msg, rid, lost: self.errors.append(
                (tag, lost)))

    def actions(self):
        def driver():
            self.client.send("fast", tag="req1")
            self.client.flush(3.0)
            self.client.send("slow", tag="req2")
            self.release.set()  # the peer acks while close() flushes
            self.client.close(flush_timeout=3.0)
        return [("driver", driver)]

    def invariants(self):
        return [Invariant(
            "close-no-orphan",
            lambda s: (not s.errors
                       or f"clean close produced orphan errors: "
                          f"{s.errors}"),
            description="close(flush_timeout) never sweeps an "
                        "about-to-be-acked request into the orphan "
                        "path")]

    def liveness(self):
        return [Liveness(
            "close-acks-all",
            lambda s: s.client._acked == 2, timeout_s=3.0,
            description="both requests acknowledged by close")]

    def teardown(self) -> None:
        self.release.set()
        try:
            self.client.close()
        except Exception:
            pass
        self.server.shutdown()


# -- sqlite group-commit durability under crash ------------------------------


class GroupCommitDurabilityScenario(Scenario):
    name = "gcs_durability"
    description = ("writers vs group commit vs injected crash: acked "
                   "writes survive, uncommitted writes never resurrect")
    points = ("gcs.put",)
    crash_points = ("gcs.commit.before", "gcs.commit.after")
    crash_budget = 1
    max_steps = 24
    # Writers block on the store lock whenever the committer is parked
    # inside the commit window — a certain, immediate block, so a
    # short grace keeps per-step cost down.
    block_grace_s = 0.02

    def __init__(self, writers: int = 1):
        # One writer is the exhaustive small scope (the property is
        # about put-vs-commit-vs-crash ordering, which one writer
        # fully exercises across the two commit windows); more writers
        # widen coverage but grow the space factorially — use bounded
        # budgets there.
        self.n_writers = writers

    def setup(self) -> None:
        from ray_tpu._private.gcs_storage import SqliteStoreClient

        fd, self.path = tempfile.mkstemp(prefix="raymc-gcs-",
                                         suffix=".db")
        os.close(fd)
        os.unlink(self.path)
        # Group-commit mode WITHOUT the background flusher: construct
        # synchronous (interval 0 starts no thread), then widen the
        # interval so puts defer their COMMIT to the scenario's
        # explicit committer action — the checker owns every commit
        # boundary instead of racing a timer.
        self.store = SqliteStoreClient(self.path, commit_interval_s=0)
        self.store._interval = 3600.0
        self.accepted: List[bytes] = []
        self.acked: set = set()
        self.durable: set = set()
        self.present: set = set()
        self.crashed: str = ""

    def actions(self):
        def writer(key):
            def body():
                try:
                    self.store.put("t", key, b"v")
                except Exception:
                    return  # store died under us: the write never took
                self.accepted.append(key)
            return body

        def committer():
            # TWO commit windows: a crash inside the first flush can
            # only lose never-acked writes (vacuous for durability —
            # flush() hasn't returned, nothing was promised). The
            # placement that bites is a death AFTER a completed, acked
            # flush: the second window provides it, with writers free
            # to interleave around both.
            for window in range(2):
                snap = list(self.accepted)
                self.store.flush()
                self.acked.update(snap)
                if window == 0:
                    # Sync gate OUTSIDE the store lock: without it the
                    # committer can barge straight from window 1 into
                    # window 2's lock hold, and whether a lock-blocked
                    # writer squeezes through between the windows is OS
                    # lock-queue luck — exactly the sub-yield-point
                    # nondeterminism that makes explorations diverge.
                    # Parked here, the lock handoff is a decision.
                    sanitize_hooks.sched_point("mc.sync.commit_gap")

        acts = [(f"writer-{chr(ord('a') + i)}",
                 writer(b"k%d" % i)) for i in range(self.n_writers)]
        acts.append(("committer", committer))
        return acts

    def independent(self, a, b) -> bool:
        # Scenario-specific structure that makes the two-writer config
        # tractable to exhaust (argued from the code, not vibes):
        # - two writers' puts commute: each writes its OWN key;
        # - a writer's start transition is PURE — the segment between
        #   its start gate and its put gate executes nothing (the
        #   gcs.put crossing is the first statement of put()) — so it
        #   commutes with every other thread's transition. The
        #   committer's start is NOT pure (it snapshots `accepted`)
        #   and keeps full conflicts.
        if a[0] == b[0] or a[3] or b[3]:
            return False
        if a[1] == "gcs.put" and b[1] == "gcs.put":
            return True
        if a[1].startswith("mc.start.writer") \
                or b[1].startswith("mc.start.writer"):
            return True
        return super().independent(a, b)

    def on_point(self, point: str, role: str) -> None:
        if point == "gcs.commit.after":
            # Crossed INSIDE the store lock right after COMMIT: exactly
            # the accepted-so-far writes are durable now (a writer
            # mid-put is blocked on the same lock and not yet in
            # `accepted`).
            self.durable.update(self.accepted)

    def on_crash(self, point: str) -> None:
        from ray_tpu._private.gcs_storage import SqliteStoreClient

        try:
            # Process death: the connection drops with the pending
            # transaction uncommitted (sqlite rolls it back). UNDER the
            # store lock: closing a sqlite connection while another
            # thread is inside conn.execute() on it is a C-level
            # use-after-free (segfaulted under full-suite load when a
            # lock-blocked writer woke the instant the crashing flush
            # released the lock). The lock sequences the close after
            # any in-flight statement; later puts hit a clean
            # ProgrammingError on the closed connection, which the
            # writer action treats as "the store died under us".
            with self.store._lock:
                self.store._conn.close()
        except Exception:
            pass
        survivor = SqliteStoreClient(self.path, commit_interval_s=0)
        try:
            self.present = {k for k, _ in survivor.get_all("t")}
        finally:
            survivor.close()
        self.crashed = point  # LAST: invariants key off it

    def invariants(self):
        def durability(s):
            if not s.crashed:
                return True
            lost = s.acked - s.present
            return (not lost
                    or f"acked writes lost across crash at "
                       f"{s.crashed}: {sorted(lost)}")

        def no_resurrection(s):
            if not s.crashed:
                return True
            ghosts = s.present - s.durable
            return (not ghosts
                    or f"uncommitted writes resurrected after crash "
                       f"at {s.crashed}: {sorted(ghosts)}")

        return [
            Invariant("gcs-durability", durability,
                      description="flushed writes survive crash"),
            Invariant("gcs-no-resurrection", no_resurrection,
                      description="unflushed writes stay dead"),
        ]

    def teardown(self) -> None:
        try:
            if not self.crashed:
                self.store.close()
        except Exception:
            pass
        for suffix in ("", "-wal", "-shm"):
            try:
                os.unlink(self.path + suffix)
            except OSError:
                pass


# -- exactly-once resubmit across connection death ---------------------------


class ExactlyOnceResubmitScenario(Scenario):
    name = "exactly_once"
    description = ("connection killed around a submit frame: the rid "
                   "resubmit (cluster_utils lost-frame path) executes "
                   "the frame exactly once")
    points = ("rpc.pipeline.send", "rpc.pipeline.reader_edge",
              "rpc.server.dispatch", "rpc.server.reply")
    crash_points = ("mc.env.conn_kill",)
    crash_budget = 1
    max_steps = 24
    block_grace_s = 0.04

    def setup(self) -> None:
        from ray_tpu._private.rpc import PipelinedClient, RpcServer

        self.executed = {}
        self._xlock = threading.Lock()
        self.resubmits = 0
        self.tids = ["t1"]
        self.server = RpcServer({"apply": self._apply},
                                dedupe_methods=frozenset({"apply"}))
        self.client = PipelinedClient(self.server.address,
                                      on_error=self._pipe_error)

    def _apply(self, task_ids=()):
        with self._xlock:
            for t in task_ids:
                self.executed[t] = self.executed.get(t, 0) + 1
        return True

    def _pipe_error(self, tag, message, rid, lost):
        """The driver-side recovery contract, verbatim from
        ``cluster_utils._batch_pipe_error``'s lost branch: a frame that
        died un-acked is resubmitted under the SAME request id so the
        node's dedupe cache makes it exactly-once."""
        if not lost:
            return
        from ray_tpu._private.rpc import RpcClient

        self.resubmits += 1
        try:
            RpcClient.to(self.server.address).call_with_rid(
                rid, "apply", task_ids=self.tids)
        except Exception:
            pass  # node truly dead → the death-sweep path owns recovery

    def actions(self):
        def driver():
            self.rid = self.client.send("apply", tag="frame",
                                        task_ids=self.tids)
            # The injected fault: the checker may kill the submit
            # connection at any point relative to the server's
            # dispatch/reply and the reader's drain.
            sanitize_hooks.crash_point("mc.env.conn_kill")

        def awaiter():
            # Keeps the execution (and so the explorer's control over
            # server/reader crossings) alive until the protocol
            # settles; must finish well inside the explorer's
            # blocked-threads grace (_wait_for_park) so a settled-but-
            # polling awaiter is never mistaken for a deadlock.
            deadline = time.monotonic() + 2.5
            while time.monotonic() < deadline:
                with self._xlock:
                    done = self.executed.get("t1", 0) >= 1
                if done and self.client.in_flight == 0:
                    return
                time.sleep(0.01)

        return [("driver", driver), ("awaiter", awaiter)]

    def on_crash(self, point: str) -> None:
        sock = self.client._sock
        if sock is not None:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass

    def invariants(self):
        return [Invariant(
            "exactly-once",
            lambda s: (s.executed.get("t1", 0) <= 1
                       or f"frame executed "
                          f"{s.executed['t1']} times"),
            description="a resubmitted frame never double-executes")]

    def liveness(self):
        return [Liveness(
            "frame-executes",
            lambda s: s.executed.get("t1", 0) == 1, timeout_s=4.0,
            description="the frame executes despite the kill")]

    def teardown(self) -> None:
        from ray_tpu._private.rpc import RpcClient

        try:
            self.client.close()
        except Exception:
            pass
        self.server.shutdown()
        addr = tuple(self.server.address)
        with RpcClient._pools_lock:
            pooled = RpcClient._pools.pop(addr, None)
        if pooled is not None:
            pooled.close()


# -- long-poll convergence across controller restart -------------------------


class LongPollRecoveryScenario(Scenario):
    name = "longpoll_recovery"
    description = ("controller killed with a listener parked mid-poll: "
                   "membership converges after the restart")
    points = ("longpoll.listen", "longpoll.notify",
              "longpoll.client.loop")
    crash_points = ("mc.env.controller_kill",)
    crash_budget = 1
    # The product client polls in an unbounded loop, so executions
    # truncate at the step bound by design: this scenario is a bounded
    # heuristic check, never an exhaustive one.
    max_steps = 18
    needs_ray = True
    block_grace_s = 0.06

    def setup(self) -> None:
        from ray_tpu.serve._private.long_poll import (LongPollClient,
                                                      LongPollHost)

        self.key = "replicas::dep"
        self.gen = 0
        self.host = LongPollHost()
        self.host.notify_changed(self.key, ("r1",))
        self.observed: List = []
        self.client = LongPollClient(
            self._make_handle(), self.key,
            lambda snap: self.observed.append(tuple(snap or ())),
            reresolve=self._make_handle)

    def _make_handle(self):
        """A controller handle bound to the CURRENT incarnation: calls
        against a superseded one raise ActorDiedError, exactly like a
        handle to a killed actor."""
        import ray_tpu
        from ray_tpu.exceptions import ActorDiedError

        scenario = self
        gen = self.gen

        def listen(key, known):
            if scenario.gen != gen:
                raise ActorDiedError("controller incarnation "
                                     f"{gen} is dead")
            result = scenario.host.listen(key, known, timeout=0.4)
            if scenario.gen != gen:
                # Died while we were parked: the poisoned answer of a
                # dead controller surfaces as the actor-death the real
                # transport would raise.
                raise ActorDiedError("controller died mid-listen")
            return ray_tpu.put(result)

        return SimpleNamespace(listen=_FakeMethod(listen))

    def actions(self):
        def env():
            self.host.notify_changed(self.key, ("r1", "r2"))
            sanitize_hooks.crash_point("mc.env.controller_kill")
        return [("env", env)]

    def on_crash(self, point: str) -> None:
        from ray_tpu.serve._private.long_poll import LongPollHost

        old = self.host
        replacement = LongPollHost()
        # The recovered controller re-broadcasts its checkpointed
        # state; clients resume from version -1 via reresolve.
        replacement.notify_changed(self.key, ("r1", "r2"))
        self.gen += 1
        self.host = replacement
        old.shutdown()  # poison: parked listeners wake NOW

    def invariants(self):
        valid = {("r1",), ("r1", "r2")}
        return [Invariant(
            "membership-sane",
            lambda s: (all(o in valid for o in s.observed)
                       or f"client observed garbage membership: "
                          f"{s.observed}"),
            description="observed snapshots are real memberships")]

    def liveness(self):
        return [Liveness(
            "membership-converges",
            lambda s: bool(s.observed)
            and s.observed[-1] == ("r1", "r2"),
            timeout_s=5.0,
            description="client converges to the post-restart "
                        "membership")]

    def teardown(self) -> None:
        self.client.stop()
        self.host.shutdown()
        self.client._thread.join(2.0)


# -- spill pipeline vs ref release vs restore --------------------------------


class SpillRaceScenario(Scenario):
    name = "spill_race"
    description = ("disk spill racing ref release and transparent "
                   "restore: an acked object is never lost, a freed "
                   "object never resurrects")
    points = ("spill.mark", "spill.restore")
    crash_points = ("spill.write.after",)
    crash_budget = 1
    max_steps = 24
    # Exhaustive sweep of this space is ~1.5k schedules (≈2s): above
    # the CLI default cap, well inside the tier-1 wall budget.
    max_schedules = 2500
    block_grace_s = 0.04

    def setup(self) -> None:
        from ray_tpu._private.config import ray_config
        from ray_tpu._private.ids import ObjectID
        from ray_tpu._private.memory_store import MemoryStore
        from ray_tpu._private.spilling import SpillManager

        # Small objects must be spill-eligible for the race to be
        # reachable at model-checking scale; restored in teardown.
        self._saved_min = ray_config.min_spilling_size_bytes
        ray_config.min_spilling_size_bytes = 1
        self.store = MemoryStore()
        # Seed while the budget is huge (no spill during setup) …
        self.manager = self.store.spill_manager = SpillManager(
            self.store, budget_bytes=10 ** 12)
        self.a_oid = ObjectID.from_random()
        self.b_oid = ObjectID.from_random()
        self.a_value = b"A" * 4096
        self.store.put(self.a_oid, self.a_value)
        self.store.put(self.b_oid, b"B" * 4096)
        # … then shrink it so the spiller action must sweep both.
        self.manager.budget = 1
        self.b_freed = False
        self.crashed = None
        self.spill_done = False
        self.a_reads: List = []

    def actions(self):
        def spiller():
            self.manager.maybe_spill()
            self.spill_done = True

        def releaser():
            self.store.free([self.b_oid])
            self.b_freed = True

        def reader():
            ready, value, error = self.store.peek(self.a_oid)
            self.a_reads.append((ready, bytes(value) if value else None,
                                 error))

        return [("spiller", spiller), ("releaser", releaser),
                ("reader", reader)]

    def _spill_path(self, url) -> str:
        return url[len("file://"):] if url else ""

    def invariants(self):
        def a_never_lost(s):
            entry = s.store._entries.get(s.a_oid)
            if entry is None or not entry.ready or entry.error is not None:
                return "acked object A lost its store entry"
            if entry.value is not None:
                return True
            path = s._spill_path(entry.spilled_url)
            return (path and os.path.exists(path)) or \
                "A is value-less with no durable spilled copy"

        def b_never_resurrects(s):
            if not s.b_freed:
                return True
            entry = s.store._entries.get(s.b_oid)
            if entry is None or entry.error is None or \
                    entry.value is not None:
                return "freed object B resurrected with a live value"
            if entry.spilled_url is not None:
                return ("freed object B still carries a restorable "
                        f"spill URL: {entry.spilled_url}")
            if s.crashed or not s.spill_done:
                # A crashed spiller may orphan its in-flight file —
                # disk garbage a dead process's storage dir reclaims,
                # unreachable by any entry; and a mid-sweep file (write
                # done, mark/delete pending) is legal in-flight state.
                return True
            # Once the sweep completed crash-free, the mark-fails→
            # delete path must have left no ghost copy behind (spill
            # files are <oid.hex()>-<token>, unique per write).
            try:
                ghosts = [n for n in os.listdir(
                    s.manager.storage.directory)
                    if n.startswith(s.b_oid.hex())]
            except OSError:
                ghosts = []
            return (not ghosts) or \
                f"freed object B left readable spill ghost(s): {ghosts}"

        return [
            Invariant("spill-no-loss", a_never_lost,
                      description="an acked object survives spill/"
                                  "restore/crash interleavings"),
            Invariant("spill-no-resurrection", b_never_resurrects,
                      description="a freed object never comes back"),
        ]

    def liveness(self):
        def a_reads_correct(s):
            # The reader ran to completion in every non-crashed
            # execution; whatever it observed must be A's real bytes.
            return all(ready and err is None and value == s.a_value
                       for ready, value, err in s.a_reads)

        return [Liveness("reader-sees-acked-value", a_reads_correct,
                         timeout_s=1.0,
                         description="peek(A) returns the acked bytes "
                                     "through any spill state")]

    def on_crash(self, point: str) -> None:
        self.crashed = point  # the spiller thread dies; nothing to kill

    def teardown(self) -> None:
        from ray_tpu._private.config import ray_config

        ray_config.min_spilling_size_bytes = self._saved_min
        try:
            self.manager.storage.destroy()
        except Exception:
            pass


SCENARIOS = {
    cls.name: cls
    for cls in (RouterCapScenario, PipelinedCloseScenario,
                GroupCommitDurabilityScenario,
                ExactlyOnceResubmitScenario, LongPollRecoveryScenario,
                SpillRaceScenario)
}

# The bounded tier-1 leg: real code, small configs, exhaustive where
# the scenario supports it (see test_raymc_ci_leg.py).
DEFAULT_SCENARIOS = ("router_cap", "gcs_durability", "pipelined_close",
                     "spill_race")
