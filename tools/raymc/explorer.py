"""raymc explorer: stateless bounded model checking over real threads.

The approach is CHESS-style systematic concurrency testing: one
execution at a time, the scenario's threads run REAL product code but
are serialized at ``sanitize_hooks`` yield points — every controlled
thread is either parked at a point, provably finished, or (after a
grace window) blocked on real synchronization. At each quiescent state
the explorer evaluates the scenario's invariants, then picks ONE parked
crossing to proceed (optionally injecting a :class:`SimulatedCrash` at
a crash-capable point), records the decision, and waits for the system
to quiesce again.

Exploration is stateless DFS over decision prefixes: the first
execution runs under a deterministic default policy; every step's
unchosen enabled crossings become backtrack prefixes (replayed
decision-for-decision, then default policy again). Sleep sets prune
commuting reorderings: an alternative independent of everything
explored from the same state is skipped (independence = different
thread AND different conflict domains per ``Scenario.conflict_key``,
never across a crash decision) — the classic partial-order reduction,
applied with a deliberately conservative relation.

An execution ends when every action thread finished (internal runtime
threads the scenario adopted mid-run are then released to free-run),
when an invariant breaks, when the step bound trips (the tail
free-runs; the check is marked non-exhaustive), or when nothing can
move (deadlock — itself a finding). A drained DFS stack with no
truncations/divergences means the property held over EVERY bounded
interleaving and crash placement: ``CheckResult.exhausted``.
"""

from __future__ import annotations

import dataclasses
import re
import threading
import time
from typing import Dict, List, Optional, Tuple

from ray_tpu._private import sanitize_hooks

from tools.raymc.scenario import DONE_POINT_PREFIX, Scenario

# Thread states.
RUNNING = "running"    # granted (or just started): owns the CPU turn
PARKED = "parked"      # waiting at a yield-point gate for a grant
BLOCKED = "blocked"    # ran past the grace window without parking:
#                        stuck on real synchronization; re-enters via
#                        its next crossing or termination
DONE = "done"


class Decision(tuple):
    """One scheduling choice: (role, point, role_occ, crash)."""

    __slots__ = ()

    def __new__(cls, role: str, point: str, role_occ: int, crash: bool):
        return super().__new__(cls, (role, point, role_occ, crash))

    role = property(lambda self: self[0])
    point = property(lambda self: self[1])
    role_occ = property(lambda self: self[2])
    crash = property(lambda self: self[3])

    def to_dict(self) -> dict:
        return {"role": self[0], "point": self[1],
                "occurrence": self[2], "crash": self[3]}

    @classmethod
    def from_dict(cls, data: dict) -> "Decision":
        return cls(data["role"], data["point"], data["occurrence"],
                   data["crash"])

    def render(self) -> str:
        tag = " CRASH" if self[3] else ""
        return f"{self[1]}@{self[0]}#{self[2]}{tag}"


@dataclasses.dataclass
class _Cross:
    """A completed (granted) crossing, in completion order.

    ``order_key`` is the crossing's position in the replay script's
    timeline: normally the grant event (segments begin at grants), but
    a done gate carries no post-segment — its only meaning is "this
    thread's final segment has completed", which happened at its
    ARRIVAL — so done gates are keyed by arrival instead. That is what
    lets an emitted script order e.g. a writer's post-put bookkeeping
    STRICTLY before a committer's snapshot (the committer's next entry
    gates behind the done crossing)."""

    point: str
    role: str
    global_occ: int
    role_occ: int
    crashed: bool
    action_role: bool
    order_key: int = 0


@dataclasses.dataclass
class _Step:
    chosen: Decision
    enabled: Tuple[Decision, ...]


class _ThreadInfo:
    __slots__ = ("role", "state", "action_role", "granted_at",
                 "parked", "grant", "arrival_seq")

    def __init__(self, role: str, action_role: bool):
        self.role = role
        self.action_role = action_role
        self.state = RUNNING
        self.granted_at = time.monotonic()
        self.parked: Optional[Tuple[str, int, int]] = None  # point, gocc, rocc
        self.grant: Optional[Decision] = None
        self.arrival_seq = 0


@dataclasses.dataclass
class ExecutionResult:
    status: str                      # ok|violation|deadlock|divergence|timeout
    steps: List[_Step]
    crossings: List[_Cross]
    pending: List[_Cross]            # parked at end, reverse-arrival order
    violations: List[str]            # "prop: detail"
    truncated: bool = False
    errors: List[str] = dataclasses.field(default_factory=list)
    sleep_leaves: int = 0
    conformance_checks: int = 0      # rayspec refinement checks run
    # Every point NAME this execution crossed, recorded before the
    # scenario-relevance filter: the raw material for the seam-coverage
    # audit (a SCHED/CRASH point no scenario ever crosses is a seam
    # the model checker never exercises).
    points_seen: List[str] = dataclasses.field(default_factory=list)


class ExplorerConfig:
    def __init__(self, max_schedules: int = 500, max_steps: int = 0,
                 time_budget_s: float = 60.0, crash_budget: int = -1,
                 dpor: bool = True, minimize: bool = True,
                 verify_replays: bool = True,
                 exec_timeout_s: float = 15.0,
                 settle_s: float = 0.008,
                 stop_on_first: bool = True):
        self.max_schedules = max_schedules
        self.max_steps = max_steps          # 0 = scenario's own bound
        self.time_budget_s = time_budget_s
        self.crash_budget = crash_budget    # -1 = scenario's own budget
        self.dpor = dpor
        self.minimize = minimize
        self.verify_replays = verify_replays
        self.exec_timeout_s = exec_timeout_s
        self.settle_s = settle_s
        self.stop_on_first = stop_on_first


class Execution:
    """One bounded execution of a scenario under a decision prefix."""

    def __init__(self, scenario: Scenario, prefix: List[Decision],
                 cfg: ExplorerConfig, sleep=frozenset()):
        self.scn = scenario
        self.prefix = prefix
        self.cfg = cfg
        # Sleep set at the state the prefix reaches: transitions whose
        # subtrees are fully explored elsewhere. The default policy
        # must never CHOOSE one (classic sleep-set blocking) — doing so
        # would re-explore a covered subtree and report zero pruning.
        self._sleep = set(sleep)
        self.sleep_leaves = 0
        self.max_steps = cfg.max_steps or scenario.max_steps
        self.crash_budget = scenario.crash_budget \
            if cfg.crash_budget < 0 else cfg.crash_budget
        self.grace = scenario.block_grace_s
        self._points = set(scenario.points)
        self._crash_points = set(scenario.crash_points)
        self._lock = threading.Condition()
        self._threads: Dict[int, _ThreadInfo] = {}
        self._action_roles: set = set()
        self._adopt_counts: Dict[str, int] = {}
        self._gcounts: Dict[str, int] = {}
        self._rcounts: Dict[Tuple[str, str], int] = {}
        self._released = False
        self._arrivals = 0
        self._crashes_used = 0
        self._crossings: List[_Cross] = []
        self._points_seen: set = set()
        self._steps: List[_Step] = []
        self._errors: List[str] = []
        self._action_threads: List[threading.Thread] = []
        self._last_grant: Dict[str, int] = {}
        self._truncated = False
        self._controller_ident: Optional[int] = None
        # rayspec conformance mode: bindings declared by the scenario,
        # a per-execution history recorder, and the check counter.
        self._conf_bindings = scenario.conformance()
        self._recorder = None
        self._conf_sessions: Optional[dict] = None
        self._conf_checks = 0

    # -- the installed yield/crash hook ------------------------------------

    def _relevant(self, name: str) -> bool:
        # Any "mc."-prefixed point is scenario-harness territory and
        # always gated: the start/done brackets, env crash points, and
        # ad-hoc sync gates scenarios add to pin lock handoffs that
        # would otherwise be sub-yield-point nondeterminism.
        return (name in self._points or name in self._crash_points
                or name.startswith("mc."))

    def _hook(self, name: str) -> None:
        self._points_seen.add(name)
        if not self._relevant(name):
            return
        ident = threading.get_ident()
        if ident == self._controller_ident:
            # The controller must never gate itself (an invariant
            # predicate or scenario bookkeeping touching product code
            # would deadlock the whole execution).
            return
        with self._lock:
            if self._released:
                return
            info = self._threads.get(ident)
            if info is None or info.state is DONE:
                # DONE + crossing again = the OS reused a finished
                # thread's ident for a fresh runtime-internal thread;
                # resurrecting the dead record would corrupt both
                # threads' scheduling state.
                info = self._adopt(ident)
            gocc = self._gcounts.get(name, 0) + 1
            self._gcounts[name] = gocc
            rocc = self._rcounts.get((name, info.role), 0) + 1
            self._rcounts[(name, info.role)] = rocc
            self._arrivals += 1
            arrival = self._arrivals
            info.parked = (name, gocc, rocc)
            info.arrival_seq = arrival
            info.state = PARKED
            self._lock.notify_all()
            while info.grant is None and not self._released:
                self._lock.wait(0.2)
            grant = info.grant
            info.grant = None
            info.parked = None
            if info.state is not DONE:
                info.state = RUNNING
                info.granted_at = time.monotonic()
            crashed = bool(grant is not None and grant.crash)
            if grant is not None:
                # Released-without-grant passes (teardown free-run) are
                # not part of the explored interleaving: keep them out
                # of the crossing log the replay script is built from.
                self._arrivals += 1  # the grant is a timeline event too
                key = arrival if name.startswith(DONE_POINT_PREFIX) \
                    else self._arrivals
                self._crossings.append(_Cross(
                    name, info.role, gocc, rocc, crashed,
                    info.role in self._action_roles, order_key=key))
            self._lock.notify_all()
        try:
            self.scn.on_point(name, info.role)
        except Exception as e:
            self._errors.append(f"on_point({name}) raised: {e!r}")
        if crashed:
            raise sanitize_hooks.SimulatedCrash(name)

    def _adopt(self, ident: int) -> _ThreadInfo:
        """A runtime-internal thread (pipelined reader, batcher
        flusher) crossed a relevant point: bring it under scheduling
        control with a run-stable role name (thread names carry ports
        and ids — strip digits, count per base)."""
        tname = threading.current_thread().name
        base = re.sub(r"[\s(].*$", "", tname)
        base = re.sub(r"[-_]?\d+$", "", base) or "thread"
        n = self._adopt_counts.get(base, 0)
        self._adopt_counts[base] = n + 1
        role = base if n == 0 else f"{base}~{n}"
        info = _ThreadInfo(role, action_role=False)
        self._threads[ident] = info
        return info

    # -- controlled action threads -----------------------------------------

    def _wrap(self, role: str, fn) -> threading.Thread:
        def run():
            ident = threading.get_ident()
            with self._lock:
                info = _ThreadInfo(role, action_role=True)
                self._threads[ident] = info
            crashed = False
            try:
                self._hook(self.scn.start_point(role))
                fn()
            except sanitize_hooks.SimulatedCrash as e:
                crashed = True
                try:
                    self.scn.on_crash(e.point)
                except Exception as e2:
                    self._errors.append(
                        f"on_crash({e.point}) raised: {e2!r}")
            except Exception as e:
                self._errors.append(f"action {role!r} raised: {e!r}")
            try:
                if not crashed:
                    # The done gate: keeps the segment after this
                    # action's last product crossing schedulable (and
                    # so replayable — see scenario.py).
                    self._hook(self.scn.done_point(role))
            finally:
                with self._lock:
                    info.state = DONE
                    info.parked = None
                    self._lock.notify_all()
        return threading.Thread(target=run, name=f"mc-{role}",
                                daemon=True)

    # -- controller --------------------------------------------------------

    def run(self) -> ExecutionResult:
        self._controller_ident = threading.get_ident()
        prev_sched = sanitize_hooks._sched_point
        prev_crash = sanitize_hooks._crash_point
        if self._conf_bindings:
            # Conformance mode: record the cores' spec-op history for
            # this whole execution — INCLUDING setup (the history must
            # account for every op that shaped the core's state, and
            # setup's seeding ops are part of that account even though
            # they are "before time zero" for interleaving purposes).
            from tools.rayspec.history import Recorder

            self._recorder = Recorder(max_events=100_000)
            self._recorder.__enter__()
        # Setup runs BEFORE the hooks go in: it is "before time zero",
        # and its crossings (initial broadcasts, warmup writes) are not
        # part of the explored interleaving. Runtime-internal threads
        # it spawns get adopted at their first post-install crossing.
        self.scn.setup()
        sanitize_hooks.install_sched_point(self._hook)
        sanitize_hooks.install_crash_point(self._hook)
        try:
            for role, fn in self.scn.actions():
                self._action_roles.add(role)
                self._action_threads.append(self._wrap(role, fn))
            for t in self._action_threads:
                t.start()
            status, violations = self._control_loop()
            pending = self._pending_snapshot()
            self._release_all()
            for t in self._action_threads:
                t.join(3.0)
            if any(t.is_alive() for t in self._action_threads):
                status = "timeout"
                self._errors.append("action threads outlived release")
            if status == "ok":
                # End-state pass: invariants again (the last transition
                # may have broken one) plus bounded liveness, plus the
                # rayspec refinement check against the final state.
                violations = self.scn.violations(include_liveness=True)
                if not violations:
                    violations = self._conformance_violations()
                if violations:
                    status = "violation"
            return ExecutionResult(
                status=status, steps=self._steps,
                crossings=self._crossings, pending=pending,
                violations=violations, truncated=self._truncated,
                errors=self._errors, sleep_leaves=self.sleep_leaves,
                conformance_checks=self._conf_checks,
                points_seen=sorted(self._points_seen))
        finally:
            sanitize_hooks.install_sched_point(prev_sched)
            sanitize_hooks.install_crash_point(prev_crash)
            try:
                self.scn.teardown()
            except Exception as e:
                self._errors.append(f"teardown raised: {e!r}")
            if self._recorder is not None:
                self._recorder.__exit__()
                self._recorder = None

    def _control_loop(self) -> Tuple[str, List[str]]:
        deadline = time.monotonic() + self.cfg.exec_timeout_s
        step = 0
        while True:
            if not self._wait_quiescent(deadline):
                return "timeout", []
            violations = self.scn.violations(include_liveness=False)
            if not violations:
                # Conformance mode: every quiescent state is also a
                # refinement check — the live cores' states must be
                # reachable by some linearization of the recorded
                # history so far.
                violations = self._conformance_violations()
            if violations:
                return "violation", violations
            with self._lock:
                actions_live = any(
                    i.action_role and i.state is not DONE
                    for i in self._threads.values())
                parked = self._parked_infos()
            if not actions_live:
                return "ok", []
            if not parked:
                # Everything live is blocked on real synchronization.
                # Give timed product waits a chance to expire, then
                # call it a deadlock (which IS a finding).
                if self._wait_for_park(deadline):
                    continue
                return "deadlock", []
            if step >= self.max_steps:
                self._truncated = True
                return "ok", []
            decision, diverged = self._decide(step, parked, deadline)
            if diverged:
                return "divergence", []
            if decision is None:
                # Every enabled transition is asleep: this whole
                # subtree is covered by branches explored earlier.
                # Free-run the tail (the end state is still real and
                # still checked) and stop branching here.
                self.sleep_leaves += 1
                return "ok", []
            if step >= len(self.prefix):
                self._sleep = {t for t in self._sleep
                               if self._indep(t, decision)}
            self._grant(decision)
            step += 1

    def _conformance_violations(self) -> List[str]:
        """Run the scenario's rayspec conformance bindings against the
        recorded history (cached across the DFS's replayed prefixes —
        see tools.rayspec.conformance). Called only at quiescent
        states: parked threads sit BEFORE the cores' locks (every spec
        tap gates outside them), so the live snapshot is consistent."""
        if not self._conf_bindings or self._recorder is None:
            return []
        if self._recorder.overflowed:
            # A truncated history cannot judge the live state — the
            # comparison would manufacture divergences (and the
            # unchanged-count skip would then freeze a stale verdict).
            # Surfacing it as an error fails the scenario loudly: the
            # fix is a bigger recorder or a smaller scenario, never a
            # silent half-check.
            msg = ("conformance recording overflowed "
                   f"({self._recorder.max_events} events) — refusing "
                   "to check against a truncated history")
            if msg not in self._errors:
                self._errors.append(msg)
            return []
        from tools.rayspec.conformance import ConformanceSession
        from tools.rayspec.specs import SPEC_CATALOG

        if self._conf_sessions is None:
            self._conf_sessions = {
                name: ConformanceSession(SPEC_CATALOG[name])
                for name, _getter in self._conf_bindings}
        out: List[str] = []
        for name, getter in self._conf_bindings:
            try:
                core = getter()
            except Exception as e:
                self._errors.append(
                    f"conformance getter {name!r} raised: {e!r}")
                continue
            if core is None:
                continue
            self._conf_checks += 1
            try:
                msg = self._conf_sessions[name].check(
                    self._recorder, core)
            except Exception as e:
                self._errors.append(
                    f"conformance check {name!r} raised: {e!r}")
                continue
            if msg is not None:
                out.append(f"conformance-{name}: {msg}")
        return out

    def _indep(self, a, b) -> bool:
        """Same doubt-answers-dependent guard as checker._independent:
        a scenario's overridden relation raising must degrade pruning,
        never kill the execution."""
        try:
            return bool(self.scn.independent(a, b))
        except Exception:
            return False

    def _parked_infos(self) -> List[_ThreadInfo]:
        return sorted(
            (i for i in self._threads.values()
             if i.state is PARKED and i.grant is None),
            key=lambda i: (i.role, i.parked[0]))

    def _wait_quiescent(self, deadline: float) -> bool:
        """Until no controlled thread is RUNNING: each either parks,
        finishes, or exceeds the grace window (→ BLOCKED). A short
        settle window after that catches blocked threads that were
        woken by the last grant and are about to park."""
        with self._lock:
            while True:
                now = time.monotonic()
                if now > deadline:
                    return False
                running = [i for i in self._threads.values()
                           if i.state is RUNNING]
                if running:
                    horizon = min(i.granted_at + self.grace
                                  for i in running)
                    if now >= horizon:
                        for i in running:
                            if now - i.granted_at >= self.grace:
                                i.state = BLOCKED
                        continue
                    self._lock.wait(min(horizon - now, 0.05))
                    continue
                if not any(i.state is BLOCKED
                           for i in self._threads.values()):
                    return True
                # Settle: woken-but-unparked threads surface here.
                before = self._arrivals
                self._lock.wait(self.cfg.settle_s)
                if self._arrivals == before and not any(
                        i.state is RUNNING
                        for i in self._threads.values()):
                    return True

    def _wait_for_park(self, deadline: float,
                       max_wait_s: float = 3.5) -> bool:
        """All live threads look blocked: wait (bounded) for any of
        them to reach a gate or finish — timed product waits (condvar
        timeouts, bounded gets) legitimately take a moment."""
        end = min(deadline, time.monotonic() + max_wait_s)
        with self._lock:
            while time.monotonic() < end:
                if self._parked_infos():
                    return True
                if not any(i.action_role and i.state is not DONE
                           for i in self._threads.values()):
                    return True
                self._lock.wait(0.05)
        return False

    def _decide(self, step: int, parked: List[_ThreadInfo],
                deadline: float):
        """The decision (from the replay prefix, or default policy)
        plus the enabled-set snapshot for DFS backtracking."""
        if step < len(self.prefix):
            want = self.prefix[step]
            info = self._await_crossing(want, deadline)
            if info is None:
                return None, True
            with self._lock:
                parked = self._parked_infos()
            enabled = self._enabled(parked)
            chosen = Decision(want.role, want.point, want.role_occ,
                              want.crash)
            self._steps.append(_Step(chosen, enabled))
            return chosen, False
        enabled = self._enabled(parked)
        awake = parked
        if self.cfg.dpor and self._sleep:
            awake = [
                i for i in parked
                if Decision(i.role, i.parked[0], i.parked[2], False)
                not in self._sleep]
            if not awake:
                return None, False  # sleep leaf: subtree covered
        # Default policy: deterministic round-robin fairness — the
        # least-recently-granted role first (action roles before
        # adopted ones on ties) so free-looping internal threads can't
        # starve the actions driving the execution forward.
        info = min(awake, key=self._fairness_key)
        point, _gocc, rocc = info.parked
        chosen = Decision(info.role, point, rocc, False)
        self._steps.append(_Step(chosen, enabled))
        return chosen, False

    def _fairness_key(self, info: _ThreadInfo):
        return (self._last_grant.get(info.role, -1),
                not info.action_role, info.role)

    def _enabled(self, parked: List[_ThreadInfo]) -> Tuple[Decision, ...]:
        out = []
        for i in parked:
            point, _gocc, rocc = i.parked
            out.append(Decision(i.role, point, rocc, False))
            if point in self._crash_points \
                    and self._crashes_used < self.crash_budget:
                out.append(Decision(i.role, point, rocc, True))
        return tuple(out)

    def _await_crossing(self, want: Decision,
                        deadline: float) -> Optional[_ThreadInfo]:
        """Wait for the prefix-decided crossing to be parked (its
        thread may still be running or mid-wake)."""
        end = min(deadline, time.monotonic() + 3.0)
        with self._lock:
            while time.monotonic() < end:
                for i in self._threads.values():
                    if i.state is PARKED and i.grant is None \
                            and i.role == want.role \
                            and i.parked[0] == want.point \
                            and i.parked[2] == want.role_occ:
                        return i
                self._lock.wait(0.05)
        return None

    def _grant(self, decision: Decision) -> None:
        with self._lock:
            for i in self._threads.values():
                if i.state is PARKED and i.grant is None \
                        and i.role == decision.role \
                        and i.parked[0] == decision.point \
                        and i.parked[2] == decision.role_occ:
                    if decision.crash:
                        self._crashes_used += 1
                    i.grant = decision
                    i.state = RUNNING
                    i.granted_at = time.monotonic()
                    self._last_grant[i.role] = len(self._steps)
                    self._lock.notify_all()
                    return
        self._errors.append(f"grant target vanished: {decision!r}")

    def _pending_snapshot(self) -> List[_Cross]:
        """Crossings parked when the execution ended, newest arrival
        first: the replay script lists them after every completed
        crossing, so each parked thread stays held until everything
        that overtook it has run — the order that reproduces the
        overtake (see minimize.py)."""
        with self._lock:
            parked = [i for i in self._threads.values()
                      if i.state is PARKED]
            parked.sort(key=lambda i: -i.arrival_seq)
            return [_Cross(i.parked[0], i.role, i.parked[1],
                           i.parked[2], False,
                           i.role in self._action_roles,
                           order_key=i.arrival_seq)
                    for i in parked]

    def _release_all(self) -> None:
        with self._lock:
            self._released = True
            self._lock.notify_all()
