"""raymc: bounded model checking for ray_tpu's distributed protocols.

The third leg of the analysis ladder — raylint (static structure),
raysan (one schedule at a time, replayed), raymc (ALL schedules within
a bound, discovered): drive real product code through its
``sanitize_hooks`` yield points, systematically exploring thread
interleavings and crash-fault placements, checking declarative
``Invariant``/``Liveness`` properties at every state, and shrinking any
violation to a minimized counterexample that replays deterministically
as a ``tools.raysan.sched.Schedule`` script.
"""

from tools.raymc.checker import CheckResult, check  # noqa: F401
from tools.raymc.explorer import (Decision, Execution,  # noqa: F401
                                  ExecutionResult, ExplorerConfig)
from tools.raymc.minimize import (build_counterexample,  # noqa: F401
                                  minimize_decisions,
                                  script_from_result)
from tools.raymc.props import (Counterexample, Finding,  # noqa: F401
                               Invariant, Liveness)
from tools.raymc.scenario import Scenario  # noqa: F401
from tools.raymc.scenarios import (DEFAULT_SCENARIOS,  # noqa: F401
                                   SCENARIOS)
