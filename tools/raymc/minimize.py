"""raymc counterexample pipeline: shrink, script, verify.

A raw violating execution carries every scheduling decision the DFS
happened to make — most of them irrelevant noise. This module:

1. **delta-debugs** the decision list (classic ddmin over chunks, with
   a bounded probe budget): a candidate sublist replays decision-for-
   decision (divergence = candidate rejected) with the default policy
   finishing the run, and survives only if the SAME property still
   breaks. The result is 1-minimal: dropping any single remaining
   decision loses the bug.
2. **emits a Schedule script** from the minimal failing run's crossing
   log: completed crossings in completion order, then the crossings
   still parked when the violation was detected in REVERSE arrival
   order — a thread that parked early and was overtaken stays gated
   until everything that overtook it has crossed, which is exactly the
   overtake the bug needs. Scenario action threads get role-qualified
   keys (``point@role[#k]``); runtime-internal threads keep global
   occurrence keys. Crash injections become ``crash_at`` entries.
3. **verifies** the script by running the scenario under a plain
   ``tools.raysan.sched.Schedule`` (no explorer) and checking the same
   property fails — what lands in the report is known-replayable.
"""

from __future__ import annotations

from typing import Callable, List, Tuple

from tools.raymc.explorer import Decision, ExecutionResult, _Cross
from tools.raymc.props import Counterexample


def _prop_names(violations: List[str]) -> set:
    return {v.split(":", 1)[0] for v in violations}


def ddmin(fails: Callable[[list], bool], items: list,
          max_probes: int = 48) -> list:
    """Generic delta-debugging minimization (classic ddmin over
    chunks, bounded probe budget): the smallest order-preserving
    sublist of ``items`` for which ``fails`` still returns truthy —
    1-minimal when the budget allows (dropping any single remaining
    item loses the failure). ``fails(items)`` is assumed truthy for
    the input. Shared engine: raymc shrinks scheduling-decision lists
    through it, rayspec shrinks non-linearizable sub-histories."""
    probes = [0]

    def check(candidate: list) -> bool:
        if probes[0] >= max_probes:
            return False
        probes[0] += 1
        return bool(fails(candidate))

    current = list(items)
    # Fast path: does the empty list already fail?
    if check([]):
        return []
    n = 2
    while len(current) >= 2 and probes[0] < max_probes:
        chunk = max(1, len(current) // n)
        reduced = False
        i = 0
        while i < len(current):
            candidate = current[:i] + current[i + chunk:]
            if check(candidate):
                current = candidate
                n = max(n - 1, 2)
                reduced = True
            else:
                i += chunk
        if not reduced:
            if n >= len(current):
                break
            n = min(n * 2, len(current))
    return current


def minimize_decisions(
        run: Callable[[List[Decision]], ExecutionResult],
        decisions: List[Decision],
        target_props: set,
        max_probes: int = 48) -> Tuple[List[Decision], ExecutionResult]:
    """ddmin over the decision list; returns (minimal decisions, the
    minimal run's result). ``run`` executes a fresh scenario instance
    under the candidate prefix."""
    results: dict = {}

    def fails(candidate: List[Decision]) -> bool:
        res = run(candidate)
        hit = res.status in ("violation", "deadlock") \
            and (_prop_names(res.violations) & target_props
                 or (res.status == "deadlock"
                     and "deadlock" in target_props))
        if hit:
            results[id_key(candidate)] = res
        return hit

    def id_key(candidate: List[Decision]) -> tuple:
        return tuple(map(tuple, candidate))

    current = ddmin(fails, list(decisions), max_probes=max_probes)
    best_res = results.get(id_key(current))
    if best_res is None:
        best_res = run(current)
    return current, best_res


def script_from_result(result: ExecutionResult) \
        -> Tuple[List[str], List[str]]:
    """(order, crash_at) Schedule entries for the failing run."""
    per_role: dict = {}
    per_name: dict = {}
    order: List[str] = []
    crash_at: List[str] = []

    def key_for(c: _Cross) -> str:
        if c.action_role or c.point.startswith("mc."):
            rocc = per_role.get((c.point, c.role), 0) + 1
            per_role[(c.point, c.role)] = rocc
            key = f"{c.point}@{c.role}"
            return key if rocc == 1 else f"{key}#{rocc}"
        gocc = per_name.get(c.point, 0) + 1
        per_name[c.point] = gocc
        return c.point if gocc == 1 else f"{c.point}#{gocc}"

    # NB occurrence numbers are recomputed over the EMITTED log (not
    # copied from the explorer's counters): the replay's Schedule
    # counts crossings from zero, and the emitted log is exactly what
    # it will see gate-worthy crossings of. Sorting by order_key puts
    # done gates at their ARRIVAL position (see explorer._Cross), so a
    # thread's final segment is strictly ordered before anything that
    # read its effects — and that applies to done gates still PENDING
    # at the end too (a crash-ended run leaves finished-but-ungranted
    # threads parked there; their final segments already ran). Other
    # pending crossings stay at the tail in reverse-arrival order:
    # they hold overtaken threads parked through everything that
    # overtook them.
    from tools.raymc.scenario import DONE_POINT_PREFIX

    done_pending = [c for c in result.pending
                    if c.point.startswith(DONE_POINT_PREFIX)]
    hold_pending = [c for c in result.pending
                    if not c.point.startswith(DONE_POINT_PREFIX)]
    timeline = sorted(result.crossings + done_pending,
                      key=lambda c: c.order_key)
    for c in timeline:
        key = key_for(c)
        order.append(key)
        if c.crashed:
            crash_at.append(key)
    for c in hold_pending:
        order.append(key_for(c))
    return order, crash_at


def build_counterexample(scenario_factory, cfg, decisions: List[Decision],
                         result: ExecutionResult,
                         target_props: set) -> Counterexample:
    """Minimize → script → verify; see module docstring."""
    from tools.raymc.explorer import Execution

    def run(prefix: List[Decision]) -> ExecutionResult:
        return Execution(scenario_factory(), list(prefix), cfg).run()

    minimal, minimal_res = (decisions, result)
    if cfg.minimize:
        minimal, minimal_res = minimize_decisions(
            run, decisions, target_props)
        if minimal_res.status not in ("violation", "deadlock"):
            # Defensive: ddmin's final answer must fail; if a rerun
            # went non-deterministic fall back to the original trace.
            minimal, minimal_res = decisions, result

    order, crash_at = script_from_result(minimal_res)
    ce = Counterexample(
        decisions=[d.to_dict() for d in minimal],
        schedule_order=order,
        crash_at=crash_at)

    if cfg.verify_replays and order:
        from tools.raysan.sched import Schedule

        scn = scenario_factory()
        try:
            sched = Schedule(order=order, crash_at=crash_at or None,
                             timeout_s=5.0)
            msgs = scn.replay_under_schedule(sched)
            ce.verified_replays = bool(
                _prop_names(msgs) & target_props)
            if not ce.verified_replays:
                ce.verify_messages = msgs
        except Exception as e:
            ce.verified_replays = False
            ce.verify_messages = [f"verification raised: {e!r}"]
    return ce
