"""raymc property DSL: what the model checker proves.

Two declarative property kinds, both evaluated against a scenario's
:meth:`~tools.raymc.scenario.Scenario.state` snapshot:

- :class:`Invariant`: must hold in EVERY reachable state. The explorer
  evaluates all invariants at each quiescent point (after every
  scheduling decision plays out) and once more at the end of each
  bounded execution; the first violated state becomes the
  counterexample. Scenarios should phrase invariants so a violation is
  *persistent* (e.g. "requests dispatched to a cap-1 replica ≤ 1" with
  requests that never complete): the minimized replay re-checks the
  property at the END of a schedule-driven run, and a self-healing
  violation would be invisible there.
- :class:`Liveness`: must hold *eventually* within a bound. Evaluated
  once per execution after every action thread finished and all gates
  were released, by polling the predicate until ``timeout_s`` —
  bounded liveness, the only kind a bounded checker can decide (e.g.
  "long-poll membership converges after the controller restart").

Predicates return truthy for "holds" and falsy for "violated" (the
property's description becomes the detail). Returning a non-empty
string reports a violation with that string as the detail — handy for
naming the exact keys/counters that went wrong.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional


class Invariant:
    """A safety property over scenario state: ``check(state)`` must be
    truthy in every explored state."""

    kind = "invariant"

    def __init__(self, name: str, check: Callable[[Any], Any],
                 description: str = ""):
        self.name = name
        self.check = check
        self.description = description or name

    def violation(self, state) -> Optional[str]:
        """None when the property holds, else the violation detail."""
        try:
            result = self.check(state)
        except Exception as e:  # a predicate that crashes is a finding
            return (f"invariant predicate raised "
                    f"{type(e).__name__}: {e}")
        if isinstance(result, str) and result:
            return result
        return None if result else self.description


class Liveness:
    """A bounded liveness property: ``check(state)`` must become truthy
    within ``timeout_s`` of the execution's actions completing."""

    kind = "liveness"

    def __init__(self, name: str, check: Callable[[Any], Any],
                 timeout_s: float = 3.0, description: str = ""):
        self.name = name
        self.check = check
        self.timeout_s = timeout_s
        self.description = description or name

    def violation(self, state) -> Optional[str]:
        import time

        deadline = time.monotonic() + self.timeout_s
        while True:
            try:
                if self.check(state):
                    return None
            except Exception as e:
                return (f"liveness predicate raised "
                        f"{type(e).__name__}: {e}")
            if time.monotonic() >= deadline:
                return (f"{self.description} (did not hold within "
                        f"{self.timeout_s:.1f}s)")
            time.sleep(0.01)


@dataclasses.dataclass
class Counterexample:
    """A minimized, replayable witness of a property violation.

    ``schedule_order``/``crash_at`` are a ready-to-run
    ``tools.raysan.sched.Schedule`` script: crossing keys in the exact
    order the failing interleaving produced them (role-qualified
    ``name@role[#k]`` for scenario action threads, global ``name[#k]``
    for runtime-internal threads), with ``crash_at`` naming the
    crossings at which a :class:`~ray_tpu._private.sanitize_hooks.
    SimulatedCrash` was injected. ``decisions`` is the explorer's own
    scheduling-choice encoding (for re-exploration); ``verified_replays``
    records whether the emitted Schedule script reproduced the
    violation when re-run outside the explorer.
    """

    decisions: List[Dict[str, Any]]
    schedule_order: List[str]
    crash_at: List[str]
    verified_replays: Optional[bool] = None
    # When verification did NOT reproduce: what the replay returned
    # instead (hangs, action/on_point exceptions, other violations) —
    # a maintainer debugs the harness from this, not from a bare
    # "REPLAY UNVERIFIED".
    verify_messages: Optional[List[str]] = None

    def replay_snippet(self, scenario_name: str = "<scenario>") -> str:
        lines = ["from tools.raysan.sched import Schedule",
                 "sched = Schedule("]
        lines.append("    order=[")
        for key in self.schedule_order:
            lines.append(f"        {key!r},")
        lines.append("    ],")
        if self.crash_at:
            lines.append(f"    crash_at={self.crash_at!r},")
        lines.append(")")
        lines.append(f"# drive the {scenario_name} actions under "
                     f"`with sched:` to replay the violation")
        return "\n".join(lines)

    def to_dict(self) -> dict:
        return {
            "decisions": self.decisions,
            "schedule_order": self.schedule_order,
            "crash_at": self.crash_at,
            "verified_replays": self.verified_replays,
            "verify_messages": self.verify_messages,
        }

    @classmethod
    def from_dict(cls, data: dict) -> "Counterexample":
        return cls(**data)


@dataclasses.dataclass
class Finding:
    """One property violation (or harness-detected failure) with its
    counterexample."""

    scenario: str
    prop: str               # property name ("router-cap", ...)
    kind: str               # invariant | liveness | deadlock | exception
    message: str
    counterexample: Optional[Counterexample] = None

    def to_dict(self) -> dict:
        return {
            "scenario": self.scenario,
            "property": self.prop,
            "kind": self.kind,
            "message": self.message,
            "counterexample": (self.counterexample.to_dict()
                               if self.counterexample else None),
        }

    @classmethod
    def from_dict(cls, data: dict) -> "Finding":
        ce = data.get("counterexample")
        return cls(scenario=data["scenario"], prop=data["property"],
                   kind=data["kind"], message=data["message"],
                   counterexample=Counterexample.from_dict(ce)
                   if ce else None)

    def render(self) -> str:
        out = (f"[{self.scenario}] {self.kind} violated: {self.prop} — "
               f"{self.message}")
        if self.counterexample:
            ce = self.counterexample
            verified = {True: "replays deterministically",
                        False: "REPLAY UNVERIFIED",
                        None: "replay not verified"}[ce.verified_replays]
            out += (f"\n  counterexample ({len(ce.decisions)} decisions,"
                    f" {verified}):")
            out += "\n    Schedule(order=["
            out += ", ".join(repr(k) for k in ce.schedule_order)
            out += "]"
            if ce.crash_at:
                out += f", crash_at={ce.crash_at!r}"
            out += ")"
        return out
