"""CLI: ``python -m tools.raymc [--scenario a,b] [--report json] ...``

Runs the named scenarios' bounded model checks and reports findings —
the form CI archives as ``RAYMC_REPORT.json``.

Exit-code contract (raylint's):
  0  every property held over the explored schedule/crash space
  1  at least one violation (or harness-detected wedge) was found
  2  usage error (unknown scenario, bad arguments)
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.raymc",
        description="bounded model checker for ray_tpu protocol "
                    "invariants")
    parser.add_argument(
        "--scenario", default="", metavar="LIST",
        help="comma-separated scenario names (default: the bounded "
             "tier-1 set; 'all' for every registered scenario)")
    parser.add_argument("--list", action="store_true",
                        help="list registered scenarios and exit")
    parser.add_argument("--report", choices=("json", "pretty"),
                        default="pretty")
    parser.add_argument("--report-file", default="", metavar="PATH",
                        help="also write the JSON report to PATH")
    parser.add_argument("--max-schedules", type=int, default=400,
                        help="per-scenario execution budget")
    parser.add_argument("--max-steps", type=int, default=0,
                        help="override scenarios' per-execution "
                             "decision bound (0 = scenario default)")
    parser.add_argument("--time-budget-s", type=float, default=45.0,
                        help="per-scenario wall-clock budget")
    parser.add_argument("--no-dpor", action="store_true",
                        help="disable sleep-set pruning (debugging "
                             "the reduction itself)")
    parser.add_argument("--no-minimize", action="store_true",
                        help="emit raw, unminimized counterexamples")
    args = parser.parse_args(argv)

    from tools.raymc.explorer import ExplorerConfig
    from tools.raymc.scenarios import DEFAULT_SCENARIOS, SCENARIOS

    if args.list:
        for name, cls in sorted(SCENARIOS.items()):
            print(f"{name:20s} {cls.description}")
        return 0

    if args.scenario.strip() == "all":
        names = sorted(SCENARIOS)
    elif args.scenario.strip():
        names = [n.strip() for n in args.scenario.split(",")
                 if n.strip()]
    else:
        names = list(DEFAULT_SCENARIOS)
    for name in names:
        if name not in SCENARIOS:
            print(f"raymc: unknown scenario {name!r}; known: "
                  f"{', '.join(sorted(SCENARIOS))}", file=sys.stderr)
            return 2

    if any(SCENARIOS[n].needs_ray for n in names):
        import ray_tpu

        ray_tpu.init(num_cpus=4)

    from tools.raymc.checker import check

    cfg = ExplorerConfig(
        max_schedules=args.max_schedules,
        max_steps=args.max_steps,
        time_budget_s=args.time_budget_s,
        dpor=not args.no_dpor,
        minimize=not args.no_minimize)

    import gc

    results = []
    # Exploration replays thousands of schedules, each allocating
    # fresh scenario state + trace records; with the cyclic GC live,
    # gen2 passes rescan the whole heap mid-exploration and the leg
    # pays 20%+ wall overhead. Pause it and collect at scenario
    # boundaries so memory stays bounded per scenario.
    gc_was_enabled = gc.isenabled()
    gc.disable()
    try:
        for name in names:
            results.append(check(SCENARIOS[name], cfg))
            gc.collect()
    finally:
        if gc_was_enabled:
            gc.enable()

    # Seam-coverage audit: which registered sched/crash points did
    # this run's scenarios actually cross? An uncovered point is a
    # seam the model checker never schedules around — dead catalog
    # weight, or (worse) a real interleaving seam with zero coverage.
    # Advisory, not a failure: partial runs (--scenario x) legitimately
    # cross few points, so the report records the gap instead of
    # failing on it; the full-set numbers are what reviews read.
    from ray_tpu._private.sanitize_hooks import (CRASH_POINTS,
                                                 SCHED_POINTS)

    catalog = set(SCHED_POINTS) | set(CRASH_POINTS)
    crossed = set()
    for r in results:
        crossed.update(r.points_crossed)
    crossed &= catalog      # "mc.*" harness gates are not seams
    report = {
        "schema_version": 1,
        "harness": "python -m tools.raymc",
        "scenarios": [r.to_dict() for r in results],
        "seam_coverage": {
            "catalog": len(catalog),
            "crossed": sorted(crossed),
            "uncovered": sorted(catalog - crossed),
        },
        "pass": all(r.ok for r in results),
    }
    if args.report == "json":
        print(json.dumps(report, indent=2))
    else:
        for r in results:
            status = "EXHAUSTIVE" if r.exhausted else "bounded"
            verdict = "ok" if r.ok else \
                f"{len(r.findings)} FINDING(S)"
            print(f"raymc[{r.scenario}]: {verdict} — "
                  f"{r.executions} schedules ({status}), "
                  f"{r.steps_total} decisions, {r.pruned} pruned, "
                  f"{r.elapsed_s:.2f}s")
            for f in r.findings:
                print("  " + f.render().replace("\n", "\n  "))
        cov = report["seam_coverage"]
        print(f"raymc[seams]: {len(cov['crossed'])}/{cov['catalog']} "
              f"registered points crossed"
              + (f"; uncovered: {', '.join(cov['uncovered'])}"
                 if cov["uncovered"] else ""))
    if args.report_file:
        # Deterministic artifact: wall-clock noise goes to the
        # .timing.json sidecar so back-to-back identical runs produce
        # byte-identical committed reports.
        from tools.reporting import write_report_artifact

        write_report_artifact(args.report_file, report,
                              volatile=("elapsed_s",))

    return 0 if report["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
