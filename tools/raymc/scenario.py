"""raymc scenario contract: a checkable slice of the real runtime.

A scenario wires REAL product objects (a ``Router``, a
``SqliteStoreClient``, a ``PipelinedClient`` + ``RpcServer`` pair, a
``LongPollHost``/``LongPollClient``) into a small closed system, names
the yield points whose interleavings matter, and declares the
properties that must hold. The explorer owns scheduling: it runs the
scenario's action threads, seizes control at every relevant
``sanitize_hooks`` crossing, and enumerates interleavings and
crash-fault placements.

The same scenario object also knows how to run under a plain
``tools.raysan.sched.Schedule`` (:meth:`replay_under_schedule`) — that
is what makes every raymc counterexample directly usable as a
deterministic regression test: the minimizer emits a Schedule script,
verifies it reproduces the violation through THIS path (no explorer
involved), and a test can pin it forever.

Design rules for scenarios:

- violations should be *persistent*: observable from the end state of a
  completed run, not only in the instant they occur (see props.py) —
  both the explorer's end check and schedule replays rely on it;
- actions must terminate on their own (bounded waits only): the
  explorer bounds each execution, but a wedged action turns every
  explored schedule into a timeout;
- ``on_crash`` performs the kill (and any restart) for an injected
  :class:`~ray_tpu._private.sanitize_hooks.SimulatedCrash`; it runs on
  the crashed thread, which terminates right after.
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional, Tuple

from ray_tpu._private import sanitize_hooks

from tools.raymc.props import Invariant, Liveness

# Synthetic per-role gates BRACKETING every action body: the start
# gate gives the explorer control over code before a thread's first
# product yield point (the very first overtake window); the done gate
# gives Schedule replays a handle on the segment AFTER a thread's last
# product crossing (under the explorer that segment runs to quiescence
# before the next grant, but a plain Schedule has no quiescence — the
# done entry is what keeps e.g. a writer's post-put bookkeeping ordered
# before a committer's snapshot in a replayed script). Scripts
# reference these as "mc.start.<role>" / "mc.done.<role>". A crashed
# action crosses no done gate — the thread is dead.
START_POINT_PREFIX = "mc.start."
DONE_POINT_PREFIX = "mc.done."


class Scenario:
    """Base class; subclasses are the property catalog (scenarios.py)."""

    name = "unnamed"
    description = ""
    # Yield-point names the explorer gates; crossings of any other
    # point pass through ungated (keeping the interleaving space the
    # size of the protocol under test, not the whole runtime).
    points: Tuple[str, ...] = ()
    # Points where the explorer may inject a SimulatedCrash (these are
    # gated too, whether or not they also appear in `points`).
    crash_points: Tuple[str, ...] = ()
    # Max injected crashes per execution (crash branching is the most
    # expensive dimension; 1 matches "a single fault" protocol specs).
    crash_budget = 1
    # Scheduling decisions per execution before the explorer stops
    # branching and free-runs the tail (marks the check non-exhaustive).
    max_steps = 48
    # Minimum schedule budget this scenario needs to DRAIN its bounded
    # space (0 = the checker config's default). A scenario whose
    # exhaustive sweep is cheap but wider than the CLI default raises
    # this so the tier-1 leg's `exhausted` claim stays honest.
    max_schedules = 0
    # Whether the scenario touches the ray_tpu runtime (ObjectRefs,
    # ray_tpu.wait/put) and needs ray_tpu.init() before checking.
    needs_ray = False
    # How long a granted-but-not-parked thread may run before the
    # explorer treats it as blocked on real synchronization and
    # schedules around it.
    block_grace_s = 0.05

    # -- lifecycle ---------------------------------------------------------

    def setup(self) -> None:
        """Build a FRESH instance of the system under test (called once
        per explored execution)."""

    def actions(self) -> List[Tuple[str, Callable[[], None]]]:
        """(role, body) pairs; each runs on its own controlled thread
        named after the role."""
        raise NotImplementedError

    def teardown(self) -> None:
        """Tear the system down (threads, sockets, files). Must be safe
        after a crash injection killed part of the system."""

    # -- properties --------------------------------------------------------

    def state(self):
        """The snapshot object invariant/liveness predicates receive.
        Defaults to the scenario itself."""
        return self

    def invariants(self) -> List[Invariant]:
        return []

    def liveness(self) -> List[Liveness]:
        return []

    def conformance(self) -> List[Tuple[str, Callable[[], object]]]:
        """rayspec conformance bindings: ``(catalog name, live-core
        getter)`` pairs. When non-empty, the explorer records the
        cores' spec-op history for each execution and, at every
        quiescent state, cross-checks the live core against the
        executable sequential spec's reachable states — each explored
        schedule becomes a refinement check, not just a property list.
        The getter runs at check time (a scenario may build the core
        in ``setup``); returning ``None`` skips the binding for this
        state."""
        return []

    # -- fault + observation seams ----------------------------------------

    def on_crash(self, point: str) -> None:
        """Perform the kill/restart an injected crash at ``point``
        models. Runs on the crashed thread; the action ends after."""

    def on_point(self, point: str, role: str) -> None:
        """State-snapshot seam: called after every relevant crossing
        completes (same thread, same instant in both explorer runs and
        Schedule replays) — the place to record protocol bookkeeping
        like "the commit boundary just passed"."""

    def conflict_key(self, point: str) -> Optional[str]:
        """Partial-order-reduction domain for ``point``: crossings by
        different threads in different domains commute (their
        reorderings are not separately explored). Default: the first
        dotted segment of a REGISTERED product point ("router.handoff"
        → "router"); None (conflicts with everything) for synthetic
        mc.* points and anything unregistered — a start gate's
        follow-on transition can touch any state, so it must never be
        pruned against."""
        if point in sanitize_hooks.POINTS:
            return point.split(".", 1)[0]
        return None

    def independent(self, a, b) -> bool:
        """Do two transitions commute? ``a``/``b`` are explorer
        decisions ``(role, point, occurrence, crash)``. The default is
        deliberately conservative: same thread never commutes with
        itself, crash injections commute with nothing, and two points
        commute only when both declare conflict domains and the
        domains differ. Scenarios that KNOW finer structure (two
        writers touching distinct keys) override this to unlock more
        sleep-set pruning — unsound overrides mean missed
        interleavings, so only claim independence you can argue from
        the data."""
        if a[0] == b[0] or a[3] or b[3]:
            return False
        da = self.conflict_key(a[1])
        db = self.conflict_key(b[1])
        return da is not None and db is not None and da != db

    # -- schedule replay ---------------------------------------------------

    def start_point(self, role: str) -> str:
        return START_POINT_PREFIX + role

    def done_point(self, role: str) -> str:
        return DONE_POINT_PREFIX + role

    def violations(self, include_liveness: bool = True) -> List[str]:
        """Evaluate every property against the current state; returns
        ``"prop-name: detail"`` strings (the shared judge for explorer
        end checks, schedule replays, and minimizer probes)."""
        out = []
        state = self.state()
        for inv in self.invariants():
            detail = inv.violation(state)
            if detail is not None:
                out.append(f"{inv.name}: {detail}")
        if include_liveness:
            for live in self.liveness():
                detail = live.violation(state)
                if detail is not None:
                    out.append(f"{live.name}: {detail}")
        return out

    def replay_under_schedule(self, schedule,
                              join_timeout_s: float = 8.0) -> List[str]:
        """Run this scenario's actions under a plain raysan
        ``Schedule`` (no explorer) and return the violated properties —
        the counterexample-verification path, and the exact shape a
        regression test pins.

        The schedule's ``on_cross`` seam is wired to :meth:`on_point`
        so protocol bookkeeping (commit boundaries, ack watermarks)
        observes the same crossings it would under the explorer.
        """
        self.setup()
        self._replay_errors: List[str] = []
        try:
            schedule.set_on_cross(self._schedule_on_cross)
            threads = []
            crash_seen: List[str] = []

            def body(role, fn):
                def run():
                    try:
                        sanitize_hooks.sched_point(self.start_point(role))
                        fn()
                    except sanitize_hooks.SimulatedCrash as e:
                        crash_seen.append(e.point)
                        try:
                            self.on_crash(e.point)
                        except Exception as e2:
                            self._replay_errors.append(
                                f"on_crash({e.point}) raised: {e2!r}")
                        return  # crashed: no done gate for the dead
                    except Exception as e:
                        # End-state properties are the judge, but a
                        # raising action is diagnosable, not silent —
                        # the explorer path records the same thing as
                        # a no-unhandled-exception finding.
                        self._replay_errors.append(
                            f"action {role!r} raised: {e!r}")
                    sanitize_hooks.sched_point(self.done_point(role))
                return run

            with schedule:
                for role, fn in self.actions():
                    t = threading.Thread(target=body(role, fn),
                                         name=role, daemon=True)
                    threads.append(t)
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(join_timeout_s)
            # Gates are released now; give stragglers a moment.
            for t in threads:
                t.join(1.0)
            hung = [t.name for t in threads if t.is_alive()]
            msgs = []
            if hung:
                msgs.append(f"replay-hang: action threads never "
                            f"finished: {hung}")
            msgs.extend(f"replay-exception: {e}"
                        for e in self._replay_errors)
            msgs.extend(self.violations())
            return msgs
        finally:
            self.teardown()

    def _schedule_on_cross(self, key: str, role: str) -> None:
        point = key.split("#")[0].split("@")[0]
        try:
            self.on_point(point, role)
        except Exception as e:
            self._replay_errors.append(
                f"on_point({point}) raised: {e!r}")
