"""Schema-driven value/frame generation and catalog simulation.

Three consumers share this module:

- the **round-trip property suite** generates natively-encodable field
  values for every registered message (no opaque sections — pickle
  bytes are not canonical, so byte-for-byte identity is only promised
  for the structural encoding);
- the **fuzzer** builds valid frames straight from the extracted
  schema, then mutates them;
- the **skew simulator** builds frames for a catalog that no longer
  (or does not yet) exist in code — ``build_frame`` is a standalone
  encoder driven entirely by schema data, and ``simulate_decode``
  replicates the decoder's semantics (version gate, unknown-field
  skip, type checks, required-field check) against a catalog entry
  given as data. That is what lets the gate decode "old wire under new
  code" AND "new wire under old code" with only the new code present.

Everything is seeded: same seed, same frames, byte for byte.
"""

from __future__ import annotations

import random
import struct
from typing import Any, Dict, List, Optional, Tuple

_U32 = struct.Struct("!I")
_U16 = struct.Struct("!H")

# Mirrors wire._SCALAR_CHECKS: the isinstance gate decode applies per
# declared field type (None always passes; int is acceptable where
# float is declared).
_TYPE_CHECKS = {
    "int": int, "float": (int, float), "str": str, "bytes": bytes,
    "bool": bool, "dict": dict, "list": list, "tuple": tuple,
}

_SAMPLE_STRS = ("", "a", "table", "spec.template", "节点", "x" * 40,
                "key:with/punct", "éß")
_SAMPLE_BYTES = (b"", b"\x00", b"oid-1234", b"\xff" * 16, b"k" * 33)


def gen_value(rng: random.Random, type_name: str, depth: int = 0) -> Any:
    """One generated value of the declared wire type."""
    if type_name == "int":
        return rng.choice((
            0, 1, -1, 7, rng.randrange(-2**31, 2**31),
            2**63 - 1, -(2**63), 2**80 + rng.randrange(1000)))
    if type_name == "float":
        return rng.choice((0.0, -0.5, 1e-9, 3.141592653589793,
                           float(rng.randrange(10**6)),
                           rng.uniform(-1e12, 1e12)))
    if type_name == "str":
        return rng.choice(_SAMPLE_STRS)
    if type_name == "bytes":
        return rng.choice(_SAMPLE_BYTES)
    if type_name == "bool":
        return rng.random() < 0.5
    if type_name == "list":
        if depth >= 2:
            return [gen_value(rng, "int", depth + 1)]
        return [gen_value(rng, rng.choice(("int", "str", "bytes")),
                          depth + 1)
                for _ in range(rng.randrange(4))]
    if type_name == "tuple":
        return tuple(gen_value(rng, "list", depth))
    if type_name == "dict":
        if depth >= 2:
            return {"k": 1}
        return {gen_value(rng, rng.choice(("str", "int", "bytes")),
                          depth + 1):
                gen_value(rng, rng.choice(
                    ("int", "str", "float", "list")), depth + 1)
                for _ in range(rng.randrange(4))}
    # Any: anything natively encodable, nesting included.
    return gen_value(rng, rng.choice(
        ("int", "float", "str", "bytes", "bool", "list", "dict",
         "tuple")), depth + 1)


def gen_fields(rng: random.Random, entry: dict) -> List[Tuple[str, Any]]:
    """Generated (name, value) pairs in the entry's declared order —
    the encode order."""
    out = []
    for f in entry["fields"]:
        v = gen_value(rng, f["type"])
        # None is always decode-legal; exercise it occasionally.
        if f["has_default"] and rng.random() < 0.1:
            v = None
        out.append((f["name"], v))
    return out


# -- catalog-driven encoding (no live classes needed) -----------------------


def _enc_str(out: bytearray, s: str) -> None:
    raw = s.encode()
    out += _U32.pack(len(raw))
    out += raw


def build_frame(name: str, version: int,
                fields: List[Tuple[str, Any]]) -> bytes:
    """An M frame for an arbitrary (possibly historical) catalog shape.
    Field VALUES ride the live scalar encoding — catalogs version
    message shapes, not the scalar tag alphabet."""
    from ray_tpu._private import wire

    out = bytearray(b"M")
    _enc_str(out, name)
    out += _U16.pack(version)
    out += _U16.pack(len(fields))
    for fname, value in fields:
        _enc_str(out, fname)
        out += wire.encode(value)
    return bytes(out)


def build_instance(wire_name: str, entry: dict, rng: random.Random):
    """A live dataclass instance with generated field values (for the
    round-trip suite: encode must take the REAL encode path)."""
    from ray_tpu._private import wire

    cls, _version = wire._REGISTRY[wire_name]
    kwargs = {f["name"]: gen_value(rng, f["type"])
              for f in entry["fields"]}
    return cls(**kwargs)


# -- simulated decode against a catalog entry given as data -----------------


def simulate_decode(frame_fields: List[Tuple[str, Any]],
                    sender_version: int,
                    entry: Optional[dict]) -> Dict[str, Any]:
    """What a receiver speaking ``entry`` would do with a frame whose
    header says ``sender_version`` and whose body carries
    ``frame_fields``. Mirrors wire._Decoder's M-tag semantics exactly:
    unknown name / newer version reject; unknown fields skip; declared
    types check (None passes, int passes for float); fields the
    receiver declares without a default must arrive.

    Returns {"ok": bool, "error": str|None, "skipped": [names]}.
    """
    if entry is None:
        return {"ok": False, "error": "unknown message type",
                "skipped": []}
    if sender_version > entry["version"]:
        return {"ok": False,
                "error": f"v{sender_version} newer than known "
                         f"v{entry['version']}",
                "skipped": []}
    declared = {f["name"]: f for f in entry["fields"]}
    skipped: List[str] = []
    seen = set()
    for fname, value in frame_fields:
        spec = declared.get(fname)
        if spec is None:
            skipped.append(fname)
            continue
        seen.add(fname)
        check = _TYPE_CHECKS.get(spec["type"])
        if value is None or check is None:
            continue
        if not isinstance(value, check):
            return {"ok": False,
                    "error": f"{fname}: expected {spec['type']}, got "
                             f"{type(value).__name__}",
                    "skipped": skipped}
    missing = [f["name"] for f in entry["fields"]
               if f["name"] not in seen and not f["has_default"]]
    if missing:
        return {"ok": False,
                "error": f"missing required field(s): {missing}",
                "skipped": skipped}
    return {"ok": True, "error": None, "skipped": skipped}
