"""Cross-version compatibility gate + empirical skew simulator.

The diff classifies every schema change against the DECODE semantics
(wire._Decoder's M tag), not against intuition:

compatible
- ``message_added``      — old receivers never see it addressed to them
  until they upgrade; new receivers decode it.
- ``field_appended``     — appended WITH a default: old receivers skip
  the unknown field; new receivers default it on old frames.

breaking
- ``message_removed``    — in-flight frames of a still-spoken version
  become undecodable ("unknown message type").
- ``field_removed``      — old frames still decode (the field is
  silently skipped) but its DATA is dropped on the floor: silent loss,
  not an error, which is worse.
- ``field_renamed``      — removal + addition in one: the old name's
  data drops silently AND the new name is absent from old frames.
- ``field_type_changed`` — old-typed values fail the new isinstance
  gate (or worse, pass by coincidence: int→float).
- ``field_appended_no_default`` — every pre-change frame is missing a
  field the receiver now requires: all old traffic rejects.
- ``field_reordered``    — name-keyed decode still succeeds, but field
  order IS the encode byte order: content hashes (template ids!) and
  dedupe keys computed over encoded bytes diverge across the fleet.
- ``version_changed``    — the escape hatch itself: new-version frames
  reject on every not-yet-upgraded receiver, so it must ride with a
  migration note (and is what LEGITIMIZES the other breaking changes).

Gate: a message with breaking changes fails unless its version literal
was bumped AND a ``# raywire: migration=<name> -- <why>`` note exists
in wire.py (the raylint suppression contract, pointed at the schema).

The skew simulator then PROVES the classification empirically for
every message in both catalogs: generated old-catalog frames are
decoded by the live decoder, generated new-catalog frames by a
catalog-driven simulation of the old receiver (gen.simulate_decode).
Every change classified compatible must decode cleanly in BOTH
directions — a compatible-classified change with an observed decode
failure fails the gate even if the diff logic has a blind spot.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Dict, List

from tools.raywire import gen

BREAKING_KINDS = frozenset((
    "message_removed", "field_removed", "field_renamed",
    "field_type_changed", "field_appended_no_default",
    "field_reordered",
))


@dataclasses.dataclass
class Change:
    message: str
    kind: str
    detail: str
    breaking: bool


def diff_schemas(old: dict, new: dict) -> List[Change]:
    changes: List[Change] = []
    old_msgs = old.get("messages", {})
    new_msgs = new.get("messages", {})

    for name in sorted(set(new_msgs) - set(old_msgs)):
        changes.append(Change(name, "message_added",
                              f"new message v{new_msgs[name]['version']}",
                              breaking=False))
    for name in sorted(set(old_msgs) - set(new_msgs)):
        changes.append(Change(
            name, "message_removed",
            "in-flight frames of a still-spoken version become "
            "undecodable", breaking=True))

    for name in sorted(set(old_msgs) & set(new_msgs)):
        o, n = old_msgs[name], new_msgs[name]
        if o["version"] != n["version"]:
            changes.append(Change(
                name, "version_changed",
                f"v{o['version']} -> v{n['version']}", breaking=False))
        ofields = {f["name"]: f for f in o["fields"]}
        nfields = {f["name"]: f for f in n["fields"]}
        removed = [f for f in ofields if f not in nfields]
        added = [f for f in nfields if f not in ofields]

        # Rename heuristic: a removed and an added field at the same
        # declared position with the same type is reported as one
        # rename (clearer triage); both halves are breaking anyway.
        opos = {f["name"]: i for i, f in enumerate(o["fields"])}
        npos = {f["name"]: i for i, f in enumerate(n["fields"])}
        renamed = set()
        for rname in list(removed):
            for aname in list(added):
                if opos[rname] == npos.get(aname, -1) \
                        and ofields[rname]["type"] == \
                        nfields[aname]["type"]:
                    changes.append(Change(
                        name, "field_renamed",
                        f"{rname} -> {aname}: the old name's data "
                        "drops silently on new receivers",
                        breaking=True))
                    renamed.update((rname, aname))
                    removed.remove(rname)
                    added.remove(aname)
                    break

        for fname in removed:
            changes.append(Change(
                name, "field_removed",
                f"{fname}: old frames decode but the value is "
                "silently dropped", breaking=True))
        for fname in added:
            if nfields[fname]["has_default"]:
                changes.append(Change(
                    name, "field_appended",
                    f"{fname} (defaulted): old receivers skip it, "
                    "old frames default it", breaking=False))
            else:
                changes.append(Change(
                    name, "field_appended_no_default",
                    f"{fname}: every pre-change frame now rejects as "
                    "missing a required field", breaking=True))

        for fname in sorted(set(ofields) & set(nfields)):
            if fname in renamed:
                continue
            if ofields[fname]["type"] != nfields[fname]["type"]:
                changes.append(Change(
                    name, "field_type_changed",
                    f"{fname}: {ofields[fname]['type']} -> "
                    f"{nfields[fname]['type']}", breaking=True))

        shared_old = [f["name"] for f in o["fields"]
                      if f["name"] in nfields and f["name"] not in renamed]
        shared_new = [f["name"] for f in n["fields"]
                      if f["name"] in ofields and f["name"] not in renamed]
        if shared_old != shared_new:
            changes.append(Change(
                name, "field_reordered",
                f"{shared_old} -> {shared_new}: encode byte order "
                "changes, content hashes/dedupe keys diverge",
                breaking=True))
    return changes


@dataclasses.dataclass
class GateResult:
    changes: List[Change]
    failures: List[str]
    skew: Dict[str, dict]

    @property
    def ok(self) -> bool:
        return not self.failures

    def as_report(self) -> dict:
        return {
            "changes": [dataclasses.asdict(c) for c in self.changes],
            "breaking": sorted({c.message for c in self.changes
                                if c.breaking}),
            "failures": list(self.failures),
            "skew": self.skew,
            "ok": self.ok,
        }


def run_gate(old: dict, new: dict,
             migration_notes: Dict[str, str],
             seed: int = 0) -> GateResult:
    changes = diff_schemas(old, new)
    failures: List[str] = []

    by_msg: Dict[str, List[Change]] = {}
    for c in changes:
        by_msg.setdefault(c.message, []).append(c)
    for name, msg_changes in sorted(by_msg.items()):
        breaking = [c for c in msg_changes if c.breaking]
        if not breaking:
            continue
        old_v = old["messages"].get(name, {}).get("version")
        new_v = new["messages"].get(name, {}).get("version")
        bumped = (old_v is not None and new_v is not None
                  and new_v > old_v)
        note = (migration_notes.get(name) or "").strip()
        what = "; ".join(f"{c.kind}: {c.detail}" for c in breaking)
        if not bumped:
            failures.append(
                f"{name}: breaking change without a version bump "
                f"({what}) — bump the @message version literal and "
                f"add `# raywire: migration={name} -- <why>`")
        elif not note:
            failures.append(
                f"{name}: version bumped v{old_v}->v{new_v} but no "
                f"justified migration note ({what}) — add "
                f"`# raywire: migration={name} -- <why>` to wire.py")

    skew = simulate_skew(old, new, changes, seed=seed)
    for name, result in sorted(skew.items()):
        for direction in ("old_to_new", "new_to_old"):
            r = result[direction]
            if result["classified"] == "compatible" and not r["ok"]:
                failures.append(
                    f"{name}: classified compatible but the skew "
                    f"simulator observed a {direction} decode "
                    f"failure: {r['error']}")
    return GateResult(changes=changes, failures=failures, skew=skew)


def simulate_skew(old: dict, new: dict, changes: List[Change],
                  seed: int = 0, trials: int = 3) -> Dict[str, dict]:
    """Empirical both-direction decode of every message present in
    both catalogs (plus byte-identity evidence for reorders).

    old→new: frames built to the OLD shape, decoded by the LIVE
    decoder (which speaks the new catalog). new→old: frames built to
    the NEW shape, decoded by the catalog-driven simulation of the old
    receiver. ``skipped`` names fields each side dropped — the silent
    dataloss evidence behind the field_removed/renamed classification.
    """
    from ray_tpu._private import wire
    from tools.raywire import extract as _extract

    # Live decode is only meaningful for the receiver shape the code
    # ACTUALLY speaks; for hypothetical catalogs (the gate's synthetic
    # fixtures, or diffing two historical baselines) the receiver is
    # simulated from catalog data on both sides.
    live = _extract._live_catalog()

    breaking_by_msg: Dict[str, bool] = {}
    for c in changes:
        if c.breaking:
            breaking_by_msg[c.message] = True
    out: Dict[str, dict] = {}
    shared = sorted(set(old.get("messages", {}))
                    & set(new.get("messages", {})))
    for name in shared:
        o, n = old["messages"][name], new["messages"][name]
        n_is_live = live.get(name) is not None and (
            live[name]["version"] == n["version"]
            and live[name]["fields"] == n["fields"])
        rng = random.Random(seed ^ hash(name) & 0xFFFFFFFF)
        o2n = {"ok": True, "error": None, "skipped": []}
        n2o = {"ok": True, "error": None, "skipped": []}
        identity = True
        for _ in range(trials):
            # Old wire, new receiver.
            ofields = gen.gen_fields(rng, o)
            known = {f["name"] for f in n["fields"]}
            sim = gen.simulate_decode(ofields, o["version"], n)
            if not sim["ok"]:
                o2n = {"ok": False, "error": sim["error"],
                       "skipped": o2n["skipped"]}
            else:
                o2n["skipped"] = sorted(
                    set(o2n["skipped"]) | set(sim["skipped"]))
            if n_is_live and o2n["ok"]:
                # Empirical confirmation against the real decoder.
                frame = gen.build_frame(name, o["version"], ofields)
                try:
                    wire.decode(frame)
                except wire.WireError as e:
                    o2n = {"ok": False, "error": str(e),
                           "skipped": o2n["skipped"]}
            # New wire, old receiver (simulated from catalog data).
            nfields = gen.gen_fields(rng, n)
            sim = gen.simulate_decode(nfields, n["version"], o)
            if not sim["ok"]:
                n2o = {"ok": False, "error": sim["error"],
                       "skipped": n2o["skipped"]}
            else:
                n2o["skipped"] = sorted(
                    set(n2o["skipped"]) | set(sim["skipped"]))
            # Byte-identity evidence for reorders: shared fields
            # encoded in each catalog's order.
            shared_names = [f["name"] for f in o["fields"]
                            if f["name"] in known]
            vals = dict(ofields)
            frame_old_order = gen.build_frame(
                name, o["version"],
                [(fn, vals[fn]) for fn in shared_names])
            new_order = [f["name"] for f in n["fields"]
                         if f["name"] in vals
                         and f["name"] in shared_names]
            frame_new_order = gen.build_frame(
                name, o["version"],
                [(fn, vals[fn]) for fn in new_order])
            if frame_old_order != frame_new_order:
                identity = False
        out[name] = {
            "classified": ("breaking" if breaking_by_msg.get(name)
                           else "compatible"),
            "old_to_new": o2n,
            "new_to_old": n2o,
            "byte_identity": identity,
        }
    return out
