"""Wire-schema extraction: wire.py's AST + the live registry.

Two independent views of the same contract, cross-checked:

- the **AST pass** walks ``ray_tpu/_private/wire.py`` without importing
  it: every ``@message("Name", version=N)`` class with its fields in
  declared (= encode) order, the tag alphabet the encoder emits AND the
  decoder accepts (a tag present on one side only is itself a finding),
  and the decode nesting bound;
- the **live pass** imports the module and reads ``_REGISTRY`` plus the
  per-class decode plans.

Any disagreement between the two (a message registered dynamically that
the AST can't see, an AST class that never registered, version or field
drift) is reported as an extraction problem — the schema the gate
diffs must be the schema the code actually speaks.

The rendered schema is canonical: sorted message names, fields in
declared order (field order IS the encode byte order — reorders are
visible), stable JSON. ``RAYWIRE_SCHEMA.json`` at the repo root is the
committed baseline the compat gate diffs against.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Dict, List, Optional, Tuple

SCHEMA_VERSION = 1
WIRE_RELPATH = "ray_tpu/_private/wire.py"

# The escape hatch the compat gate honors for a breaking change:
#   # raywire: migration=<wire.Name> -- <justification>
# anywhere in wire.py (raylint's suppression grammar, pointed at the
# schema instead of a rule).
MIGRATION_RE = re.compile(
    r"#\s*raywire:\s*migration=([\w.]+)\s*--\s*(?P<why>.+?)\s*$")

WIRE_SCALARS = ("int", "float", "str", "bytes", "bool", "dict", "list",
                "tuple")


@dataclasses.dataclass
class FieldSpec:
    name: str
    type: str            # a WIRE_SCALARS entry or "Any"
    has_default: bool

    def as_schema(self) -> dict:
        return {"name": self.name, "type": self.type,
                "has_default": self.has_default}


@dataclasses.dataclass
class MessageSpec:
    name: str            # wire name ("rpc.Request")
    version: int
    pyclass: str
    line: int
    fields: List[FieldSpec]

    def as_schema(self) -> dict:
        return {"version": self.version, "class": self.pyclass,
                "fields": [f.as_schema() for f in self.fields]}


@dataclasses.dataclass
class Extraction:
    schema: dict
    migration_notes: Dict[str, str]     # wire name -> justification
    problems: List[str]

    @property
    def ok(self) -> bool:
        return not self.problems


def _annotation_name(node) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Subscript):
        return _annotation_name(node.value)
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return ast.dump(node)


def _message_decorator(cls: ast.ClassDef) -> Optional[Tuple[str, int, int]]:
    """(wire_name, version, lineno) when cls carries @message(...)."""
    for dec in cls.decorator_list:
        if not (isinstance(dec, ast.Call)
                and isinstance(dec.func, ast.Name)
                and dec.func.id == "message"):
            continue
        if not (dec.args and isinstance(dec.args[0], ast.Constant)
                and isinstance(dec.args[0].value, str)):
            return None
        name = dec.args[0].value
        version = 1
        if len(dec.args) > 1 and isinstance(dec.args[1], ast.Constant):
            version = dec.args[1].value
        for kw in dec.keywords:
            if kw.arg == "version" and isinstance(kw.value, ast.Constant):
                version = kw.value.value
        return name, version, dec.lineno
    return None


def _ast_messages(tree: ast.Module) -> List[MessageSpec]:
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        dec = _message_decorator(node)
        if dec is None:
            continue
        wire_name, version, line = dec
        fields = []
        for stmt in node.body:
            if not (isinstance(stmt, ast.AnnAssign)
                    and isinstance(stmt.target, ast.Name)):
                continue
            tname = _annotation_name(stmt.annotation)
            if tname not in WIRE_SCALARS:
                tname = "Any" if tname == "Any" else tname
            fields.append(FieldSpec(
                name=stmt.target.id, type=tname,
                has_default=stmt.value is not None))
        out.append(MessageSpec(name=wire_name, version=version,
                               pyclass=node.name, line=line,
                               fields=fields))
    return out


def _byte_const(node) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, bytes) \
            and len(node.value) == 1:
        return node.value.decode("latin-1")
    return None


def _encoder_tags(tree: ast.Module) -> set:
    """Tags the encoder can emit: `out += b"X"` in _encode_value."""
    tags = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) \
                and node.name == "_encode_value":
            for sub in ast.walk(node):
                if isinstance(sub, ast.AugAssign):
                    # The value may be a bare constant or a
                    # conditional (`b"l" if isinstance(...) else
                    # b"t"`): walk the whole value expression.
                    for leaf in ast.walk(sub.value):
                        t = _byte_const(leaf)
                        if t is not None:
                            tags.add(t)
    return tags


def _decoder_tags(tree: ast.Module) -> set:
    """Tags the decoder accepts: comparisons against `tag` in
    _Decoder.value (both `tag == b"X"` and `tag in (b"l", b"t")`)."""
    tags = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.ClassDef)
                and node.name == "_Decoder"):
            continue
        for fn in node.body:
            if not (isinstance(fn, ast.FunctionDef)
                    and fn.name == "value"):
                continue
            for sub in ast.walk(fn):
                if not isinstance(sub, ast.Compare):
                    continue
                for cmp_node in sub.comparators:
                    t = _byte_const(cmp_node)
                    if t is not None:
                        tags.add(t)
                    if isinstance(cmp_node, (ast.Tuple, ast.List)):
                        for el in cmp_node.elts:
                            t = _byte_const(el)
                            if t is not None:
                                tags.add(t)
    return tags


def _max_depth(tree: ast.Module) -> Optional[int]:
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) \
                        and tgt.id == "_MAX_DEPTH" \
                        and isinstance(node.value, ast.Constant):
                    return node.value.value
    return None


def _live_catalog() -> Dict[str, dict]:
    """The imported module's view: registry + decode plans."""
    import dataclasses as dc

    from ray_tpu._private import wire

    out = {}
    for name, (cls, version) in wire._REGISTRY.items():
        plan = wire._declared_fields(cls)
        fields = []
        for f in dc.fields(cls):
            base_name, _checks = plan[f.name]
            has_default = (f.default is not dc.MISSING
                           or f.default_factory is not dc.MISSING)
            fields.append({"name": f.name, "type": base_name,
                           "has_default": has_default})
        out[name] = {"version": version, "class": cls.__name__,
                     "fields": fields}
    return out


def extract(repo_root: Optional[str] = None) -> Extraction:
    root = os.path.abspath(repo_root or os.getcwd())
    path = os.path.join(root, WIRE_RELPATH)
    with open(path, "r", encoding="utf-8") as f:
        source = f.read()
    tree = ast.parse(source, filename=path)
    problems: List[str] = []

    messages = _ast_messages(tree)
    by_name: Dict[str, MessageSpec] = {}
    for m in messages:
        if m.name in by_name:
            problems.append(
                f"duplicate @message name {m.name!r} "
                f"(classes {by_name[m.name].pyclass} and {m.pyclass})")
        by_name[m.name] = m

    enc_tags = _encoder_tags(tree)
    dec_tags = _decoder_tags(tree)
    if enc_tags - dec_tags:
        problems.append(
            "encoder emits tags the decoder does not accept: "
            f"{sorted(enc_tags - dec_tags)}")
    if dec_tags - enc_tags:
        problems.append(
            "decoder accepts tags the encoder never emits: "
            f"{sorted(dec_tags - enc_tags)}")
    depth = _max_depth(tree)
    if depth is None:
        problems.append("wire.py declares no _MAX_DEPTH nesting bound")

    # Cross-check AST vs live registry.
    live = _live_catalog()
    for name in sorted(set(by_name) - set(live)):
        problems.append(
            f"@message class {name!r} in the AST never registered "
            "(import-order or conditional registration?)")
    for name in sorted(set(live) - set(by_name)):
        problems.append(
            f"registered message {name!r} has no @message class in "
            f"{WIRE_RELPATH} (dynamic registration defeats review)")
    for name in sorted(set(by_name) & set(live)):
        a, lv = by_name[name], live[name]
        if a.version != lv["version"]:
            problems.append(
                f"{name}: AST version {a.version} != live "
                f"{lv['version']}")
        ast_fields = [(f.name, f.type, f.has_default) for f in a.fields]
        live_fields = [(f["name"], f["type"], f["has_default"])
                       for f in lv["fields"]]
        if ast_fields != live_fields:
            problems.append(
                f"{name}: AST fields {ast_fields} != live decode plan "
                f"{live_fields}")

    notes: Dict[str, str] = {}
    for line in source.splitlines():
        m = MIGRATION_RE.search(line)
        if m:
            notes[m.group(1)] = m.group("why")

    schema = {
        "schema_version": SCHEMA_VERSION,
        "source": WIRE_RELPATH,
        "frame": {
            "tags": sorted(enc_tags | dec_tags),
            "length_prefix": "u32 big-endian",
            "message_header": "M tag, name:str, version:u16, "
                              "nfields:u16, then nfields x "
                              "(name:str, value)",
            "max_depth": depth,
        },
        "messages": {name: by_name[name].as_schema()
                     for name in sorted(by_name)},
    }
    return Extraction(schema=schema, migration_notes=notes,
                      problems=problems)


def render_schema(schema: dict) -> str:
    """Canonical bytes for the committed baseline (stable ordering so
    regeneration is diff-clean)."""
    return json.dumps(schema, indent=2, sort_keys=True) + "\n"


def load_baseline(path: str) -> Optional[dict]:
    if not os.path.exists(path):
        return None
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)
