"""raywire: the wire-protocol analysis rung.

The fifth rung of the analysis ladder (raylint proves structure, raysan
replays one schedule, raymc exhausts interleavings, rayspec proves
sequential refinement — raywire proves the wire):

- ``extract``  — static schema extraction: wire.py's AST plus the live
  ``_REGISTRY``, cross-checked, rendered into the canonical committed
  baseline ``RAYWIRE_SCHEMA.json``;
- ``compat``   — cross-version compatibility gate: diff extracted vs
  baseline, classify every change against the actual decode semantics,
  fail breaking changes unless the version literal was bumped with a
  justified migration note, and prove the classification empirically
  with a skew simulator (old-catalog frames under the new catalog and
  vice versa);
- ``fuzz``     — grammar-derived structure-aware fuzzing of
  ``wire.decode``, the rpc length-prefix framing, ``head.ShardRow``
  application, and the serve proxy's HTTP/1.1 parser: every input must
  decode or reject TYPED within a time/allocation bound;
- ``fixtures`` — ddmin-minimized hex-blob regression fixtures for every
  defect the fuzzer ever surfaced (``tests/core/wire_fixtures/``).

CLI: ``python -m tools.raywire`` (see __main__.py for the exit-code
contract and the ``RAYWIRE_REPORT.json`` artifact).
"""
