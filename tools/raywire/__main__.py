"""CLI: ``python -m tools.raywire [--fuzz N] [--report json] ...``

One invocation runs the whole rung and reports the form CI archives as
``RAYWIRE_REPORT.json``:

1. **extract** — schema from wire.py's AST cross-checked against the
   live registry (any disagreement is a failure on its own);
2. **gate** — diff against the committed ``RAYWIRE_SCHEMA.json``
   baseline, classify changes, enforce version-bump + migration-note
   on breaking ones, and prove the classification with the skew
   simulator;
3. **fuzz** — the seeded grammar-derived campaign over wire.decode,
   the rpc framing, shard-row application, and the proxy parser,
   plus the allocation-bomb probes;
4. **roundtrip** — byte-identity encode(decode(encode(x))) over
   generated instances of every registered message;
5. **fixtures** — replay the minimized regression corpus.

Exit-code contract (raylint's):
  0  clean
  1  at least one finding/failure in any stage
  2  usage error (no baseline without --write-baseline, bad args)

``--write-baseline`` regenerates ``RAYWIRE_SCHEMA.json`` from the
current wire.py — the one sanctioned way to accept a schema change
(the gate still demands the version bump + migration note first).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

BASELINE_NAME = "RAYWIRE_SCHEMA.json"


def _roundtrip_suite(schema: dict, per_message: int,
                     seed: int) -> dict:
    """encode -> decode -> encode byte identity for every registered
    message, natively-typed generated values."""
    import random

    from ray_tpu._private import wire
    from tools.raywire import gen

    rng = random.Random(seed)
    failures = []
    checked = 0
    for name in sorted(schema["messages"]):
        entry = schema["messages"][name]
        for _ in range(per_message):
            inst = gen.build_instance(name, entry, rng)
            raw = wire.encode(inst)
            back = wire.decode(raw)
            checked += 1
            if back != inst:
                failures.append({"message": name,
                                 "kind": "value_mismatch",
                                 "input_hex": raw[:256].hex()})
            elif wire.encode(back) != raw:
                failures.append({"message": name,
                                 "kind": "byte_identity",
                                 "input_hex": raw[:256].hex()})
    return {"checked": checked, "failures": failures,
            "ok": not failures}


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.raywire",
        description="wire-schema extraction, compatibility gating, "
                    "and grammar-derived decode fuzzing")
    parser.add_argument("--fuzz", type=int, default=10000,
                        metavar="N",
                        help="fuzz inputs per run (0 disables)")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--roundtrip-per-message", type=int,
                        default=25, metavar="N")
    parser.add_argument("--report", choices=("json", "pretty"),
                        default="pretty")
    parser.add_argument("--report-file", default="", metavar="PATH",
                        help="also write the JSON report to PATH")
    parser.add_argument("--baseline", default="", metavar="PATH",
                        help=f"schema baseline (default: "
                             f"{BASELINE_NAME} at the repo root)")
    parser.add_argument("--write-baseline", action="store_true",
                        help="regenerate the baseline from the "
                             "current wire.py and exit")
    parser.add_argument("--repo-root", default="", metavar="DIR")
    args = parser.parse_args(argv)

    root = os.path.abspath(args.repo_root or os.getcwd())
    baseline_path = args.baseline or os.path.join(root, BASELINE_NAME)

    from tools.raywire import compat, extract, fixtures, fuzz

    t0 = time.monotonic()
    ex = extract.extract(root)

    if args.write_baseline:
        if not ex.ok:
            for p in ex.problems:
                print(f"raywire: extraction problem: {p}",
                      file=sys.stderr)
            print("raywire: refusing to write a baseline from a "
                  "schema the code disagrees with", file=sys.stderr)
            return 1
        with open(baseline_path, "w", encoding="utf-8") as f:
            f.write(extract.render_schema(ex.schema))
        print(f"raywire: wrote {baseline_path} "
              f"({len(ex.schema['messages'])} messages)")
        return 0

    baseline = extract.load_baseline(baseline_path)
    if baseline is None:
        print(f"raywire: no baseline at {baseline_path}; run "
              "--write-baseline once and commit it", file=sys.stderr)
        return 2

    gate = compat.run_gate(baseline, ex.schema, ex.migration_notes,
                           seed=args.seed)
    fuzz_report = (fuzz.run_fuzz(ex.schema, n_inputs=args.fuzz,
                                 seed=args.seed)
                   if args.fuzz > 0 else None)
    roundtrip = _roundtrip_suite(ex.schema,
                                 args.roundtrip_per_message,
                                 args.seed)
    fixture_results = fixtures.replay_all(
        os.path.join(root, fixtures.FIXTURE_DIR))
    fixture_failures = [r for r in fixture_results if not r["ok"]]

    fuzz_ok = (fuzz_report is None
               or (not fuzz_report["findings"]
                   and not fuzz_report["slow"]
                   and all(p["ok"]
                           for p in fuzz_report["alloc_probes"])))
    report = {
        "schema_version": 1,
        "harness": "python -m tools.raywire",
        "extraction": {"ok": ex.ok, "problems": ex.problems,
                       "messages": len(ex.schema["messages"])},
        "gate": gate.as_report(),
        "fuzz": fuzz_report,
        "roundtrip": roundtrip,
        "fixtures": {"replayed": len(fixture_results),
                     "failures": fixture_failures,
                     "ok": not fixture_failures
                     and bool(fixture_results)},
        "elapsed_s": round(time.monotonic() - t0, 3),
    }
    report["pass"] = (ex.ok and gate.ok and fuzz_ok
                      and roundtrip["ok"]
                      and report["fixtures"]["ok"])

    if args.report == "json":
        print(json.dumps(report, indent=2))
    else:
        print(f"raywire[extract]: "
              f"{'ok' if ex.ok else 'PROBLEMS'} — "
              f"{len(ex.schema['messages'])} messages")
        for p in ex.problems:
            print(f"  {p}")
        print(f"raywire[gate]: {'ok' if gate.ok else 'FAIL'} — "
              f"{len(gate.changes)} change(s), "
              f"{len(gate.failures)} failure(s)")
        for c in gate.changes:
            marker = "BREAKING" if c.breaking else "compatible"
            print(f"  [{marker}] {c.message}: {c.kind} — {c.detail}")
        for f in gate.failures:
            print(f"  FAIL: {f}")
        if fuzz_report is not None:
            print(f"raywire[fuzz]: "
                  f"{'ok' if fuzz_ok else 'FINDINGS'} — "
                  f"{fuzz_report['inputs']} inputs, "
                  f"{len(fuzz_report['findings'])} finding(s), "
                  f"{len(fuzz_report['slow'])} slow, alloc probes "
                  f"{'ok' if all(p['ok'] for p in fuzz_report['alloc_probes']) else 'FAIL'}")
            for f in fuzz_report["findings"][:20]:
                print(f"  {f['target']}/{f['mutator']}: "
                      f"{f['exc_type']}: {f['message']} "
                      f"[{f['input_hex'][:80]}]")
        print(f"raywire[roundtrip]: "
              f"{'ok' if roundtrip['ok'] else 'FAIL'} — "
              f"{roundtrip['checked']} instances")
        print(f"raywire[fixtures]: "
              f"{'ok' if report['fixtures']['ok'] else 'FAIL'} — "
              f"{len(fixture_results)} replayed")
        for r in fixture_failures:
            print(f"  FAIL {r['name']}: got {r['got']}, "
                  f"want {r['want']}")

    if args.report_file:
        from tools.reporting import write_report_artifact

        write_report_artifact(args.report_file, report,
                              volatile=("elapsed_s", "peak_bytes"))

    return 0 if report["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
