"""Minimized fuzz-fixture corpus: load, classify, replay.

Every defect the fuzzer ever surfaced lives on as a checked-in fixture
under ``tests/core/wire_fixtures/`` — a small JSON file holding the
ddmin-minimized input as a hex blob plus the outcome the fixed code
must produce. The replay is the regression test: each input is driven
against its live target and must land on a TYPED outcome (accept, or
reject with the recorded exception family) with nothing else escaping.

Fixture file shape (one JSON object per ``.json`` file):

    {
      "name": "wire-deep-nest",
      "target": "wire" | "rpc" | "shard" | "proxy",
      "input_hex": "4d…",
      "expect": "accept" | "reject",
      "exc_type": "WireError",          # when expect == "reject"
      "note": "why this input exists (the defect it once triggered)"
    }
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List

FIXTURE_DIR = os.path.join("tests", "core", "wire_fixtures")


def load_fixtures(dirpath: str = FIXTURE_DIR) -> List[Dict[str, Any]]:
    out = []
    if not os.path.isdir(dirpath):
        return out
    for fname in sorted(os.listdir(dirpath)):
        if not fname.endswith(".json"):
            continue
        with open(os.path.join(dirpath, fname), "r",
                  encoding="utf-8") as f:
            fx = json.load(f)
        fx["_file"] = fname
        out.append(fx)
    return out


def save_fixture(fx: Dict[str, Any],
                 dirpath: str = FIXTURE_DIR) -> str:
    os.makedirs(dirpath, exist_ok=True)
    path = os.path.join(dirpath, fx["name"] + ".json")
    clean = {k: v for k, v in fx.items() if not k.startswith("_")}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(clean, f, indent=2, sort_keys=True)
        f.write("\n")
    return path


def classify(target: str, data: bytes) -> Dict[str, Any]:
    """Drive ``data`` against the raw target operation and name the
    outcome: {"outcome": "accept" | "reject", "exc_type", "detail"}.
    Anything OTHER than the target's typed-rejection family
    propagates to the caller — that is the regression."""
    from ray_tpu._private import wire

    if target == "wire":
        try:
            wire.decode(data)
            return {"outcome": "accept", "exc_type": None,
                    "detail": None}
        except wire.WireError as e:
            return {"outcome": "reject",
                    "exc_type": type(e).__name__,
                    "detail": str(e)[:200]}
    if target == "rpc":
        from ray_tpu._private import rpc
        from tools.raywire.fuzz import _BufSock

        try:
            rpc.recv_msg(_BufSock(data))
            return {"outcome": "accept", "exc_type": None,
                    "detail": None}
        except (wire.WireError, ConnectionError) as e:
            return {"outcome": "reject",
                    "exc_type": type(e).__name__,
                    "detail": str(e)[:200]}
    if target == "shard":
        from ray_tpu._private.head_shards import HeadShardState

        try:
            msg = wire.decode(data)
        except wire.WireError as e:
            return {"outcome": "reject",
                    "exc_type": type(e).__name__,
                    "detail": str(e)[:200]}
        state = HeadShardState(0, 1)
        try:
            state.apply([msg])
            return {"outcome": "accept", "exc_type": None,
                    "detail": None}
        except wire.WireError as e:
            return {"outcome": "reject",
                    "exc_type": type(e).__name__,
                    "detail": str(e)[:200]}
    if target == "proxy":
        from tools.raywire.fuzz import _fresh_conn

        conn = _fresh_conn()
        conn.buf = data
        conn._parse()
        errors = [r.error for r in conn.backlog
                  if getattr(r, "error", None) is not None]
        if errors:
            status, body = errors[0]
            return {"outcome": "reject", "exc_type": f"http_{status}",
                    "detail": body.decode("utf-8", "replace")[:200]}
        return {"outcome": "accept", "exc_type": None,
                "detail": f"{len(conn.backlog)} request(s) parsed, "
                          f"{len(conn.buf)} byte(s) pending"}
    raise ValueError(f"unknown fixture target {target!r}")


def replay(fx: Dict[str, Any]) -> Dict[str, Any]:
    """Replay one fixture. Returns {"ok", "got", "want", "name"} —
    ok means the outcome matched AND nothing untyped escaped (an
    escaped exception propagates out of classify and fails the
    caller loudly, which is the point)."""
    data = bytes.fromhex(fx["input_hex"])
    got = classify(fx["target"], data)
    want_outcome = fx["expect"]
    ok = got["outcome"] == want_outcome
    if ok and want_outcome == "reject" and fx.get("exc_type"):
        ok = got["exc_type"] == fx["exc_type"]
    return {"ok": ok, "name": fx["name"], "got": got,
            "want": {"outcome": want_outcome,
                     "exc_type": fx.get("exc_type")}}


def replay_all(dirpath: str = FIXTURE_DIR) -> List[Dict[str, Any]]:
    return [replay(fx) for fx in load_fixtures(dirpath)]
