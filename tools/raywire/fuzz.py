"""Grammar-derived structure-aware decode fuzzing.

Random bytes barely scratch a tagged format — the first byte is an
invalid tag 95% of the time and the run never gets past the header. So
the generator starts from the extracted schema: every input begins life
as a VALID frame (correct tags, correct length prefixes, registered
message names, in-range versions) and is then broken in exactly one
structured way (truncation, length-field inflation, future version,
unknown name, nesting past the bound, oversized strings, tag swaps,
byte flips). That lands inputs deep in the decoder where the interesting
branches are.

Four drive targets, one contract each:

- ``wire``  — ``wire.decode(data)`` returns a value or raises
  ``WireError``. Any other exception type is a finding.
- ``rpc``   — ``recv_msg`` over a buffer-backed socket: a value,
  ``WireError`` (``FrameTooLarge`` included), or ``ConnectionError``
  (short stream). Nothing else.
- ``shard`` — fuzzed frames that decode to ``head.ShardRow`` (plus raw
  fuzzed tuples) fed to ``HeadShardState.apply``: applied or
  ``WireError``. Unknown tables/ops/key types must reject, not corrupt.
- ``proxy`` — mutated HTTP/1.1 request bytes through the serve proxy's
  ``_Conn._parse``: requests land in the backlog, the parser waits for
  more bytes, or it halts with an error pseudo-request. No exception.

Every input also runs under a wall-time bound (decode must be O(input),
never O(declared length)), and dedicated length-inflation probes run
under ``tracemalloc`` to prove a 2 GiB length prefix costs bytes of
allocation, not gigabytes.

Crashing inputs are ddmin-minimized (``tools.raymc.minimize`` — the
same delta debugger raymc uses on schedule traces, applied to byte
positions) before being reported, so fixtures stay readable.
"""

from __future__ import annotations

import dataclasses
import random
import struct
import time
import tracemalloc
from typing import Callable, Dict, List, Tuple

from tools.raymc.minimize import ddmin
from tools.raywire import gen

_U32 = struct.Struct("!I")
_U16 = struct.Struct("!H")

# Generous per-input ceiling: a healthy decode of a <64KiB frame is
# microseconds; blowing 250ms means super-linear work (e.g. decode
# driven by a declared length instead of actual bytes).
TIME_BOUND_S = 0.25

# A length-inflation probe claims ~2GiB; decoding its <100 bytes must
# allocate no more than this.
ALLOC_BOUND_BYTES = 1 << 20


@dataclasses.dataclass
class Finding:
    target: str
    mutator: str
    exc_type: str
    message: str
    input_hex: str           # ddmin-minimized reproducer
    minimized_from: int      # original input length in bytes


# -- seed-frame generation ---------------------------------------------------


def gen_seed_frame(rng: random.Random, schema: dict) -> bytes:
    """A fully valid frame: usually a registered message, sometimes a
    bare scalar (the decoder accepts both at top level)."""
    from ray_tpu._private import wire

    messages = schema["messages"]
    if rng.random() < 0.8 and messages:
        name = rng.choice(sorted(messages))
        entry = messages[name]
        return gen.build_frame(name, entry["version"],
                               gen.gen_fields(rng, entry))
    return wire.encode(gen.gen_value(rng, "Any"))


# -- structured mutators -----------------------------------------------------
#
# Each takes (rng, frame) -> bytes. "identity" keeps a slice of the
# corpus valid so the nominal path stays covered too.


def _mut_identity(rng: random.Random, frame: bytes) -> bytes:
    return frame


def _mut_truncate(rng: random.Random, frame: bytes) -> bytes:
    if len(frame) <= 1:
        return b""
    return frame[:rng.randrange(len(frame))]


def _mut_inflate_length(rng: random.Random, frame: bytes) -> bytes:
    """Overwrite one plausible u32 length field with a huge value —
    the canonical allocation-bomb shape."""
    if len(frame) < 5:
        return frame + _U32.pack(0xFFFFFFF0)
    pos = rng.randrange(len(frame) - 4)
    huge = rng.choice((0x7FFFFFFF, 0xFFFFFFFF, 1 << 30))
    return frame[:pos] + _U32.pack(huge) + frame[pos + 4:]


def _mut_future_version(rng: random.Random, frame: bytes) -> bytes:
    """Bump the version u16 of an M frame (header: M, str name,
    u16 version)."""
    if not frame.startswith(b"M") or len(frame) < 7:
        return frame
    name_len = _U32.unpack_from(frame, 1)[0]
    vpos = 5 + name_len
    if vpos + 2 > len(frame):
        return frame
    return frame[:vpos] + _U16.pack(rng.choice((99, 2, 0xFFFF))) \
        + frame[vpos + 2:]


def _mut_unknown_name(rng: random.Random, frame: bytes) -> bytes:
    if not frame.startswith(b"M"):
        return frame
    name = rng.choice((b"no.SuchMsg", b"", b"\xff\xfe bad utf8",
                       b"rpc.Request2"))
    name_len = _U32.unpack_from(frame, 1)[0] if len(frame) >= 5 else 0
    rest = frame[5 + name_len:]
    return b"M" + _U32.pack(len(name)) + name + rest


def _mut_deep_nest(rng: random.Random, frame: bytes) -> bytes:
    """Nesting past _MAX_DEPTH: 200 one-element-list shells."""
    depth = rng.choice((70, 200, 1000))
    return b"l" + _U32.pack(1) * 1 \
        + (b"l" + _U32.pack(1)) * (depth - 1) + b"i" \
        + struct.Struct("!q").pack(0)


def _mut_oversized_string(rng: random.Random, frame: bytes) -> bytes:
    """A string whose declared length exceeds the bytes present."""
    claimed = rng.choice((10**6, 0x7FFFFFFF))
    body = b"x" * rng.randrange(64)
    return b"s" + _U32.pack(claimed) + body


def _mut_tag_swap(rng: random.Random, frame: bytes) -> bytes:
    if not frame:
        return frame
    pos = rng.randrange(len(frame))
    tag = rng.choice(b"NTFiIdsbltmMO\xff\x00")
    return frame[:pos] + bytes((tag,)) + frame[pos + 1:]


def _mut_bit_flip(rng: random.Random, frame: bytes) -> bytes:
    if not frame:
        return b"\x00"
    pos = rng.randrange(len(frame))
    return frame[:pos] + bytes((frame[pos] ^ (1 << rng.randrange(8)),)) \
        + frame[pos + 1:]


def _mut_splice(rng: random.Random, frame: bytes) -> bytes:
    """Concatenate a frame into itself at a random cut — misaligned
    nested structures."""
    if len(frame) < 2:
        return frame + frame
    cut = rng.randrange(len(frame))
    return frame[:cut] + frame + frame[cut:]


def _mut_http_dup_cl(rng: random.Random, frame: bytes) -> bytes:
    """A second, conflicting Content-Length — the classic
    request-smuggling shape the proxy must 400."""
    cl = rng.choice((b"Content-Length: 0\r\n",
                     b"Content-Length: 9999\r\n",
                     b"content-length: 1\r\n"))
    end = frame.find(b"\r\n\r\n")
    if end < 0:
        return cl + frame
    return frame[:end + 2] + cl + frame[end + 2:]


def _mut_http_bad_cl(rng: random.Random, frame: bytes) -> bytes:
    """Content-Length values int() accepts but RFC 9110 does not."""
    bad = rng.choice((b"+5", b" 7 ", b"1_0", b"-3", b"0x10",
                      "٥".encode(),  # ARABIC-INDIC digit five
                      b"99999999999999999999"))
    end = frame.find(b"\r\n\r\n")
    hdr = b"Content-Length: " + bad + b"\r\n"
    if end < 0:
        return hdr + frame
    return frame[:end + 2] + hdr + frame[end + 2:]


MUTATORS: List[Tuple[str, Callable[[random.Random, bytes], bytes]]] = [
    ("identity", _mut_identity),
    ("truncate", _mut_truncate),
    ("inflate_length", _mut_inflate_length),
    ("future_version", _mut_future_version),
    ("unknown_name", _mut_unknown_name),
    ("deep_nest", _mut_deep_nest),
    ("oversized_string", _mut_oversized_string),
    ("tag_swap", _mut_tag_swap),
    ("bit_flip", _mut_bit_flip),
    ("splice", _mut_splice),
    ("http_dup_cl", _mut_http_dup_cl),
    ("http_bad_cl", _mut_http_bad_cl),
]


# -- drive targets -----------------------------------------------------------


class _BufSock:
    """A socket whose recv() serves a fixed byte buffer, then EOF."""

    def __init__(self, data: bytes):
        self._buf = data

    def recv(self, n: int) -> bytes:
        chunk, self._buf = self._buf[:n], self._buf[n:]
        return chunk


def drive_wire(data: bytes) -> None:
    from ray_tpu._private import wire

    try:
        wire.decode(data)
    except wire.WireError:
        pass


def drive_rpc(data: bytes) -> None:
    """The length-prefixed framing layer: the fuzz payload arrives as
    the body of a well-formed frame AND as the raw stream itself (so
    both the prefix parse and the body decode are exercised)."""
    from ray_tpu._private import rpc, wire

    for stream in (_U32.pack(len(data)) + data, data):
        try:
            rpc.recv_msg(_BufSock(stream))
        except (wire.WireError, ConnectionError):
            pass


def drive_shard(data: bytes) -> None:
    """Frames that decode into ShardRow (or anything else) go through
    HeadShardState.apply — the skew seam where a newer/older peer's
    rows enter this process's tables."""
    from ray_tpu._private import wire
    from ray_tpu._private.head_shards import HeadShardState

    try:
        msg = wire.decode(data)
    except wire.WireError:
        return
    state = HeadShardState(0, 1)
    try:
        state.apply([msg])
    except wire.WireError:
        pass


def _fresh_conn():
    """A _Conn with only the parser's state, no event loop."""
    from ray_tpu.serve._private.http_proxy import _Conn
    from collections import deque

    conn = _Conn.__new__(_Conn)
    conn.buf = b""
    conn.backlog = deque()
    conn._need = None
    conn._halt_parse = False
    return conn


def gen_http_request(rng: random.Random) -> bytes:
    method = rng.choice(("GET", "POST", "PUT", "DELETE"))
    path = rng.choice(("/", "/v1/chat", "/-/healthz", "/app/x%20y"))
    body = bytes(rng.randrange(256) for _ in range(rng.randrange(48)))
    headers = [f"Host: fuzz", f"X-Trace-Id: t{rng.randrange(999)}"]
    if body or rng.random() < 0.5:
        headers.append(f"Content-Length: {len(body)}")
    if rng.random() < 0.3:
        headers.append("Connection: " + rng.choice(("close",
                                                    "keep-alive")))
    head = f"{method} {path} HTTP/1.1\r\n" + "\r\n".join(headers)
    return head.encode() + b"\r\n\r\n" + body


def drive_proxy(data: bytes) -> None:
    """Feed the bytes whole and in a 1..7-byte dribble (re-entrant
    _parse with partial state) — outcomes are backlog entries, a wait
    for more bytes, or a parse halt. Never an exception."""
    conn = _fresh_conn()
    conn.buf = data
    conn._parse()
    conn2 = _fresh_conn()
    step = 1 + (len(data) % 7)
    for i in range(0, len(data), step):
        conn2.buf += data[i:i + step]
        conn2._parse()


TARGETS: Dict[str, Callable[[bytes], None]] = {
    "wire": drive_wire,
    "rpc": drive_rpc,
    "shard": drive_shard,
    "proxy": drive_proxy,
}

# Mutators for HTTP inputs (the wire mutators assume tag grammar).
_HTTP_MUTATORS = ("identity", "truncate", "bit_flip", "splice",
                  "oversized_string", "http_dup_cl", "http_bad_cl")
_HTTP_ONLY = ("http_dup_cl", "http_bad_cl")


def _minimize(data: bytes, drive: Callable[[bytes], None],
              exc_type: type) -> bytes:
    """ddmin over byte positions: the smallest subsequence that still
    raises the same exception type out of the same driver."""

    def fails(positions: List[int]) -> bool:
        candidate = bytes(data[i] for i in positions)
        try:
            drive(candidate)
        except exc_type:
            return True
        except Exception:
            return False
        return False

    positions = list(range(len(data)))
    if not fails(positions):      # flaky (timing-only) — keep as-is
        return data
    kept = ddmin(fails, positions, max_probes=128)
    return bytes(data[i] for i in kept)


def run_fuzz(schema: dict, n_inputs: int = 10000, seed: int = 0,
             time_bound_s: float = TIME_BOUND_S) -> dict:
    """The full campaign. Returns a report dict:
    {"inputs", "per_target", "per_mutator", "slow", "findings"}."""
    rng = random.Random(seed)
    findings: List[Finding] = []
    per_target: Dict[str, int] = {t: 0 for t in TARGETS}
    per_mutator: Dict[str, int] = {m: 0 for m, _fn in MUTATORS}
    slow: List[dict] = []
    wire_targets = ("wire", "rpc", "shard")

    for i in range(n_inputs):
        if rng.random() < 0.25:
            target = "proxy"
            seed_input = gen_http_request(rng)
            mut_name, mut = rng.choice(MUTATORS)
            while mut_name not in _HTTP_MUTATORS:
                mut_name, mut = rng.choice(MUTATORS)
        else:
            target = rng.choice(wire_targets)
            seed_input = gen_seed_frame(rng, schema)
            mut_name, mut = rng.choice(MUTATORS)
            while mut_name in _HTTP_ONLY:
                mut_name, mut = rng.choice(MUTATORS)
        data = mut(rng, seed_input)
        per_target[target] += 1
        per_mutator[mut_name] += 1
        drive = TARGETS[target]

        t0 = time.monotonic()
        try:
            drive(data)
        except Exception as e:
            minimized = _minimize(data, drive, type(e))
            findings.append(Finding(
                target=target, mutator=mut_name,
                exc_type=type(e).__name__, message=str(e)[:200],
                input_hex=minimized.hex(),
                minimized_from=len(data)))
            continue
        elapsed = time.monotonic() - t0
        if elapsed > time_bound_s:
            slow.append({"target": target, "mutator": mut_name,
                         "elapsed_s": round(elapsed, 3),
                         "input_hex": data[:256].hex(),
                         "input_len": len(data)})

    report = {
        "inputs": n_inputs,
        "seed": seed,
        "per_target": per_target,
        "per_mutator": per_mutator,
        "slow": slow,
        "alloc_probes": run_alloc_probes(),
        "findings": [dataclasses.asdict(f) for f in findings],
    }
    return report


def run_alloc_probes() -> List[dict]:
    """Crafted allocation bombs under tracemalloc: each claims ~2GiB
    in a length field; the decode/reject must stay under
    ALLOC_BOUND_BYTES of peak allocation."""
    from ray_tpu._private import rpc, wire

    huge = 0x7FFFFF00
    probes = [
        ("wire_str", lambda: _swallow(
            wire.decode, b"s" + _U32.pack(huge))),
        ("wire_bytes", lambda: _swallow(
            wire.decode, b"b" + _U32.pack(huge))),
        ("wire_list_count", lambda: _swallow(
            wire.decode, b"l" + _U32.pack(huge))),
        ("rpc_frame_prefix", lambda: _swallow(
            rpc.recv_msg, _BufSock(_U32.pack(huge) + b"x" * 64))),
    ]
    out = []
    for name, fn in probes:
        tracemalloc.start()
        try:
            fn()
            _current, peak = tracemalloc.get_traced_memory()
        finally:
            tracemalloc.stop()
        out.append({"probe": name, "peak_bytes": peak,
                    "ok": peak < ALLOC_BOUND_BYTES})
    return out


def _swallow(fn, arg):
    from ray_tpu._private import wire

    try:
        fn(arg)
    except (wire.WireError, ConnectionError):
        pass
