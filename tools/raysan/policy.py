"""Default suppression policy: the justified, deliberate exceptions.

raylint keeps its suppressions inline (a disable comment carrying a
justification) because static findings anchor to a source line.
Runtime findings anchor to *state* (an fd, a thread, a registry key),
so the justified exceptions live here instead — one :class:`Allow` per
deliberately-leaked resource class, justification REQUIRED (a
reason-less entry is itself reported; see ``core.apply_policy``).

Keep this list short and specific: every entry is a hole in the
sanitizer. Per-test exceptions belong on the test as
``@pytest.mark.sanitize_allow(...)``, not here.
"""

from __future__ import annotations

from tools.raysan.core import Allow

DEFAULT_POLICY = [
    Allow(
        "leaks", r"pooled RpcClient",
        reason="RpcClient._pools is process-lifetime by design: one "
               "connection per (process, address), reused across "
               "tests the way production reuses it across jobs; "
               "closing per test would retest connection setup, not "
               "the runtime"),
    Allow(
        "leaks", r"thread leaked: 'pydev|thread leaked: 'IPython",
        reason="debugger/REPL host threads are owned by the tool "
               "running the suite, not by the code under test"),
    Allow(
        "leaks", r"thread leaked: 'critical-path-folder",
        reason="the stage-span fold thread is process-lifetime by "
               "design: hot paths pay one deque append and the folder "
               "absorbs the accumulation off the request path; it is "
               "started once on first record and parks in sleep() "
               "between 100ms fold beats"),
    Allow(
        "leaks", r"fd leaked: file fd=\d+ \(/dev/shm/ray_tpu",
        reason="SharedPlane.destroy(unmap=False) at cluster teardown "
               "unlinks the segment but DELIBERATELY leaves the "
               "driver's mapping (and its dup'd fd) intact: fetch "
               "threads mid-read keep a valid mapping instead of "
               "segfaulting; the unlinked pages free at process exit"),
]
