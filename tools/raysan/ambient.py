"""Ambient/global-state sanitizer: residue that outlives its test.

Two recurring flake classes motivated this (CHANGES.md PR 6):

1. **Thread-local ambient tags on pooled threads.** The ambient job id
   and trace parent (``task_spec.set_ambient_job_id`` /
   ``set_ambient_trace_parent``) ride ``threading.local`` — invisible
   from any other thread, so a set without a try/finally restore on a
   pooled executor thread silently tags every later task that thread
   runs. The sanitizer taps the setters through
   ``sanitize_hooks.install_ambient_observer`` (the only way to see
   per-thread residue from the outside) and flags any *live* thread
   whose tag is still set at teardown.

2. **Process-global registries mutated without reset.** The
   ``serve_request_seconds`` fast-path distributions, the global
   ``health.tracker`` burn-rate history, and the loop-lag sample/token
   tables are process-global by design; a test that records into them
   and exits poisons every later test that assumes a clean baseline —
   the order-dependent healthz flake, exactly. The sanitizer snapshots
   them before each test (via the runtime's own reset hooks:
   ``perf_stats.snapshot_records`` / ``health.snapshot_state``) and
   flags any un-restored mutation.

Findings **self-heal**: after flagging, the sanitizer restores the
baseline (and adopts ambient residue into it), so one offending test
produces one finding instead of cascading failures through the rest of
the run. The autouse fixture in ``tests/conftest.py`` restores the
same state unconditionally, which is why the suite passes this
sanitizer clean — remove the fixture and the sanitizer tells you which
test needed it.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Tuple

from tools.raysan.core import Finding, Sanitizer

_AMBIENT_KINDS = ("job_id", "trace_parent")


class AmbientSanitizer(Sanitizer):
    name = "ambient"

    def __init__(self):
        self._lock = threading.Lock()
        # (kind, thread ident) -> last value the setter wrote
        self._ambient: Dict[Tuple[str, int], object] = {}
        self._ambient_base: Dict[Tuple[str, int], object] = {}
        self._serve_snap = None
        self._health_snap = None
        self._prev_observer = None

    # -- session -----------------------------------------------------------

    def start_session(self) -> None:
        from ray_tpu._private import sanitize_hooks

        self._prev_observer = sanitize_hooks._ambient_set
        sanitize_hooks.install_ambient_observer(self._observe)

    def stop_session(self) -> None:
        from ray_tpu._private import sanitize_hooks

        sanitize_hooks.install_ambient_observer(self._prev_observer)

    def _observe(self, kind: str, ident: int, value: object) -> None:
        with self._lock:
            self._ambient[(kind, ident)] = value

    # -- per-test ----------------------------------------------------------

    def before_test(self, test_id: str) -> None:
        from ray_tpu._private import health, perf_stats

        with self._lock:
            self._ambient_base = dict(self._ambient)
        self._serve_snap = perf_stats.snapshot_records(
            "serve_request_seconds")
        self._health_snap = health.snapshot_state()

    def after_test(self, test_id: str) -> List[Finding]:
        from ray_tpu._private import health, perf_stats

        findings: List[Finding] = []

        # -- ambient thread-local residue --------------------------------
        live = {t.ident: t.name for t in threading.enumerate()
                if t.is_alive()}
        with self._lock:
            current = dict(self._ambient)
        for (kind, ident), value in sorted(current.items(),
                                           key=lambda kv: repr(kv[0])):
            if value is None or ident not in live:
                continue
            if self._ambient_base.get((kind, ident)) == value:
                continue  # pre-existing residue: flagged at its source
            findings.append(Finding(
                sanitizer=self.name, test=test_id,
                message=f"ambient {kind} {value!r} left set on live "
                        f"thread {live[ident]!r} — a pooled executor "
                        f"thread will silently tag unrelated work",
                detail="set without a token/try-finally restore "
                       "(raylint R7's dynamic counterpart)"))

        # -- serve_request_seconds records -------------------------------
        # Zeroed == absent: a series first created during the test and
        # rolled back by restore_records stays registered with empty
        # records (dropping it would orphan live references) — that is
        # a clean restore, not residue.
        now = self._nonzero(
            perf_stats.snapshot_records("serve_request_seconds"))
        base = self._nonzero(self._serve_snap)
        if now != base:
            changed = sorted(
                {tags for tags in set(now) | set(base)
                 if now.get(tags) != base.get(tags)})
            findings.append(Finding(
                sanitizer=self.name, test=test_id,
                message=f"serve_request_seconds records mutated without "
                        f"reset ({len(changed)} tagged series): "
                        f"{changed[:4]}",
                detail="process-global dist: un-reset records read as "
                       "live SLO burn in every later healthz test "
                       "(the PR 6 order-dependent flake class)"))
            perf_stats.restore_records("serve_request_seconds",
                                       self._serve_snap)

        # -- health tracker + loop-lag tables ----------------------------
        now_health = health.snapshot_state()
        if not self._health_equiv(now_health, self._health_snap):
            findings.append(Finding(
                sanitizer=self.name, test=test_id,
                message="health tracker/loop-lag state mutated without "
                        "reset (burn-rate history or lag components "
                        "survived the test)",
                detail=self._health_diff(self._health_snap, now_health)))
            health.restore_state(self._health_snap)
        return findings

    @staticmethod
    def _nonzero(snap: dict) -> dict:
        out = {}
        for tags, rec in snap.items():
            if isinstance(rec, tuple):
                counts, total, total_sum = rec
                if total == 0 and total_sum == 0 and not any(counts):
                    continue
            elif not rec:
                continue
            out[tags] = rec
        return out

    @staticmethod
    def _health_equiv(a: dict, b: dict) -> bool:
        # Full dict equality: key-only comparison would miss in-place
        # VALUE mutations (an existing component's lag overwritten, a
        # sampler token replaced) — the exact residue being hunted.
        return (a["tracker_samples"] == b["tracker_samples"]
                and a["loop_lag"] == b["loop_lag"]
                and a["sampler_components"] == b["sampler_components"])

    @staticmethod
    def _health_diff(before: dict, after: dict) -> str:
        parts = []
        if after["tracker_samples"] != before["tracker_samples"]:
            parts.append(
                f"tracker snapshots: {len(before['tracker_samples'])} "
                f"-> {len(after['tracker_samples'])}")
        for key in ("loop_lag", "sampler_components"):
            gained = sorted(set(after[key]) - set(before[key]))
            lost = sorted(set(before[key]) - set(after[key]))
            if gained:
                parts.append(f"{key} gained {gained}")
            if lost:
                parts.append(f"{key} lost {lost}")
        return "; ".join(parts) or "(content drift)"
