"""pytest integration: ``pytest --sanitize=locks,loop,leaks,ambient``.

The plugin owns the sanitizer lifecycle around the ordinary test
protocol:

- session start: instantiate the requested sanitizers and install
  their process-wide observation (lock-factory wrappers, the asyncio
  handle timer, the ambient-setter tap);
- per test: snapshot BEFORE fixture setup (so fixture-created
  resources are attributed to the test that requested them) and diff
  AFTER every fixture finalizer has run (so anything a fixture tears
  down is already gone) — an unsuppressed finding raises at the end of
  teardown and fails the test like any teardown error, pointing at the
  exact test that leaked;
- session end: the accumulated findings (suppressed ones included,
  with their justifications) are written as a JSON report when
  ``--sanitize-report=PATH`` is given — the CI artifact.

Per-test suppression: ``@pytest.mark.sanitize_allow(sanitizer,
pattern, reason="...")`` — the reason is required (raylint R0
semantics: a bare allow does not suppress and is itself reported).
"""

from __future__ import annotations

import pytest


def pytest_addoption(parser):
    group = parser.getgroup("raysan", "runtime sanitizers")
    group.addoption(
        "--sanitize", default="", metavar="LIST",
        help="comma-separated runtime sanitizers to enable: "
             "locks,loop,leaks,ambient (or 'all')")
    group.addoption(
        "--sanitize-report", default="", metavar="PATH",
        help="write the session's sanitizer findings as JSON to PATH")
    group.addoption(
        "--sanitize-loop-threshold-ms", type=float, default=100.0,
        metavar="MS",
        help="loop sanitizer: flag event-loop callbacks holding the "
             "loop longer than MS milliseconds (default 100)")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "sanitize_allow(sanitizer, pattern, reason=...): suppress "
        "matching raysan findings for this test; reason is REQUIRED "
        "(a reason-less allow is itself a finding)")
    spec = config.getoption("--sanitize")
    if not spec:
        return
    from tools.raysan.core import SANITIZER_NAMES, Session, \
        make_sanitizers

    names = list(SANITIZER_NAMES) if spec.strip() == "all" \
        else [n for n in spec.split(",") if n.strip()]
    try:
        sanitizers = make_sanitizers(
            names,
            loop_threshold_ms=config.getoption(
                "--sanitize-loop-threshold-ms"))
    except KeyError as e:
        raise pytest.UsageError(f"--sanitize: {e.args[0]}")
    config._raysan = Session(sanitizers)
    config._raysan.start()


def pytest_unconfigure(config):
    session = getattr(config, "_raysan", None)
    if session is None:
        return
    session.stop()
    path = config.getoption("--sanitize-report")
    if path:
        with open(path, "w", encoding="utf-8") as f:
            f.write(session.report().to_json())
    config._raysan = None


def _test_allows(item):
    from tools.raysan.core import Allow

    allows = []
    for mark in item.iter_markers("sanitize_allow"):
        sanitizer = mark.args[0] if mark.args else ""
        pattern = mark.args[1] if len(mark.args) > 1 else ".*"
        allows.append(Allow(sanitizer, pattern,
                            mark.kwargs.get("reason", "")))
    return allows


@pytest.hookimpl(tryfirst=True)
def pytest_runtest_setup(item):
    session = getattr(item.config, "_raysan", None)
    if session is not None:
        session.before_test(item.nodeid)


class SanitizerFailure(Exception):
    """Raised at the end of teardown when a test left unsuppressed
    sanitizer findings; pytest reports it as a teardown error on the
    offending test."""

    # Hide the plugin frame from the traceback pytest prints.
    __module__ = "builtins"


@pytest.hookimpl(trylast=True)
def pytest_runtest_teardown(item, nextitem):
    session = getattr(item.config, "_raysan", None)
    if session is None:
        return
    findings = session.after_test(item.nodeid,
                                  test_allows=_test_allows(item))
    active = [f for f in findings if not f.suppressed]
    if active:
        raise SanitizerFailure(
            "raysan: %d unsuppressed finding(s):\n%s" % (
                len(active), "\n".join(f.render() for f in active)))
