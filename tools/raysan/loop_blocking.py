"""Event-loop blocking detector: dynamic raylint R1.

R1 statically flags synchronous calls inside ``async def``; this is
the runtime complement: every callback an asyncio loop runs is timed
(a patch over ``asyncio.events.Handle._run``, which both plain and
timer handles funnel through), and a callback that holds the loop for
longer than the threshold becomes a finding.

The *offending stack* is captured live, not reconstructed: a watchdog
thread wakes at a fraction of the threshold and, when it sees a
callback that has already overstayed, samples the loop thread's
current frame via ``sys._current_frames()`` — i.e. the stack of
whatever synchronous work is actually wedging the loop mid-stall,
which is the thing the static rule can only guess at.

Per test, stalls aggregate by callback description (one finding per
offender with count + worst-case duration) so a hot callback cannot
flood the report.
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from typing import Dict, List, Tuple

from tools.raysan.core import Finding, Sanitizer


class LoopBlockingSanitizer(Sanitizer):
    name = "loop"

    def __init__(self, threshold_ms: float = 100.0):
        self.threshold_s = threshold_ms / 1000.0
        self._orig_run = None
        # loop-thread ident -> (handle, t0, stack_holder)
        self._running: Dict[int, Tuple[object, float, list]] = {}
        self._lock = threading.Lock()
        # desc -> (count, worst_s, stack) for the current test
        self._stalls: Dict[str, Tuple[int, float, str]] = {}
        self._watchdog_stop = threading.Event()
        self._watchdog = None

    # -- installation ------------------------------------------------------

    def start_session(self) -> None:
        import asyncio.events

        sanitizer = self
        self._orig_run = orig = asyncio.events.Handle._run

        def timed_run(handle):
            ident = threading.get_ident()
            holder: list = []
            sanitizer._running[ident] = (handle, time.monotonic(), holder)
            try:
                return orig(handle)
            finally:
                entry = sanitizer._running.pop(ident, None)
                if entry is not None:
                    elapsed = time.monotonic() - entry[1]
                    if elapsed >= sanitizer.threshold_s:
                        sanitizer._record(handle, elapsed, holder)

        asyncio.events.Handle._run = timed_run
        self._watchdog_stop.clear()
        self._watchdog = threading.Thread(  # raylint: disable=R4 -- stop_session() (the Sanitizer-protocol teardown the pytest plugin invokes at session end) sets the stop event and joins this watchdog; R4's name list just doesn't know the sanitizer lifecycle verbs
            target=self._watch, daemon=True, name="raysan-loop-watchdog")
        self._watchdog.start()

    def stop_session(self) -> None:
        import asyncio.events

        if self._orig_run is not None:
            asyncio.events.Handle._run = self._orig_run
            self._orig_run = None
        self._watchdog_stop.set()
        if self._watchdog is not None:
            self._watchdog.join(timeout=2.0)
            self._watchdog = None

    # -- sampling ----------------------------------------------------------

    def _watch(self) -> None:
        period = max(self.threshold_s / 4.0, 0.005)
        while not self._watchdog_stop.wait(period):
            now = time.monotonic()
            for ident, (handle, t0, holder) in list(
                    self._running.items()):
                if now - t0 < self.threshold_s or holder:
                    continue
                frame = sys._current_frames().get(ident)
                if frame is not None:
                    holder.append("".join(
                        traceback.format_stack(frame, limit=12)))

    @staticmethod
    def _describe(handle) -> str:
        cb = getattr(handle, "_callback", None)
        if cb is None:
            return repr(handle)
        name = getattr(cb, "__qualname__", None) or repr(cb)
        mod = getattr(cb, "__module__", "")
        return f"{mod}.{name}" if mod else name

    def _record(self, handle, elapsed: float, holder: list) -> None:
        desc = self._describe(handle)
        stack = holder[0] if holder else "(stall ended before the " \
                                        "watchdog sampled a stack)"
        with self._lock:
            count, worst, first_stack = self._stalls.get(
                desc, (0, 0.0, stack))
            self._stalls[desc] = (count + 1, max(worst, elapsed),
                                  first_stack)

    # -- per-test ----------------------------------------------------------

    def before_test(self, test_id: str) -> None:
        with self._lock:
            self._stalls.clear()

    def after_test(self, test_id: str) -> List[Finding]:
        with self._lock:
            stalls, self._stalls = self._stalls, {}
        return [
            Finding(
                sanitizer=self.name, test=test_id,
                message=f"event loop blocked {worst * 1e3:.0f}ms by "
                        f"{desc} ({count} stall(s) over "
                        f"{self.threshold_s * 1e3:.0f}ms)",
                detail=stack)
            for desc, (count, worst, stack) in sorted(stalls.items())
        ]
