"""raysan.sched: deterministic interleaving schedules over yield points.

The runtime's fixed races (router reserved-slot oversubscription, the
``PipelinedClient`` close-before-flush orphan sweep) were all *ordering*
bugs: two threads crossing a handful of well-known boundaries in an
unlucky order. This module makes that order a first-class, replayable
test input instead of a property of the OS scheduler.

Product code exposes named **yield points** at its concurrency
boundaries via ``ray_tpu._private.sanitize_hooks.sched_point`` (the
router's reserved→in-flight handoff, the batcher drain, the pipelined
reader's loop edge, ...). With no schedule installed a point is a
no-op. Under a :class:`Schedule` a crossing can be *gated*:

- **Scripted mode** (``Schedule(order=[...])``): ``order`` is the exact
  sequence of point crossings the test demands. A thread crossing a
  listed point parks until every earlier entry has been crossed;
  unlisted crossings pass freely. Entries are ``"name"`` (first
  crossing of ``name``), ``"name#k"`` (the k-th crossing), or —
  when symmetric threads cross the same point and global occurrence
  numbers can't tell them apart — ``"name@role"`` / ``"name@role#k"``
  (the k-th crossing of ``name`` by the thread NAMED ``role``; raymc's
  emitted counterexamples use this form so each scenario thread is
  pinned individually). This is fully deterministic: the same script
  forces the same interleaving on every run — the replay half of the
  harness.
- **Crash injection** (``Schedule(order=[...], crash_at=[...])``):
  each ``crash_at`` entry (same key syntax) raises
  ``sanitize_hooks.SimulatedCrash`` out of the matching crossing after
  it is gated and recorded — the replay half of raymc's crash-fault
  exploration: a minimized counterexample that killed a component at a
  crash point replays that death at exactly the same crossing.
- **Seeded mode** (``Schedule(seed=n)``): every crossing consults a
  seeded RNG to decide whether to pause briefly — long enough for any
  concurrently-running thread to overtake through the window — before
  proceeding. Pauses are bounded (``pause_max_s``), so exploration can
  never deadlock; the crossing log (:attr:`trace`) converts to a
  script via :meth:`trace_order` for exact replay of whatever a seed
  found.

Tests mark their own side of an interleaving with
:meth:`Schedule.cross` (a manual point), so scripts can order test
actions against internal threads the test never created (e.g. the
pipelined client's reader).

A gated thread that waits longer than ``timeout_s`` raises
:class:`ScheduleTimeout` naming every pending entry and every parked
thread — a wrong script fails loudly in seconds, never hangs a suite.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Dict, List, Optional, Tuple

from ray_tpu._private import sanitize_hooks


class ScheduleTimeout(RuntimeError):
    """A gated crossing waited out ``timeout_s`` — the script demands
    an ordering the code under test never produced (or the schedule
    deadlocked against a real lock)."""


class Schedule:
    """One deterministic (scripted) or seeded (exploring) interleaving.

    Use as a context manager to install the yield-point hook::

        sched = Schedule(order=["pipe.closed_set", "pipe.reader_loop#2"])
        with sched:
            ...   # run the threads under test
        assert sched.completed

    Only one schedule can be installed at a time (they are process-wide
    by design: internal runtime threads must see the same schedule as
    the test's own threads).
    """

    def __init__(self, order: Optional[List[str]] = None,
                 seed: Optional[int] = None,
                 timeout_s: float = 5.0,
                 pause_prob: float = 0.5,
                 pause_max_s: float = 0.05,
                 crash_at: Optional[List[str]] = None,
                 on_cross=None):
        if order is not None and seed is not None:
            raise ValueError("order= and seed= are mutually exclusive")
        self._order = list(order) if order else []
        if len(set(self._order)) != len(self._order):
            raise ValueError(f"duplicate entries in order: {self._order}")
        self._rng = random.Random(seed) if seed is not None else None
        self._timeout = timeout_s
        self._pause_prob = pause_prob
        self._pause_max = pause_max_s
        self._cond = threading.Condition()
        self._counts: Dict[str, int] = {}   # name -> crossings so far
        # (name, thread name) -> crossings so far, for @role entries.
        self._role_counts: Dict[Tuple[str, str], int] = {}
        self._done = [False] * len(self._order)
        self._generation = 0                # bumps on every crossing
        self._parked: Dict[int, str] = {}   # thread ident -> entry/point
        self._released = False              # __exit__ opened all gates
        self._crash_at = set(crash_at or [])
        self._crashes_fired: set = set()
        # State-snapshot seam: called as on_cross(key, thread_name)
        # after every recorded crossing, in the crossing thread, so a
        # checker can snapshot protocol state at exactly this boundary
        # (raymc's invariant bookkeeping rides it during replays).
        self._on_cross = on_cross
        self.trace: List[Tuple[str, str]] = []  # (key, thread name)
        self._prev_hook = None
        self._prev_crash_hook = None

    def set_on_cross(self, fn) -> None:
        """Install the state-snapshot seam after construction (raymc
        wires a scenario's bookkeeping into replayed counterexamples
        this way)."""
        self._on_cross = fn

    # -- installation ------------------------------------------------------

    def __enter__(self) -> "Schedule":
        self._prev_hook = sanitize_hooks._sched_point
        self._prev_crash_hook = sanitize_hooks._crash_point
        sanitize_hooks.install_sched_point(self.point)
        # Crash points gate like yield points under a schedule (and are
        # the targets crash_at kills), so install into that seam too.
        sanitize_hooks.install_crash_point(self.point)
        return self

    def __exit__(self, *exc) -> None:
        sanitize_hooks.install_sched_point(self._prev_hook)
        sanitize_hooks.install_crash_point(self._prev_crash_hook)
        # Release anything still parked so stray threads don't hold the
        # suite hostage after the test body is done with the schedule —
        # WITHOUT forging `_done`: `completed` must keep reporting
        # whether the script actually played out (the race fixtures'
        # acceptance assertions read it after this block).
        with self._cond:
            self._released = True
            self._cond.notify_all()

    # -- crossing ----------------------------------------------------------

    def cross(self, name: str) -> None:
        """A test-side yield point: identical to product code crossing
        ``sanitize_hooks.sched_point(name)``."""
        self.point(name)

    def point(self, name: str) -> None:
        role = threading.current_thread().name
        with self._cond:
            occ = self._counts.get(name, 0) + 1
            self._counts[name] = occ
            rocc = self._role_counts.get((name, role), 0) + 1
            self._role_counts[(name, role)] = rocc
            key = f"{name}#{occ}"
            candidates = self._candidate_keys(name, role, occ, rocc)
            idx = self._entry_index(candidates)
        if idx is not None:
            self._gate(idx, key)
        elif self._rng is not None:
            self._maybe_pause(key)
        else:
            self._record(key)
        self._after_cross(name, role, key, candidates)

    @staticmethod
    def _candidate_keys(name: str, role: str, occ: int,
                        rocc: int) -> List[str]:
        """Entry keys this crossing can satisfy, most specific first
        (a role-qualified entry wins over a global-occurrence one)."""
        cands = [f"{name}@{role}#{rocc}"]
        if rocc == 1:
            cands.append(f"{name}@{role}")
        cands.append(f"{name}#{occ}")
        if occ == 1:
            cands.append(name)
        return cands

    def _entry_index(self, candidates: List[str]) -> Optional[int]:
        for key in candidates:
            if key in self._order:
                return self._order.index(key)
        return None

    def _after_cross(self, name: str, role: str, key: str,
                     candidates: List[str]) -> None:
        """Post-crossing seams: the on_cross snapshot callback, then
        crash injection — the crossing is recorded and its gate marked
        done BEFORE the simulated death, so `completed` and the trace
        reflect that the kill really happened at this boundary."""
        cb = self._on_cross
        if cb is not None:
            try:
                cb(key, role)
            except Exception:
                pass
        if not self._crash_at:
            return
        with self._cond:
            if self._released:
                return  # torn down: don't kill cleanup-phase threads
            hit = None
            for k in candidates:
                if k in self._crash_at and k not in self._crashes_fired:
                    hit = k
                    break
            if hit is not None:
                self._crashes_fired.add(hit)
        if hit is not None:
            raise sanitize_hooks.SimulatedCrash(name)

    def _gate(self, idx: int, key: str) -> None:
        deadline = time.monotonic() + self._timeout
        ident = threading.get_ident()
        with self._cond:
            self._parked[ident] = self._order[idx]
            try:
                while not all(self._done[:idx]):
                    if self._released:
                        # Torn down mid-park: pass the thread through
                        # but do NOT mark the entry done — the script
                        # did not play out, and `completed` says so.
                        self._record_locked(key)
                        return
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise ScheduleTimeout(self._timeout_msg(idx))
                    self._cond.wait(remaining)
            finally:
                self._parked.pop(ident, None)
            self._done[idx] = True
            self._record_locked(key)
            self._cond.notify_all()

    def _maybe_pause(self, key: str) -> None:
        pause = self._rng.random() < self._pause_prob
        with self._cond:
            if pause:
                # Hold this thread in the window until some OTHER
                # crossing happens (another thread overtaking through
                # the race window) or the bounded pause expires.
                gen = self._generation
                deadline = time.monotonic() + self._pause_max
                while self._generation == gen:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(remaining)
            self._record_locked(key)
            self._cond.notify_all()

    def _record(self, key: str) -> None:
        with self._cond:
            self._record_locked(key)
            self._cond.notify_all()

    def _record_locked(self, key: str) -> None:
        self._generation += 1
        self.trace.append((key, threading.current_thread().name))

    def _timeout_msg(self, idx: int) -> str:
        pending = [self._order[i] for i in range(idx)
                   if not self._done[i]]
        parked = {threading.current_thread().name: self._order[idx]}
        for ident, entry in self._parked.items():
            for t in threading.enumerate():
                if t.ident == ident:
                    parked[t.name] = entry
        if self.trace:
            last_key, last_thread = self.trace[-1]
            last = f"{last_key} (by {last_thread})"
        else:
            last = "<none - no point was ever crossed>"
        return (f"schedule timeout at {self._order[idx]!r}: waiting on "
                f"{pending}; last successfully crossed point: {last}; "
                f"parked threads: {parked}; "
                f"crossed so far: {[k for k, _ in self.trace]}")

    def parked_at(self, name: str) -> bool:
        """True while some thread is parked at the gate for ``name``
        (exact entry, or any ``name#k`` / ``name@role[#k]`` occurrence
        of it) — the test-side synchronization for 'wait until A is in
        the window'."""
        with self._cond:
            return any(entry == name
                       or entry.split("#")[0].split("@")[0] == name
                       for entry in self._parked.values())

    # -- results -----------------------------------------------------------

    @property
    def completed(self) -> bool:
        """True when every scripted entry was crossed."""
        with self._cond:
            return all(self._done)

    def trace_order(self) -> List[str]:
        """The crossing log as a script: feed to ``Schedule(order=...)``
        to replay exactly the interleaving this (seeded) run produced."""
        with self._cond:
            return [key for key, _ in self.trace]


def find_race(run, seeds=range(16), **schedule_kwargs):
    """Exploration driver: run ``run(schedule)`` under each seed until
    one reproduces the race. ``run`` returns truthy when the race
    manifested (or raises — treated the same, with the exception
    swallowed into the result).

    Returns ``(seed, trace_order)`` for the first reproducing seed, or
    ``None`` when no seed in the sweep found it. The returned trace
    replays the interleaving deterministically via
    ``Schedule(order=trace_order)``.
    """
    for seed in seeds:
        sched = Schedule(seed=seed, **schedule_kwargs)
        try:
            with sched:
                hit = run(sched)
        except Exception:
            hit = True
        if hit:
            return seed, sched.trace_order()
    return None
