"""raysan core: findings, suppression policy, and the sanitizer session.

raylint (``tools/raylint``) is the static half of the concurrency
story; raysan is the dynamic half. A **sanitizer** observes one class
of runtime state (held locks, event-loop stalls, process resources,
ambient/global mutations) across a test and reports :class:`Finding`\\ s
at teardown. The pytest plugin (``tools.raysan.pytest_plugin``) drives
the per-test snapshot/diff cycle; ``python -m tools.raysan`` wraps a
whole run and emits the JSON artifact CI archives.

Suppression mirrors raylint's contract: a finding is only suppressed
by an :class:`Allow` entry that carries a justification — the default
policy (``tools/raysan/policy.py``) and per-test
``@pytest.mark.sanitize_allow(sanitizer, pattern, reason=...)``
markers both use it, and a reason-less allow is itself a finding
(the ``policy`` meta sanitizer, raylint's R0 analog).
"""

from __future__ import annotations

import dataclasses
import json
import re
import time
from typing import Iterable, List, Optional

SANITIZER_NAMES = ("locks", "loop", "leaks", "ambient")


@dataclasses.dataclass
class Finding:
    sanitizer: str          # "locks" | "loop" | "leaks" | "ambient" | "policy"
    test: str               # pytest nodeid ("" outside any test)
    message: str            # one-line defect statement
    detail: str = ""        # stacks / diffs / edge sites
    suppressed: bool = False
    justification: str = ""

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, data: dict) -> "Finding":
        return cls(**data)

    def render(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        head = f"[{self.sanitizer}]{tag} {self.test or '<session>'}: " \
               f"{self.message}"
        if self.detail:
            indented = "\n".join("    " + ln
                                 for ln in self.detail.splitlines())
            return head + "\n" + indented
        return head


@dataclasses.dataclass(frozen=True)
class Allow:
    """One justified suppression: findings from ``sanitizer`` whose
    message matches ``pattern`` (regex, searched) are suppressed,
    carrying ``reason`` into the report. A reason-less Allow does not
    suppress and is reported by the policy meta-check instead."""

    sanitizer: str
    pattern: str
    reason: str = ""

    def matches(self, finding: Finding) -> bool:
        return (self.sanitizer == finding.sanitizer
                and re.search(self.pattern, finding.message) is not None)


def apply_policy(findings: Iterable[Finding],
                 allows: List[Allow],
                 reported_bad: Optional[set] = None) -> List[Finding]:
    """Mark suppressed findings in place (raylint semantics: an allow
    without a reason fails to suppress, and surfaces as a ``policy``
    finding once per offending allow). ``reported_bad`` carries the
    already-reported reason-less allows across calls — the Session
    passes one per run, so a bad SESSION-LEVEL allow fails once (the
    R0 analog reports a bare disable once), not on every test."""
    out = list(findings)
    bad_allows = []
    for allow in allows:
        if not allow.reason:
            if reported_bad is not None:
                if allow in reported_bad:
                    continue
                reported_bad.add(allow)
            if allow not in bad_allows:
                bad_allows.append(allow)
    for f in out:
        for allow in allows:
            if allow.reason and allow.matches(f):
                f.suppressed = True
                f.justification = allow.reason
                break
    for allow in bad_allows:
        out.append(Finding(
            sanitizer="policy", test="",
            message=f"allow({allow.sanitizer!r}, {allow.pattern!r}) has "
                    f"no justification: every suppression needs "
                    f"`reason=...` (raylint R0 analog)"))
    return out


class Sanitizer:
    """Base class: a sanitizer installs process-wide observation at
    session start, snapshots before each test, and diffs at teardown.

    ``after_test`` runs after every fixture finalizer for the test has
    completed, so anything a fixture tears down has already been torn
    down — what is left is what leaked."""

    name = "?"

    def start_session(self) -> None:
        pass

    def stop_session(self) -> None:
        pass

    def before_test(self, test_id: str) -> None:
        pass

    def after_test(self, test_id: str) -> List[Finding]:
        return []


@dataclasses.dataclass
class Report:
    sanitizers: List[str]
    findings: List[Finding]
    tests_checked: int
    elapsed_s: float

    @property
    def active(self) -> List[Finding]:
        return [f for f in self.findings if not f.suppressed]

    @property
    def suppressed(self) -> List[Finding]:
        return [f for f in self.findings if f.suppressed]

    def to_json(self) -> str:
        return json.dumps({
            "sanitizers": self.sanitizers,
            "tests_checked": self.tests_checked,
            "elapsed_s": round(self.elapsed_s, 3),
            "findings": [f.to_dict() for f in self.active],
            "suppressed": [f.to_dict() for f in self.suppressed],
        }, indent=2)

    def render_pretty(self) -> str:
        lines = [f.render() for f in self.active]
        lines.append(
            f"raysan[{','.join(self.sanitizers)}]: "
            f"{self.tests_checked} tests, {len(self.active)} finding(s), "
            f"{len(self.suppressed)} suppressed, {self.elapsed_s:.2f}s")
        return "\n".join(lines)


def make_sanitizers(names: Iterable[str], **options) -> List[Sanitizer]:
    """Instantiate the requested sanitizers (unknown name -> KeyError
    listing the catalog). Options are passed to the sanitizers that
    take them (currently ``loop_threshold_ms``)."""
    from tools.raysan.ambient import AmbientSanitizer
    from tools.raysan.leaks import LeakSanitizer
    from tools.raysan.lock_witness import LockOrderSanitizer
    from tools.raysan.loop_blocking import LoopBlockingSanitizer

    table = {
        "locks": LockOrderSanitizer,
        "loop": lambda: LoopBlockingSanitizer(
            threshold_ms=options.get("loop_threshold_ms", 100.0)),
        "leaks": LeakSanitizer,
        "ambient": AmbientSanitizer,
    }
    out: List[Sanitizer] = []
    for name in names:
        name = name.strip()
        if not name:
            continue
        if name not in table:
            raise KeyError(
                f"unknown sanitizer {name!r}; known: "
                f"{', '.join(SANITIZER_NAMES)}")
        out.append(table[name]())
    return out


class Session:
    """One sanitizer run: owns the active sanitizers, accumulates
    findings, applies the suppression policy, renders the report."""

    def __init__(self, sanitizers: List[Sanitizer],
                 extra_allows: Optional[List[Allow]] = None):
        from tools.raysan.policy import DEFAULT_POLICY

        self.sanitizers = sanitizers
        self.allows = list(DEFAULT_POLICY) + list(extra_allows or [])
        self.findings: List[Finding] = []
        self.tests_checked = 0
        self._reported_bad_allows: set = set()
        self._t0 = time.monotonic()

    def start(self) -> None:
        for s in self.sanitizers:
            s.start_session()

    def stop(self) -> None:
        for s in self.sanitizers:
            s.stop_session()

    def before_test(self, test_id: str) -> None:
        for s in self.sanitizers:
            s.before_test(test_id)

    def after_test(self, test_id: str,
                   test_allows: Optional[List[Allow]] = None) \
            -> List[Finding]:
        """Diff every sanitizer, apply policy + per-test allows, record
        into the session report; returns this test's findings."""
        self.tests_checked += 1
        new: List[Finding] = []
        for s in self.sanitizers:
            new.extend(s.after_test(test_id))
        new = apply_policy(new, self.allows + list(test_allows or []),
                           reported_bad=self._reported_bad_allows)
        self.findings.extend(new)
        return new

    def report(self) -> Report:
        return Report(
            sanitizers=[s.name for s in self.sanitizers],
            findings=list(self.findings),
            tests_checked=self.tests_checked,
            elapsed_s=time.monotonic() - self._t0)
