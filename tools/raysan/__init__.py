"""raysan: runtime concurrency/leak sanitizers + deterministic-schedule
race replay for the ray_tpu runtime.

The dynamic half of the concurrency story (raylint, ``tools/raylint``,
is the static half — same rule numbering, opposite phase):

- ``locks``   — lock-order witness: runtime held-before graph with
  cycle detection over wrapped ``threading`` locks (dynamic R2);
- ``loop``    — event-loop blocking detector: times every asyncio
  callback, samples the offending stack mid-stall (dynamic R1);
- ``leaks``   — per-test accounting of threads, fds (sockets, sqlite),
  actors, and ``memory_store`` entries with teardown diffing
  (dynamic R4);
- ``ambient`` — thread-local ambient tags and process-global
  registries (``serve_request_seconds``, ``health.tracker``) mutated
  by a test but not reset — the order-dependent-flake class
  (dynamic R7).

Run via pytest (``pytest --sanitize=leaks,ambient tests/core``) or the
CLI (``python -m tools.raysan --report json``). ``raysan.sched``
(:class:`Schedule`, :func:`find_race`) is the deterministic
interleaving harness the race-replay regression fixtures use.
"""

from tools.raysan.core import (  # noqa: F401
    Allow,
    Finding,
    Report,
    Sanitizer,
    Session,
    make_sanitizers,
)
from tools.raysan.sched import (  # noqa: F401
    Schedule,
    ScheduleTimeout,
    find_race,
)
