"""Leak sanitizer: per-test resource accounting with teardown diffing.

The dynamic half of raylint R4 (resource-lifecycle): R4 proves a
teardown *exists*; this proves it *ran*. Before each test the sanitizer
snapshots the process's resource census — live threads, open file
descriptors (sockets, sqlite/database files, pipes — read straight
from ``/proc/self/fd``), registered actors, and ``memory_store``
entries — and diffs it after every fixture finalizer has completed.
Anything the test created and nobody released is a finding.

Thread findings get a grace window first (daemon threads legitimately
take a few scheduler ticks to observe a shutdown flag); fd findings run
after the grace so a retiring thread's socket close counts. New fds
belonging to the process-lifetime ``RpcClient`` connection pool are
attributed by name so the default policy can suppress them with a
justification instead of the report showing anonymous socket inodes.

Store/actor diffs only fire when the *same* store/backend instance
survived the test (a test that inits and shuts down its own runtime
has nothing to leak into).
"""

from __future__ import annotations

import gc
import os
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from tools.raysan.core import Finding, Sanitizer

_FD_DIR = "/proc/self/fd"


def scan_fds() -> Dict[int, str]:
    """fd -> readlink target ("socket:[123]", "/path/to/file", ...).
    fds that vanish mid-scan (the scan's own directory handle, a racing
    close) are skipped."""
    out: Dict[int, str] = {}
    try:
        names = os.listdir(_FD_DIR)
    except OSError:
        return out
    for name in names:
        try:
            out[int(name)] = os.readlink(os.path.join(_FD_DIR, name))
        except (OSError, ValueError):
            continue
    return out


def _classify(target: str) -> str:
    if target.startswith("socket:"):
        return "socket"
    if target.endswith((".db", ".sqlite", ".sqlite3")) \
            or "gcs" in target:
        return "sqlite/db file"
    if target.startswith(("pipe:", "anon_inode:")):
        return "pipe/eventfd"
    return "file"


def _pooled_rpc_filenos() -> Dict[int, str]:
    """fileno -> label for sockets owned by the process-lifetime
    RpcClient pool (kept across tests by design)."""
    out: Dict[int, str] = {}
    try:
        from ray_tpu._private.rpc import RpcClient
    except Exception:
        return out
    with RpcClient._pools_lock:
        clients = list(RpcClient._pools.items())
    for addr, client in clients:
        sock = client._sock
        if sock is not None:
            try:
                out[sock.fileno()] = f"pooled RpcClient to {addr}"
            except OSError:
                continue
    return out


def _store_census() -> Optional[Tuple[int, int]]:
    """(id(store), entry count) for the live worker's memory store."""
    try:
        from ray_tpu._private.worker import global_worker_or_none
    except Exception:
        return None
    w = global_worker_or_none()
    store = getattr(w, "memory_store", None) if w is not None else None
    if store is None:
        return None
    return id(store), store.num_objects()


def _actor_census() -> Optional[Tuple[int, Set]]:
    """(id(backend), live actor ids) for the live worker's local
    backend."""
    try:
        from ray_tpu._private.worker import global_worker_or_none
    except Exception:
        return None
    w = global_worker_or_none()
    backend = getattr(w, "backend", None) if w is not None else None
    backend = getattr(backend, "local_backend", backend)
    actors = getattr(backend, "_actors", None)
    if actors is None:
        return None
    return id(backend), set(actors.keys())


class LeakSanitizer(Sanitizer):
    name = "leaks"

    def __init__(self, grace_s: float = 1.5):
        self.grace_s = grace_s
        self._threads: Dict[int, str] = {}
        self._fds: Dict[int, str] = {}
        self._store: Optional[Tuple[int, int]] = None
        self._actors: Optional[Tuple[int, Set]] = None

    def before_test(self, test_id: str) -> None:
        self._threads = {t.ident: t.name
                         for t in threading.enumerate() if t.is_alive()}
        self._fds = scan_fds()
        self._store = _store_census()
        self._actors = _actor_census()

    def after_test(self, test_id: str) -> List[Finding]:
        findings: List[Finding] = []
        # Failed tests keep their frames (and every local ref in them)
        # alive in the traceback; collect cycles so only genuinely
        # reachable resources count.
        gc.collect()

        # -- threads, with a grace window --------------------------------
        deadline = time.monotonic() + self.grace_s
        new_threads = self._new_threads()
        while new_threads and time.monotonic() < deadline:
            time.sleep(0.02)
            new_threads = self._new_threads()
        for t in new_threads:
            findings.append(Finding(
                sanitizer=self.name, test=test_id,
                message=f"thread leaked: {t.name!r} "
                        f"(daemon={t.daemon}) still alive "
                        f"{self.grace_s:.1f}s after teardown",
                detail=f"target={getattr(t, '_target', None)!r}"))

        # -- fds (after the thread grace, so closes-in-progress land) ----
        pooled = _pooled_rpc_filenos()
        for fd, target in sorted(scan_fds().items()):
            if self._fds.get(fd) == target:
                continue
            label = pooled.get(fd)
            kind = _classify(target)
            findings.append(Finding(
                sanitizer=self.name, test=test_id,
                message=f"fd leaked: {label or kind} fd={fd} "
                        f"({target}) open after teardown"))

        # -- actors ------------------------------------------------------
        after_actors = _actor_census()
        if self._actors is not None and after_actors is not None \
                and after_actors[0] == self._actors[0]:
            for actor_id in sorted(after_actors[1] - self._actors[1],
                                   key=repr):
                findings.append(Finding(
                    sanitizer=self.name, test=test_id,
                    message=f"actor leaked: {actor_id!r} still "
                            f"registered after teardown"))

        # -- memory_store entries ---------------------------------------
        after_store = _store_census()
        if self._store is not None and after_store is not None \
                and after_store[0] == self._store[0] \
                and after_store[1] > self._store[1]:
            findings.append(Finding(
                sanitizer=self.name, test=test_id,
                message=f"memory_store leaked "
                        f"{after_store[1] - self._store[1]} entry(ies) "
                        f"({self._store[1]} -> {after_store[1]}) "
                        f"after teardown"))
        return findings

    def _new_threads(self) -> List[threading.Thread]:
        return [t for t in threading.enumerate()
                if t.is_alive() and t.ident not in self._threads
                and t is not threading.current_thread()]
