"""Lock-order witness: runtime held-before graph with cycle detection.

raylint R2 derives the lock-order graph *statically* (per-class lock
attributes, Tarjan SCC over ``with self._lock`` nesting). This is the
dynamic cross-check: while the sanitizer is installed, every lock built
through ``threading.Lock`` / ``threading.RLock`` /
``threading.Condition`` is wrapped so acquisitions record **held-before
edges** — "lock at site A was held while the lock at site B was
acquired" — with the acquiring stack attached to each edge's first
observation. At test teardown any cycle in the edge graph (the classic
AB/BA deadlock shape, any length) becomes a finding naming every edge
site in the cycle.

Lock identity is the **creation site** (``path:line`` of the
``threading.Lock()`` call), matching raylint R2's per-class-attribute
aggregation: every instance of ``Router._lock`` shares one node, so an
ordering inversion between two *instances* of the same pair of classes
is still a cycle. The cross-check test
(``tests/core/test_concurrency_races.py``) asserts the runtime SCC and
R2's static SCC agree on the same fixture code.

Only locks created while the sanitizer is installed are witnessed
(wrapping live C-lock instances retroactively is impossible); that is
the right scope for per-test sanitization — the locks a test's code
creates are the ones whose ordering the test exercises. Reacquisition
after a ``Condition.wait`` deliberately records no edge: the condvar
protocol's reacquire is not an ordering decision.
"""

from __future__ import annotations

import threading
import traceback
from typing import Dict, List, Optional, Set, Tuple

from tools.raysan.core import Finding, Sanitizer

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_CONDITION = threading.Condition

# (outer_site, inner_site) -> (stack text, test_id at first observation)
_edges: Dict[Tuple[str, str], Tuple[str, str]] = {}
_edges_lock = _REAL_LOCK()
_held = threading.local()        # per-thread list of site strings
_installed = False
_current_test = ""
# Repo root derived from this module's own location (tools/raysan/..):
# site keys must be repo-relative like raylint's relpaths, on ANY
# checkout path — not just ones containing a '/repo/' component.
_REPO_ROOT = __file__.replace("\\", "/").rsplit("/", 3)[0] + "/"


def _site() -> Optional[str]:
    """Creation site of the lock: the first frame outside this module
    and ``threading``. Returns None for raysan-internal creations
    (witnessing the witness's own synchronization would only add noise
    edges)."""
    for frame in traceback.extract_stack()[-2::-1]:
        fn = frame.filename.replace("\\", "/")
        if fn.endswith(("threading.py", "/lock_witness.py")):
            continue
        if "/raysan/" in fn:
            return None
        if fn.startswith(_REPO_ROOT):
            fn = fn[len(_REPO_ROOT):]
        return f"{fn}:{frame.lineno}"
    return None


def _note_acquire(site: str, record_edges: bool = True) -> None:
    stack = getattr(_held, "stack", None)
    if stack is None:
        stack = _held.stack = []
    if record_edges and _installed:
        for outer in stack:
            if outer != site:
                key = (outer, site)
                if key not in _edges:
                    tb = "".join(traceback.format_stack(limit=8)[:-2])
                    with _edges_lock:
                        _edges.setdefault(key, (tb, _current_test))
    stack.append(site)


def _note_release(site: str) -> None:
    stack = getattr(_held, "stack", None)
    if stack and site in stack:
        # Remove the innermost occurrence (lock sets are small).
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == site:
                del stack[i]
                break


class _WitnessLock:
    """Duck-typed stand-in for a ``threading.Lock``/``RLock``: records
    held-before edges around the real lock. RLock reentrancy is depth-
    counted per thread so only the 0→1 acquire and 1→0 release touch
    the held stack."""

    def __init__(self, inner, site: str, reentrant: bool):
        self._inner = inner
        self._site = site
        self._reentrant = reentrant
        self._depth: Dict[int, int] = {}

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            ident = threading.get_ident()
            depth = self._depth.get(ident, 0) + 1
            self._depth[ident] = depth
            if depth == 1 or not self._reentrant:
                _note_acquire(self._site)
        return got

    def release(self):
        ident = threading.get_ident()
        depth = self._depth.get(ident, 1) - 1
        if depth <= 0:
            self._depth.pop(ident, None)
        else:
            self._depth[ident] = depth
        self._inner.release()
        if depth <= 0 or not self._reentrant:
            _note_release(self._site)

    def locked(self):
        return self._inner.locked() if hasattr(self._inner, "locked") \
            else False

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # Condition-protocol hooks: a real ``threading.Condition`` built
    # before the sanitizer installed may wrap a witnessed lock; these
    # keep its wait() releasing/restoring the full reentrant depth.
    def _release_save(self):
        state = getattr(self._inner, "_release_save", None)
        ident = threading.get_ident()
        depth = self._depth.pop(ident, 0)
        _note_release(self._site)
        if state is not None:
            return (state(), depth)
        self._inner.release()
        return (None, depth)

    def _acquire_restore(self, saved):
        state, depth = saved
        if state is not None:
            self._inner._acquire_restore(state)
        else:
            self._inner.acquire()
        if depth:
            self._depth[threading.get_ident()] = depth
        _note_acquire(self._site, record_edges=False)

    def _is_owned(self):
        owned = getattr(self._inner, "_is_owned", None)
        if owned is not None:
            return owned()
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def __repr__(self):
        return f"<WitnessLock {self._site} over {self._inner!r}>"


class _WitnessCondition:
    """Condition facade: delegates to a real Condition over the real
    underlying lock, recording acquisition ordering under the wrapped
    (or implicit) lock's identity. ``Condition(existing_lock)`` aliases
    to that lock's site — the same aliasing raylint R2 applies."""

    def __init__(self, lock=None, site: str = "?"):
        if isinstance(lock, _WitnessLock):
            self._site = lock._site
            inner = lock._inner
        elif lock is not None:
            self._site = site
            inner = lock
        else:
            self._site = site
            inner = _REAL_RLOCK()
        self._cond = _REAL_CONDITION(inner)

    def acquire(self, *args, **kwargs):
        got = self._cond.acquire(*args, **kwargs)
        if got:
            _note_acquire(self._site)
        return got

    def release(self):
        self._cond.release()
        _note_release(self._site)

    def __enter__(self):
        self._cond.__enter__()
        _note_acquire(self._site)
        return self

    def __exit__(self, *exc):
        _note_release(self._site)
        return self._cond.__exit__(*exc)

    def wait(self, timeout=None):
        _note_release(self._site)
        try:
            return self._cond.wait(timeout)
        finally:
            _note_acquire(self._site, record_edges=False)

    def wait_for(self, predicate, timeout=None):
        _note_release(self._site)
        try:
            return self._cond.wait_for(predicate, timeout)
        finally:
            _note_acquire(self._site, record_edges=False)

    def notify(self, n=1):
        self._cond.notify(n)

    def notify_all(self):
        self._cond.notify_all()

    def __repr__(self):
        return f"<WitnessCondition {self._site}>"


def _make_lock():
    site = _site()
    inner = _REAL_LOCK()
    if site is None or not _installed:
        return inner
    return _WitnessLock(inner, site, reentrant=False)


def _make_rlock():
    site = _site()
    inner = _REAL_RLOCK()
    if site is None or not _installed:
        return inner
    return _WitnessLock(inner, site, reentrant=True)


def _make_condition(lock=None):
    site = _site()
    if site is None or not _installed:
        if isinstance(lock, _WitnessLock):
            lock = lock._inner
        return _REAL_CONDITION(lock)
    return _WitnessCondition(lock, site=site)


def find_cycles(edges: Optional[Dict] = None) -> List[List[str]]:
    """SCCs of size > 1 in the held-before graph (each is a lock-order
    cycle). Iterative Tarjan — the graphs are tiny but recursion limits
    are not ours to burn."""
    edge_map = edges if edges is not None else dict(_edges)
    graph: Dict[str, Set[str]] = {}
    for a, b in edge_map:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    counter = [0]
    sccs: List[List[str]] = []

    def connect(root):
        work = [(root, iter(sorted(graph[root])))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(sorted(graph[nxt]))))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                low[work[-1][0]] = min(low[work[-1][0]], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                sccs.append(comp)

    for v in sorted(graph):
        if v not in index:
            connect(v)
    return [sorted(c) for c in sccs if len(c) > 1]


def witnessed_edges() -> Dict[Tuple[str, str], Tuple[str, str]]:
    with _edges_lock:
        return dict(_edges)


def reset() -> None:
    with _edges_lock:
        _edges.clear()


class LockOrderSanitizer(Sanitizer):
    name = "locks"

    def start_session(self) -> None:
        global _installed
        reset()
        _installed = True
        threading.Lock = _make_lock
        threading.RLock = _make_rlock
        threading.Condition = _make_condition

    def stop_session(self) -> None:
        global _installed
        _installed = False
        threading.Lock = _REAL_LOCK
        threading.RLock = _REAL_RLOCK
        threading.Condition = _REAL_CONDITION

    def before_test(self, test_id: str) -> None:
        global _current_test
        _current_test = test_id

    def after_test(self, test_id: str) -> List[Finding]:
        """Cycles over the FULL session graph, reported when this test
        contributed at least one participating edge — cross-test
        inversions (test A locks X→Y, test B locks Y→X on the same
        classes) are real deadlocks and must not escape by arriving one
        half at a time."""
        with _edges_lock:
            edges = dict(_edges)
        findings = []
        for cycle in find_cycles(edges):
            comp = set(cycle)
            sites = [(a, b, tb, owner) for (a, b), (tb, owner)
                     in sorted(edges.items())
                     if a in comp and b in comp]
            if not any(owner == test_id for _, _, _, owner in sites):
                continue
            detail = []
            for a, b, tb, owner in sites:
                detail.append(f"{a} held while acquiring {b} "
                              f"(first seen in {owner or '<session>'}):")
                detail.extend("  " + ln for ln in tb.splitlines()[-4:])
            findings.append(Finding(
                sanitizer=self.name, test=test_id,
                message=f"lock-order cycle among {{{', '.join(cycle)}}}",
                detail="\n".join(detail)))
            # Break the cycle's edges out of the graph so every later
            # test is not re-failed for the same inversion.
            with _edges_lock:
                for key in [k for k in _edges
                            if k[0] in comp and k[1] in comp]:
                    del _edges[key]
        return findings
