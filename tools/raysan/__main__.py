"""CLI: ``python -m tools.raysan [paths] [--sanitize LIST] [--report json]``

Wraps a pytest run with the raysan plugin enabled and emits the
session's sanitizer report — the form CI archives as an artifact.

Exit-code contract (raylint's, extended over test outcomes):
  0  tests passed and no unsuppressed sanitizer findings
  1  test failures and/or unsuppressed findings
  2  usage error (unknown sanitizer, bad path)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

DEFAULT_PATHS = ("tests/core/test_concurrency_races.py",
                 "tests/serve/test_concurrency_fixes.py")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.raysan",
        description="runtime concurrency/leak sanitizers for ray_tpu")
    parser.add_argument(
        "paths", nargs="*", default=list(DEFAULT_PATHS),
        help="test files/directories to run under the sanitizers "
             f"(default: the concurrency regression suites "
             f"{', '.join(DEFAULT_PATHS)})")
    parser.add_argument(
        "--sanitize", default="leaks,ambient", metavar="LIST",
        help="sanitizers to enable (default: leaks,ambient — the "
             "bounded CI leg; 'all' adds locks,loop)")
    parser.add_argument(
        "--report", choices=("json", "pretty"), default="pretty",
        help="report format on stdout")
    parser.add_argument(
        "--report-file", default="", metavar="PATH",
        help="also write the JSON report to PATH")
    parser.add_argument(
        "--loop-threshold-ms", type=float, default=100.0)
    parser.add_argument(
        "--pytest-args", default="-q", metavar="ARGS",
        help="extra arguments handed to pytest (default: -q)")
    args = parser.parse_args(argv)

    from tools.raysan.core import SANITIZER_NAMES

    for name in args.sanitize.split(","):
        if name.strip() and name.strip() != "all" \
                and name.strip() not in SANITIZER_NAMES:
            print(f"raysan: unknown sanitizer {name.strip()!r}; known: "
                  f"{', '.join(SANITIZER_NAMES)}", file=sys.stderr)
            return 2
    for path in args.paths:
        if not os.path.exists(path):
            print(f"raysan: no such path: {path}", file=sys.stderr)
            return 2

    import pytest

    fd, report_path = tempfile.mkstemp(prefix="raysan-", suffix=".json")
    os.close(fd)
    report = None
    try:
        rc = pytest.main(
            args.paths + args.pytest_args.split() + [
                "-p", "tools.raysan.pytest_plugin",
                f"--sanitize={args.sanitize}",
                f"--sanitize-report={report_path}",
                "--sanitize-loop-threshold-ms",
                str(args.loop_threshold_ms),
            ])
        try:
            with open(report_path, "r", encoding="utf-8") as f:
                report = json.load(f)
        except (OSError, ValueError):
            print("raysan: pytest run produced no report",
                  file=sys.stderr)
            return 2
    finally:
        if args.report_file and report is not None:
            # Deterministic artifact: the run's wall clock goes to the
            # .timing.json sidecar so back-to-back identical runs
            # produce byte-identical committed reports. (Replaces the
            # tmp-file move — the artifact is re-serialized, which
            # also dodges the historical cross-fs EXDEV hazard.)
            from tools.reporting import write_report_artifact

            write_report_artifact(args.report_file, report,
                                  volatile=("elapsed_s",))
        if os.path.exists(report_path):
            os.unlink(report_path)

    if args.report == "json":
        print(json.dumps(report, indent=2))
    else:
        for f in report["findings"]:
            print(f"[{f['sanitizer']}] {f['test']}: {f['message']}")
        print(f"raysan[{','.join(report['sanitizers'])}]: "
              f"{report['tests_checked']} tests, "
              f"{len(report['findings'])} finding(s), "
              f"{len(report['suppressed'])} suppressed, "
              f"{report['elapsed_s']:.2f}s")

    if int(rc) == 4:  # pytest usage error
        return 2
    if report["findings"] or int(rc) != 0:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
