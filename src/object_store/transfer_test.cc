// Transfer plane tests: chunked pull between two stores over loopback.
// Coverage model: the reference's object manager tests
// (src/ray/object_manager/test/object_manager_test.cc) — serve, pull,
// missing-object, idempotent re-pull, and a 1 GiB streamed object.

#include <assert.h>
#include <string.h>
#include <sys/mman.h>

#include <chrono>
#include <cstdio>
#include <thread>

#include "store.h"
#include "transfer.h"

using ray_tpu::PullObject;
using ray_tpu::ShmStore;
using ray_tpu::TransferServer;

static void make_id(uint8_t* id, int n) {
  memset(id, 0, ray_tpu::kIdSize);
  memcpy(id, &n, sizeof(n));
}

int main() {
  const uint64_t kGiB = 1ULL << 30;
  ShmStore* a = ShmStore::Create("/raytpu_xfer_a", kGiB + (64 << 20), 64);
  ShmStore* b = ShmStore::Create("/raytpu_xfer_b", kGiB + (64 << 20), 64);
  assert(a && b);
  // Let the background page-populate finish so the timed pull measures
  // transfer, not first-touch faulting.
  std::this_thread::sleep_for(std::chrono::seconds(20));

  TransferServer* srv = TransferServer::Start(a, 0);
  assert(srv && srv->port() != 0);

  // Small object round-trip with content check.
  uint8_t id[ray_tpu::kIdSize];
  make_id(id, 1);
  {
    uint8_t* p = a->CreateObject(id, 4096);
    assert(p);
    for (int i = 0; i < 4096; i++) p[i] = (uint8_t)(i * 7);
    assert(a->Seal(id));
    int rc = PullObject(b, id, "127.0.0.1", srv->port(), nullptr,
                        /*allow_local=*/false);  // cover the wire path
    assert(rc == 0);
    uint64_t size = 0;
    const uint8_t* q = b->Get(id, &size);
    assert(q && size == 4096);
    for (int i = 0; i < 4096; i++) assert(q[i] == (uint8_t)(i * 7));
    b->Release(id);
  }

  // Re-pull is a no-op (-5 already present).
  assert(PullObject(b, id, "127.0.0.1", srv->port(), nullptr) == -5);

  // Missing object → -2.
  uint8_t missing[ray_tpu::kIdSize];
  make_id(missing, 99);
  assert(PullObject(b, missing, "127.0.0.1", srv->port(), nullptr) == -2);

  // 1 GiB object, both transfer paths content-checked and timed:
  // forced TCP stream (the true cross-host path) and the same-host
  // segment-to-segment fast path (the default when the serving segment
  // is mapped on this machine).
  uint8_t big_id[ray_tpu::kIdSize];
  make_id(big_id, 2);
  {
    uint8_t* p = a->CreateObject(big_id, kGiB);
    assert(p);
    // Stamp a recognizable pattern at chunk boundaries.
    for (uint64_t off = 0; off < kGiB; off += ray_tpu::kChunkSize) {
      memcpy(p + off, &off, sizeof(off));
    }
    p[kGiB - 1] = 0x5A;
    assert(a->Seal(big_id));

    auto check = [&](const char* label, double dt) {
      uint64_t size = 0;
      const uint8_t* q = b->Get(big_id, &size);
      assert(q && size == kGiB);
      for (uint64_t off = 0; off < kGiB; off += ray_tpu::kChunkSize) {
        uint64_t v;
        memcpy(&v, q + off, sizeof(v));
        assert(v == off);
      }
      assert(q[kGiB - 1] == 0x5A);
      b->Release(big_id);
      printf("1GiB pull (%s): %.2f GB/s\n", label, 1.0 / dt);
    };

    auto t0 = std::chrono::steady_clock::now();
    int rc = PullObject(b, big_id, "127.0.0.1", srv->port(), nullptr,
                        /*allow_local=*/false);
    auto dt = std::chrono::duration<double>(
                  std::chrono::steady_clock::now() - t0)
                  .count();
    assert(rc == 0);
    check("tcp stream", dt);

    assert(b->Delete(big_id));
    t0 = std::chrono::steady_clock::now();
    rc = PullObject(b, big_id, "127.0.0.1", srv->port(), nullptr);
    dt = std::chrono::duration<double>(
             std::chrono::steady_clock::now() - t0)
             .count();
    assert(rc == 0);
    check("same-host", dt);
  }

  auto st = srv->stats();
  // TCP path streamed the small object + one 1 GiB copy; the same-host
  // pull only cost a meta round-trip (no payload bytes on the wire,
  // no objects_served increment).
  assert(st.objects_served == 2);
  assert(st.bytes_sent == 4096 + kGiB);

  // Striped parallel pull: 4 range streams, content identical, timed.
  {
    assert(b->Delete(big_id));
    auto t0 = std::chrono::steady_clock::now();
    int rc = PullObjectStriped(b, big_id, "127.0.0.1", srv->port(), 4,
                               nullptr, /*allow_local=*/false);
    auto dt = std::chrono::duration<double>(
                  std::chrono::steady_clock::now() - t0)
                  .count();
    assert(rc == 0);
    uint64_t size = 0;
    const uint8_t* q = b->Get(big_id, &size);
    assert(q && size == kGiB);
    for (uint64_t off = 0; off < kGiB; off += ray_tpu::kChunkSize) {
      uint64_t v;
      memcpy(&v, q + off, sizeof(v));
      assert(v == off);
    }
    assert(q[kGiB - 1] == 0x5A);
    b->Release(big_id);
    printf("1GiB pull (striped x4): %.2f GB/s\n", 1.0 / dt);
    // Striped into a store that already has it: -5.
    assert(PullObjectStriped(b, big_id, "127.0.0.1", srv->port(), 4,
                             nullptr, false) == -5);
  }

  // PUSH path: b proactively streams an object into a's server-side
  // peer... push runs against a TransferServer, so start one for b.
  {
    TransferServer* srv_b = TransferServer::Start(b, 0);
    assert(srv_b && srv_b->port() != 0);
    uint8_t push_id[ray_tpu::kIdSize];
    make_id(push_id, 3);
    uint8_t* p = a->CreateObject(push_id, 1 << 20);
    assert(p);
    for (int i = 0; i < (1 << 20); i++) p[i] = (uint8_t)(i * 13);
    assert(a->Seal(push_id));
    // a pushes into b's transfer server.
    assert(PushObject(a, push_id, "127.0.0.1", srv_b->port(),
                      nullptr) == 0);
    uint64_t size = 0;
    const uint8_t* q = b->Get(push_id, &size);
    assert(q && size == (1 << 20));
    for (int i = 0; i < (1 << 20); i++) {
      assert(q[i] == (uint8_t)(i * 13));
    }
    b->Release(push_id);
    // Re-push: remote already has it.
    assert(PushObject(a, push_id, "127.0.0.1", srv_b->port(),
                      nullptr) == -5);
    // Pushing a missing local object: -2.
    uint8_t nothere[ray_tpu::kIdSize];
    make_id(nothere, 77);
    assert(PushObject(a, nothere, "127.0.0.1", srv_b->port(),
                      nullptr) == -2);
    srv_b->Stop();
    delete srv_b;
  }

  srv->Stop();
  delete srv;
  delete a;
  delete b;
  shm_unlink("/raytpu_xfer_a");
  shm_unlink("/raytpu_xfer_b");
  printf("transfer_test: all assertions passed\n");
  return 0;
}
