// Unit tests for the shm object store (no gtest dep — plain asserts).
// Mirrors the coverage style of the reference's plasma tests
// (src/ray/object_manager/plasma/test/).

#include <assert.h>
#include <string.h>
#include <sys/mman.h>

#include <cstdio>

#include "store.h"

using ray_tpu::ShmStore;
using ray_tpu::StoreStats;

static void make_id(uint8_t* id, int n) {
  memset(id, 0, ray_tpu::kIdSize);
  memcpy(id, &n, sizeof(n));
}

int main() {
  const char* name = "/raytpu_store_test";
  ShmStore* store = ShmStore::Create(name, 1 << 20, 64);
  assert(store);

  // create / seal / get / release / delete lifecycle
  uint8_t id[ray_tpu::kIdSize];
  make_id(id, 1);
  uint8_t* p = store->CreateObject(id, 1000);
  assert(p);
  memset(p, 0xAB, 1000);
  assert(!store->Contains(id));  // not sealed yet
  assert(store->CreateObject(id, 10) == nullptr);  // duplicate
  assert(store->Seal(id));
  assert(store->Contains(id));
  uint64_t size = 0;
  const uint8_t* q = store->Get(id, &size);
  assert(q && size == 1000 && q[999] == 0xAB);

  // second client attaches and sees the object zero-copy
  ShmStore* client = ShmStore::Attach(name);
  assert(client);
  uint64_t csize = 0;
  const uint8_t* cq = client->Get(id, &csize);
  assert(cq && csize == 1000 && cq[0] == 0xAB);
  assert(client->Release(id));

  assert(store->Release(id));
  assert(store->Delete(id));
  assert(!store->Contains(id));

  // eviction under pressure: fill with unpinned sealed objects, then
  // allocate something big.
  for (int i = 10; i < 16; i++) {
    make_id(id, i);
    uint8_t* pi = store->CreateObject(id, 150 * 1024);
    assert(pi);
    assert(store->Seal(id));
    assert(store->Release(id) == false);  // refcount already 0 post-seal
  }
  StoreStats st = store->Stats();
  assert(st.num_sealed == 6);
  make_id(id, 99);
  uint8_t* big = store->CreateObject(id, 700 * 1024);
  assert(big);  // must have evicted LRU objects
  st = store->Stats();
  assert(st.evictions > 0);
  assert(store->Seal(id));

  // pinned objects are not evictable: pin everything, then fail create.
  uint64_t sz;
  assert(store->Get(id, &sz));
  uint8_t id2[ray_tpu::kIdSize];
  make_id(id2, 100);
  uint8_t* impossible = store->CreateObject(id2, 900 * 1024);
  assert(impossible == nullptr);
  st = store->Stats();
  assert(st.create_failures > 0);

  delete client;
  delete store;
  shm_unlink(name);
  printf("store_test: all assertions passed\n");
  return 0;
}
