// Shared-memory object store: the plasma equivalent.
//
// Role-equivalent to the reference's plasma store
// (src/ray/object_manager/plasma/store.h:55, client.h, dlmalloc.cc): an
// mmap'd arena shared across processes on one node holding immutable
// sealed objects, with create/seal/get/release lifecycle, refcounting,
// and LRU eviction of unpinned sealed objects under memory pressure.
//
// Differences from the reference's design, on purpose:
// - One shm segment with an in-arena first-fit allocator instead of
//   dlmalloc-over-fd-passing: clients attach by name (shm_open) rather
//   than receiving fds over a unix socket, which removes the store
//   server thread entirely — all operations are lock-protected
//   (process-shared robust mutex) direct calls.
// - Object IDs are fixed 20 bytes (matching the Python ObjectID).
//
// The C API at the bottom is the ctypes surface for Python
// (ray_tpu/_private/shm_store.py) and keeps zero-copy semantics: Python
// maps the same segment and wraps object payloads in numpy arrays
// without copying.

#pragma once

#include <atomic>
#include <cstddef>
#include <cstdint>

namespace ray_tpu {

// Batched PTE/page population over a mapped range (MADV_POPULATE_*,
// with page-alignment handled here — madvise EINVALs on unaligned
// addresses, which silently disabled an earlier inline version). One
// shared implementation for the create/attach background prefaults and
// the transfer plane's pre-copy populate. `cancel` (optional) aborts
// between chunks so a closing store can join its prefault thread fast.
void PopulateRange(const void* addr, uint64_t len, bool write,
                   uint64_t step = 16ULL << 20,
                   const std::atomic<bool>* cancel = nullptr);

constexpr uint32_t kIdSize = 20;
// Layout version rides in the magic: v2 added `uuid` to StoreHeader
// BEFORE the process-shared mutex, so a v1 build attaching a v2 segment
// would lock garbage. Mixed builds must refuse to inter-attach.
constexpr uint64_t kMagic = 0x3255505459415253ULL;  // "SRAYTPU2"

enum class ObjectState : int32_t {
  kFree = 0,
  kCreated = 1,  // allocated, writer filling it
  kSealed = 2,   // immutable, readable
};

struct ObjectEntry {
  uint8_t id[kIdSize];
  uint64_t offset;    // payload offset from arena base
  uint64_t size;      // payload size
  uint64_t metadata_size;
  int32_t state;      // ObjectState
  int32_t refcount;   // pins: created=1 by writer; get +1, release -1
  uint64_t lru_tick;  // for eviction ordering
  uint64_t create_ns;
};

// Free/used block header embedded in the arena (first-fit allocator with
// forward coalescing).
struct BlockHeader {
  uint64_t size;  // payload bytes following this header
  uint32_t free;  // 1 = free
  uint32_t pad;
};

struct StoreStats {
  uint64_t capacity;
  uint64_t allocated;
  uint64_t num_objects;
  uint64_t num_sealed;
  uint64_t evictions;
  uint64_t create_failures;
};

struct StoreHeader;  // opaque in public API

class ShmStore {
 public:
  // Create a new segment (unlinks existing with same name) or attach.
  // `prefault=false` skips the background page-table populate — used by
  // the transfer plane's peer attaches, which populate exactly the
  // ranges they copy instead.
  static ShmStore* Create(const char* name, uint64_t capacity,
                          uint32_t max_objects);
  static ShmStore* Attach(const char* name, bool prefault = true);
  ~ShmStore();

  // Returns payload pointer or null (exists / no space after eviction).
  uint8_t* CreateObject(const uint8_t* id, uint64_t size);
  bool Seal(const uint8_t* id);
  // Pins + returns payload (null if absent or unsealed).
  const uint8_t* Get(const uint8_t* id, uint64_t* size_out);
  bool Contains(const uint8_t* id);
  bool Release(const uint8_t* id);
  bool Delete(const uint8_t* id);  // refcount must be 0
  // Current pin count of a sealed object, or -1 when absent/unsealed.
  // The spill victim selector uses this: an object whose only pin is
  // the owner's own can leave the arena without invalidating any
  // other process's zero-copy view.
  int32_t Refcount(const uint8_t* id);
  StoreStats Stats();

  const char* name() const { return name_; }
  const uint8_t* base() const { return base_; }
  uint64_t map_size() const { return map_size_; }
  // Backing tmpfs fd (open for the store's lifetime) — lets the
  // transfer server sendfile() payloads straight from the page cache,
  // skipping the user->kernel copy of a send() from the mapping.
  int fd() const { return fd_; }
  // Segment identity (random per Create) — the transfer plane's
  // same-host detection token.
  uint64_t uuid() const;

 private:
  ShmStore() = default;
  bool EvictUntil(uint64_t needed);
  uint8_t* Allocate(uint64_t size);
  void FreeBlock(uint64_t payload_offset);
  ObjectEntry* FindEntry(const uint8_t* id);
  ObjectEntry* FindFreeEntry();

  void StartPrefault(bool write);

  StoreHeader* header_ = nullptr;
  uint8_t* base_ = nullptr;   // mmap base
  uint8_t* arena_ = nullptr;  // data arena base
  uint64_t map_size_ = 0;
  int fd_ = -1;
  bool owner_ = false;
  char name_[256] = {0};
  // Background prefault: tracked (not detached) so the destructor can
  // cancel + join before munmap — a detached thread would race the
  // unmap and could madvise whatever mapping reuses the range.
  void* prefault_thread_ = nullptr;  // std::thread*
  std::atomic<bool> prefault_cancel_{false};
};

}  // namespace ray_tpu

// ---------------------------------------------------------------------------
// C API (ctypes surface)
// ---------------------------------------------------------------------------
extern "C" {
void* shm_store_create(const char* name, uint64_t capacity,
                       uint32_t max_objects);
void* shm_store_attach(const char* name);
void shm_store_close(void* store);
void shm_store_destroy(const char* name);  // unlink the segment
// Returns offset from mmap base (so Python can slice its own mapping), or
// UINT64_MAX on failure.
uint64_t shm_obj_create(void* store, const uint8_t* id, uint64_t size);
int shm_obj_seal(void* store, const uint8_t* id);
uint64_t shm_obj_get(void* store, const uint8_t* id, uint64_t* size_out);
int shm_obj_contains(void* store, const uint8_t* id);
int shm_obj_release(void* store, const uint8_t* id);
int shm_obj_delete(void* store, const uint8_t* id);
int32_t shm_obj_refcount(void* store, const uint8_t* id);
void shm_store_stats(void* store, ray_tpu::StoreStats* out);
uint64_t shm_store_mmap_size(void* store);
}
