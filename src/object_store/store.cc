#include "store.h"

#include <errno.h>
#include <fcntl.h>
#include <pthread.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

#include <cstdio>
#include <new>
#include <thread>
#include <new>

namespace ray_tpu {

namespace {
constexpr uint64_t kAlign = 64;

uint64_t AlignUp(uint64_t v) { return (v + kAlign - 1) & ~(kAlign - 1); }

uint64_t NowNs() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return uint64_t(ts.tv_sec) * 1000000000ull + ts.tv_nsec;
}
}  // namespace

#ifndef MADV_POPULATE_READ
#define MADV_POPULATE_READ 22
#define MADV_POPULATE_WRITE 23
#endif

void PopulateRange(const void* addr, uint64_t len, bool write,
                   uint64_t step, const std::atomic<bool>* cancel) {
  uintptr_t a = (uintptr_t)addr;
  uintptr_t page = a & ~(uintptr_t)4095;
  len += a - page;
  int advice = write ? MADV_POPULATE_WRITE : MADV_POPULATE_READ;
  for (uint64_t off = 0; off < len; off += step) {
    if (cancel != nullptr && cancel->load(std::memory_order_relaxed)) {
      return;
    }
    uint64_t n = len - off < step ? len - off : step;
    madvise((void*)(page + off), n, advice);
  }
}

void ShmStore::StartPrefault(bool write) {
  uint8_t* map_base = base_;
  uint64_t total_len = map_size_;
  const std::atomic<bool>* cancel = &prefault_cancel_;
  prefault_thread_ = new std::thread([map_base, total_len, write,
                                      cancel] {
    PopulateRange(map_base, total_len, write, 16ULL << 20, cancel);
  });
}

struct StoreHeader {
  uint64_t magic;
  uint64_t capacity;      // arena bytes
  uint64_t arena_offset;  // from mmap base
  uint32_t max_objects;
  uint32_t pad0;
  uint64_t allocated;
  uint64_t lru_clock;
  uint64_t evictions;
  uint64_t create_failures;
  uint64_t uuid;          // segment identity (same-host pull fast path)
  pthread_mutex_t mutex;  // process-shared
  // ObjectEntry table follows immediately after this struct.
};

static ObjectEntry* EntryTable(StoreHeader* h) {
  return reinterpret_cast<ObjectEntry*>(reinterpret_cast<uint8_t*>(h) +
                                        sizeof(StoreHeader));
}

class MutexGuard {
 public:
  explicit MutexGuard(pthread_mutex_t* m) : m_(m) {
    int rc = pthread_mutex_lock(m_);
    if (rc == EOWNERDEAD) {
      // A crashed process held the lock; state is best-effort consistent
      // (all mutations are single-word or order-safe), recover.
      pthread_mutex_consistent(m_);
    }
  }
  ~MutexGuard() { pthread_mutex_unlock(m_); }

 private:
  pthread_mutex_t* m_;
};

ShmStore* ShmStore::Create(const char* name, uint64_t capacity,
                           uint32_t max_objects) {
  shm_unlink(name);
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  uint64_t table_bytes = sizeof(StoreHeader) +
                         uint64_t(max_objects) * sizeof(ObjectEntry);
  uint64_t arena_off = AlignUp(table_bytes);
  uint64_t total = arena_off + capacity;
  if (ftruncate(fd, (off_t)total) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* base = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED,
                    fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  auto* h = new (base) StoreHeader();
  h->magic = kMagic;
  h->capacity = capacity;
  h->arena_offset = arena_off;
  h->max_objects = max_objects;
  h->allocated = 0;
  h->lru_clock = 1;
  h->evictions = 0;
  h->create_failures = 0;
  {
    // Random identity so a same-named segment on a DIFFERENT machine
    // can never be mistaken for this one by the transfer fast path.
    uint64_t u = NowNs() ^ (uint64_t(getpid()) << 32);
    FILE* f = fopen("/dev/urandom", "rb");
    if (f != nullptr) {
      if (fread(&u, sizeof(u), 1, f) != 1) u ^= NowNs();
      fclose(f);
    }
    h->uuid = u;
  }
  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&h->mutex, &attr);
  memset(EntryTable(h), 0, uint64_t(max_objects) * sizeof(ObjectEntry));
  // One giant free block spans the arena.
  auto* first = reinterpret_cast<BlockHeader*>(
      reinterpret_cast<uint8_t*>(base) + arena_off);
  first->size = capacity - sizeof(BlockHeader);
  first->free = 1;

  auto* s = new ShmStore();
  s->header_ = h;
  s->base_ = reinterpret_cast<uint8_t*>(base);
  s->arena_ = s->base_ + arena_off;
  s->map_size_ = total;
  s->fd_ = fd;
  s->owner_ = true;
  snprintf(s->name_, sizeof(s->name_), "%s", name);
  // Instantiate tmpfs pages in the background: first-touch faulting
  // costs ~10x memcpy speed, so copy-ins into cold regions crawl until
  // the kernel has populated them. MADV_POPULATE_WRITE allocates the
  // pages without writing, so it is race-free against live writers.
  {
    s->StartPrefault(/*write=*/true);
  }
  return s;
}

ShmStore* ShmStore::Attach(const char* name, bool prefault) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, (size_t)st.st_size, PROT_READ | PROT_WRITE,
                    MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  auto* h = reinterpret_cast<StoreHeader*>(base);
  if (h->magic != kMagic) {
    munmap(base, (size_t)st.st_size);
    close(fd);
    return nullptr;
  }
  auto* s = new ShmStore();
  s->header_ = h;
  s->base_ = reinterpret_cast<uint8_t*>(base);
  s->arena_ = s->base_ + h->arena_offset;
  s->map_size_ = (uint64_t)st.st_size;
  s->fd_ = fd;
  s->owner_ = false;
  snprintf(s->name_, sizeof(s->name_), "%s", name);
  // Populate this process's page tables in the background (pages
  // already exist; this is PTE setup only, so it is quick) — an
  // attaching node otherwise pays a minor fault per 4K page on its
  // first pass over the segment.
  if (prefault) s->StartPrefault(/*write=*/false);
  return s;
}

ShmStore::~ShmStore() {
  auto* t = static_cast<std::thread*>(prefault_thread_);
  if (t != nullptr) {
    prefault_cancel_.store(true);
    if (t->joinable()) t->join();  // bounded by one madvise chunk
    delete t;
  }
  if (base_) munmap(base_, map_size_);
  if (fd_ >= 0) close(fd_);
}

ObjectEntry* ShmStore::FindEntry(const uint8_t* id) {
  ObjectEntry* table = EntryTable(header_);
  for (uint32_t i = 0; i < header_->max_objects; i++) {
    if (table[i].state != (int32_t)ObjectState::kFree &&
        memcmp(table[i].id, id, kIdSize) == 0) {
      return &table[i];
    }
  }
  return nullptr;
}

ObjectEntry* ShmStore::FindFreeEntry() {
  ObjectEntry* table = EntryTable(header_);
  for (uint32_t i = 0; i < header_->max_objects; i++) {
    if (table[i].state == (int32_t)ObjectState::kFree) return &table[i];
  }
  return nullptr;
}

uint8_t* ShmStore::Allocate(uint64_t size) {
  uint64_t need = AlignUp(size);
  uint8_t* cursor = arena_;
  uint8_t* end = arena_ + header_->capacity;
  while (cursor + sizeof(BlockHeader) <= end) {
    auto* blk = reinterpret_cast<BlockHeader*>(cursor);
    if (blk->size == 0) break;  // corrupt / end sentinel
    if (blk->free) {
      // Forward-coalesce adjacent free blocks.
      uint8_t* nxt = cursor + sizeof(BlockHeader) + blk->size;
      while (nxt + sizeof(BlockHeader) <= end) {
        auto* nblk = reinterpret_cast<BlockHeader*>(nxt);
        if (!nblk->free || nblk->size == 0) break;
        blk->size += sizeof(BlockHeader) + nblk->size;
        nxt = cursor + sizeof(BlockHeader) + blk->size;
      }
      if (blk->size >= need) {
        // Split if the tail is worth keeping.
        if (blk->size >= need + sizeof(BlockHeader) + kAlign) {
          auto* tail = reinterpret_cast<BlockHeader*>(
              cursor + sizeof(BlockHeader) + need);
          tail->size = blk->size - need - sizeof(BlockHeader);
          tail->free = 1;
          blk->size = need;
        }
        blk->free = 0;
        header_->allocated += blk->size + sizeof(BlockHeader);
        return cursor + sizeof(BlockHeader);
      }
    }
    cursor += sizeof(BlockHeader) + blk->size;
  }
  return nullptr;
}

void ShmStore::FreeBlock(uint64_t payload_offset) {
  auto* blk = reinterpret_cast<BlockHeader*>(arena_ + payload_offset -
                                             sizeof(BlockHeader));
  blk->free = 1;
  header_->allocated -= blk->size + sizeof(BlockHeader);
}

bool ShmStore::EvictUntil(uint64_t /*needed*/) {
  // Evict the single LRU sealed+unpinned object; the caller retries the
  // allocation after each eviction (total-free is a bad proxy under
  // fragmentation — only a successful first-fit proves there is room).
  ObjectEntry* table = EntryTable(header_);
  ObjectEntry* victim = nullptr;
  for (uint32_t i = 0; i < header_->max_objects; i++) {
    ObjectEntry* e = &table[i];
    if (e->state == (int32_t)ObjectState::kSealed && e->refcount == 0) {
      if (!victim || e->lru_tick < victim->lru_tick) victim = e;
    }
  }
  if (!victim) return false;
  FreeBlock(victim->offset);
  victim->state = (int32_t)ObjectState::kFree;
  header_->evictions++;
  return true;
}

uint8_t* ShmStore::CreateObject(const uint8_t* id, uint64_t size) {
  MutexGuard g(&header_->mutex);
  if (FindEntry(id)) return nullptr;  // already exists
  ObjectEntry* e = FindFreeEntry();
  if (!e) {
    header_->create_failures++;
    return nullptr;
  }
  uint8_t* p = Allocate(size);
  while (!p && EvictUntil(size)) {
    p = Allocate(size);
  }
  if (!p) {
    header_->create_failures++;
    return nullptr;
  }
  memcpy(e->id, id, kIdSize);
  e->offset = (uint64_t)(p - arena_);
  e->size = size;
  e->state = (int32_t)ObjectState::kCreated;
  e->refcount = 1;  // writer pin
  e->lru_tick = header_->lru_clock++;
  e->create_ns = NowNs();
  return p;
}

bool ShmStore::Seal(const uint8_t* id) {
  MutexGuard g(&header_->mutex);
  ObjectEntry* e = FindEntry(id);
  if (!e || e->state != (int32_t)ObjectState::kCreated) return false;
  e->state = (int32_t)ObjectState::kSealed;
  e->refcount -= 1;  // drop writer pin
  return true;
}

const uint8_t* ShmStore::Get(const uint8_t* id, uint64_t* size_out) {
  MutexGuard g(&header_->mutex);
  ObjectEntry* e = FindEntry(id);
  if (!e || e->state != (int32_t)ObjectState::kSealed) return nullptr;
  e->refcount += 1;
  e->lru_tick = header_->lru_clock++;
  if (size_out) *size_out = e->size;
  return arena_ + e->offset;
}

bool ShmStore::Contains(const uint8_t* id) {
  MutexGuard g(&header_->mutex);
  ObjectEntry* e = FindEntry(id);
  return e && e->state == (int32_t)ObjectState::kSealed;
}

bool ShmStore::Release(const uint8_t* id) {
  MutexGuard g(&header_->mutex);
  ObjectEntry* e = FindEntry(id);
  if (!e || e->refcount <= 0) return false;
  e->refcount -= 1;
  return true;
}

bool ShmStore::Delete(const uint8_t* id) {
  MutexGuard g(&header_->mutex);
  ObjectEntry* e = FindEntry(id);
  if (!e || e->refcount > 0) return false;
  FreeBlock(e->offset);
  e->state = (int32_t)ObjectState::kFree;
  return true;
}

int32_t ShmStore::Refcount(const uint8_t* id) {
  MutexGuard g(&header_->mutex);
  ObjectEntry* e = FindEntry(id);
  if (!e || e->state != (int32_t)ObjectState::kSealed) return -1;
  return e->refcount;
}

uint64_t ShmStore::uuid() const { return header_->uuid; }

StoreStats ShmStore::Stats() {
  MutexGuard g(&header_->mutex);
  StoreStats out;
  out.capacity = header_->capacity;
  out.allocated = header_->allocated;
  out.evictions = header_->evictions;
  out.create_failures = header_->create_failures;
  out.num_objects = 0;
  out.num_sealed = 0;
  ObjectEntry* table = EntryTable(header_);
  for (uint32_t i = 0; i < header_->max_objects; i++) {
    if (table[i].state != (int32_t)ObjectState::kFree) out.num_objects++;
    if (table[i].state == (int32_t)ObjectState::kSealed) out.num_sealed++;
  }
  return out;
}

}  // namespace ray_tpu

// -- C API ------------------------------------------------------------------

using ray_tpu::ShmStore;

extern "C" {

void* shm_store_create(const char* name, uint64_t capacity,
                       uint32_t max_objects) {
  return ShmStore::Create(name, capacity, max_objects);
}

void* shm_store_attach(const char* name) { return ShmStore::Attach(name); }

void shm_store_close(void* store) { delete static_cast<ShmStore*>(store); }

void shm_store_destroy(const char* name) { shm_unlink(name); }

uint64_t shm_obj_create(void* store, const uint8_t* id, uint64_t size) {
  auto* s = static_cast<ShmStore*>(store);
  uint8_t* p = s->CreateObject(id, size);
  if (!p) return UINT64_MAX;
  // Offset from mmap base so the Python side can address its own mapping.
  return (uint64_t)(p - s->base());
}

int shm_obj_seal(void* store, const uint8_t* id) {
  return static_cast<ShmStore*>(store)->Seal(id) ? 1 : 0;
}

uint64_t shm_obj_get(void* store, const uint8_t* id, uint64_t* size_out) {
  auto* s = static_cast<ShmStore*>(store);
  const uint8_t* p = s->Get(id, size_out);
  if (!p) return UINT64_MAX;
  return (uint64_t)(p - s->base());
}

int shm_obj_contains(void* store, const uint8_t* id) {
  return static_cast<ShmStore*>(store)->Contains(id) ? 1 : 0;
}

int shm_obj_release(void* store, const uint8_t* id) {
  return static_cast<ShmStore*>(store)->Release(id) ? 1 : 0;
}

int shm_obj_delete(void* store, const uint8_t* id) {
  return static_cast<ShmStore*>(store)->Delete(id) ? 1 : 0;
}

int32_t shm_obj_refcount(void* store, const uint8_t* id) {
  return static_cast<ShmStore*>(store)->Refcount(id);
}

void shm_store_stats(void* store, ray_tpu::StoreStats* out) {
  *out = static_cast<ShmStore*>(store)->Stats();
}

uint64_t shm_store_mmap_size(void* store) {
  return static_cast<ShmStore*>(store)->map_size();
}

}  // extern "C"
