// Object transfer plane: chunked node-to-node object movement.
//
// Role-equivalent to the reference's ObjectManager push/pull
// (src/ray/object_manager/object_manager.h:117, pull_manager.h:52,
// object_manager.proto chunked Push/Pull): each node runs a native
// transfer server that serves object payloads straight out of the
// shared-memory store (store.h) over TCP in fixed-size chunks; a pull
// client writes the incoming stream directly into its own store's
// arena (CreateObject → recv into payload → Seal). The Python layer
// never touches the bytes — it only orchestrates who pulls from whom.
//
// Wire protocol (all little-endian):
//   request:  [u32 magic 'RTXF'][u8 op][20B object id][u64 offset][u64 len]
//   response: [u64 total_size]  (UINT64_MAX = object not present)
//             then `len` payload bytes (chunked recv; len==0 → whole object)
//
// Unlike the reference there is no gRPC: one purpose-built framed
// stream keeps the hot path at two syscalls per chunk with no
// serialization, which a 1-chip-per-host TPU fleet's DCN can saturate.

#pragma once

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <mutex>
#include <set>

#include "store.h"

namespace ray_tpu {

constexpr uint32_t kTransferMagic = 0x46585452;  // "RTXF"
constexpr uint64_t kChunkSize = 4 << 20;         // 4 MiB

enum class TransferOp : uint8_t {
  kGet = 1,      // pull a byte range (len 0 = to end) of an object
  kStat = 2,     // size lookup only
  kGetMeta = 3,  // size + serving segment identity (same-host fast path)
  kPush = 4,     // sender streams an object INTO this store
                 // (reference: push_manager.h proactive transfers)
};

// Reply to kGetMeta: lets a puller on the SAME machine as the server
// skip TCP entirely — it shm-attaches the advertised segment (identity
// confirmed by uuid, so a coincidentally same-named segment on another
// machine can't alias) and memcpys the payload at memory bandwidth.
struct MetaReply {
  uint64_t size;  // UINT64_MAX = object not present
  uint64_t uuid;  // serving store's segment identity
  char segment[128];
} __attribute__((packed));

struct TransferStats {
  uint64_t bytes_sent;
  uint64_t bytes_received;
  uint64_t objects_served;
  uint64_t objects_pulled;
  uint64_t errors;
  // Inbound proactive pushes, counted separately so push-vs-pull
  // traffic is distinguishable (push_manager diagnosis).
  uint64_t objects_pushed_in;
  uint64_t bytes_pushed_in;
};

class TransferServer {
 public:
  // Serves objects from `store` on `port` (0 = ephemeral). Spawns an
  // accept thread; per-connection handling on detached threads whose
  // fds are tracked so Stop() can shut them down and drain before the
  // server (and the store behind it) is torn down.
  static TransferServer* Start(ShmStore* store, uint16_t port);
  ~TransferServer();

  uint16_t port() const { return port_; }
  TransferStats stats() const;
  void Stop();

 private:
  TransferServer() = default;
  void AcceptLoop();
  void HandleConn(int fd);

  ShmStore* store_ = nullptr;
  int listen_fd_ = -1;
  uint16_t port_ = 0;
  std::atomic<bool> stopping_{false};
  void* accept_thread_ = nullptr;  // std::thread*
  TransferStats stats_ = {};

  // Live connection tracking: Stop() shuts each fd down (unblocking
  // handlers mid-recv) then waits for the set to drain, so no handler
  // can touch store_/stats_ after Stop() returns.
  std::mutex conn_mu_;
  std::condition_variable conn_cv_;
  std::set<int> conn_fds_;
};

// Pulls object `id` from host:port into `store` (create → recv → seal).
// Returns 0 on success, negative errno-style codes otherwise.
// `allow_local` (default) probes the kGetMeta same-host fast path
// first; tests pass false to exercise the TCP stream unconditionally.
int PullObject(ShmStore* store, const uint8_t* id, const char* host,
               uint16_t port, TransferStats* stats,
               bool allow_local = true);

// Striped pull: `streams` parallel connections each pull a disjoint
// byte range into the same arena allocation (reference:
// object_manager chunked parallel pulls). On multi-core hosts with
// fast NICs each stream rides its own core; on a single-core loopback
// it degrades gracefully to ~single-stream throughput.
int PullObjectStriped(ShmStore* store, const uint8_t* id,
                      const char* host, uint16_t port, int streams,
                      TransferStats* stats, bool allow_local = true);

// PUSH path (reference push_manager.h): stream a LOCAL object into the
// remote node's store without waiting for it to ask. Returns 0 ok,
// -1 connect, -2 local missing, -4 io error, -5 remote already has it.
int PushObject(ShmStore* store, const uint8_t* id, const char* host,
               uint16_t port, TransferStats* stats);

}  // namespace ray_tpu

// ---------------------------------------------------------------------------
// C API (ctypes surface)
// ---------------------------------------------------------------------------
extern "C" {
void* shm_transfer_start(void* store, uint16_t port);
uint16_t shm_transfer_port(void* server);
void shm_transfer_stop(void* server);
// Pull into the local store from a remote transfer server.
// Returns 0 ok, -1 connect failure, -2 remote missing, -3 local store
// full, -4 protocol/io error, -5 already present (not an error for
// callers that race).
int shm_transfer_pull(void* store, const uint8_t* id, const char* host,
                      uint16_t port);
// As above with an explicit same-host fast-path switch (allow_local=0
// forces the TCP stream — used when simulating remote hosts on one
// machine, where the fast path would silently bypass the wire).
int shm_transfer_pull_opts(void* store, const uint8_t* id,
                           const char* host, uint16_t port,
                           int allow_local);
// Striped parallel pull (streams<=1 behaves like shm_transfer_pull).
int shm_transfer_pull_striped(void* store, const uint8_t* id,
                              const char* host, uint16_t port,
                              int streams, int allow_local);
// Proactive push of a local object into a remote store.
int shm_transfer_push(void* store, const uint8_t* id, const char* host,
                      uint16_t port);
void shm_transfer_stats(void* server, ray_tpu::TransferStats* out);
}
