// Chunked TCP object transfer between node stores. See transfer.h.

#include "transfer.h"

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/sendfile.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <cstdio>
#include <map>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <vector>
#include <utility>

namespace ray_tpu {

namespace {

std::mutex g_stats_mu;

// Large socket buffers: the path is syscall/context-switch bound on
// loopback (sender and receiver alternate on shared cores); deep
// buffers keep both sides streaming instead of ping-ponging per 64 KB.
constexpr int kSockBufBytes = 8 << 20;

void TuneSocket(int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  int buf = kSockBufBytes;
  setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &buf, sizeof(buf));
  setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &buf, sizeof(buf));
  // Per-syscall progress timeout: a half-open peer (partition, NIC
  // death without RST) must not pin a handler or a puller — and with
  // the Python side bounding concurrent pulls, a hung pull would
  // otherwise starve the whole object plane. 120s of zero progress on
  // ONE send/recv is unambiguous death, not a slow link.
  timeval tv = {};
  tv.tv_sec = 120;
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
}

bool SendAll(int fd, const void* buf, uint64_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t w = send(fd, p, n, MSG_NOSIGNAL);
    if (w <= 0) {
      if (w < 0 && (errno == EINTR)) continue;
      return false;
    }
    p += w;
    n -= w;
  }
  return true;
}

bool RecvAll(int fd, void* buf, uint64_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = recv(fd, p, n, 0);
    if (r <= 0) {
      if (r < 0 && errno == EINTR) continue;
      return false;
    }
    p += r;
    n -= r;
  }
  return true;
}

struct Request {
  uint32_t magic;
  uint8_t op;
  uint8_t id[kIdSize];
  uint64_t offset;
  uint64_t len;
} __attribute__((packed));

// Connect + tune one socket to host:port; -1 on failure.
int ConnectTo(const char* host, uint16_t port) {
  addrinfo hints = {};
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  addrinfo* res = nullptr;
  char port_str[16];
  snprintf(port_str, sizeof(port_str), "%u", port);
  if (getaddrinfo(host, port_str, &hints, &res) != 0 || res == nullptr) {
    return -1;
  }
  int fd = socket(res->ai_family, res->ai_socktype, res->ai_protocol);
  if (fd < 0 || connect(fd, res->ai_addr, res->ai_addrlen) != 0) {
    freeaddrinfo(res);
    if (fd >= 0) close(fd);
    return -1;
  }
  freeaddrinfo(res);
  TuneSocket(fd);
  return fd;
}

}  // namespace

TransferServer* TransferServer::Start(ShmStore* store, uint16_t port) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr = {};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(port);
  if (bind(fd, (sockaddr*)&addr, sizeof(addr)) != 0 ||
      listen(fd, 64) != 0) {
    close(fd);
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  getsockname(fd, (sockaddr*)&addr, &alen);

  auto* srv = new TransferServer();
  srv->store_ = store;
  srv->listen_fd_ = fd;
  srv->port_ = ntohs(addr.sin_port);
  srv->accept_thread_ = new std::thread([srv] { srv->AcceptLoop(); });
  return srv;
}

TransferServer::~TransferServer() { Stop(); }

void TransferServer::Stop() {
  if (stopping_.exchange(true)) return;
  if (listen_fd_ >= 0) {
    shutdown(listen_fd_, SHUT_RDWR);
    close(listen_fd_);
    listen_fd_ = -1;
  }
  auto* t = static_cast<std::thread*>(accept_thread_);
  if (t != nullptr) {
    if (t->joinable()) t->join();
    delete t;
    accept_thread_ = nullptr;
  }
  // Unblock in-flight handlers (they may be mid-recv on a slow peer)
  // and wait for every one to finish before the caller frees us / the
  // store we serve from.
  std::unique_lock<std::mutex> lk(conn_mu_);
  for (int fd : conn_fds_) shutdown(fd, SHUT_RDWR);
  conn_cv_.wait(lk, [this] { return conn_fds_.empty(); });
}

void TransferServer::AcceptLoop() {
  while (!stopping_.load()) {
    int conn = accept(listen_fd_, nullptr, nullptr);
    if (conn < 0) {
      if (stopping_.load()) return;
      if (errno == EINTR) continue;
      return;
    }
    TuneSocket(conn);
    {
      std::lock_guard<std::mutex> g(conn_mu_);
      if (stopping_.load()) {  // Stop() may have run since accept()
        close(conn);
        continue;
      }
      conn_fds_.insert(conn);
    }
    std::thread([this, conn] { HandleConn(conn); }).detach();
  }
}

void TransferServer::HandleConn(int fd) {
  Request req;
  while (!stopping_ && RecvAll(fd, &req, sizeof(req))) {
    if (req.magic != kTransferMagic) break;
    if (req.op == (uint8_t)TransferOp::kPush) {
      // Inbound proactive push: accept(1)/have-it(2)/refuse(0), then
      // recv straight into a fresh arena allocation and seal.
      if (store_->Contains(req.id)) {
        uint8_t a = 2;
        if (!SendAll(fd, &a, sizeof(a))) break;
        continue;  // sender stops streaming on 2
      }
      uint8_t* dst = store_->CreateObject(req.id, req.len);
      uint8_t a = dst == nullptr ? 0 : 1;
      if (!SendAll(fd, &a, sizeof(a)) || dst == nullptr) break;
      uint64_t got = 0;
      bool ok = true;
      while (ok && got < req.len) {
        uint64_t n =
            req.len - got < kChunkSize ? req.len - got : kChunkSize;
        ok = RecvAll(fd, dst + got, n);
        got += n;
      }
      if (!ok) {
        store_->Release(req.id);
        store_->Delete(req.id);
        {
          std::lock_guard<std::mutex> lk(g_stats_mu);
          stats_.errors += 1;
        }
        break;
      }
      store_->Seal(req.id);
      {
        std::lock_guard<std::mutex> lk(g_stats_mu);
        stats_.bytes_pushed_in += got;
        stats_.objects_pushed_in += 1;
      }
      uint8_t sealed = 1;
      if (!SendAll(fd, &sealed, sizeof(sealed))) break;
      continue;
    }
    uint64_t size = 0;
    const uint8_t* payload = store_->Get(req.id, &size);  // pins
    if (req.op == (uint8_t)TransferOp::kGetMeta) {
      MetaReply meta = {};
      meta.size = payload == nullptr ? UINT64_MAX : size;
      meta.uuid = store_->uuid();
      memcpy(meta.segment, store_->name(),
               sizeof(meta.segment) - 1);  // name_ is 256B, reply 128
      bool sent_ok = SendAll(fd, &meta, sizeof(meta));
      if (payload != nullptr) store_->Release(req.id);
      if (!sent_ok) break;
      continue;
    }
    if (payload == nullptr) {
      uint64_t missing = UINT64_MAX;
      if (!SendAll(fd, &missing, sizeof(missing))) break;
      continue;
    }
    bool ok = SendAll(fd, &size, sizeof(size));
    if (ok && req.op == (uint8_t)TransferOp::kGet) {
      uint64_t off = req.offset < size ? req.offset : size;
      uint64_t len = req.len == 0 ? size - off : req.len;
      if (off + len > size) len = size - off;
      // Zero-copy send: sendfile() streams tmpfs pages into the socket
      // without the user->kernel copy a send()-from-mmap pays. Chunked
      // so a slow peer can't pin a huge buffer and stats stay live.
      // Falls back to SendAll if sendfile is refused (e.g. exotic fs).
      off_t file_off =
          (off_t)((payload - store_->base()) + off);
      uint64_t sent = 0;
      bool use_sendfile = store_->fd() >= 0;
      while (ok && sent < len) {
        uint64_t n = len - sent < kChunkSize ? len - sent : kChunkSize;
        if (use_sendfile) {
          ssize_t w = sendfile(fd, store_->fd(), &file_off, n);
          if (w < 0) {
            if (errno == EINTR) continue;
            if (sent == 0 && (errno == EINVAL || errno == ENOSYS)) {
              use_sendfile = false;  // fall back for the whole object
              continue;
            }
            ok = false;
            break;
          }
          sent += (uint64_t)w;  // sendfile may short-write; loop covers it
        } else {
          ok = SendAll(fd, payload + off + sent, n);
          sent += n;
        }
      }
      std::lock_guard<std::mutex> g(g_stats_mu);
      stats_.bytes_sent += sent;
      stats_.objects_served += 1;
      if (!ok) stats_.errors += 1;
    }
    store_->Release(req.id);
    if (!ok) break;
  }
  {
    // Notify while holding the lock: Stop()'s waiter may observe the
    // empty set, return, and let the destructor destroy conn_cv_ — an
    // unlocked notify_all would then touch a freed condvar (TSan-caught
    // pthread_cond_destroy/broadcast race).
    std::lock_guard<std::mutex> g(conn_mu_);
    conn_fds_.erase(fd);
    conn_cv_.notify_all();
  }
  close(fd);
}

TransferStats TransferServer::stats() const {
  std::lock_guard<std::mutex> g(g_stats_mu);
  return stats_;
}

// Cache of peer segments this process has attached for same-host pulls.
// Entries are validated by uuid on every use; a stale mapping (peer
// segment recreated) is deliberately LEAKED rather than deleted — other
// threads may be mid-memcpy on it, and the count of recreations over a
// process lifetime is tiny.
ShmStore* AttachPeerCached(const char* name, uint64_t uuid) {
  static std::mutex mu;
  static std::map<std::string, ShmStore*>* cache =
      new std::map<std::string, ShmStore*>();
  // Negative cache: a (name, uuid) that attached to a DIFFERENT
  // segment is a same-named store on another machine — without this,
  // every pull from that peer re-mmaps and re-unmaps the whole local
  // segment just to re-discover the mismatch.
  static std::set<std::pair<std::string, uint64_t>>* known_foreign =
      new std::set<std::pair<std::string, uint64_t>>();
  std::lock_guard<std::mutex> g(mu);
  auto it = cache->find(name);
  if (it != cache->end()) {
    if (it->second->uuid() == uuid) return it->second;
    cache->erase(it);  // stale; leak the old mapping (see above)
  }
  if (known_foreign->count({name, uuid})) return nullptr;
  // No background prefault for peer attaches: TryLocalPull populates
  // exactly the ranges it copies.
  ShmStore* s = ShmStore::Attach(name, /*prefault=*/false);
  if (s == nullptr) return nullptr;  // not on this machine
  if (s->uuid() != uuid) {
    delete s;  // same name, different segment (other machine / rebuilt)
    known_foreign->insert({name, uuid});
    return nullptr;
  }
  (*cache)[name] = s;
  return s;
}

// Same-host fast path: copy straight between mapped segments at memory
// bandwidth (the source object stays pinned for the duration). Returns
// a PullObject code, or 1 if the fast path does not apply.
int TryLocalPull(ShmStore* store, const uint8_t* id,
                 const MetaReply& meta, TransferStats* stats) {
  if (meta.uuid == store->uuid()) return -5;  // pulling from ourselves
  ShmStore* peer = AttachPeerCached(meta.segment, meta.uuid);
  if (peer == nullptr) return 1;
  uint64_t psize = 0;
  const uint8_t* src = peer->Get(id, &psize);  // pins against eviction
  if (src == nullptr) return 1;  // evicted since the meta reply
  if (psize != meta.size) {
    peer->Release(id);
    return 1;
  }
  uint8_t* dst = store->CreateObject(id, psize);
  if (dst == nullptr) {
    peer->Release(id);
    return store->Contains(id) ? -5 : -3;
  }
  // Populate PTEs in bulk before copying: a fresh Attach mapping would
  // otherwise take one minor fault per 4K page, which costs several
  // times the memcpy itself for GiB objects (one syscall batches the
  // whole range kernel-side). Advisory — the copy is correct either way.
  PopulateRange(src, psize, /*write=*/false);
  PopulateRange(dst, psize, /*write=*/true);
  memcpy(dst, src, psize);
  peer->Release(id);
  store->Seal(id);
  if (stats) {
    stats->bytes_received += psize;
    stats->objects_pulled += 1;
  }
  return 0;
}

int PullObject(ShmStore* store, const uint8_t* id, const char* host,
               uint16_t port, TransferStats* stats, bool allow_local) {
  if (store->Contains(id)) return -5;
  int fd = ConnectTo(host, port);
  if (fd < 0) return -1;

  Request req = {};
  req.magic = kTransferMagic;
  memcpy(req.id, id, kIdSize);
  req.offset = 0;
  req.len = 0;
  if (allow_local) {
    // Identity handshake first: if the serving segment is mapped on
    // THIS machine, copy segment-to-segment and skip the TCP stream
    // (loopback TCP tops out well below memcpy bandwidth).
    req.op = (uint8_t)TransferOp::kGetMeta;
    MetaReply meta = {};
    if (!SendAll(fd, &req, sizeof(req)) ||
        !RecvAll(fd, &meta, sizeof(meta))) {
      close(fd);
      return -4;
    }
    if (meta.size == UINT64_MAX) {
      close(fd);
      return -2;
    }
    meta.segment[sizeof(meta.segment) - 1] = '\0';
    int rc = TryLocalPull(store, id, meta, stats);
    if (rc <= 0) {
      close(fd);
      return rc;
    }
    // Fast path inapplicable: stream over the same connection.
  }
  req.op = (uint8_t)TransferOp::kGet;
  uint64_t size = 0;
  if (!SendAll(fd, &req, sizeof(req)) ||
      !RecvAll(fd, &size, sizeof(size))) {
    close(fd);
    return -4;
  }
  if (size == UINT64_MAX) {
    close(fd);
    return -2;
  }

  uint8_t* dst = store->CreateObject(id, size);
  if (dst == nullptr) {
    // Either a racing pull created it, or no space after eviction.
    close(fd);
    return store->Contains(id) ? -5 : -3;
  }
  // Chunked recv straight into the arena payload — no staging buffer.
  uint64_t got = 0;
  bool ok = true;
  while (ok && got < size) {
    uint64_t n = size - got < kChunkSize ? size - got : kChunkSize;
    ok = RecvAll(fd, dst + got, n);
    got += n;
  }
  close(fd);
  if (!ok) {
    store->Release(id);  // drop writer pin; entry stays unsealed
    store->Delete(id);
    if (stats) stats->errors += 1;
    return -4;
  }
  store->Seal(id);
  if (stats) {
    stats->bytes_received += got;
    stats->objects_pulled += 1;
  }
  return 0;
}

namespace {

// Pull one byte range over its own connection into dst (pre-sized).
bool PullRange(const uint8_t* id, const char* host, uint16_t port,
               uint64_t offset, uint64_t len, uint8_t* dst) {
  int fd = ConnectTo(host, port);
  if (fd < 0) return false;
  Request req = {};
  req.magic = kTransferMagic;
  req.op = (uint8_t)TransferOp::kGet;
  memcpy(req.id, id, kIdSize);
  req.offset = offset;
  req.len = len;
  uint64_t size = 0;
  bool ok = SendAll(fd, &req, sizeof(req)) &&
            RecvAll(fd, &size, sizeof(size)) && size != UINT64_MAX;
  uint64_t got = 0;
  while (ok && got < len) {
    uint64_t n = len - got < kChunkSize ? len - got : kChunkSize;
    ok = RecvAll(fd, dst + got, n);
    got += n;
  }
  close(fd);
  return ok;
}

}  // namespace

int PullObjectStriped(ShmStore* store, const uint8_t* id,
                      const char* host, uint16_t port, int streams,
                      TransferStats* stats, bool allow_local) {
  if (streams <= 1) {
    return PullObject(store, id, host, port, stats, allow_local);
  }
  if (store->Contains(id)) return -5;
  int fd = ConnectTo(host, port);
  if (fd < 0) return -1;
  Request req = {};
  req.magic = kTransferMagic;
  memcpy(req.id, id, kIdSize);
  if (allow_local) {
    req.op = (uint8_t)TransferOp::kGetMeta;
    MetaReply meta = {};
    if (!SendAll(fd, &req, sizeof(req)) ||
        !RecvAll(fd, &meta, sizeof(meta))) {
      close(fd);
      return -4;
    }
    if (meta.size == UINT64_MAX) {
      close(fd);
      return -2;
    }
    meta.segment[sizeof(meta.segment) - 1] = '\0';
    int rc = TryLocalPull(store, id, meta, stats);
    if (rc <= 0) {
      close(fd);
      return rc;
    }
  }
  // Size probe on the control connection, then fan the range pulls out.
  req.op = (uint8_t)TransferOp::kStat;
  uint64_t size = 0;
  bool ok = SendAll(fd, &req, sizeof(req)) &&
            RecvAll(fd, &size, sizeof(size));
  close(fd);
  if (!ok) return -4;
  if (size == UINT64_MAX) return -2;

  uint8_t* dst = store->CreateObject(id, size);
  if (dst == nullptr) return store->Contains(id) ? -5 : -3;
  // Stripe boundaries chunk-aligned so each stream's recv loop stays in
  // whole chunks; last stripe takes the remainder.
  uint64_t stripe = (size / (uint64_t)streams) / kChunkSize * kChunkSize;
  if (stripe == 0) stripe = size;  // small object: one live stream
  std::vector<std::thread> workers;
  std::atomic<bool> all_ok{true};
  uint64_t off = 0;
  while (off < size) {
    uint64_t len = off + stripe < size && workers.size() + 1 <
                   (size_t)streams ? stripe : size - off;
    workers.emplace_back([&, off, len] {
      if (!PullRange(id, host, port, off, len, dst + off)) {
        all_ok = false;
      }
    });
    off += len;
  }
  for (auto& t : workers) t.join();
  if (!all_ok) {
    store->Release(id);
    store->Delete(id);
    if (stats) stats->errors += 1;
    return -4;
  }
  store->Seal(id);
  if (stats) {
    stats->bytes_received += size;
    stats->objects_pulled += 1;
  }
  return 0;
}

int PushObject(ShmStore* store, const uint8_t* id, const char* host,
               uint16_t port, TransferStats* stats) {
  uint64_t size = 0;
  const uint8_t* payload = store->Get(id, &size);  // pins
  if (payload == nullptr) return -2;
  int fd = ConnectTo(host, port);
  if (fd < 0) {
    store->Release(id);
    return -1;
  }
  Request req = {};
  req.magic = kTransferMagic;
  req.op = (uint8_t)TransferOp::kPush;
  memcpy(req.id, id, kIdSize);
  req.offset = 0;
  req.len = size;
  uint8_t accept = 0;
  bool ok = SendAll(fd, &req, sizeof(req)) &&
            RecvAll(fd, &accept, sizeof(accept));
  if (ok && accept == 2) {  // remote already has it
    close(fd);
    store->Release(id);
    return -5;
  }
  if (ok && accept != 1) ok = false;  // remote store full / refused
  uint64_t sent = 0;
  while (ok && sent < size) {
    uint64_t n = size - sent < kChunkSize ? size - sent : kChunkSize;
    ok = SendAll(fd, payload + sent, n);
    sent += n;
  }
  uint8_t sealed = 0;
  if (ok) ok = RecvAll(fd, &sealed, sizeof(sealed)) && sealed == 1;
  close(fd);
  store->Release(id);
  if (!ok) {
    if (stats) stats->errors += 1;
    return -4;
  }
  return 0;
}

}  // namespace ray_tpu

// ---------------------------------------------------------------------------
// C API
// ---------------------------------------------------------------------------
extern "C" {

void* shm_transfer_start(void* store, uint16_t port) {
  return ray_tpu::TransferServer::Start(
      static_cast<ray_tpu::ShmStore*>(store), port);
}

uint16_t shm_transfer_port(void* server) {
  return static_cast<ray_tpu::TransferServer*>(server)->port();
}

void shm_transfer_stop(void* server) {
  auto* s = static_cast<ray_tpu::TransferServer*>(server);
  s->Stop();
  delete s;
}

int shm_transfer_pull(void* store, const uint8_t* id, const char* host,
                      uint16_t port) {
  return ray_tpu::PullObject(static_cast<ray_tpu::ShmStore*>(store), id,
                             host, port, nullptr);
}

int shm_transfer_pull_opts(void* store, const uint8_t* id,
                           const char* host, uint16_t port,
                           int allow_local) {
  return ray_tpu::PullObject(static_cast<ray_tpu::ShmStore*>(store), id,
                             host, port, nullptr, allow_local != 0);
}

void shm_transfer_stats(void* server, ray_tpu::TransferStats* out) {
  *out = static_cast<ray_tpu::TransferServer*>(server)->stats();
}

int shm_transfer_pull_striped(void* store, const uint8_t* id,
                              const char* host, uint16_t port,
                              int streams, int allow_local) {
  return ray_tpu::PullObjectStriped(
      static_cast<ray_tpu::ShmStore*>(store), id, host, port, streams,
      nullptr, allow_local != 0);
}

int shm_transfer_push(void* store, const uint8_t* id, const char* host,
                      uint16_t port) {
  return ray_tpu::PushObject(static_cast<ray_tpu::ShmStore*>(store), id,
                             host, port, nullptr);
}
}
