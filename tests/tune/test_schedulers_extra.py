"""Round-4 scheduler breadth: HyperBand brackets, PB2's GP-bandit
explore, ResourceChangingScheduler (reference `tune/schedulers/
hyperband.py`, `pb2.py`, `resource_changing_scheduler.py`)."""

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.tune import (
    PB2,
    Checkpoint,
    HyperBandScheduler,
    ResourceChangingScheduler,
    TuneConfig,
    Tuner,
)


@pytest.fixture(autouse=True)
def ray():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8)
    yield
    ray_tpu.shutdown()


def test_hyperband_culls_bad_trials_across_brackets():
    def trainable(config):
        import time

        for i in range(30):
            # Pace reports: an unthrottled loop buffers all 30 results
            # before the scheduler processes the first milestone, so
            # whether culling truncates the history becomes a driver/
            # actor timing race (observed flaky on BOTH sides of the
            # PR 2 control-plane change, ~3/8 runs).
            time.sleep(0.002)
            tune.report({"score": config["q"] * (i + 1)})

    hb = HyperBandScheduler(metric="score", mode="max", max_t=30,
                            reduction_factor=3, brackets=2,
                            grace_period=1)
    tuner = Tuner(
        trainable,
        param_space={"q": tune.grid_search(
            [0.1, 0.2, 0.3, 0.4, 1.0, 2.0])},
        tune_config=TuneConfig(metric="score", mode="max",
                               scheduler=hb))
    grid = tuner.fit()
    iters = {r.config["q"]: len(r.metrics_history) for r in grid}
    # The best configs run to completion; the worst get culled early.
    assert iters[2.0] == 30
    assert iters[0.1] < 30
    # Brackets genuinely differ in their first-cull milestone.
    graces = {b.grace_period for b in hb._brackets}
    assert len(graces) == 2


def test_pb2_gp_explore_proposes_within_bounds_and_learns():
    """PB2 on a quadratic landscape: exploit + GP-UCB explore should
    carry trials toward the good region and never leave the bounds."""

    def trainable(config):
        ck = tune.get_checkpoint()
        x = ck.to_dict()["x"] if ck else 0.0
        for _ in range(30):
            # score rate peaks at lr=1.0 inside [0, 1]
            x += 1.0 - (config["lr"] - 1.0) ** 2
            tune.report({"x": x, "lr": config["lr"]},
                        checkpoint=Checkpoint.from_dict({"x": x}))

    pb2 = PB2(metric="x", mode="max", perturbation_interval=5,
              hyperparam_bounds={"lr": [0.0, 1.0]}, seed=0)
    tuner = Tuner(
        trainable,
        param_space={"lr": tune.grid_search([0.05, 0.1, 0.9, 1.0])},
        tune_config=TuneConfig(metric="x", mode="max", scheduler=pb2))
    grid = tuner.fit()
    finals = sorted(r.metrics["x"] for r in grid)
    # exploitation must lift the stragglers well above their solo value
    # (lr=0.05 alone finishes at 30*(1-0.9025)=2.9)
    assert finals[0] > 5.0, finals
    for r in grid:
        assert 0.0 <= r.metrics["lr"] <= 1.0
    # the GP actually accumulated observations
    assert len(pb2._y) > 4


def test_resource_changing_scheduler_restarts_with_new_resources():
    seen = []

    def trainable(config):
        ck = tune.get_checkpoint()
        i0 = ck.to_dict()["i"] if ck else 0
        for i in range(i0, 12):
            tune.report({"i": i},
                        checkpoint=Checkpoint.from_dict({"i": i}))

    applied = []

    def alloc(runner, trial, result):
        if trial.resources == {"CPU": 2}:
            applied.append(result["i"])  # upgrade took effect
            return None
        # Bump CPU allocation once the trial passes iteration 5.
        if result.get("i", 0) >= 5:
            return {"CPU": 2}
        return None

    rcs = ResourceChangingScheduler(resources_allocation_function=alloc)
    tuner = Tuner(trainable,
                  param_space={"a": tune.grid_search([1])},
                  tune_config=TuneConfig(scheduler=rcs))
    grid = tuner.fit()
    r = grid[0]
    assert r.metrics["i"] == 11  # resumed from checkpoint, not restarted
    assert applied, "resource upgrade never took effect"
    assert min(applied) >= 5  # post-restart results ran on new resources
    assert r.error is None


def test_bohb_search_with_hyperband():
    """BOHB (reference tune/search/bohb): budget-aware TPE paired with
    HyperBand brackets — high-budget observations steer sampling."""
    from ray_tpu.tune.search import BOHBSearch

    def trainable(config):
        for i in range(9):
            # quality ~ -(x-0.6)^2, noisily revealed with budget
            tune.report({"score": -(config["x"] - 0.6) ** 2 * (i + 1)})

    searcher = BOHBSearch({"x": tune.uniform(0.0, 1.0)},
                          metric="score", mode="max", n_startup=5,
                          min_points_per_budget=4, seed=0)
    hb = HyperBandScheduler(metric="score", mode="max", max_t=9,
                            reduction_factor=3, brackets=2)
    tuner = Tuner(trainable,
                  tune_config=TuneConfig(metric="score", mode="max",
                                         search_alg=searcher,
                                         scheduler=hb, num_samples=30))
    grid = tuner.fit()
    best = grid.get_best_result("score", "max")
    assert abs(best.config["x"] - 0.6) < 0.2, best.config
    # rung-level observations accumulated per budget AND the fitted
    # model actually produced suggestions (an eager driver would leave
    # this at 0 and silently degrade BOHB to random search)
    assert searcher._by_budget and max(searcher._by_budget) >= 3
    assert searcher.model_suggestions > 0, \
        "model phase never engaged — suggestions were all random"



def test_pb2_beats_or_matches_random_pbt_on_quadratic():
    """The round-4 verdict's honesty check: PB2's GP-UCB explore vs
    plain PBT's random perturbation on the same quadratic landscape,
    same seeds and trial budget. Both exploit identically, so the
    difference is explore quality — the GP must not LOSE to random
    search, and should land trials near the optimum."""
    import numpy as np

    from ray_tpu.tune.schedulers import PopulationBasedTraining

    def trainable(config):
        ck = tune.get_checkpoint()
        x = ck.to_dict()["x"] if ck else 0.0
        for _ in range(30):
            x += 1.0 - (config["lr"] - 1.0) ** 2
            tune.report({"x": x, "lr": config["lr"]},
                        checkpoint=Checkpoint.from_dict({"x": x}))

    # Starting population biased far from the optimum at lr=1.0.
    start = [0.05, 0.1, 0.2, 0.3]

    def run(scheduler):
        tuner = Tuner(
            trainable,
            param_space={"lr": tune.grid_search(list(start))},
            tune_config=TuneConfig(metric="x", mode="max",
                                   scheduler=scheduler))
        grid = tuner.fit()
        return float(np.mean([r.metrics["x"] for r in grid]))

    pb2_mean = run(PB2(metric="x", mode="max", perturbation_interval=5,
                       hyperparam_bounds={"lr": [0.0, 1.0]}, seed=3))
    pbt_mean = run(PopulationBasedTraining(
        metric="x", mode="max", perturbation_interval=5,
        hyperparam_mutations={"lr": lambda rng: rng.uniform(0.0, 1.0)},
        resample_probability=0.5, seed=3))
    # GP-guided explore must at least match random perturbation (small
    # tolerance: both are stochastic on a tiny budget).
    assert pb2_mean >= 0.9 * pbt_mean, (pb2_mean, pbt_mean)
    # And in absolute terms PB2 carried the biased population to a
    # usable region (solo lr=0.3 finishes at 30*(1-0.49)=15.3).
    assert pb2_mean > 15.0, pb2_mean
