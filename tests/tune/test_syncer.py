"""Syncer: experiment artifacts ship to upload_dir; restore from it."""

import os

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.air import RunConfig
from ray_tpu.tune import LocalSyncer, SyncConfig, Syncer, Tuner, TuneConfig


@pytest.fixture(autouse=True)
def ray():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def _train(config):
    for i in range(3):
        tune.report({"score": config["x"] * (i + 1)},
                    checkpoint=tune.Checkpoint.from_dict({"i": i}))


def test_sync_up_and_restore_from_upload_dir(tmp_path):
    storage = tmp_path / "local"
    upload = tmp_path / "durable"
    upload.mkdir()
    tuner = Tuner(
        _train,
        param_space={"x": tune.grid_search([1, 2])},
        tune_config=TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(
            name="exp1", storage_path=str(storage),
            sync_config=SyncConfig(upload_dir=str(upload))))
    results = tuner.fit()
    assert results.get_best_result("score", "max").metrics["score"] == 6

    # The experiment dir was uploaded (state + usable for restore).
    synced = upload / "exp1"
    assert (synced / "experiment_state.pkl").exists()

    # Wipe the local copy; restore straight from the synced dir.
    import shutil

    shutil.rmtree(storage)
    restored = Tuner.restore(str(synced), _train)
    grid = restored.fit()
    assert grid.get_best_result("score", "max").metrics["score"] == 6


def test_custom_syncer_plugs_in(tmp_path):
    calls = []

    class RecordingSyncer(Syncer):
        def sync_up(self, local_dir, remote_dir):
            calls.append(("up", local_dir, remote_dir))
            return LocalSyncer().sync_up(local_dir, remote_dir)

    tuner = Tuner(
        _train, param_space={"x": 1},
        run_config=RunConfig(
            name="exp2", storage_path=str(tmp_path / "l"),
            sync_config=SyncConfig(upload_dir=str(tmp_path / "r"),
                                   syncer=RecordingSyncer())))
    tuner.fit()
    assert calls  # custom syncer used
    assert os.path.exists(tmp_path / "r" / "exp2" /
                          "experiment_state.pkl")


def test_sync_disabled_when_no_upload_dir(tmp_path):
    tuner = Tuner(
        _train, param_space={"x": 1},
        run_config=RunConfig(name="exp3",
                             storage_path=str(tmp_path),
                             sync_config=SyncConfig(upload_dir=None)))
    tuner.fit()  # no crash, no sync
    assert not os.path.exists(tmp_path / "exp3_remote")


def test_sync_period_fires_without_checkpoint_trigger(tmp_path, monkeypatch):
    """sync_on_checkpoint=False disables only the checkpoint trigger;
    period-based syncing must still upload (ADVICE r3)."""
    from ray_tpu.tune.syncer import SyncerCallback

    calls = []

    class Spy(Syncer):
        def sync_up(self, local_dir, remote_dir):
            calls.append(local_dir)
            return True

        def sync_down(self, remote_dir, local_dir):
            return True

    exp = tmp_path / "exp"
    exp.mkdir()
    cb = SyncerCallback(
        SyncConfig(upload_dir=str(tmp_path / "up"), syncer=Spy(),
                   sync_period=0.0, sync_on_checkpoint=False),
        str(exp))
    cb.maybe_sync()
    cb.maybe_sync()
    assert len(calls) == 2  # period elapsed (0s) => both fire

    # With a long period, sync_on_checkpoint=False must NOT sync on
    # checkpoint events after the first upload...
    calls.clear()
    cb2 = SyncerCallback(
        SyncConfig(upload_dir=str(tmp_path / "up"), syncer=Spy(),
                   sync_period=3600.0, sync_on_checkpoint=False),
        str(exp))
    cb2.maybe_sync(on_checkpoint=True)  # first: period_due (never synced)
    cb2.maybe_sync(on_checkpoint=True)
    assert len(calls) == 1
    # ...while sync_on_checkpoint=True syncs on every checkpoint event.
    calls.clear()
    cb3 = SyncerCallback(
        SyncConfig(upload_dir=str(tmp_path / "up"), syncer=Spy(),
                   sync_period=3600.0, sync_on_checkpoint=True),
        str(exp))
    cb3.maybe_sync(on_checkpoint=True)
    cb3.maybe_sync(on_checkpoint=True)
    assert len(calls) == 2


def test_background_sync_error_does_not_abort_experiment(tmp_path):
    """A transient background upload failure must be swallowed by
    maybe_sync (logged + counted), not abort the experiment loop;
    close() still surfaces a terminal failure (ADVICE r3)."""
    from ray_tpu.tune.syncer import SyncerCallback, _BackgroundSyncer

    class Flaky(Syncer):
        def __init__(self):
            self.n = 0

        def sync_up(self, local_dir, remote_dir):
            self.n += 1
            raise OSError("disk temporarily gone")

        def sync_down(self, remote_dir, local_dir):
            return True

    exp = tmp_path / "exp"
    exp.mkdir()
    cb = SyncerCallback(
        SyncConfig(upload_dir=str(tmp_path / "up"),
                   syncer=_BackgroundSyncer(Flaky()), sync_period=0.0),
        str(exp))
    cb.maybe_sync()  # starts background upload that fails
    cb.maybe_sync()  # wait() re-raises inside sync_up -> must be caught
    cb.maybe_sync()
    assert cb.sync_errors >= 1
    with pytest.raises(RuntimeError, match="background sync failed"):
        cb.close()
