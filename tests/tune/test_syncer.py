"""Syncer: experiment artifacts ship to upload_dir; restore from it."""

import os

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.air import RunConfig
from ray_tpu.tune import LocalSyncer, SyncConfig, Syncer, Tuner, TuneConfig


@pytest.fixture(autouse=True)
def ray():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def _train(config):
    for i in range(3):
        tune.report({"score": config["x"] * (i + 1)},
                    checkpoint=tune.Checkpoint.from_dict({"i": i}))


def test_sync_up_and_restore_from_upload_dir(tmp_path):
    storage = tmp_path / "local"
    upload = tmp_path / "durable"
    upload.mkdir()
    tuner = Tuner(
        _train,
        param_space={"x": tune.grid_search([1, 2])},
        tune_config=TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(
            name="exp1", storage_path=str(storage),
            sync_config=SyncConfig(upload_dir=str(upload))))
    results = tuner.fit()
    assert results.get_best_result("score", "max").metrics["score"] == 6

    # The experiment dir was uploaded (state + usable for restore).
    synced = upload / "exp1"
    assert (synced / "experiment_state.pkl").exists()

    # Wipe the local copy; restore straight from the synced dir.
    import shutil

    shutil.rmtree(storage)
    restored = Tuner.restore(str(synced), _train)
    grid = restored.fit()
    assert grid.get_best_result("score", "max").metrics["score"] == 6


def test_custom_syncer_plugs_in(tmp_path):
    calls = []

    class RecordingSyncer(Syncer):
        def sync_up(self, local_dir, remote_dir):
            calls.append(("up", local_dir, remote_dir))
            return LocalSyncer().sync_up(local_dir, remote_dir)

    tuner = Tuner(
        _train, param_space={"x": 1},
        run_config=RunConfig(
            name="exp2", storage_path=str(tmp_path / "l"),
            sync_config=SyncConfig(upload_dir=str(tmp_path / "r"),
                                   syncer=RecordingSyncer())))
    tuner.fit()
    assert calls  # custom syncer used
    assert os.path.exists(tmp_path / "r" / "exp2" /
                          "experiment_state.pkl")


def test_sync_disabled_when_no_upload_dir(tmp_path):
    tuner = Tuner(
        _train, param_space={"x": 1},
        run_config=RunConfig(name="exp3",
                             storage_path=str(tmp_path),
                             sync_config=SyncConfig(upload_dir=None)))
    tuner.fit()  # no crash, no sync
    assert not os.path.exists(tmp_path / "exp3_remote")
