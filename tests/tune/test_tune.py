"""Tune layer tests: searchers, schedulers, checkpointing, PBT."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.air import Checkpoint, RunConfig
from ray_tpu.air.config import CheckpointConfig, FailureConfig
from ray_tpu.tune import (
    ASHAScheduler,
    MaximumIterationStopper,
    PopulationBasedTraining,
    TuneConfig,
    Tuner,
)


@pytest.fixture(autouse=True)
def ray():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8)
    yield
    ray_tpu.shutdown()


def test_grid_search_expansion():
    def trainable(config):
        tune.report({"score": config["a"] * 10 + config["b"]})

    tuner = Tuner(trainable, param_space={
        "a": tune.grid_search([1, 2, 3]),
        "b": tune.grid_search([0, 1]),
    })
    grid = tuner.fit()
    assert len(grid) == 6
    best = grid.get_best_result("score")
    assert best.metrics["score"] == 31


def test_random_sampling_and_num_samples():
    def trainable(config):
        tune.report({"v": config["x"]})

    tuner = Tuner(trainable,
                  param_space={"x": tune.uniform(0, 1)},
                  tune_config=TuneConfig(num_samples=5, seed=7))
    grid = tuner.fit()
    vals = [r.metrics["v"] for r in grid]
    assert len(vals) == 5
    assert len(set(vals)) == 5
    assert all(0 <= v <= 1 for v in vals)


def test_class_trainable_and_stop_criteria():
    class MyTrainable(tune.Trainable):
        def setup(self, config):
            self.x = config.get("start", 0)

        def step(self):
            self.x += 1
            return {"x": self.x}

        def save_checkpoint(self):
            return {"x": self.x}

        def load_checkpoint(self, data):
            self.x = data["x"]

    tuner = Tuner(MyTrainable, param_space={"start": 5},
                  run_config=RunConfig(stop={"x": 8}))
    grid = tuner.fit()
    assert grid[0].metrics["x"] == 8


def test_asha_stops_bad_trials():
    def trainable(config):
        for i in range(20):
            # quality determines convergence speed
            tune.report({"acc": config["q"] * (i + 1) / 20})

    scheduler = ASHAScheduler(max_t=20, grace_period=2,
                              reduction_factor=2)
    tuner = Tuner(trainable,
                  param_space={"q": tune.grid_search(
                      [0.1, 0.2, 0.5, 0.9])},
                  tune_config=TuneConfig(metric="acc", mode="max",
                                         scheduler=scheduler))
    grid = tuner.fit()
    best = grid.get_best_result("acc")
    assert best.metrics["config"]["q"] == 0.9
    # at least one bad trial was cut early
    iters = [len(r.metrics_history) for r in grid]
    assert min(iters) < 20


def test_checkpoint_keep_top_k():
    def trainable(config):
        for i, score in enumerate([1, 5, 3, 9, 2]):
            tune.report({"score": score},
                        checkpoint=Checkpoint.from_dict({"i": i,
                                                         "score": score}))

    tuner = Tuner(trainable, run_config=RunConfig(
        checkpoint_config=CheckpointConfig(
            num_to_keep=2, checkpoint_score_attribute="score")))
    grid = tuner.fit()
    best = grid[0].checkpoint
    assert best.to_dict()["score"] == 9
    kept = [m["score"] for _, m in grid[0].best_checkpoints]
    assert sorted(kept) == [5, 9]


def test_failure_retry_from_checkpoint():
    attempts = {"n": 0}

    class Flaky(tune.Trainable):
        def setup(self, config):
            self.i = 0

        def step(self):
            self.i += 1
            if self.i == 3 and attempts["n"] == 0:
                attempts["n"] += 1
                raise RuntimeError("transient failure")
            return {"i": self.i, "done": self.i >= 5}

        def save_checkpoint(self):
            return {"i": self.i}

        def load_checkpoint(self, data):
            self.i = data["i"]

    tuner = Tuner(Flaky, run_config=RunConfig(
        failure_config=FailureConfig(max_failures=2),
        stop={"i": 5}))
    grid = tuner.fit()
    assert grid[0].error is None
    assert grid[0].metrics["i"] == 5


def test_pbt_clones_good_config():
    """Bad-config trials should end up near the good config's performance
    after exploiting its checkpoint."""

    def trainable(config):
        ck = tune.get_checkpoint()
        x = ck.to_dict()["x"] if ck else 0.0
        for _ in range(30):
            x += config["lr"]
            tune.report({"x": x},
                        checkpoint=Checkpoint.from_dict({"x": x}))

    pbt = PopulationBasedTraining(
        metric="x", mode="max", perturbation_interval=5,
        hyperparam_mutations={"lr": [0.01, 1.0]}, seed=0)
    tuner = Tuner(trainable,
                  param_space={"lr": tune.grid_search([0.01, 1.0])},
                  tune_config=TuneConfig(metric="x", mode="max",
                                         scheduler=pbt))
    grid = tuner.fit()
    finals = sorted(r.metrics["x"] for r in grid)
    # Without PBT the bad trial ends at 0.3; with exploitation it should
    # ride the good trial's checkpoint well past that.
    assert finals[0] > 1.0, finals


def test_stopper_max_iterations():
    def trainable(config):
        for i in range(100):
            tune.report({"i": i})

    tuner = Tuner(trainable, run_config=RunConfig(
        stop=MaximumIterationStopper(5)))
    grid = tuner.fit()
    assert len(grid[0].metrics_history) == 5


def test_tune_run_shim():
    grid = tune.run(lambda cfg: tune.report({"m": cfg["x"] ** 2}),
                    config={"x": tune.grid_search([2, 3])},
                    metric="m", mode="max")
    assert grid.get_best_result("m").metrics["m"] == 9


def test_with_parameters():
    big = np.arange(1000)

    def trainable(config, data=None):
        tune.report({"s": int(data.sum()) + config["x"]})

    wrapped = tune.with_parameters(trainable, data=big)
    grid = Tuner(wrapped, param_space={"x": 1}).fit()
    assert grid[0].metrics["s"] == int(big.sum()) + 1
