"""Experiment-level durability: state snapshots + Tuner.restore.

Reference: `tune/execution/trial_runner.py:427` (experiment checkpoint),
`Tuner.restore` resume semantics: finished trials keep results, unfinished
trials resume from their last checkpoint.
"""

import os

import pytest

import ray_tpu
from ray_tpu.air.config import CheckpointConfig, RunConfig
from ray_tpu.tune import TuneConfig, Tuner
from ray_tpu.tune.trainable import Trainable


@pytest.fixture
def ray_local():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


class StepTrainable(Trainable):
    """Counts steps; score = config lr * step. Checkpoints every save."""

    def setup(self, config):
        self.lr = config["lr"]
        self.iter = 0

    def step(self):
        self.iter += 1
        return {"score": self.lr * self.iter, "training_iteration": self.iter}

    def save_checkpoint(self):
        return {"iter": self.iter}

    def load_checkpoint(self, data):
        self.iter = data["iter"]


def test_experiment_state_saved_and_restored(ray_local, tmp_path):
    run_cfg = RunConfig(
        name="exp1", storage_path=str(tmp_path),
        stop={"training_iteration": 3},
        checkpoint_config=CheckpointConfig(checkpoint_frequency=1))
    tuner = Tuner(StepTrainable,
                  param_space={"lr": ray_tpu.tune.grid_search([1.0, 2.0])},
                  tune_config=TuneConfig(metric="score", mode="max"),
                  run_config=run_cfg)
    grid = tuner.fit()
    assert len(grid) == 2
    state_file = tmp_path / "exp1" / "experiment_state.pkl"
    assert state_file.exists()

    # Restore a *completed* experiment: results come back without re-run.
    restored = Tuner.restore(str(tmp_path / "exp1"), StepTrainable)
    grid2 = restored.fit()
    best = grid2.get_best_result(metric="score", mode="max")
    assert best.metrics["score"] == 6.0  # lr=2.0 * 3 iters


def test_restore_resumes_unfinished_from_checkpoint(ray_local, tmp_path):
    """Kill the driver mid-sweep (simulated by doctoring the saved state
    so one trial looks interrupted), restore, and the resumed trial
    continues from its checkpoint instead of starting over."""
    import pickle

    import cloudpickle

    run_cfg = RunConfig(
        name="exp2", storage_path=str(tmp_path),
        stop={"training_iteration": 4},
        checkpoint_config=CheckpointConfig(checkpoint_frequency=1))
    tuner = Tuner(StepTrainable, param_space={"lr": 1.0},
                  tune_config=TuneConfig(metric="score", mode="max"),
                  run_config=run_cfg)
    tuner.fit()

    state_file = tmp_path / "exp2" / "experiment_state.pkl"
    state = pickle.loads(state_file.read_bytes())
    # Rewind the trial to "interrupted after 2 iters, checkpoint at 2".
    ts = state["trials"][0]
    ts["status"] = "RUNNING"
    ts["checkpoint"] = {"iter": 2}
    ts["results"] = ts["results"][:2]
    ts["last_result"] = ts["results"][-1]
    state_file.write_bytes(cloudpickle.dumps(state))

    restored = Tuner.restore(str(tmp_path / "exp2"), StepTrainable)
    grid = restored.fit()
    result = grid.get_best_result(metric="score", mode="max")
    # Resumed from iter 2 → continued to 4; if it had restarted from
    # scratch the stop criterion would still read 4, but the resumed
    # trial's *first new* result is iteration 3.
    trial = restored._trials[0]
    new_iters = [r["training_iteration"] for r in trial.results[2:]]
    assert new_iters[0] == 3, new_iters
    assert result.metrics["training_iteration"] == 4
