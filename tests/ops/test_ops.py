"""Op-level tests: Pallas kernels (interpret mode on CPU) vs reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops import (
    apply_rope,
    flash_attention,
    layer_norm,
    rms_norm,
    rope_frequencies,
    softmax_cross_entropy,
)
from ray_tpu.ops.attention import attention_reference
from ray_tpu.ops.cross_entropy import softmax_cross_entropy_reference
from ray_tpu.ops.norms import rms_norm_pallas, rms_norm_reference


@pytest.mark.parametrize("causal", [True, False])
def test_flash_attention_interpret_matches_reference(causal):
    b, s, h, d = 2, 128, 4, 32
    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, s, h, d), jnp.float32)
    v = jax.random.normal(kv, (b, s, h, d), jnp.float32)
    got = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64,
                          interpret=True)
    expected = attention_reference(
        q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3), causal, d ** -0.5,
    ).transpose(0, 2, 1, 3)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-5, atol=2e-6)


def test_flash_attention_gqa():
    b, s, h, h_kv, d = 1, 64, 8, 2, 16
    key = jax.random.PRNGKey(1)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, s, h_kv, d), jnp.float32)
    v = jax.random.normal(kv, (b, s, h_kv, d), jnp.float32)
    got = flash_attention(q, k, v, block_q=32, block_k=32, interpret=True)
    expected = attention_reference(
        q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3), True, d ** -0.5,
    ).transpose(0, 2, 1, 3)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-5, atol=2e-6)


def test_flash_attention_grad():
    b, s, h, d = 1, 64, 2, 16
    q = jax.random.normal(jax.random.PRNGKey(2), (b, s, h, d))
    k = jax.random.normal(jax.random.PRNGKey(3), (b, s, h, d))
    v = jax.random.normal(jax.random.PRNGKey(4), (b, s, h, d))

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, block_q=32, block_k=32,
                               interpret=True).sum()

    def loss_ref(q, k, v):
        return attention_reference(
            q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3), True, d ** -0.5).sum()

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("shape", [
    (1, 64, 64, 4, 2, 16),     # GQA, even blocks
    (2, 96, 96, 4, 4, 16),     # ragged q and k blocks (96 % 32 != 0 w/ 64)
    (1, 100, 100, 2, 2, 16),   # ragged both
])
def test_flash_attention_grad_pallas_bwd(causal, shape):
    """Pallas dq/dk/dv kernels vs reference autodiff, incl. GQA + ragged."""
    b, sq, sk, h, h_kv, d = shape
    keys = jax.random.split(jax.random.PRNGKey(7), 4)
    q = jax.random.normal(keys[0], (b, sq, h, d))
    k = jax.random.normal(keys[1], (b, sk, h_kv, d))
    v = jax.random.normal(keys[2], (b, sk, h_kv, d))
    do = jax.random.normal(keys[3], (b, sq, h, d))

    def flash(q, k, v):
        return flash_attention(q, k, v, causal=causal, block_q=64,
                               block_k=64, interpret=True)

    def ref(q, k, v):
        return attention_reference(
            q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3), causal, d ** -0.5,
        ).transpose(0, 2, 1, 3)

    _, vjp1 = jax.vjp(flash, q, k, v)
    _, vjp2 = jax.vjp(ref, q, k, v)
    for a, b_ in zip(vjp1(do), vjp2(do)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=1e-4)


def test_rms_norm_pallas_matches_reference():
    x = jax.random.normal(jax.random.PRNGKey(5), (4, 96, 256), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(6), (256,)) * 0.1 + 1.0
    got = rms_norm_pallas(x, w, interpret=True)
    expected = rms_norm_reference(x, w)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=1e-5, atol=1e-6)


def test_layer_norm():
    x = jax.random.normal(jax.random.PRNGKey(7), (8, 64), jnp.float32)
    w = jnp.ones(64)
    b = jnp.zeros(64)
    out = layer_norm(x, w, b)
    np.testing.assert_allclose(np.asarray(out).mean(-1), 0.0, atol=1e-5)
    np.testing.assert_allclose(np.asarray(out).std(-1), 1.0, atol=1e-2)


def test_rope_rotation_preserves_norm():
    cos, sin = rope_frequencies(64, 128)
    x = jax.random.normal(jax.random.PRNGKey(8), (2, 100, 4, 64))
    out = apply_rope(x, cos, sin)
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(out), axis=-1),
        np.linalg.norm(np.asarray(x), axis=-1), rtol=1e-5)
    # position 0 is identity
    np.testing.assert_allclose(np.asarray(out[:, 0]), np.asarray(x[:, 0]),
                               atol=1e-6)


def test_rope_positions_arg():
    cos, sin = rope_frequencies(32, 64)
    x = jax.random.normal(jax.random.PRNGKey(9), (1, 8, 2, 32))
    pos = jnp.arange(8)[None, :]
    a = apply_rope(x, cos, sin)
    b = apply_rope(x, cos, sin, positions=pos)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_cross_entropy_blockwise_matches_reference():
    n, v = 32, 1000
    logits = jax.random.normal(jax.random.PRNGKey(10), (n, v)) * 3
    labels = jax.random.randint(jax.random.PRNGKey(11), (n,), 0, v)
    got = softmax_cross_entropy(logits, labels, 256)
    expected = softmax_cross_entropy_reference(logits, labels)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=1e-5, atol=1e-5)


def test_cross_entropy_grad_matches_reference():
    n, v = 16, 500
    logits = jax.random.normal(jax.random.PRNGKey(12), (n, v))
    labels = jax.random.randint(jax.random.PRNGKey(13), (n,), 0, v)

    g1 = jax.grad(lambda l: softmax_cross_entropy(l, labels, 128).mean())(
        logits)
    g2 = jax.grad(
        lambda l: softmax_cross_entropy_reference(l, labels).mean())(logits)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-4, atol=1e-6)


@pytest.mark.parametrize("v,block", [(500, 128), (384, 128), (1000, 1000)])
def test_fused_linear_cross_entropy_matches_unfused(v, block):
    from ray_tpu.ops.cross_entropy import fused_linear_cross_entropy

    n, d = 24, 32
    x = jax.random.normal(jax.random.PRNGKey(20), (n, d))
    w = jax.random.normal(jax.random.PRNGKey(21), (d, v)) * 0.1
    labels = jax.random.randint(jax.random.PRNGKey(22), (n,), 0, v)

    got = fused_linear_cross_entropy(x, w, labels, block)
    expected = softmax_cross_entropy_reference(x @ w, labels)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=1e-4, atol=1e-4)

    # Gradients wrt both x and w match the unfused composition.
    gx1, gw1 = jax.grad(
        lambda x, w: fused_linear_cross_entropy(x, w, labels, block).mean(),
        argnums=(0, 1))(x, w)
    gx2, gw2 = jax.grad(
        lambda x, w: softmax_cross_entropy_reference(x @ w, labels).mean(),
        argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gx1), np.asarray(gx2),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gw1), np.asarray(gw2),
                               rtol=1e-4, atol=1e-5)
