"""Round-4 datasource breadth: webdataset tar shards, SQL reads, and
parquet row-group-parallel reads (reference webdataset_datasource.py,
sql_datasource.py, parquet metadata provider)."""

import io
import os
import sqlite3
import tarfile

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rt_data


@pytest.fixture(autouse=True)
def ray():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def _make_shard(path, start, n):
    with tarfile.open(path, "w") as tf:
        for i in range(start, start + n):
            key = f"sample{i:05d}"
            for ext, payload in (
                    ("txt", f"caption {i}".encode()),
                    ("cls", str(i % 10).encode()),
                    ("json", ('{"idx": %d}' % i).encode())):
                info = tarfile.TarInfo(f"{key}.{ext}")
                info.size = len(payload)
                tf.addfile(info, io.BytesIO(payload))


def test_read_webdataset_groups_samples(tmp_path):
    _make_shard(tmp_path / "shard0.tar", 0, 8)
    _make_shard(tmp_path / "shard1.tar", 8, 8)
    ds = rt_data.read_webdataset(str(tmp_path / "*.tar"))
    rows = sorted(ds.take_all(), key=lambda r: r["__key__"])
    assert len(rows) == 16
    assert rows[3]["txt"] == "caption 3"
    assert rows[3]["cls"] == 3
    assert rows[3]["json"]["idx"] == 3
    assert rows[12]["cls"] == 2  # 12 % 10


def test_read_sql_sqlite(tmp_path):
    db = str(tmp_path / "t.db")
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE items (id INTEGER, name TEXT)")
    conn.executemany("INSERT INTO items VALUES (?, ?)",
                     [(i, f"n{i}") for i in range(50)])
    conn.commit()
    conn.close()

    ds = rt_data.read_sql("SELECT * FROM items",
                          lambda: sqlite3.connect(db))
    rows = sorted(ds.take_all(), key=lambda r: r["id"])
    assert len(rows) == 50 and rows[7] == {"id": 7, "name": "n7"}

    # caller-partitioned parallel read
    ds2 = rt_data.read_sql(
        "", lambda: sqlite3.connect(db),
        queries=[f"SELECT * FROM items WHERE id % 2 = {p}"
                 for p in (0, 1)])
    assert sorted(r["id"] for r in ds2.take_all()) == list(range(50))


def test_parquet_row_group_parallel_read(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq

    path = str(tmp_path / "big.parquet")
    table = pa.table({"x": list(range(1000))})
    pq.write_table(table, path, row_group_size=100)  # 10 row groups

    from ray_tpu.data.datasource import ParquetDatasource

    src = ParquetDatasource(path)
    tasks = src.get_read_tasks(parallelism=-1)
    assert len(tasks) == 10  # one task per row group, from metadata
    assert all(t.metadata.num_rows == 100 for t in tasks)

    ds = rt_data.read_parquet(path)
    assert sorted(r["x"] for r in ds.take_all()) == list(range(1000))


# ---------------------------------------------------------------------------
# Parallel file-metadata discovery
# ---------------------------------------------------------------------------


def test_many_file_discovery_plans_in_parallel(tmp_path):
    """Planning a many-file read fans per-file metadata IO onto a
    thread pool: wall time is O(files / pool), not O(files). Verified
    two ways — peak concurrency > 1, and wall clock far below the
    serial sum."""
    import threading
    import time as _time

    from ray_tpu.data.datasource import BlockMetadata, FileDatasource, ReadTask

    n_files, delay = 32, 0.03
    paths = []
    for i in range(n_files):
        p = tmp_path / f"part-{i:04d}.bin"
        p.write_bytes(b"x")
        paths.append(str(p))

    peak = [0]
    active = [0]
    lock = threading.Lock()

    class SlowMetaSource(FileDatasource):
        def _plan_file(self, path):
            with lock:
                active[0] += 1
                peak[0] = max(peak[0], active[0])
            _time.sleep(delay)  # simulated footer/stat IO
            with lock:
                active[0] -= 1
            return [ReadTask(lambda: [b"x"],
                             BlockMetadata(input_files=[path]))]

    src = SlowMetaSource(paths)
    t0 = _time.perf_counter()
    tasks = src.get_read_tasks(parallelism=n_files)
    wall = _time.perf_counter() - t0
    assert len(tasks) == n_files
    # Order preserved despite parallel discovery.
    assert [t.metadata.input_files[0] for t in tasks] == paths
    assert peak[0] > 1, "metadata discovery ran serially"
    serial = n_files * delay
    assert wall < serial * 0.6, \
        f"planning not O(files/N): {wall:.2f}s vs serial {serial:.2f}s"


def test_parquet_row_group_plan_unchanged_by_parallel_discovery(tmp_path):
    """Parquet footers discovered on the pool still yield the same
    per-row-group task split, in file order."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from ray_tpu.data.datasource import ParquetDatasource

    paths = []
    for i in range(3):
        p = str(tmp_path / f"f{i}.parquet")
        pq.write_table(pa.table({"v": list(range(i * 10, i * 10 + 10))}),
                       p, row_group_size=5)
        paths.append(p)
    tasks = ParquetDatasource(paths).get_read_tasks(parallelism=8)
    assert len(tasks) == 6  # 3 files x 2 row groups
    assert [t.metadata.num_rows for t in tasks] == [5] * 6
    got = sorted(int(x) for t in tasks for b in t() for x in b["v"].to_pylist())
    assert got == list(range(30))
