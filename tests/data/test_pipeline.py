"""DatasetPipeline: windowed + repeated streaming execution."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd


@pytest.fixture(autouse=True)
def ray_local():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_window_streams_all_rows():
    ds = rd.range(100, parallelism=10)
    pipe = ds.window(blocks_per_window=3)
    assert pipe.num_windows() == 4  # ceil(10 / 3)
    rows = sorted(r["id"] if isinstance(r, dict) else r
                  for r in pipe.iter_rows())
    assert rows == list(range(100))


def test_window_transforms_apply_per_window():
    ds = rd.range(40, parallelism=8)
    pipe = (ds.window(blocks_per_window=4)
            .map(lambda x: (x["id"] if isinstance(x, dict) else x) * 2)
            .filter(lambda x: x % 4 == 0))
    got = sorted(pipe.iter_rows())
    expect = sorted(x * 2 for x in range(40) if (x * 2) % 4 == 0)
    assert got == expect


def test_repeat_epochs():
    ds = rd.range(10, parallelism=2)
    pipe = ds.repeat(3)
    assert pipe.num_windows() == 3
    rows = [r["id"] if isinstance(r, dict) else r
            for r in pipe.iter_rows()]
    assert len(rows) == 30
    assert sorted(rows) == sorted(list(range(10)) * 3)
    # iter_epochs yields one pipeline per epoch
    epochs = list(pipe.iter_epochs())
    assert len(epochs) == 3
    assert epochs[0].count() == 10


def test_window_then_repeat_and_shuffle():
    ds = rd.range(24, parallelism=6)
    pipe = (ds.window(blocks_per_window=2)
            .random_shuffle_each_window(seed=0)
            .repeat(2))
    rows = [r["id"] if isinstance(r, dict) else r
            for r in pipe.iter_rows()]
    assert len(rows) == 48
    assert sorted(rows) == sorted(list(range(24)) * 2)


def test_read_images(tmp_path):
    from PIL import Image

    for i in range(3):
        arr = np.full((8, 6, 3), i * 40, np.uint8)
        Image.fromarray(arr).save(tmp_path / f"img{i}.png")
    ds = rd.read_images(str(tmp_path / "*.png"), size=(4, 4), mode="RGB")
    rows = ds.take_all()
    assert len(rows) == 3
    img = rows[0]["image"]
    assert np.asarray(img).shape == (4, 4, 3)


def test_read_tfrecords_roundtrip(tmp_path):
    from ray_tpu.data.datasource import write_tfrecords

    records = [b"alpha", b"beta", b"\x00" * 100]
    path = tmp_path / "data.tfrecord"
    write_tfrecords(records, str(path))
    ds = rd.read_tfrecords(str(path))
    got = [bytes(r["bytes"]) for r in ds.take_all()]
    assert got == records

    # Corruption is detected.
    blob = bytearray(path.read_bytes())
    blob[-3] ^= 0xFF
    bad = tmp_path / "bad.tfrecord"
    bad.write_bytes(bytes(blob))
    with pytest.raises(Exception, match="crc"):
        rd.read_tfrecords(str(bad)).take_all()
    # ...and can be skipped.
    got = [bytes(r["bytes"])
           for r in rd.read_tfrecords(str(bad),
                                      validate_crc=False).take_all()]
    assert len(got) == 3


def test_window_iter_batches():
    ds = rd.from_items(list(range(32)))
    pipe = ds.window(blocks_per_window=2)
    batches = list(pipe.iter_batches(batch_size=8, batch_format="numpy"))
    total = sum(len(np.atleast_1d(b)) if not isinstance(b, dict)
                else len(next(iter(b.values()))) for b in batches)
    assert total == 32
