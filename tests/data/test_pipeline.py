"""DatasetPipeline: windowed + repeated streaming execution."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd


@pytest.fixture(autouse=True)
def ray_local():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_window_streams_all_rows():
    ds = rd.range(100, parallelism=10)
    pipe = ds.window(blocks_per_window=3)
    assert pipe.num_windows() == 4  # ceil(10 / 3)
    rows = sorted(r["id"] if isinstance(r, dict) else r
                  for r in pipe.iter_rows())
    assert rows == list(range(100))


def test_window_transforms_apply_per_window():
    ds = rd.range(40, parallelism=8)
    pipe = (ds.window(blocks_per_window=4)
            .map(lambda x: (x["id"] if isinstance(x, dict) else x) * 2)
            .filter(lambda x: x % 4 == 0))
    got = sorted(pipe.iter_rows())
    expect = sorted(x * 2 for x in range(40) if (x * 2) % 4 == 0)
    assert got == expect


def test_repeat_epochs():
    ds = rd.range(10, parallelism=2)
    pipe = ds.repeat(3)
    assert pipe.num_windows() == 3
    rows = [r["id"] if isinstance(r, dict) else r
            for r in pipe.iter_rows()]
    assert len(rows) == 30
    assert sorted(rows) == sorted(list(range(10)) * 3)
    # iter_epochs yields one pipeline per epoch
    epochs = list(pipe.iter_epochs())
    assert len(epochs) == 3
    assert epochs[0].count() == 10


def test_window_then_repeat_and_shuffle():
    ds = rd.range(24, parallelism=6)
    pipe = (ds.window(blocks_per_window=2)
            .random_shuffle_each_window(seed=0)
            .repeat(2))
    rows = [r["id"] if isinstance(r, dict) else r
            for r in pipe.iter_rows()]
    assert len(rows) == 48
    assert sorted(rows) == sorted(list(range(24)) * 2)


def test_window_iter_batches():
    ds = rd.from_items(list(range(32)))
    pipe = ds.window(blocks_per_window=2)
    batches = list(pipe.iter_batches(batch_size=8, batch_format="numpy"))
    total = sum(len(np.atleast_1d(b)) if not isinstance(b, dict)
                else len(next(iter(b.values()))) for b in batches)
    assert total == 32
