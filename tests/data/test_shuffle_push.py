"""Push-based shuffle (reference `_internal/push_based_shuffle.py`) and
streaming-executor backpressure under producer/consumer speed mismatch."""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rt_data


@pytest.fixture(autouse=True)
def ray():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8)
    yield
    ray_tpu.shutdown()


def _rows(ds):
    return sorted(r["x"] for r in ds.take_all())


def test_push_based_shuffle_is_a_permutation():
    items = [{"x": i} for i in range(500)]
    ds = rt_data.from_items(items, parallelism=10)
    out = ds.random_shuffle(seed=7, push_based=True)
    assert _rows(out) == list(range(500))
    # genuinely shuffled (probability of identity is ~0)
    flat = [r["x"] for r in out.take_all()]
    assert flat != list(range(500))


def test_push_and_pull_paths_both_selectable():
    items = [{"x": i} for i in range(300)]
    ds = rt_data.from_items(items, parallelism=9)
    pull = ds.random_shuffle(seed=3, push_based=False)
    push = ds.random_shuffle(seed=3, push_based=True)
    assert _rows(pull) == list(range(300))
    assert _rows(push) == list(range(300))


def test_push_shuffle_env_default(monkeypatch):
    monkeypatch.setenv("RAY_TPU_PUSH_BASED_SHUFFLE", "1")
    items = [{"x": i} for i in range(100)]
    ds = rt_data.from_items(items, parallelism=5)
    assert _rows(ds.random_shuffle(seed=1)) == list(range(100))


def test_streaming_backpressure_bounds_in_flight():
    """A fast producer feeding a slow consumer must be throttled by the
    per-op in-flight caps and the consumer window — never buffering the
    whole dataset (stress: 60 instantly-ready blocks vs a 10 ms/block
    consumer with a window of 2)."""
    from ray_tpu.data.streaming_executor import (MapOp, SourceOp,
                                                 StreamingExecutor)

    blocks = [[{"x": i}] * 5 for i in range(60)]
    src = SourceOp("src", blocks=blocks, max_in_flight=4)

    def slow(block):
        time.sleep(0.01)
        return block

    op = MapOp("slow", slow, max_in_flight=4)
    ex = StreamingExecutor([src, op])
    out = [ray_tpu.get(r) for r in ex.iter_refs(window=2)]
    assert len(out) == 60
    stats = {s["name"]: s for s in ex.stats()}
    assert stats["src"]["peak_in_flight"] <= 4, stats
    assert stats["slow"]["peak_in_flight"] <= 4, stats
    assert stats["slow"]["blocks"] == 60


def test_logical_optimizer_rules():
    """Reference `logical/optimizers.py` role: redundant all-to-all ops
    are rewritten away before execution."""
    from ray_tpu.data.plan import (ExecutionPlan, RandomShuffle,
                                   Repartition, Sort)

    ds = rt_data.from_items([{"x": i} for i in range(40)], parallelism=4)
    # shuffle ∘ shuffle → one shuffle
    dd = ds.random_shuffle(seed=1).random_shuffle(seed=2)
    shuffles = [op for op in dd._plan._optimize(dd._plan.ops)
                if isinstance(op, RandomShuffle)]
    assert len(shuffles) == 1 and shuffles[0].seed == 2
    assert sorted(r["x"] for r in dd.take_all()) == list(range(40))

    # shuffle before sort is KEPT: the stable sort pipeline preserves
    # the shuffle's intra-group order for tied keys, so it's observable.
    dsort = ds.random_shuffle(seed=1).sort("x")
    opt = dsort._plan._optimize(dsort._plan.ops)
    assert any(isinstance(op, RandomShuffle) for op in opt)
    assert isinstance(opt[-1], Sort)
    assert [r["x"] for r in dsort.take_all()] == list(range(40))

    # repartition ∘ repartition → last wins
    dr = ds.repartition(8).repartition(2)
    opt = dr._plan._optimize(dr._plan.ops)
    reps = [op for op in opt if isinstance(op, Repartition)]
    assert len(reps) == 1 and reps[0].num_blocks == 2
    assert dr.num_blocks() == 2
