"""File IO datasources, writers, preprocessors."""

import os

import numpy as np
import pandas as pd
import pytest

import ray_tpu
from ray_tpu import data as rd
from ray_tpu.data.preprocessors import (
    BatchMapper,
    Chain,
    Concatenator,
    LabelEncoder,
    MinMaxScaler,
    OneHotEncoder,
    SimpleImputer,
    StandardScaler,
)


@pytest.fixture(autouse=True)
def ray():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8)
    yield
    ray_tpu.shutdown()


def test_parquet_roundtrip(tmp_path):
    df = pd.DataFrame({"a": range(50), "b": np.random.rand(50)})
    ds = rd.from_pandas(df).repartition(4)
    files = ds.write_parquet(str(tmp_path / "out"))
    assert len(files) == 4
    back = rd.read_parquet(str(tmp_path / "out"))
    assert back.count() == 50
    pd.testing.assert_frame_equal(
        back.to_pandas().sort_values("a").reset_index(drop=True), df)


def test_csv_roundtrip(tmp_path):
    df = pd.DataFrame({"x": [1, 2, 3], "y": ["a", "b", "c"]})
    rd.from_pandas(df).write_csv(str(tmp_path / "csv"))
    back = rd.read_csv(str(tmp_path / "csv"))
    assert back.count() == 3
    assert back.take(1)[0] == {"x": 1, "y": "a"}


def test_json_roundtrip(tmp_path):
    df = pd.DataFrame({"x": [1, 2, 3]})
    rd.from_pandas(df).write_json(str(tmp_path / "js"))
    back = rd.read_json(str(tmp_path / "js"))
    assert back.count() == 3


def test_read_text_and_binary(tmp_path):
    p = tmp_path / "f.txt"
    p.write_text("hello\nworld\n")
    ds = rd.read_text(str(p))
    assert [r["text"] for r in ds.take_all()] == ["hello", "world"]
    ds2 = rd.read_binary_files(str(p))
    row = ds2.take(1)[0]
    assert row["bytes"] == b"hello\nworld\n"


def test_numpy_roundtrip(tmp_path):
    arr = np.arange(12, dtype=np.float32).reshape(6, 2)
    np.save(tmp_path / "a.npy", arr)
    ds = rd.read_numpy(str(tmp_path / "a.npy"))
    np.testing.assert_allclose(ds.to_numpy("data"), arr)


def test_standard_scaler():
    ds = rd.from_pandas(pd.DataFrame({"a": [1.0, 2.0, 3.0, 4.0]}))
    sc = StandardScaler(["a"]).fit(ds)
    out = sc.transform(ds).to_numpy("a")
    np.testing.assert_allclose(out.mean(), 0.0, atol=1e-7)
    np.testing.assert_allclose(out.std(), 1.0, atol=1e-7)


def test_minmax_label_onehot():
    df = pd.DataFrame({"a": [0.0, 5.0, 10.0], "lbl": ["x", "y", "x"]})
    ds = rd.from_pandas(df)
    mm = MinMaxScaler(["a"]).fit(ds)
    np.testing.assert_allclose(mm.transform(ds).to_numpy("a"),
                               [0.0, 0.5, 1.0])
    le = LabelEncoder("lbl").fit(ds)
    assert le.transform(ds).to_numpy("lbl").tolist() == [0, 1, 0]
    oh = OneHotEncoder(["lbl"]).fit(ds)
    out = oh.transform(ds).to_numpy("lbl")
    np.testing.assert_allclose(out, [[1, 0], [0, 1], [1, 0]])


def test_imputer_and_concatenator():
    df = pd.DataFrame({"a": [1.0, np.nan, 3.0], "b": [4.0, 5.0, 6.0]})
    ds = rd.from_pandas(df)
    imp = SimpleImputer(["a"]).fit(ds)
    out = imp.transform(ds).to_numpy("a")
    np.testing.assert_allclose(out, [1.0, 2.0, 3.0])
    cat = Concatenator(output_column_name="features")
    out2 = cat.transform(imp.transform(ds)).to_numpy("features")
    assert out2.shape == (3, 2)


def test_chain_and_batch_mapper():
    df = pd.DataFrame({"a": [1.0, 2.0, 3.0]})
    ds = rd.from_pandas(df)
    chain = Chain(
        StandardScaler(["a"]),
        BatchMapper(lambda b: {"a": b["a"] * 2}),
    ).fit(ds)
    out = chain.transform(ds).to_numpy("a")
    np.testing.assert_allclose(out.mean(), 0.0, atol=1e-7)
    np.testing.assert_allclose(out.std(), 2.0, atol=1e-7)
    batch_out = chain.transform_batch({"a": np.array([2.0])})
    assert "a" in batch_out
