"""Dataset API tests (reference model: python/ray/data/tests/)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd


@pytest.fixture(autouse=True)
def ray():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8)
    yield
    ray_tpu.shutdown()


def test_range_count_take():
    ds = rd.range(100, parallelism=5)
    assert ds.count() == 100
    assert ds.num_blocks() == 5
    rows = ds.take(3)
    assert [r["id"] for r in rows] == [0, 1, 2]


def test_from_items_and_map():
    ds = rd.from_items(list(range(10))).map(lambda x: x * 2)
    assert ds.take_all() == [x * 2 for x in range(10)]


def test_map_batches_numpy():
    ds = rd.range(64, parallelism=4).map_batches(
        lambda b: {"id": b["id"] * 10}, batch_size=8)
    vals = [r["id"] for r in ds.take_all()]
    assert vals == [i * 10 for i in range(64)]


def test_map_batches_pandas():
    def add_col(df):
        df = df.copy()
        df["sq"] = df["id"] ** 2
        return df

    ds = rd.range(16).map_batches(add_col, batch_format="pandas")
    rows = ds.take_all()
    assert rows[3] == {"id": 3, "sq": 9}


def test_filter_flat_map():
    ds = rd.from_items(list(range(10))).filter(lambda x: x % 2 == 0)
    assert ds.take_all() == [0, 2, 4, 6, 8]
    ds2 = rd.from_items([1, 2]).flat_map(lambda x: [x, x * 10])
    assert ds2.take_all() == [1, 10, 2, 20]


def test_repartition():
    ds = rd.range(100, parallelism=10).repartition(3)
    assert ds.num_blocks() == 3
    assert ds.count() == 100
    # rows preserved in order for non-shuffle repartition
    assert [r["id"] for r in ds.take(5)] == [0, 1, 2, 3, 4]


def test_random_shuffle_preserves_multiset():
    ds = rd.range(50, parallelism=5).random_shuffle(seed=42)
    vals = sorted(r["id"] for r in ds.take_all())
    assert vals == list(range(50))
    # A fixed-seed shuffle should not be the identity permutation.
    vals2 = [r["id"] for r in ds.take_all()]
    assert vals2 != list(range(50))


def test_sort():
    ds = rd.from_items([{"v": x} for x in [5, 3, 8, 1, 9, 2, 7]])
    got = [r["v"] for r in ds.sort("v").take_all()]
    assert got == [1, 2, 3, 5, 7, 8, 9]
    got_desc = [r["v"] for r in ds.sort("v", descending=True).take_all()]
    assert got_desc == [9, 8, 7, 5, 3, 2, 1]


def test_limit_union_zip():
    assert rd.range(100).limit(7).count() == 7
    u = rd.range(5).union(rd.range(5))
    assert u.count() == 10
    z = rd.range(4).zip(rd.range(4).map_batches(
        lambda b: {"other": b["id"] + 100}))
    rows = z.take_all()
    assert rows[0] == {"id": 0, "other": 100}


def test_split():
    parts = rd.range(90, parallelism=9).split(3)
    assert len(parts) == 3
    assert sum(p.count() for p in parts) == 90


def test_split_at_indices():
    parts = rd.range(10).split_at_indices([3, 7])
    assert [p.count() for p in parts] == [3, 4, 3]
    assert [r["id"] for r in parts[1].take_all()] == [3, 4, 5, 6]


def test_aggregates():
    ds = rd.range(10)
    assert ds.sum("id") == 45
    assert ds.min("id") == 0
    assert ds.max("id") == 9
    assert ds.mean("id") == 4.5


def test_groupby():
    ds = rd.from_items([{"k": i % 3, "v": i} for i in range(12)])
    out = ds.groupby("k").sum("v").to_pandas().sort_values("k")
    assert list(out["k"]) == [0, 1, 2]
    assert list(out["sum(v)"]) == [sum(i for i in range(12) if i % 3 == k)
                                   for k in range(3)]


def test_groupby_map_groups():
    ds = rd.from_items([{"k": i % 2, "v": float(i)} for i in range(8)])
    out = ds.groupby("k").map_groups(
        lambda b: {"k": b["k"][:1], "n": np.array([len(b["v"])])})
    rows = sorted(out.take_all(), key=lambda r: r["k"])
    assert rows == [{"k": 0, "n": 4}, {"k": 1, "n": 4}]


def test_iter_batches_fixed_size():
    ds = rd.range(100, parallelism=7)
    sizes = [len(b["id"]) for b in ds.iter_batches(batch_size=32)]
    assert sizes == [32, 32, 32, 4]
    sizes = [len(b["id"]) for b in ds.iter_batches(batch_size=32,
                                                   drop_last=True)]
    assert sizes == [32, 32, 32]


def test_iter_jax_batches():
    ds = rd.range(64, parallelism=4)
    batches = list(ds.iter_jax_batches(batch_size=16))
    assert len(batches) == 4
    import jax

    assert isinstance(batches[0]["id"], jax.Array)
    all_ids = np.concatenate([np.asarray(b["id"]) for b in batches])
    assert sorted(all_ids.tolist()) == list(range(64))


def test_actor_pool_compute():
    class Doubler:
        def __call__(self, batch):
            return {"id": batch["id"] * 2}

    ds = rd.range(40, parallelism=4).map_batches(
        Doubler, compute=rd.ActorPoolStrategy(size=2))
    vals = sorted(r["id"] for r in ds.take_all())
    assert vals == [i * 2 for i in range(40)]


def test_tensor_columns_roundtrip():
    arr = np.random.rand(20, 3, 4).astype(np.float32)
    ds = rd.from_numpy(arr)
    out = ds.to_numpy("data")
    np.testing.assert_allclose(out, arr)
    mapped = ds.map_batches(lambda b: {"data": b["data"] * 2})
    np.testing.assert_allclose(mapped.to_numpy("data"), arr * 2)


def test_fusion_stages():
    ds = rd.range(10).map(lambda x: x).filter(lambda r: True).map(
        lambda x: x)
    ds.materialize()
    # Read + one fused map stage
    names = [s.name for s in ds._plan.stats]
    assert len(names) == 2, names


def test_stats():
    ds = rd.range(10).map_batches(lambda b: b)
    ds.materialize()
    import json

    stats = json.loads(ds.stats())
    assert all("wall_s" in s for s in stats)
