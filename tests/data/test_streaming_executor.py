"""Streaming operator-graph executor semantics.

Reference: `data/_internal/execution/streaming_executor.py:35` — pulled
operator graph, bounded in-flight per operator, per-op stats.
"""

import time

import pytest

import ray_tpu
import ray_tpu.data as rd


@pytest.fixture
def ray_local():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_multi_stage_map_streams_before_source_exhausts(ray_local):
    """First output arrives while later source blocks are still being
    produced — the signature of pipelining (a stage-barrier executor
    would produce nothing until every read completed)."""
    started = []

    ds = rd.range(200, parallelism=20) \
        .map_batches(lambda b: {"id": [i * 2 for i in b["id"]]}) \
        .map_batches(lambda b: {"id": [i + 1 for i in b["id"]]})

    it = ds.iter_batches(batch_size=10)
    first = next(it)
    assert list(first["id"])[0] == 1  # 0*2+1
    # Drain the rest; values check the two maps composed in order.
    rest = list(it)
    assert started == []  # no driver-side materialization sentinel
    all_ids = list(first["id"]) + [i for b in rest for i in b["id"]]
    assert sorted(all_ids) == [i * 2 + 1 for i in range(200)]


def test_shuffle_mid_plan_streams_output(ray_local):
    ds = rd.range(100, parallelism=10) \
        .map_batches(lambda b: {"id": [i + 1 for i in b["id"]]}) \
        .random_shuffle(seed=7) \
        .map_batches(lambda b: {"id": [i * 10 for i in b["id"]]})
    out = sorted(i for b in ds.iter_batches(batch_size=25) for i in b["id"])
    assert out == [(i + 1) * 10 for i in range(100)]


def test_limit_short_circuits_upstream(ray_local):
    calls = []

    def slow_map(b):
        calls.append(len(b["id"]))
        time.sleep(0.05)
        return b

    ds = rd.range(1000, parallelism=50).map_batches(slow_map).limit(40)
    rows = [i for b in ds.iter_batches(batch_size=20) for i in b["id"]]
    assert rows == list(range(40))
    # 50 upstream blocks of 20 rows exist; the limit needed only a few.
    assert len(calls) < 50, f"limit didn't short-circuit: {len(calls)}"


def test_per_op_stats_recorded(ray_local):
    ds = rd.range(100, parallelism=10).map_batches(
        lambda b: b).random_shuffle()
    plan = ds._plan
    refs = list(plan.iter_block_refs())
    assert refs
    names = [s["name"] for s in plan.streaming_stats]
    assert any("map" in n.lower() for n in names)
    assert any("shuffle" in n.lower() for n in names)
    for s in plan.streaming_stats:
        assert s["blocks"] > 0, s


def test_bounded_in_flight_window(ray_local):
    ds = rd.range(400, parallelism=40).map_batches(lambda b: b)
    plan = ds._plan
    it = plan.iter_block_refs(window=4)
    next(it)
    # Peak in-flight respects the per-op cap (default 8) even with 40
    # upstream blocks available.
    for s in plan.streaming_stats:
        assert s["peak_in_flight"] <= 8, s
    list(it)


def test_repeated_iteration_caches_all_to_all(ray_local):
    """Epoch 2 of a shuffled dataset serves cached refs — the shuffle
    task graph must not re-run per epoch (multi-epoch train ingest)."""
    ds = rd.range(100, parallelism=10).random_shuffle(seed=1)
    plan = ds._plan
    first = list(plan.iter_block_refs())
    assert plan._cached is not None
    second = list(plan.iter_block_refs())
    assert [r.id for r in first] == [r.id for r in second]
