"""Streaming operator-graph executor semantics.

Reference: `data/_internal/execution/streaming_executor.py:35` — pulled
operator graph, bounded in-flight per operator, per-op stats.
"""

import time

import pytest

import ray_tpu
import ray_tpu.data as rd


@pytest.fixture
def ray_local():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_multi_stage_map_streams_before_source_exhausts(ray_local):
    """First output arrives while later source blocks are still being
    produced — the signature of pipelining (a stage-barrier executor
    would produce nothing until every read completed)."""
    started = []

    ds = rd.range(200, parallelism=20) \
        .map_batches(lambda b: {"id": [i * 2 for i in b["id"]]}) \
        .map_batches(lambda b: {"id": [i + 1 for i in b["id"]]})

    it = ds.iter_batches(batch_size=10)
    first = next(it)
    assert list(first["id"])[0] == 1  # 0*2+1
    # Drain the rest; values check the two maps composed in order.
    rest = list(it)
    assert started == []  # no driver-side materialization sentinel
    all_ids = list(first["id"]) + [i for b in rest for i in b["id"]]
    assert sorted(all_ids) == [i * 2 + 1 for i in range(200)]


def test_shuffle_mid_plan_streams_output(ray_local):
    ds = rd.range(100, parallelism=10) \
        .map_batches(lambda b: {"id": [i + 1 for i in b["id"]]}) \
        .random_shuffle(seed=7) \
        .map_batches(lambda b: {"id": [i * 10 for i in b["id"]]})
    out = sorted(i for b in ds.iter_batches(batch_size=25) for i in b["id"])
    assert out == [(i + 1) * 10 for i in range(100)]


def test_limit_short_circuits_upstream(ray_local):
    calls = []

    def slow_map(b):
        calls.append(len(b["id"]))
        time.sleep(0.05)
        return b

    ds = rd.range(1000, parallelism=50).map_batches(slow_map).limit(40)
    rows = [i for b in ds.iter_batches(batch_size=20) for i in b["id"]]
    assert rows == list(range(40))
    # 50 upstream blocks of 20 rows exist; the limit needed only a few.
    assert len(calls) < 50, f"limit didn't short-circuit: {len(calls)}"


def test_per_op_stats_recorded(ray_local):
    ds = rd.range(100, parallelism=10).map_batches(
        lambda b: b).random_shuffle()
    plan = ds._plan
    refs = list(plan.iter_block_refs())
    assert refs
    names = [s["name"] for s in plan.streaming_stats]
    assert any("map" in n.lower() for n in names)
    assert any("shuffle" in n.lower() for n in names)
    for s in plan.streaming_stats:
        assert s["blocks"] > 0, s


def test_bounded_in_flight_window(ray_local):
    ds = rd.range(400, parallelism=40).map_batches(lambda b: b)
    plan = ds._plan
    it = plan.iter_block_refs(window=4)
    next(it)
    # Peak in-flight respects the per-op cap (default 8) even with 40
    # upstream blocks available.
    for s in plan.streaming_stats:
        assert s["peak_in_flight"] <= 8, s
    list(it)


def test_repeated_iteration_caches_all_to_all(ray_local):
    """Epoch 2 of a shuffled dataset serves cached refs — the shuffle
    task graph must not re-run per epoch (multi-epoch train ingest)."""
    ds = rd.range(100, parallelism=10).random_shuffle(seed=1)
    plan = ds._plan
    first = list(plan.iter_block_refs())
    assert plan._cached is not None
    second = list(plan.iter_block_refs())
    assert [r.id for r in first] == [r.id for r in second]


def test_fifo_order_preserved_under_out_of_order_completion(ray_local):
    """Per-op FIFO: blocks whose tasks finish OUT of submission order
    must still stream downstream IN submission order (the batched
    event-driven poll pops only the completed head-of-line prefix)."""
    from ray_tpu.data.streaming_executor import (
        MapOp,
        SourceOp,
        StreamingExecutor,
    )

    @ray_tpu.remote(num_cpus=0.1)
    def delayed(i):
        # Earlier blocks sleep LONGER: completion order is reversed
        # relative to submission order.
        time.sleep((8 - i) * 0.03)
        return [i]

    refs = [delayed.remote(i) for i in range(8)]
    source = SourceOp("source", refs=refs, max_in_flight=8)
    map_op = MapOp("map", fn=lambda b: b, num_cpus=0.1, max_in_flight=8)
    out = [ray_tpu.get(r)[0] for r in
           StreamingExecutor([source, map_op]).iter_refs(window=8)]
    assert out == list(range(8)), f"FIFO order broken: {out}"


def test_poll_batched_wait_single_call(ray_local):
    """poll() issues ONE batched wait over the in-flight window instead
    of one wait per ref."""
    from unittest import mock

    from ray_tpu.data.streaming_executor import PhysicalOp

    op = PhysicalOp("probe", max_in_flight=8)

    @ray_tpu.remote(num_cpus=0.1)
    def unit(i):
        return i

    refs = [unit.remote(i) for i in range(6)]
    ray_tpu.get(refs)  # all resolved
    for r in refs:
        op._track(r)
    with mock.patch("ray_tpu.wait", wraps=ray_tpu.wait) as spy:
        assert op.poll()
    assert spy.call_count == 1
    assert len(op.outputs) == 6
