"""Llama model tests: correctness, sharded equivalence, train step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# Known pre-existing divergence (see CHANGES.md, PR 3): under this
# image's jax 0.4.37 the version-portable shard_map compat path makes
# the sharded forward numerically diverge from single-device beyond
# test tolerance on CPU. Real sharding bugs show up as shape/axis
# errors or wild divergence, which xfail(strict=False) still surfaces
# as XPASS→investigate when the underlying jax is fixed.
_SHARDED_NUMERICS_XFAIL = pytest.mark.xfail(
    reason="pre-existing sharded-vs-single-device numeric divergence "
           "under jax 0.4.37 shard_map compat (tracked in CHANGES.md)",
    strict=False)

from ray_tpu.models import (
    LlamaConfig,
    TrainState,
    forward,
    init_params,
    init_params_sharded,
    init_train_state,
    loss_fn,
    make_optimizer,
    make_train_step,
    param_logical_axes,
)
from ray_tpu.parallel import MeshConfig, create_mesh


def _batch(cfg, b=2, s=32, seed=0):
    key = jax.random.PRNGKey(seed)
    tokens = jax.random.randint(key, (b, s), 0, cfg.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)
    return {"tokens": tokens, "targets": targets}


def test_forward_shapes_and_finite():
    cfg = LlamaConfig.debug()
    params = init_params(cfg, jax.random.PRNGKey(0))
    batch = _batch(cfg)
    logits = forward(params, batch["tokens"], cfg)
    assert logits.shape == (2, 32, cfg.vocab_size)
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_num_params_matches_tree():
    cfg = LlamaConfig.debug()
    params = init_params(cfg, jax.random.PRNGKey(0))
    actual = sum(x.size for x in jax.tree.leaves(params))
    assert actual == cfg.num_params()


def test_param_logical_axes_structure_matches():
    cfg = LlamaConfig.debug()
    params = init_params(cfg, jax.random.PRNGKey(0))
    axes = param_logical_axes(cfg)
    jax.tree.map(
        lambda p, a: None if p.ndim == len(a) else (_ for _ in ()).throw(
            AssertionError(f"{p.shape} vs {a}")),
        params, axes,
        is_leaf=lambda x: isinstance(x, tuple) and all(
            y is None or isinstance(y, str) for y in x),
    )


@_SHARDED_NUMERICS_XFAIL
def test_sharded_forward_matches_single_device():
    cfg = LlamaConfig.debug()
    params = init_params(cfg, jax.random.PRNGKey(0))
    batch = _batch(cfg)
    expected = forward(params, batch["tokens"], cfg)

    mesh = create_mesh(MeshConfig(data=2, fsdp=2, tensor=2))
    sharded_params = init_params_sharded(cfg, mesh, jax.random.PRNGKey(0))
    got = jax.jit(
        lambda p, t: forward(p, t, cfg, mesh=mesh)
    )(sharded_params, batch["tokens"])
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-4, atol=2e-4)


def test_context_parallel_forward_matches():
    cfg = LlamaConfig.debug()
    params = init_params(cfg, jax.random.PRNGKey(0))
    batch = _batch(cfg, b=2, s=64)
    expected = forward(params, batch["tokens"], cfg)

    mesh = create_mesh(MeshConfig(data=2, seq=4))
    sharded = init_params_sharded(cfg, mesh, jax.random.PRNGKey(0))
    cfg_ring = LlamaConfig.debug()
    cfg_ring = cfg_ring.__class__(**{**cfg_ring.__dict__,
                                     "attention": "ring"})
    # Global positions must be provided under context parallelism.
    positions = jnp.broadcast_to(jnp.arange(64), (2, 64))
    got = jax.jit(
        lambda p, t, pos: forward(p, t, cfg_ring, mesh=mesh, positions=pos)
    )(sharded, batch["tokens"], positions)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-4, atol=2e-4)


def test_train_step_descends():
    cfg = LlamaConfig.debug()
    mesh = create_mesh(MeshConfig(data=4, tensor=2))
    params = init_params_sharded(cfg, mesh, jax.random.PRNGKey(0))
    tx = make_optimizer(1e-2, warmup_steps=0)
    state = init_train_state(params, tx)

    step = make_train_step(
        lambda p, b: loss_fn(p, b, cfg, mesh=mesh), tx, mesh=mesh,
        batch_logical={"tokens": ("batch", "seq"),
                       "targets": ("batch", "seq")},
    )
    batch = _batch(cfg, b=4, s=32)
    losses = []
    for _ in range(5):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0], losses
    assert int(state.step) == 5


@_SHARDED_NUMERICS_XFAIL
def test_positions_shift_changes_logits():
    cfg = LlamaConfig.debug()
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = _batch(cfg, b=1, s=16)["tokens"]
    base = forward(params, tokens, cfg)
    shifted = forward(params, tokens, cfg,
                      positions=jnp.arange(16)[None, :] + 5)
    assert not np.allclose(np.asarray(base), np.asarray(shifted))
