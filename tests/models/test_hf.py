"""HF interop: converted weights must reproduce transformers' logits."""

import numpy as np
import pytest

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")

import jax.numpy as jnp  # noqa: E402

from ray_tpu.models.hf import (  # noqa: E402
    config_from_hf,
    params_from_hf_state_dict,
)
from ray_tpu.models.llama import forward  # noqa: E402


@pytest.fixture(scope="module")
def tiny_hf_model():
    hf_cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=64,
        rms_norm_eps=1e-5, rope_theta=10000.0,
        tie_word_embeddings=False, attn_implementation="eager")
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(hf_cfg)
    model.eval()
    return model


def test_logits_match_transformers(tiny_hf_model):
    model = tiny_hf_model
    cfg = config_from_hf(model.config)
    cfg = cfg.__class__(**{**cfg.__dict__, "dtype": jnp.float32,
                           "remat": False})
    params = params_from_hf_state_dict(model.state_dict(), cfg,
                                       dtype=jnp.float32)

    tokens = np.array([[1, 5, 9, 33, 77, 2, 4, 8]], np.int32)
    with torch.no_grad():
        hf_logits = model(torch.tensor(tokens, dtype=torch.long)
                          ).logits.numpy()
    ours = np.asarray(forward(params, jnp.asarray(tokens), cfg))
    # atol-dominated: near-zero logits make rtol meaningless; 1e-2 vs a
    # ~±10 logit range is numerically identical up to f32 op ordering.
    np.testing.assert_allclose(ours, hf_logits, rtol=1e-2, atol=1e-2)


def test_greedy_continuations_match(tiny_hf_model):
    model = tiny_hf_model
    cfg = config_from_hf(model.config)
    cfg = cfg.__class__(**{**cfg.__dict__, "dtype": jnp.float32,
                           "remat": False})
    params = params_from_hf_state_dict(model.state_dict(), cfg,
                                       dtype=jnp.float32)
    prompt = [3, 17, 42, 8]
    with torch.no_grad():
        hf_out = model.generate(
            torch.tensor([prompt], dtype=torch.long), max_new_tokens=8,
            do_sample=False).numpy()[0][len(prompt):]
    tokens = list(prompt)
    for _ in range(8):
        logits = forward(params, jnp.asarray([tokens]), cfg)
        tokens.append(int(logits[0, -1].argmax()))
    np.testing.assert_array_equal(tokens[len(prompt):], hf_out)
