"""MoE model tests: routing, expert-parallel equivalence, training."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# Known pre-existing divergence (see CHANGES.md, PR 3): under this
# image's jax 0.4.37 the version-portable shard_map compat path makes
# the expert-parallel forward numerically diverge from single-device
# beyond test tolerance on CPU.
_SHARDED_NUMERICS_XFAIL = pytest.mark.xfail(
    reason="pre-existing sharded-vs-single-device numeric divergence "
           "under jax 0.4.37 shard_map compat (tracked in CHANGES.md)",
    strict=False)

from ray_tpu.models import (
    init_train_state,
    make_optimizer,
    make_train_step,
)
from ray_tpu.models.moe import (
    MoEConfig,
    init_moe_params,
    init_moe_params_sharded,
    moe_forward,
    moe_loss_fn,
)
from ray_tpu.parallel import MeshConfig, create_mesh


def _batch(cfg, b=2, s=16, seed=0):
    key = jax.random.PRNGKey(seed)
    tokens = jax.random.randint(key, (b, s), 0, cfg.vocab_size)
    return {"tokens": tokens, "targets": jnp.roll(tokens, -1, axis=1)}


def test_moe_forward_finite_and_aux():
    cfg = MoEConfig.debug_moe()
    params = init_moe_params(cfg, jax.random.PRNGKey(0))
    batch = _batch(cfg)
    logits, aux = moe_forward(params, batch["tokens"], cfg)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert bool(jnp.all(jnp.isfinite(logits)))
    # Balanced-ish routing at init: aux near its floor of 1.0.
    assert 0.9 < float(aux) < 3.0


@_SHARDED_NUMERICS_XFAIL
def test_moe_expert_parallel_matches_single_device():
    cfg = MoEConfig.debug_moe()
    params = init_moe_params(cfg, jax.random.PRNGKey(0))
    batch = _batch(cfg)
    expected, aux0 = moe_forward(params, batch["tokens"], cfg)

    mesh = create_mesh(MeshConfig(data=2, expert=2, tensor=2))
    sharded = init_moe_params_sharded(cfg, mesh, jax.random.PRNGKey(0))
    got, aux1 = jax.jit(
        lambda p, t: moe_forward(p, t, cfg, mesh=mesh)
    )(sharded, batch["tokens"])
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(float(aux1), float(aux0), rtol=1e-4)


def test_moe_train_step_descends():
    cfg = MoEConfig.debug_moe()
    mesh = create_mesh(MeshConfig(data=2, expert=2, tensor=2))
    params = init_moe_params_sharded(cfg, mesh, jax.random.PRNGKey(0))
    tx = make_optimizer(5e-3, warmup_steps=0)
    state = init_train_state(params, tx)
    step = make_train_step(
        lambda p, b: moe_loss_fn(p, b, cfg, mesh=mesh), tx, mesh=mesh,
        batch_logical={"tokens": ("batch", "seq"),
                       "targets": ("batch", "seq")})
    batch = _batch(cfg, b=4, s=16)
    losses = []
    for _ in range(5):
        state, metrics = step(state, batch)
        losses.append(float(metrics["ce_loss"]))
    assert losses[-1] < losses[0], losses


def test_moe_topk_gating_selects_k_experts():
    cfg = MoEConfig.debug_moe()
    from ray_tpu.models.moe import _moe_ffn, _init_moe_layer

    from ray_tpu.parallel.sharding import DEFAULT_RULES

    lp = _init_moe_layer(cfg, jax.random.PRNGKey(1))
    x = jax.random.normal(jax.random.PRNGKey(2), (1, 8, cfg.dim))
    out, aux = _moe_ffn(cfg, lp, x, None, DEFAULT_RULES)
    assert out.shape == x.shape
    # Recompute gates to confirm exactly k nonzero per token.
    logits = jnp.einsum("bsd,de->bse", x, lp["router"])
    probs = jax.nn.softmax(logits, -1)
    topk_vals, _ = jax.lax.top_k(probs, cfg.n_experts_per_token)
    gates = jnp.where(probs >= topk_vals[..., -1:], probs, 0.0)
    nonzero = (gates > 0).sum(-1)
    assert int(nonzero.max()) == cfg.n_experts_per_token
