"""Per-chip HBM planning + abstract shape-check (the 8B north-star
gate; see `ray_tpu/models/memory_plan.py`)."""

import jax.numpy as jnp

from ray_tpu.models.llama import LlamaConfig
from ray_tpu.models.memory_plan import plan_llama, shape_check_llama


def test_plan_8b_fits_v5e64():
    cfg = LlamaConfig.llama3_8b()
    plan = plan_llama(cfg, {"data": 1, "fsdp": 16, "tensor": 4},
                      batch_per_chip=4, seq_len=2048, chip="v5e")
    assert plan["chips"] == 64
    assert plan["fits"]
    gib = plan["per_chip_gib"]
    # Sanity: bf16 params = 2*8.03e9/64 chips ≈ 0.23 GiB/chip.
    assert 0.2 < gib["params"] < 0.3
    assert gib["total"] < plan["hbm_gib"]
    # Without sharding the same model cannot fit one chip.
    solo = plan_llama(cfg, {"data": 1}, batch_per_chip=4,
                      seq_len=2048, chip="v5e")
    assert not solo["fits"]


def test_plan_remat_policies_order():
    cfg = LlamaConfig.llama3_8b()
    kw = dict(batch_per_chip=4, seq_len=2048, chip="v5e")
    mesh = {"data": 1, "fsdp": 16, "tensor": 4}
    base = plan_llama(cfg, mesh, remat=True, **kw)
    gate = plan_llama(cfg, mesh, remat="gate", **kw)
    mlp = plan_llama(cfg, mesh, remat="mlp", **kw)
    none = plan_llama(cfg, mesh, remat=False, **kw)
    a = [p["per_chip_gib"]["activations_saved"]
         for p in (base, gate, mlp, none)]
    assert a[0] < a[1] < a[2] < a[3]


def test_shape_check_small_config_on_test_mesh():
    """The abstract-eval path itself, on the 8-device test mesh."""
    cfg = LlamaConfig.debug()
    out = shape_check_llama(cfg, {"data": 2, "fsdp": 2, "tensor": 2},
                            batch_per_chip=1, seq_len=32,
                            moment_dtype=jnp.bfloat16)
    assert out["ok"] and out["chips"] == 8
    assert out["sharding_resolved"]
