"""Multi-host SPMD path: JaxBackend(distributed=True) forms a real
multi-process JAX world (2 OS processes × 2 CPU devices = 4-device global
mesh) and runs an SPMD computation with cross-process collectives.

This is the CPU stand-in for a TPU pod (SURVEY.md §7 multi-controller
JAX): same `jax.distributed.initialize` + global-mesh code path the pod
uses, exercised with the gloo CPU-collectives plugin.
"""

import pytest

import ray_tpu
from ray_tpu.air import session
from ray_tpu.air.config import ScalingConfig
from ray_tpu.train.jax_trainer import JaxConfig, JaxTrainer

# Multi-process / soak tests: excluded from the quick
# tier (pytest -m 'not slow').
pytestmark = pytest.mark.slow


@pytest.fixture
def ray_4cpu():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_jax_distributed_two_process_mesh(ray_4cpu):
    def train_loop():
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        assert jax.process_count() == 2, jax.process_count()
        rank = jax.process_index()
        devs = jax.devices()
        assert len(devs) == 4  # 2 processes x 2 local cpu devices

        mesh = Mesh(devs, ("data",))
        x = jnp.ones((4, 8)) * (rank + 1)
        arr = jax.make_array_from_process_local_data(
            NamedSharding(mesh, P("data")), x)
        total = jax.jit(lambda a: a.sum(),
                        out_shardings=NamedSharding(mesh, P()))(arr)
        # Cross-process reduction: 4*8*1 + 4*8*2 = 96 on every rank.
        session.report({"total": float(total), "rank": rank})

    trainer = JaxTrainer(
        train_loop,
        jax_config=JaxConfig(distributed=True, coordinator_port=7921,
                             platform="cpu", num_local_devices=2),
        scaling_config=ScalingConfig(num_workers=2,
                                     resources_per_worker={"CPU": 1}),
    )
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.metrics["total"] == 96.0
