"""GBDT + HuggingFace trainer integrations (reference
`train/gbdt_trainer.py`, `train/huggingface/huggingface_trainer.py`)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rt_data


@pytest.fixture(autouse=True)
def ray():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_gbdt_trainer_fits_and_checkpoints():
    from ray_tpu.train.gbdt_trainer import XGBoostTrainer

    rng = np.random.RandomState(0)
    X = rng.normal(size=(600, 4))
    y = (X @ np.array([1.0, -2.0, 0.5, 0.0]) + 0.01 *
         rng.normal(size=600))
    rows = [{"f0": X[i, 0], "f1": X[i, 1], "f2": X[i, 2],
             "f3": X[i, 3], "y": y[i]} for i in range(600)]
    train = rt_data.from_items(rows[:500], parallelism=4)
    valid = rt_data.from_items(rows[500:], parallelism=2)

    trainer = XGBoostTrainer(
        label_column="y", num_boost_round=40,
        params={"learning_rate": 0.2},
        datasets={"train": train, "valid": valid})
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.metrics["train_score"] > 0.9
    assert result.metrics["valid_score"] > 0.8
    model = XGBoostTrainer.get_model(result.checkpoint)
    pred = model.predict(X[:10])
    assert np.abs(pred - y[:10]).mean() < 1.0


def test_gbdt_classifier_objective():
    from ray_tpu.train.gbdt_trainer import XGBoostTrainer

    rng = np.random.RandomState(1)
    X = rng.normal(size=(400, 3))
    y = (X[:, 0] + X[:, 1] > 0).astype(int)
    rows = [{"a": X[i, 0], "b": X[i, 1], "c": X[i, 2], "label": y[i]}
            for i in range(400)]
    trainer = XGBoostTrainer(
        label_column="label", num_boost_round=30,
        params={"objective": "classification"},
        datasets={"train": rt_data.from_items(rows, parallelism=4)})
    result = trainer.fit()
    assert result.metrics["train_score"] > 0.9


def test_huggingface_trainer_tiny_model(tmp_path):
    from ray_tpu.train.huggingface import HuggingFaceTrainer

    def trainer_init(train_ds, eval_ds, **cfg):
        import torch
        from transformers import (GPT2Config, GPT2LMHeadModel, Trainer,
                                  TrainingArguments)

        model = GPT2LMHeadModel(GPT2Config(
            n_embd=32, n_layer=2, n_head=2, vocab_size=128,
            n_positions=32))

        class TokenDataset(torch.utils.data.Dataset):
            def __init__(self):
                rng = np.random.RandomState(0)
                self.data = rng.randint(0, 128, (64, 16))

            def __len__(self):
                return len(self.data)

            def __getitem__(self, i):
                ids = torch.tensor(self.data[i], dtype=torch.long)
                return {"input_ids": ids, "labels": ids}

        args = TrainingArguments(
            output_dir=str(tmp_path), per_device_train_batch_size=8,
            num_train_epochs=1, logging_steps=2, report_to=[],
            save_strategy="no", use_cpu=True)
        return Trainer(model=model, args=args,
                       train_dataset=TokenDataset())

    trainer = HuggingFaceTrainer(trainer_init)
    result = trainer.fit()
    assert result.error is None, result.error
    sd = HuggingFaceTrainer.get_state_dict(result.checkpoint)
    assert any("wte" in k for k in sd)
