"""TorchTrainer: 2-process gloo DDP parity on CPU torch."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.air import session
from ray_tpu.air.config import ScalingConfig
from ray_tpu.train import TorchCheckpoint, TorchConfig, TorchTrainer


@pytest.fixture(autouse=True)
def ray():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def _train_loop(config):
    import torch
    import torch.distributed as dist
    from torch.utils.data import DataLoader, TensorDataset

    from ray_tpu.train.torch import (prepare_data_loader, prepare_model,
                                     TorchCheckpoint)

    torch.manual_seed(0)
    rank = dist.get_rank()
    world = dist.get_world_size()
    assert world == 2

    # y = 3x - 1 regression; all ranks share the same dataset file of
    # 64 rows; the DistributedSampler splits it.
    xs = torch.linspace(-1, 1, 64).unsqueeze(1)
    ys = 3 * xs - 1
    loader = DataLoader(TensorDataset(xs, ys), batch_size=8)
    loader = prepare_data_loader(loader)
    assert len(loader) == 4  # 64 rows / 2 ranks / batch 8

    model = prepare_model(torch.nn.Linear(1, 1))
    opt = torch.optim.SGD(model.parameters(), lr=0.3)
    loss_fn = torch.nn.MSELoss()
    for epoch in range(30):
        for xb, yb in loader:
            opt.zero_grad()
            loss = loss_fn(model(xb), yb)
            loss.backward()
            opt.step()
    # DDP keeps replicas in sync: weights must match across ranks.
    w = model.module.weight.item()
    gathered = [None, None]
    dist.all_gather_object(gathered, w)
    assert abs(gathered[0] - gathered[1]) < 1e-6
    session.report({"loss": float(loss), "rank": rank, "weight": w},
                   checkpoint=TorchCheckpoint.from_model(model))


def test_torch_trainer_ddp_learns():
    trainer = TorchTrainer(
        _train_loop,
        torch_config=TorchConfig(init_port=7033),
        scaling_config=ScalingConfig(num_workers=2,
                                     resources_per_worker={"CPU": 1}))
    result = trainer.fit()
    assert result.metrics["loss"] < 0.05, result.metrics
    assert result.checkpoint is not None

    import torch

    model = TorchCheckpoint.get_model(result.checkpoint,
                                      torch.nn.Linear(1, 1))
    w, b = model.weight.item(), model.bias.item()
    assert abs(w - 3.0) < 0.3 and abs(b + 1.0) < 0.3, (w, b)
