"""BatchPredictor: checkpoint -> predictor -> dataset map with
actor-pool compute."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd
from ray_tpu.air import BatchPredictor, Checkpoint, JaxPredictor
from ray_tpu.air.batch_predictor import TorchPredictor


@pytest.fixture(autouse=True)
def ray():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def _linear_apply(params, x):
    return x @ params["w"] + params["b"]


def test_jax_batch_prediction_over_dataset():
    w = np.array([[2.0], [1.0]], np.float32)
    ckpt = Checkpoint.from_dict(
        {"params": {"w": w, "b": np.float32(3.0)}})
    predictor = BatchPredictor.from_checkpoint(
        ckpt, JaxPredictor, apply_fn=_linear_apply)

    rows = np.arange(20, dtype=np.float32).reshape(10, 2)
    ds = rd.from_numpy(rows)
    out = predictor.predict(ds, batch_size=4)
    preds = np.concatenate(
        [np.atleast_1d(r["predictions"]).ravel()
         for r in out.take_all()])
    expect = (rows @ w + 3.0).ravel()
    np.testing.assert_allclose(np.sort(preds), np.sort(expect),
                               rtol=1e-5)


def test_torch_predictor_roundtrip():
    import torch

    from ray_tpu.train.torch import TorchCheckpoint

    model = torch.nn.Linear(2, 1)
    with torch.no_grad():
        model.weight[:] = torch.tensor([[2.0, 1.0]])
        model.bias[:] = torch.tensor([3.0])
    ckpt = TorchCheckpoint.from_model(model)
    pred = TorchPredictor.from_checkpoint(ckpt,
                                          model=torch.nn.Linear(2, 1))
    out = pred.predict({"data": np.array([[1.0, 2.0]], np.float32)})
    np.testing.assert_allclose(out["predictions"], [[7.0]], rtol=1e-5)


def test_iter_torch_batches():
    import torch

    rows = np.arange(12, dtype=np.float32).reshape(6, 2)
    ds = rd.from_numpy(rows)
    batches = list(ds.iter_torch_batches(batch_size=4))
    total = 0
    for b in batches:
        t = b["data"] if isinstance(b, dict) else b
        assert isinstance(t, torch.Tensor)
        total += t.shape[0]
    assert total == 6

    # dtype override applies: per-column dict AND single dtype for
    # bare-array batches.
    batches = list(ds.iter_torch_batches(
        batch_size=4, dtypes={"data": torch.float64}))
    t = batches[0]["data"] if isinstance(batches[0], dict) else batches[0]
    if isinstance(batches[0], dict):
        assert t.dtype == torch.float64
    batches = list(ds.iter_torch_batches(batch_size=4,
                                         dtypes=torch.float64))
    t = batches[0]["data"] if isinstance(batches[0], dict) else batches[0]
    assert t.dtype == torch.float64
