"""Train layer tests: air plumbing, worker group, trainers."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd
from ray_tpu.air import Checkpoint, ScalingConfig, RunConfig, session
from ray_tpu.train import DataParallelTrainer, JaxTrainer


@pytest.fixture(autouse=True)
def ray():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8)
    yield
    ray_tpu.shutdown()


def test_checkpoint_dict_dir_roundtrip(tmp_path):
    ck = Checkpoint.from_dict({"a": 1, "b": np.arange(3)})
    d = ck.to_directory(str(tmp_path / "ck"))
    back = Checkpoint.from_directory(d).to_dict()
    assert back["a"] == 1
    np.testing.assert_array_equal(back["b"], np.arange(3))


def test_checkpoint_pytree_roundtrip(tmp_path):
    import jax.numpy as jnp

    tree = {"w": jnp.ones((2, 2)), "b": jnp.zeros(2)}
    ck = Checkpoint.from_pytree(tree, step=5)
    d = ck.to_directory(str(tmp_path / "ck"))
    restored = Checkpoint.from_directory(d)
    tree2 = restored.to_pytree()
    np.testing.assert_array_equal(np.asarray(tree2["w"]), np.ones((2, 2)))
    assert restored.metadata()["step"] == 5


def test_data_parallel_trainer_basic():
    def loop(config):
        rank = session.get_world_rank()
        world = session.get_world_size()
        assert world == 2
        for step in range(3):
            session.report({"step": step, "rank": rank,
                            "value": config["x"] * (step + 1)})

    trainer = DataParallelTrainer(
        loop, train_loop_config={"x": 10},
        scaling_config=ScalingConfig(num_workers=2))
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["value"] == 30
    assert len(result.metrics_history) == 3


def test_trainer_dataset_sharding():
    def loop(config):
        shard = session.get_dataset_shard("train")
        total = sum(b["id"].sum() for b in shard.iter_batches(
            batch_size=None))
        session.report({"partial": int(total),
                        "rows": shard.count()})

    ds = rd.range(100, parallelism=4)
    trainer = DataParallelTrainer(
        loop, scaling_config=ScalingConfig(num_workers=2),
        datasets={"train": ds})
    result = trainer.fit()
    assert result.error is None
    # Each worker sees half the rows.
    assert result.metrics["rows"] == 50


def test_trainer_checkpoint_and_resume():
    def loop(config):
        start = 0
        ck = session.get_checkpoint()
        if ck:
            start = ck.to_dict()["step"] + 1
        for step in range(start, 4):
            session.report({"step": step},
                           checkpoint=Checkpoint.from_dict({"step": step}))

    trainer = DataParallelTrainer(
        loop, scaling_config=ScalingConfig(num_workers=1))
    result = trainer.fit()
    assert result.metrics["step"] == 3
    assert result.checkpoint is not None

    trainer2 = DataParallelTrainer(
        loop, scaling_config=ScalingConfig(num_workers=1),
        resume_from_checkpoint=Checkpoint.from_dict({"step": 1}))
    result2 = trainer2.fit()
    assert result2.metrics_history[0]["step"] == 2


def test_trainer_worker_failure_surfaces():
    def loop(config):
        if session.get_world_rank() == 1:
            raise RuntimeError("boom")
        session.report({"ok": 1})

    trainer = DataParallelTrainer(
        loop, scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig())
    result = trainer.fit()
    assert result.error is not None
    assert "boom" in str(result.error)


def test_collective_allreduce_in_train_loop():
    def loop(config):
        from ray_tpu.util import collective

        rank = session.get_world_rank()
        total = collective.allreduce(np.array([float(rank + 1)]))
        session.report({"total": float(total[0])})

    trainer = DataParallelTrainer(
        loop, scaling_config=ScalingConfig(num_workers=3))
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["total"] == 6.0  # 1+2+3


def test_collective_sharded_allreduce_large_tensor():
    """Tensors above the shard threshold split across the shard-actor
    pool (no single-actor funnel) and still reduce exactly."""
    def loop(config):
        from ray_tpu.util import collective

        rank = session.get_world_rank()
        big = np.full(200_000, float(rank + 1), np.float64)  # 1.6 MB
        total = collective.allreduce(big)
        # Mixed pytree: one big leaf (sharded) + one small (batched).
        tree = {"w": np.full(150_000, float(rank + 1), np.float64),
                "b": np.array([float(rank + 1)])}
        avg = collective.allreduce_pytree(tree, op="mean")
        session.report({
            "total0": float(total[0]),
            "total_last": float(total[-1]),
            "w_mean": float(np.mean(avg["w"])),
            "b": float(avg["b"][0]),
        })

    trainer = DataParallelTrainer(
        loop, scaling_config=ScalingConfig(num_workers=3))
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["total0"] == 6.0
    assert result.metrics["total_last"] == 6.0
    assert abs(result.metrics["w_mean"] - 2.0) < 1e-9
    assert abs(result.metrics["b"] - 2.0) < 1e-9


def test_jax_trainer_ddp_parity():
    """Host-level DDP: N workers averaging grads through the collective
    must match single-worker training on the full batch (the reference's
    torch DDP parity assertion, air_benchmarks/workloads/torch_benchmark)."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.mlp import MLPConfig, mlp_init, mlp_loss
    from ray_tpu.train import allreduce_gradients

    cfg = MLPConfig(in_dim=8, hidden=(16,), n_classes=3)
    xs = np.random.RandomState(0).randn(32, 8).astype(np.float32)
    ys = np.random.RandomState(1).randint(0, 3, 32)

    def make_loop(n_steps=3, lr=0.1):
        def loop(config):
            rank = session.get_world_rank()
            world = session.get_world_size()
            params = mlp_init(cfg, jax.random.PRNGKey(0))
            shard = slice(rank * 32 // world, (rank + 1) * 32 // world)
            batch = {"x": jnp.asarray(xs[shard]),
                     "y": jnp.asarray(ys[shard])}
            grad_fn = jax.jit(jax.grad(lambda p, b: mlp_loss(p, b)[0]))
            for _ in range(n_steps):
                grads = grad_fn(params, batch)
                grads = allreduce_gradients(grads)
                params = jax.tree.map(lambda p, g: p - lr * g, params,
                                      grads)
            loss, _ = mlp_loss(params, {"x": jnp.asarray(xs),
                                        "y": jnp.asarray(ys)})
            session.report({"final_loss": float(loss)})
        return loop

    r1 = JaxTrainer(make_loop(),
                    scaling_config=ScalingConfig(num_workers=1)).fit()
    r2 = JaxTrainer(make_loop(),
                    scaling_config=ScalingConfig(num_workers=2)).fit()
    assert r1.error is None and r2.error is None
    np.testing.assert_allclose(r1.metrics["final_loss"],
                               r2.metrics["final_loss"], rtol=1e-5)
