"""Serve under load: replica autoscaling driven by real queue pressure,
and the max_concurrent_queries in-flight cap under stress (reference:
`serve/_private/autoscaling_policy.py` + router concurrency caps,
exercised by `release/serve_tests`)."""

import threading
import time

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(autouse=True)
def ray():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8)
    yield
    try:
        serve.shutdown()
    except Exception:
        pass
    ray_tpu.shutdown()


def _wait_for(predicate, timeout=60.0, interval=0.2):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


def test_autoscaling_scales_up_under_load_then_down():
    @serve.deployment(
        autoscaling_config={"min_replicas": 1, "max_replicas": 3,
                            "target_num_ongoing_requests_per_replica": 2},
        max_concurrent_queries=2)
    class Slow:
        def __call__(self, x):
            time.sleep(0.3)
            return x

    handle = serve.run(Slow.bind())

    def replica_count():
        return serve.status()["Slow"]["num_replicas"]

    assert replica_count() == 1

    # Sustained pressure: a rolling window of in-flight requests keeps
    # the router's queue metric high while the controller reconciles.
    stop = threading.Event()
    errors = []

    def pound():
        while not stop.is_set():
            try:
                ray_tpu.get(handle.remote(1), timeout=60)
            except Exception as e:  # noqa: BLE001
                errors.append(e)
                return

    threads = [threading.Thread(target=pound) for _ in range(12)]
    for t in threads:
        t.start()
    try:
        assert _wait_for(lambda: replica_count() >= 2, timeout=60), \
            f"never scaled up: {serve.status()}"
    finally:
        stop.set()
        for t in threads:
            t.join()
    assert not errors, errors[0]

    # Load gone: the controller must scale back toward min_replicas.
    assert _wait_for(lambda: replica_count() == 1, timeout=60), \
        f"never scaled down: {serve.status()}"


def test_max_concurrent_queries_cap_under_stress():
    observed = {"max": 0, "now": 0}
    lock = threading.Lock()

    @serve.deployment(num_replicas=1, max_concurrent_queries=2)
    class Capped:
        def __call__(self, x):
            with lock:
                observed["now"] += 1
                observed["max"] = max(observed["max"], observed["now"])
            time.sleep(0.05)
            with lock:
                observed["now"] -= 1
            return x

    handle = serve.run(Capped.bind())

    results = []

    def fire(i):
        results.append(ray_tpu.get(handle.remote(i), timeout=120))

    threads = [threading.Thread(target=fire, args=(i,))
               for i in range(30)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sorted(results) == list(range(30))
    # The router's per-replica in-flight cap bounds concurrency inside
    # the replica. (Replicas run in-process here, so the closure's
    # counter observes true concurrency.)
    assert observed["max"] <= 2, observed
