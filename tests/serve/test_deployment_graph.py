"""Deployment graphs: bound deployments as constructor args become
handles; DAGDriver exposes the pipeline over HTTP.

Reference: `serve/_private/deployment_graph_build.py` + `serve/drivers.py`.
"""

import http.client
import json

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture
def serve_up():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def test_diamond_graph_composes(serve_up):
    @serve.deployment
    class Doubler:
        def __call__(self, x):
            return x * 2

    @serve.deployment
    class Adder:
        def __call__(self, x):
            return x + 10

    @serve.deployment
    class Combiner:
        def __init__(self, doubler, adder):
            self.doubler = doubler
            self.adder = adder

        def __call__(self, x):
            a = self.doubler.remote(x)
            b = self.adder.remote(x)
            return {"doubled": ray_tpu.get(a), "added": ray_tpu.get(b)}

    graph = Combiner.bind(Doubler.bind(), Adder.bind())
    handle = serve.run(graph)
    out = ray_tpu.get(handle.remote(5), timeout=60)
    assert out == {"doubled": 10, "added": 15}
    # All three deployments exist in the controller.
    assert {"Doubler", "Adder", "Combiner"} <= set(serve.status())


def test_shared_node_deploys_once(serve_up):
    @serve.deployment
    class Leaf:
        def __call__(self, x):
            return x + 1

    @serve.deployment
    class Root:
        def __init__(self, left, right):
            # Diamond: both sides are the same bound node.
            self.same = left is not None and right is not None
            self.left, self.right = left, right

        def __call__(self, x):
            return ray_tpu.get(self.left.remote(x)) + \
                ray_tpu.get(self.right.remote(x))

    leaf = Leaf.bind()
    handle = serve.run(Root.bind(leaf, leaf))
    assert ray_tpu.get(handle.remote(1), timeout=60) == 4
    assert serve.status()["Leaf"]["num_replicas"] == 1


def test_dagdriver_routes_http(serve_up):
    @serve.deployment
    class Model:
        def __call__(self, payload):
            return {"score": payload["x"] * 3}

    serve.run(serve.DAGDriver.bind(Model.bind()), route_prefix="/pipe")
    proxy = serve.start_http_proxy()
    conn = http.client.HTTPConnection(proxy.host, proxy.port, timeout=30)
    conn.request("POST", "/pipe", body=json.dumps({"x": 7}))
    resp = conn.getresponse()
    assert resp.status == 200
    assert json.loads(resp.read()) == {"score": 21}
    conn.close()
