"""The asyncio event-loop HTTP ingress: keep-alive across streamed
responses (chunked transfer-encoding), bounded-concurrency backpressure
(503 + Retry-After), pipelining order, and no head-of-line starvation
between connections."""

import http.client
import json
import socket
import threading
import time

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture
def serve_up():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def _read_sse(resp):
    """Drain one SSE body (chunked or close-delimited) into its JSON
    events; http.client handles the chunked framing."""
    items = []
    buf = b""
    while True:
        chunk = resp.read1(65536)
        if not chunk:
            break
        buf += chunk
        done = False
        while b"\n\n" in buf:
            line, buf = buf.split(b"\n\n", 1)
            if not line.startswith(b"data: "):
                continue
            payload = line[len(b"data: "):]
            if payload == b"[DONE]":
                done = True
                break
            items.append(json.loads(payload))
        if done:
            break
    return items


def test_stream_unary_stream_one_connection(serve_up):
    """THE keep-alive streaming regression: stream → unary → stream on
    one persistent connection, all three complete without a reconnect.
    Before chunked transfer-encoding, request 1's SSE reply forced
    Connection: close and request 2 needed a new TCP connect."""

    @serve.deployment
    class Mixed:
        def __call__(self, request):
            if isinstance(request, dict) and request.get("stream"):
                def gen():
                    for i in range(3):
                        yield {"i": i}
                return gen()
            return {"unary": request}

    serve.run(Mixed.bind(), route_prefix="/mixed")
    proxy = serve.start_http_proxy()

    conn = http.client.HTTPConnection(proxy.host, proxy.port, timeout=30)
    local_port = None
    for request_no, payload in enumerate(
            [{"stream": True}, {"x": 1}, {"stream": True}]):
        conn.request("POST", "/mixed", body=json.dumps(payload),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 200
        # Same TCP connection throughout: the client socket's local
        # port never changes (http.client reconnects transparently, so
        # the port is the witness that it never had to).
        port_now = conn.sock.getsockname()[1]
        if local_port is None:
            local_port = port_now
        assert port_now == local_port, \
            f"request {request_no} forced a reconnect"
        if payload.get("stream"):
            assert resp.headers.get("Content-Type") == "text/event-stream"
            assert resp.headers.get("Transfer-Encoding") == "chunked"
            assert resp.headers.get("Connection") != "close"
            items = _read_sse(resp)
            assert [c["i"] for c in items] == [0, 1, 2]
            resp.read()  # drain chunk terminator
        else:
            assert json.loads(resp.read()) == {"unary": {"x": 1}}
    conn.close()


def test_backpressure_503_with_retry_after(serve_up):
    """Past the in-flight cap the proxy sheds load with 503 +
    Retry-After instead of queueing without bound; the connection stays
    usable and recovers once load drains."""
    release = threading.Event()

    @serve.deployment(max_concurrent_queries=8)
    class Block:
        def __call__(self, request):
            release.wait(30)
            return {"ok": True}

    serve.run(Block.bind(), route_prefix="/block")
    proxy = serve.start_http_proxy(max_in_flight=2, queue_timeout_s=1.0)
    body = json.dumps({}).encode()
    req = (b"POST /block HTTP/1.1\r\nHost: t\r\n"
           b"Content-Type: application/json\r\nContent-Length: "
           + str(len(body)).encode() + b"\r\n\r\n" + body)

    # Fill the cap with two requests that park in the replica.
    parked = [socket.create_connection(("127.0.0.1", proxy.port),
                                       timeout=30) for _ in range(2)]
    for s in parked:
        s.sendall(req)
    deadline = time.monotonic() + 10
    while proxy.stats()["in_flight"] < 2:
        assert time.monotonic() < deadline, proxy.stats()
        time.sleep(0.02)

    # The third request must be shed immediately.
    conn = http.client.HTTPConnection(proxy.host, proxy.port, timeout=30)
    conn.request("POST", "/block", body=body,
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 503
    assert resp.headers.get("Retry-After") is not None
    resp.read()
    assert proxy.stats()["shed_503"] >= 1

    # Load drains -> the SAME connection serves a 200 (503 did not
    # poison keep-alive).
    release.set()
    deadline = time.monotonic() + 15
    status = None
    while time.monotonic() < deadline:
        conn.request("POST", "/block", body=body,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        status = resp.status
        resp.read()
        if status == 200:
            break
        time.sleep(0.1)
    assert status == 200
    conn.close()
    for s in parked:
        s.close()


def test_router_saturation_maps_to_503(serve_up):
    """No replica slot within queue_timeout_s -> 503 (load shedding),
    not a 500 or a hung connection."""
    release = threading.Event()

    @serve.deployment(max_concurrent_queries=1)
    class Slow:
        def __call__(self, request):
            release.wait(20)
            return {"ok": True}

    serve.run(Slow.bind(), route_prefix="/slow")
    proxy = serve.start_http_proxy(queue_timeout_s=0.5)
    try:
        body = json.dumps({}).encode()
        hdrs = {"Content-Type": "application/json"}

        blocker = http.client.HTTPConnection(proxy.host, proxy.port,
                                             timeout=30)
        blocker.request("POST", "/slow", body=body, headers=hdrs)
        time.sleep(0.3)  # let it occupy the single replica slot

        conn = http.client.HTTPConnection(proxy.host, proxy.port,
                                          timeout=30)
        t0 = time.perf_counter()
        conn.request("POST", "/slow", body=body, headers=hdrs)
        resp = conn.getresponse()
        waited = time.perf_counter() - t0
        assert resp.status == 503
        assert waited < 10, waited
        resp.read()
        conn.close()
    finally:
        release.set()
        blocker.close()


def test_hung_deployment_times_out_with_500_and_frees_slot(serve_up):
    """A deployment that never returns becomes a 500 after
    result_timeout_s and releases its in-flight slot — one buggy
    handler must not wedge the ingress's bounded-concurrency budget."""
    release = threading.Event()

    @serve.deployment
    class Hang:
        def __call__(self, request):
            release.wait(30)
            return {"ok": True}

    serve.run(Hang.bind(), route_prefix="/hang")
    proxy = serve.start_http_proxy()
    proxy.result_timeout_s = 1.0
    try:
        conn = http.client.HTTPConnection(proxy.host, proxy.port,
                                          timeout=30)
        conn.request("POST", "/hang", body=json.dumps({}),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 500
        assert b"no result within" in resp.read()
        conn.close()
        # The slot came back — not leaked as permanent in-flight.
        deadline = time.monotonic() + 5
        while proxy.stats()["in_flight"] and time.monotonic() < deadline:
            time.sleep(0.05)
        assert proxy.stats()["in_flight"] == 0
        # Timeouts are failures, not load shedding.
        assert proxy.stats()["shed_503"] == 0
    finally:
        release.set()


def test_deployment_raised_timeout_is_500_not_503(serve_up):
    """A TimeoutError raised BY the deployment is an application
    failure (500), never misreported as 503 load-shedding."""

    @serve.deployment
    class Boom:
        def __call__(self, request):
            raise TimeoutError("downstream call timed out")

    serve.run(Boom.bind(), route_prefix="/boom")
    proxy = serve.start_http_proxy()
    conn = http.client.HTTPConnection(proxy.host, proxy.port, timeout=30)
    conn.request("POST", "/boom", body=json.dumps({}),
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 500
    assert b"downstream call timed out" in resp.read()
    conn.close()
    assert proxy.stats()["shed_503"] == 0


def test_pipelined_requests_answered_in_order(serve_up):
    @serve.deployment
    class Echo:
        def __call__(self, request):
            return {"echo": request}

    serve.run(Echo.bind(), route_prefix="/echo")
    proxy = serve.start_http_proxy()

    sock = socket.create_connection(("127.0.0.1", proxy.port),
                                    timeout=30)
    burst = b""
    for i in range(5):
        body = json.dumps({"i": i}).encode()
        burst += (b"POST /echo HTTP/1.1\r\nHost: t\r\n"
                  b"Content-Type: application/json\r\nContent-Length: "
                  + str(len(body)).encode() + b"\r\n\r\n" + body)
    sock.sendall(burst)  # 5 pipelined requests in one segment
    buf = b""
    got = []
    deadline = time.monotonic() + 20
    while len(got) < 5 and time.monotonic() < deadline:
        chunk = sock.recv(65536)
        assert chunk, "server closed mid-pipeline"
        buf += chunk
        while b"\r\n\r\n" in buf:
            head, rest = buf.split(b"\r\n\r\n", 1)
            clen = 0
            for ln in head.split(b"\r\n")[1:]:
                if ln.lower().startswith(b"content-length:"):
                    clen = int(ln.split(b":", 1)[1])
            if len(rest) < clen:
                break
            got.append(json.loads(rest[:clen]))
            buf = rest[clen:]
    sock.close()
    assert [g["echo"]["i"] for g in got] == [0, 1, 2, 3, 4]


def test_idle_connections_are_reaped(serve_up):
    @serve.deployment
    class Echo:
        def __call__(self, request=None):
            return {"ok": True}

    serve.run(Echo.bind(), route_prefix="/e")
    proxy = serve.start_http_proxy()
    proxy.idle_timeout_s = 0.5  # shrink for the test
    sock = socket.create_connection(("127.0.0.1", proxy.port),
                                    timeout=30)
    time.sleep(0.1)
    assert proxy.stats()["open_connections"] >= 1
    deadline = time.monotonic() + 15
    closed = False
    while time.monotonic() < deadline and not closed:
        sock.settimeout(1.0)
        try:
            closed = sock.recv(1) == b""
        except socket.timeout:
            pass
    assert closed, "idle connection never reaped"
    sock.close()


def test_negative_content_length_rejected(serve_up):
    """A negative Content-Length must be a hard 400 + close — letting
    it through would slice pipelined successors into the body (request
    smuggling)."""

    @serve.deployment
    class Echo:
        def __call__(self, request=None):
            return {"echo": request}

    serve.run(Echo.bind(), route_prefix="/e")
    proxy = serve.start_http_proxy()
    sock = socket.create_connection(("127.0.0.1", proxy.port),
                                    timeout=10)
    smuggled = json.dumps({"smuggled": True}).encode()
    sock.sendall(b"POST /e HTTP/1.1\r\nHost: t\r\n"
                 b"Content-Length: -1\r\n\r\n"
                 b"POST /e HTTP/1.1\r\nHost: t\r\n"
                 b"Content-Type: application/json\r\n"
                 b"Content-Length: " + str(len(smuggled)).encode()
                 + b"\r\n\r\n" + smuggled)
    buf = b""
    sock.settimeout(10)
    try:
        while True:
            chunk = sock.recv(65536)
            if not chunk:
                break
            buf += chunk
    except socket.timeout:
        pass
    sock.close()
    assert buf.startswith(b"HTTP/1.1 400 "), buf[:80]
    # Exactly one response (the 400) — the second request was NOT
    # parsed off a desynced stream, and nothing was echoed back.
    assert buf.count(b"HTTP/1.1 ") == 1
    assert b"smuggled" not in buf


def test_oversized_body_sheds_with_413(serve_up):
    @serve.deployment
    class Echo:
        def __call__(self, request=None):
            return {"echo": request}

    serve.run(Echo.bind(), route_prefix="/big")
    proxy = serve.start_http_proxy()
    sock = socket.create_connection(("127.0.0.1", proxy.port),
                                    timeout=10)
    # Declare 10GB; the proxy must refuse at the header, not buffer.
    sock.sendall(b"POST /big HTTP/1.1\r\nHost: t\r\n"
                 b"Content-Length: 10737418240\r\n\r\n")
    buf = b""
    sock.settimeout(10)
    try:
        while True:
            chunk = sock.recv(65536)
            if not chunk:
                break
            buf += chunk
    except socket.timeout:
        pass
    sock.close()
    assert buf.startswith(b"HTTP/1.1 413 "), buf[:80]


def test_http10_keepalive_gets_explicit_header(serve_up):
    @serve.deployment
    class Echo:
        def __call__(self, request=None):
            return {"ok": True}

    serve.run(Echo.bind(), route_prefix="/h10")
    proxy = serve.start_http_proxy()
    sock = socket.create_connection(("127.0.0.1", proxy.port),
                                    timeout=10)
    for _ in range(2):  # the connection really does survive
        sock.sendall(b"GET /h10 HTTP/1.0\r\nHost: t\r\n"
                     b"Connection: keep-alive\r\n\r\n")
        buf = b""
        while b"\r\n\r\n" not in buf:
            chunk = sock.recv(65536)
            assert chunk, "server closed an HTTP/1.0 keep-alive conn"
            buf += chunk
        head, rest = buf.split(b"\r\n\r\n", 1)
        assert b"HTTP/1.1 200" in head
        # Explicit grant, or the 1.0 client assumes close-delimited.
        assert b"connection: keep-alive" in head.lower(), head
        clen = int([ln.split(b":")[1] for ln in head.split(b"\r\n")
                    if ln.lower().startswith(b"content-length")][0])
        while len(rest) < clen:
            rest += sock.recv(65536)
    sock.close()


def test_chunked_request_body_rejected(serve_up):
    @serve.deployment
    class Echo:
        def __call__(self, request=None):
            return {"ok": True}

    serve.run(Echo.bind(), route_prefix="/c")
    proxy = serve.start_http_proxy()
    # Raw socket, whole request in one write: the proxy answers 501 and
    # CLOSES the moment it sees the chunked header, which legitimately
    # races a client still streaming its chunks — http.client's
    # iterator body turned that race into a BrokenPipeError flake
    # (server behavior correct, client mid-send).
    sock = socket.create_connection((proxy.host, proxy.port),
                                    timeout=30)
    try:
        try:
            sock.sendall(
                b"POST /c HTTP/1.1\r\nHost: t\r\n"
                b"Content-Type: application/json\r\n"
                b"Transfer-Encoding: chunked\r\n\r\n"
                b"2\r\nab\r\n2\r\ncd\r\n0\r\n\r\n")
        except OSError:
            pass  # server already refused + closed: fine, read below
        buf = b""
        while b"\r\n\r\n" not in buf:
            chunk = sock.recv(65536)
            if not chunk:
                break
            buf += chunk
        assert b" 501 " in buf.split(b"\r\n", 1)[0], buf[:200]
    finally:
        sock.close()


def test_access_log_and_trace_header_on_keepalive(serve_up, caplog):
    """The structured access log (off by default, enabled via
    ray_config.serve_access_log): one JSON line per request — method,
    route, status, latency_ms, trace_id — across keep-alive stream and
    unary requests on ONE connection; the response echoes the trace id
    in X-Trace-Id."""
    import logging

    from ray_tpu._private.config import ray_config

    @serve.deployment
    class Mixed:
        def __call__(self, request):
            if isinstance(request, dict) and request.get("stream"):
                def gen():
                    for i in range(2):
                        yield {"i": i}
                return gen()
            return {"unary": request}

    serve.run(Mixed.bind(), route_prefix="/logged")
    proxy = serve.start_http_proxy()

    ray_config.serve_access_log = True
    try:
        with caplog.at_level(logging.INFO,
                             logger="ray_tpu.serve.access"):
            conn = http.client.HTTPConnection(proxy.host, proxy.port,
                                              timeout=30)
            for payload in [{"stream": True}, {"x": 1}]:
                conn.request(
                    "POST", "/logged?tenant=1", body=json.dumps(payload),
                    headers={"Content-Type": "application/json",
                             "X-Trace-Id": "trace-ka-1",
                             "X-Job-Id": "tenant-log"})
                resp = conn.getresponse()
                assert resp.status == 200
                # Trace id and job tag echo on unary and streamed
                # replies alike.
                assert resp.headers.get("X-Trace-Id") == "trace-ka-1"
                assert resp.headers.get("X-Job-Id") == "tenant-log"
                if payload.get("stream"):
                    _read_sse(resp)
                    resp.read()
                else:
                    resp.read()
            conn.close()
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and len(
                    caplog.records) < 2:
                time.sleep(0.05)
    finally:
        ray_config.serve_access_log = False

    lines = [json.loads(r.getMessage()) for r in caplog.records]
    assert len(lines) >= 2
    for line in lines[:2]:
        assert line["method"] == "POST"
        # Route is the NORMALIZED matched prefix (bounded cardinality);
        # the raw client path (query string and all) rides separately.
        assert line["route"] == "/logged"
        assert line["path"] == "/logged?tenant=1"
        assert line["status"] == 200
        assert line["latency_ms"] > 0
        assert line["trace_id"] == "trace-ka-1"
        assert line["job_id"] == "tenant-log"

    # And the request landed in the per-route/status latency stats.
    from ray_tpu._private import perf_stats

    stat = perf_stats.latency("serve_request_seconds",
                              tags={"route": "/logged",
                                    "status": "200"})
    assert stat.total >= 2


def test_request_trace_flows_to_replica_and_tasks(serve_up):
    """An HTTP request's trace id flows proxy → router → replica actor
    task → tasks the replica submits: one traceId, parent chain rooted
    at the request."""
    from ray_tpu.experimental import tracing

    @serve.deployment
    class Traced:
        def __call__(self, request):
            @ray_tpu.remote
            def nested(x):
                return x

            return {"nested": ray_tpu.get(nested.remote(7))}

    serve.run(Traced.bind(), route_prefix="/traced")
    proxy = serve.start_http_proxy()
    conn = http.client.HTTPConnection(proxy.host, proxy.port,
                                      timeout=30)
    conn.request("POST", "/traced", body=json.dumps({}),
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 200
    trace_id = resp.headers.get("X-Trace-Id")
    assert trace_id
    assert json.loads(resp.read()) == {"nested": 7}
    conn.close()

    spans = tracing.get_trace(trace_id)
    names = [s["name"] for s in spans]
    replica_span = next(s for s in spans
                        if "handle_request" in s["name"])
    nested_span = next(s for s in spans if "nested" in s["name"])
    # The request is the trace root: the replica call hangs off it, the
    # replica-submitted task hangs off the replica call.
    assert replica_span["parentSpanId"] == trace_id
    assert nested_span["traceId"] == trace_id
    assert nested_span["parentSpanId"] == replica_span["spanId"], names


@pytest.mark.slow
def test_no_head_of_line_starvation_under_load(serve_up):
    """Concurrent keep-alive clients + one slow-streaming client: the
    stream trickling for seconds must not stall the unary clients
    sharing the event loop (each connection is its own task; chunk
    writes await only their own transport)."""

    @serve.deployment(num_replicas=2, max_concurrent_queries=32)
    class Mixed:
        def __call__(self, request):
            if isinstance(request, dict) and request.get("stream"):
                def gen():
                    for i in range(8):
                        yield {"i": i}
                        time.sleep(0.25)
                return gen()
            return {"ok": True}

    serve.run(Mixed.bind(), route_prefix="/m")
    proxy = serve.start_http_proxy()
    hdrs = {"Content-Type": "application/json"}

    stream_items = []

    def slow_streamer():
        conn = http.client.HTTPConnection(proxy.host, proxy.port,
                                          timeout=60)
        conn.request("POST", "/m", body=json.dumps({"stream": True}),
                     headers=hdrs)
        stream_items.extend(_read_sse(conn.getresponse()))
        conn.close()

    unary_lat = []
    lock = threading.Lock()
    stop = threading.Event()

    def unary_client():
        conn = http.client.HTTPConnection(proxy.host, proxy.port,
                                          timeout=60)
        while not stop.is_set():
            t0 = time.perf_counter()
            conn.request("POST", "/m", body=json.dumps({}),
                         headers=hdrs)
            resp = conn.getresponse()
            resp.read()
            assert resp.status == 200
            with lock:
                unary_lat.append(time.perf_counter() - t0)
        conn.close()

    streamer = threading.Thread(target=slow_streamer)
    clients = [threading.Thread(target=unary_client) for _ in range(4)]
    streamer.start()
    for c in clients:
        c.start()
    streamer.join(timeout=60)
    stop.set()
    for c in clients:
        c.join(timeout=30)

    assert [c["i"] for c in stream_items] == list(range(8))
    assert len(unary_lat) > 50, \
        f"unary clients starved: {len(unary_lat)} requests in ~2s+"
    unary_lat.sort()
    p95 = unary_lat[int(len(unary_lat) * 0.95)]
    # The stream spans ~2s; unary requests must keep completing far
    # faster than a stream chunk interval throughout.
    assert p95 < 1.0, f"head-of-line starvation: unary p95={p95:.3f}s"
