"""Importable sample application for schema/CLI tests."""

from ray_tpu import serve


@serve.deployment
class Doubler:
    def __call__(self, x):
        return 2 * x


@serve.deployment(name="adder")
class Adder:
    def __init__(self, doubler, offset=1):
        self.doubler = doubler
        self.offset = offset

    def __call__(self, x):
        import ray_tpu

        return ray_tpu.get(self.doubler.remote(x)) + self.offset


app = Adder.bind(Doubler.bind())
