"""Regression tests for the concurrency fixes the raylint rules drove.

Each test pins ONE fixed invariant:

- R2 (router): the dispatch RPC runs with the router lock RELEASED,
  and the ``_reserved`` slot accounting keeps the per-replica cap exact
  while a send is in flight (no oversubscription, no lock-holding).
- R2 (router): the controller metrics report is sent with the lock
  released — a stalled controller send must never block dispatchers.
- R1 (util.queue): ``Queue.shutdown(block=False)`` returns without
  waiting on the kill RPC — the form event-loop consumers
  (``aiter_stream`` teardown) must use.
- R4 (rpc): ``CoalescingBatcher.close(drain_timeout=...)`` hands every
  accepted item to send_frame before returning (the shutdown-boundary
  contract); the default close stays non-blocking.
- R4 (serve.batch): ``_Batcher.shutdown`` retires the drain thread and
  still completes work queued before the call.
- R4 (gcs): the base ``StoreClient.close`` flushes, so every backend
  inherits durability at teardown unless it overrides both.
- R4 (cluster): ``drain_channels`` flush-closes every submit batcher
  and pipelined channel exactly once at the shutdown boundary.
"""

import threading
import time

import pytest

import ray_tpu
from ray_tpu._private.rpc import CoalescingBatcher
from ray_tpu.serve._private.router import Router


class _FakeMethod:
    def __init__(self, fn):
        self._fn = fn

    def remote(self, *args, **kwargs):
        return self._fn(*args, **kwargs)


class _FakeController:
    """Enough controller surface for a Router: long-poll listens fail
    (the client backs off quietly) and metric reports are recorded."""

    def __init__(self):
        self.reports = []
        self.listen = _FakeMethod(self._listen)
        self.record_handle_metrics = _FakeMethod(
            lambda dep, total: self.reports.append((dep, total)))

    def _listen(self, *a, **k):
        raise RuntimeError("no controller in this test")


class _Replica:
    def __init__(self, fn):
        self.handle_request = _FakeMethod(fn)


def _make_router(replica, max_concurrent):
    router = Router(_FakeController(), "dep",
                    max_concurrent_queries=max_concurrent)
    router._update_replicas([replica])
    return router


def test_router_lock_released_during_dispatch(ray_start_regular):
    """The fixed invariant itself: while the dispatch RPC executes,
    another thread can take the router lock."""
    lock_free_during_send = []

    def handle(method, args, kwargs):
        # Probe from ANOTHER thread: the router lock is a Condition
        # over an RLock, so probing from this thread would succeed
        # reentrantly even if dispatch still held it.
        result = []

        def probe():
            got = router._lock.acquire(timeout=1.0)
            result.append(got)
            if got:
                router._lock.release()

        t = threading.Thread(target=probe)
        t.start()
        t.join()
        lock_free_during_send.append(result[0])
        return ray_tpu.put("ok")

    router = _make_router(_Replica(handle), max_concurrent=4)
    try:
        ref = router.try_assign_request("__call__", (), {})
        assert ref is not None and ray_tpu.get(ref) == "ok"
        assert lock_free_during_send == [True], (
            "router lock was held across the dispatch RPC")
    finally:
        router.shutdown()


def test_router_reserved_slots_prevent_oversubscription(
        ray_start_regular):
    """A dispatch mid-send counts against the cap: a concurrent
    dispatcher must get None, not a second slot on the same replica."""
    in_send = threading.Event()
    release = threading.Event()
    refs = []

    def handle(method, args, kwargs):
        in_send.set()
        assert release.wait(5.0)
        return ray_tpu.put("ok")

    router = _make_router(_Replica(handle), max_concurrent=1)
    try:
        t = threading.Thread(
            target=lambda: refs.append(
                router.try_assign_request("__call__", (), {})))
        t.start()
        assert in_send.wait(5.0)
        # First dispatch is parked inside the send; its slot is only
        # *reserved* (not yet in _in_flight) — the cap must still hold.
        assert router.try_assign_request("__call__", (), {}) is None
        release.set()
        t.join(5.0)
        assert refs and refs[0] is not None
        assert ray_tpu.get(refs[0]) == "ok"
    finally:
        router.shutdown()


def test_router_metrics_report_sent_outside_lock(ray_start_regular):
    """A controller send that itself needs the router lock (worst-case
    stand-in for 'slow send') must not deadlock the reporter path."""
    controller = _FakeController()
    recorded = []

    def record(dep, total):
        # Would deadlock if _send_report ran under router._lock.
        got = router._lock.acquire(timeout=1.0)
        assert got, "metrics report was sent while holding router lock"
        router._lock.release()
        recorded.append(total)

    controller.record_handle_metrics = _FakeMethod(record)
    router = Router(controller, "dep", max_concurrent_queries=4)
    router._update_replicas(
        [_Replica(lambda m, a, k: ray_tpu.put("ok"))])
    try:
        router._last_report = 0.0  # open the rate-limit window
        ref = router.try_assign_request("__call__", (), {})
        assert ref is not None
        assert recorded, "dispatch did not ship a metrics report"
    finally:
        router.shutdown()


def test_queue_shutdown_nonblocking_returns_promptly(
        ray_start_regular, monkeypatch):
    from ray_tpu.util.queue import Queue

    q = Queue(maxsize=2)
    q.put(1)

    killed = threading.Event()
    real_kill = ray_tpu.kill

    def slow_kill(actor, **kw):
        time.sleep(0.5)
        real_kill(actor, **kw)
        killed.set()

    monkeypatch.setattr(ray_tpu, "kill", slow_kill)
    t0 = time.monotonic()
    q.shutdown(block=False)
    elapsed = time.monotonic() - t0
    assert elapsed < 0.3, (
        f"shutdown(block=False) blocked for {elapsed:.2f}s — it must "
        f"hand the kill RPC to a worker thread (event-loop callers)")
    assert killed.wait(5.0), "async shutdown never killed the actor"


def test_batcher_close_drain_timeout_delivers_everything():
    sent = []
    gate = threading.Event()

    def send(batch):
        gate.wait(5.0)  # first frame parks until the test says go
        sent.extend(batch)
        time.sleep(0.01)

    batcher = CoalescingBatcher(send, name="test-drain")
    for i in range(50):
        batcher.add(i)
    gate.set()
    batcher.close(drain_timeout=5.0)
    assert sorted(sent) == list(range(50)), (
        "close(drain_timeout) returned before every accepted item was "
        "handed to send_frame")
    with pytest.raises(ConnectionError):
        batcher.add(99)


def test_batcher_default_close_stays_nonblocking():
    release = threading.Event()

    def send(batch):
        release.wait(5.0)

    batcher = CoalescingBatcher(send, name="test-noblock")
    batcher.add(1)
    time.sleep(0.05)  # let the flusher pick the frame up and park
    t0 = time.monotonic()
    batcher.close()  # failure-path form: must not wait on our own send
    assert time.monotonic() - t0 < 0.2
    release.set()


def test_serve_batch_shutdown_drains_then_retires():
    from ray_tpu.serve.batching import batch

    calls = []

    @batch(max_batch_size=4, batch_wait_timeout_s=0.01)
    def handler(items):
        calls.append(list(items))
        return [x * 2 for x in items]

    futures = [handler._submit((i,)) for i in range(6)]
    handler.shutdown(timeout=5.0)
    assert [f.result(timeout=5.0) for f in futures] == [
        0, 2, 4, 6, 8, 10], "queued work was dropped by shutdown"
    for b in handler._batchers.values():
        assert not b._thread.is_alive(), "batcher thread not retired"


def test_serve_batch_submit_after_shutdown_fails_fast():
    from ray_tpu.serve.batching import batch

    @batch(max_batch_size=2, batch_wait_timeout_s=0.01)
    def handler(items):
        return items

    assert handler._submit((1,)).result(timeout=5.0) == 1
    handler.shutdown(timeout=5.0)
    f = handler._submit((2,))
    with pytest.raises(RuntimeError, match="shut down"):
        f.result(timeout=5.0)


def test_store_client_base_close_flushes():
    from ray_tpu._private.gcs_storage import StoreClient

    class Recorder(StoreClient):
        def __init__(self):
            self.flushed = 0

        def flush(self):
            self.flushed += 1

    rec = Recorder()
    rec.close()
    assert rec.flushed == 1, (
        "StoreClient.close must flush — backends inheriting close() "
        "get the at-teardown durability contract for free")


def test_cluster_drain_channels_flush_closes_once():
    from ray_tpu.cluster_utils import ClusterBackendMixin

    class FakeChannel:
        def __init__(self):
            self.closed_with = []

        def close(self, drain_timeout=None, flush_timeout=None):
            self.closed_with.append((drain_timeout, flush_timeout))

    from types import SimpleNamespace

    backend = ClusterBackendMixin.__new__(ClusterBackendMixin)
    backend._lease_lock = threading.Lock()
    backend._lease_locks = [threading.Lock()]
    # Tenancy-drain state the real __init__ would set up.
    backend._quota_stop = threading.Event()
    backend._quota_drainer = None
    backend._park_thread = None
    backend._fallback_ledger = None
    backend.local_backend = SimpleNamespace()
    batcher, pipe = FakeChannel(), FakeChannel()
    backend._batchers = {"n1": batcher}
    backend._pipes = {"n1": pipe}
    backend._leases = {"shape": [{"node_id": "n1"}]}

    backend.drain_channels(timeout=1.5)
    assert batcher.closed_with == [(1.5, None)]
    assert pipe.closed_with == [(None, 1.5)]
    assert not backend._batchers and not backend._pipes \
        and not backend._leases
    backend.drain_channels(timeout=1.5)  # idempotent
    assert batcher.closed_with == [(1.5, None)]


def test_serve_batch_stale_retire_sentinel_does_not_strand_work():
    """retire() can race the drain thread's idle exit, leaving its
    sentinel in an empty queue; the next submit's respawned thread
    must hand off past the stale sentinel instead of eating it and
    stranding the submitted item's future."""
    from ray_tpu.serve.batching import batch

    @batch(max_batch_size=2, batch_wait_timeout_s=0.01)
    def handler(items):
        return [x + 1 for x in items]

    assert handler._submit((1,)).result(timeout=5.0) == 2
    b = next(iter(handler._batchers.values()))
    b._thread.join(6.0)  # let the drain thread idle out (5s poll)
    assert not b._thread.is_alive()
    b.queue.put(b._STOP)  # the lost-race retire sentinel
    f = handler._submit((41,))
    assert f.result(timeout=5.0) == 42, (
        "stale retire sentinel stranded a submitted item")
    handler.shutdown(timeout=5.0)
