"""Proxy-actor fleet: HTTP service from actors fed by the controller's
route long-poll channel."""

import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(autouse=True)
def ray():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def _post(url, payload):
    req = urllib.request.Request(
        url, method="POST", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read())


def test_proxy_actor_routes_and_updates():
    @serve.deployment
    class Doubler:
        def __call__(self, x):
            return 2 * x

    serve.run(Doubler.bind(), route_prefix="/double")

    fleet = serve.start_proxy_fleet(num_proxies=2)
    assert len(fleet) == 2
    try:
        for _actor, (host, port) in fleet:
            out = _post(f"http://{host}:{port}/double", 21)
            assert out == 42

        # A route added AFTER the fleet started propagates via long-poll.
        @serve.deployment
        class Tripler:
            def __call__(self, x):
                return 3 * x

        serve.run(Tripler.bind(), route_prefix="/triple")
        _actor, (host, port) = fleet[0]
        deadline = time.monotonic() + 15
        ok = False
        while time.monotonic() < deadline and not ok:
            try:
                ok = _post(f"http://{host}:{port}/triple", 10) == 30
            except Exception:
                time.sleep(0.2)
        assert ok

        # Unknown path -> 404
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://{host}:{port}/nope", timeout=10)
        assert ei.value.code == 404
    finally:
        for actor, _addr in fleet:
            ray_tpu.get(actor.shutdown.remote())


def test_delete_retracts_routes_from_proxies():
    @serve.deployment
    class Echo:
        def __call__(self, x):
            return x

    serve.run(Echo.bind(), route_prefix="/echo")
    fleet = serve.start_proxy_fleet(num_proxies=1)
    try:
        _actor, (host, port) = fleet[0]
        assert _post(f"http://{host}:{port}/echo", 7) == 7
        serve.delete("Echo")
        deadline = time.monotonic() + 15
        gone = False
        while time.monotonic() < deadline and not gone:
            try:
                _post(f"http://{host}:{port}/echo", 7)
                time.sleep(0.2)
            except urllib.error.HTTPError as e:
                gone = e.code == 404
        assert gone, "route survived serve.delete"
    finally:
        for actor, _addr in fleet:
            ray_tpu.get(actor.shutdown.remote())
