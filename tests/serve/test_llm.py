"""LLM engine tests: KV-cache correctness + continuous batching +
prefix-cache reuse + admission behavior under slot pressure."""

import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ray_tpu._private.config import ray_config
from ray_tpu.models.llama import (
    LlamaConfig,
    forward,
    forward_with_cache,
    init_kv_cache,
    init_params,
)
from ray_tpu.serve.llm import (
    LLMEngine,
    PromptTooLongError,
    SamplingParams,
)

# Multi-process / soak tests: excluded from the quick
# tier (pytest -m 'not slow').
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def model():
    cfg = LlamaConfig.debug()
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def naive_greedy(cfg, params, prompt, n_tokens):
    """Generate by re-running the full forward each step (ground truth)."""
    tokens = list(prompt)
    for _ in range(n_tokens):
        logits = forward(params, jnp.asarray([tokens]), cfg)
        tokens.append(int(logits[0, -1].argmax()))
    return tokens[len(prompt):]


def test_cache_prefill_matches_full_forward(model):
    cfg, params = model
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                cfg.vocab_size)
    full = forward(params, tokens, cfg)
    cache = init_kv_cache(cfg, 2, 32)
    cached, _ = forward_with_cache(params, tokens, cfg, cache,
                                   jnp.zeros(2, jnp.int32))
    np.testing.assert_allclose(np.asarray(cached), np.asarray(full),
                               rtol=2e-4, atol=2e-4)


def test_cache_incremental_matches_full(model):
    cfg, params = model
    tokens = jax.random.randint(jax.random.PRNGKey(2), (1, 12), 0,
                                cfg.vocab_size)
    full = forward(params, tokens, cfg)
    cache = init_kv_cache(cfg, 1, 32)
    # Prefill 8, then decode 4 one at a time.
    _, cache = forward_with_cache(params, tokens[:, :8], cfg, cache,
                                  jnp.zeros(1, jnp.int32))
    outs = []
    for i in range(8, 12):
        logits, cache = forward_with_cache(
            params, tokens[:, i:i + 1], cfg, cache,
            jnp.asarray([i], jnp.int32))
        outs.append(logits[:, 0])
    got = jnp.stack(outs, axis=1)
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(full[:, 8:12]),
                               rtol=2e-4, atol=2e-4)


def test_engine_greedy_matches_naive(model):
    cfg, params = model
    prompt = [3, 17, 42, 8]
    expected = naive_greedy(cfg, params, prompt, 8)
    engine = LLMEngine(cfg, params, max_batch_size=2, max_seq_len=64)
    got = engine.generate(prompt, SamplingParams(max_tokens=8))
    engine.stop()
    assert got == expected


def test_engine_concurrent_requests(model):
    cfg, params = model
    engine = LLMEngine(cfg, params, max_batch_size=4, max_seq_len=64)
    prompts = [[1, 2, 3], [9, 8], [5, 5, 5, 5], [7], [11, 13], [2, 4, 6]]
    expected = [naive_greedy(cfg, params, p, 6) for p in prompts]

    import threading

    results = [None] * len(prompts)

    def worker(i):
        results[i] = engine.generate(prompts[i],
                                     SamplingParams(max_tokens=6))

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    engine.stop()
    for got, exp in zip(results, expected):
        assert got == exp


def test_engine_streaming_and_metrics(model):
    cfg, params = model
    engine = LLMEngine(cfg, params, max_batch_size=2, max_seq_len=64)
    stream = engine.generate([4, 2], SamplingParams(max_tokens=5),
                             stream=True)
    tokens = list(stream)
    assert len(tokens) == 5
    m = engine.metrics()
    assert m["active_slots"] == 0 and m["free_slots"] == 2
    engine.stop()


# -- PR 16: prefix/KV cache + admission behavior -------------------------


def test_prompt_longer_than_cap_rejected_typed(model):
    """The old behavior silently truncated the prompt HEAD (corrupting
    answers); now an over-cap prompt fails loudly with a typed error
    before any slot/queue resource is touched."""
    cfg, params = model
    engine = LLMEngine(cfg, params, max_batch_size=2, max_seq_len=16)
    with pytest.raises(PromptTooLongError) as ei:
        engine.generate(list(range(1, 30)), SamplingParams(max_tokens=2))
    assert ei.value.n_tokens == 29 and ei.value.cap == 15
    m = engine.metrics()
    assert m["queued"] == 0 and m["active_slots"] == 0
    engine.stop()


def test_slot_exhaustion_parks_then_admits(model):
    """More concurrent requests than slots: the overflow request parks
    in the queue (never dropped, never doubly assigned) and admits as
    soon as a retirement frees a slot — continuous batching's core
    contract."""
    cfg, params = model
    engine = LLMEngine(cfg, params, max_batch_size=2, max_seq_len=64)
    prompts = [[1, 2], [3, 4], [5, 6], [7, 8], [9, 10]]
    expected = [naive_greedy(cfg, params, p, 4) for p in prompts]
    results = [None] * len(prompts)

    def worker(i):
        results[i] = engine.generate(prompts[i],
                                     SamplingParams(max_tokens=4))

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(len(prompts))]
    for t in threads:
        t.start()
    # With 2 slots and 5 requests, at least one must park mid-flight.
    deadline = time.monotonic() + 30
    saw_queued = False
    while time.monotonic() < deadline and not saw_queued:
        if engine.metrics()["queued"] > 0:
            saw_queued = True
        time.sleep(0.001)
    for t in threads:
        t.join(timeout=120)
    engine.stop()
    assert saw_queued, "5 requests over 2 slots never queued"
    for got, exp in zip(results, expected):
        assert got == exp


def test_retired_slot_reuse_never_leaks_prior_tokens(model):
    """A slot retired by request A and re-admitted for request B must
    produce exactly B's tokens: stale KV from A beyond B's length can
    never be attended (positions are overwritten before any query
    reaches them). Run a LONG request then a SHORT one through a
    1-slot engine — same slot, different lengths — and cross-check
    the short one against ground truth."""
    cfg, params = model
    engine = LLMEngine(cfg, params, max_batch_size=1, max_seq_len=64)
    long_prompt = list(range(1, 25))
    short_prompt = [42, 7]
    exp_long = naive_greedy(cfg, params, long_prompt, 6)
    exp_short = naive_greedy(cfg, params, short_prompt, 6)
    assert engine.generate(long_prompt,
                           SamplingParams(max_tokens=6)) == exp_long
    assert engine.generate(short_prompt,
                           SamplingParams(max_tokens=6)) == exp_short
    engine.stop()


def test_prefix_cache_greedy_identical_and_hits(model, monkeypatch):
    """The tentpole's correctness bar: greedy output is TOKEN-IDENTICAL
    with the prefix cache on vs off (copied-in KV blocks are
    byte-equivalent to recomputed prefill), and the shared-head
    workload actually HITS the cache (the perf claim isn't vacuous)."""
    cfg, params = model
    monkeypatch.setattr(ray_config, "llm_kv_block_tokens", 4)
    monkeypatch.setattr(ray_config, "llm_prefix_shm_tier", False)
    shared = list(range(1, 18))  # 17 tokens = 4 full blocks + tail
    prompts = [shared + [50 + i] for i in range(4)]

    def run(cache_on):
        monkeypatch.setattr(ray_config, "llm_prefix_cache", cache_on)
        engine = LLMEngine(cfg, params, max_batch_size=2,
                           max_seq_len=64, model="m")
        outs = [engine.generate(p, SamplingParams(max_tokens=6))
                for p in prompts]
        stats = engine.prefix_cache.stats() if engine.prefix_cache \
            else None
        engine.stop()
        return outs, stats

    off, off_stats = run(False)
    on, on_stats = run(True)
    assert off_stats is None
    assert on == off, "prefix cache changed greedy output"
    assert on_stats["hits"] >= 3 * 4, on_stats  # 4 shared blocks x 3 reqs
    assert on_stats["blocks"] > 0 and on_stats["bytes"] > 0


def test_multi_model_chain_seeds_never_cross_hit(model):
    """Two models on one replica must never share prefix-cache keys:
    the chain seed commits to the model identity, so identical prompts
    under different models produce disjoint chains."""
    from ray_tpu._private.kv_cache import chain_keys

    cfg, params = model
    engine_a = LLMEngine(cfg, params, max_batch_size=1, model="a")
    engine_b = LLMEngine(cfg, params, max_batch_size=1, model="b")
    toks = list(range(32))
    ka = chain_keys(toks, 16, engine_a._chain_seed)
    kb = chain_keys(toks, 16, engine_b._chain_seed)
    assert ka and kb and not (set(ka) & set(kb))
    engine_a.stop()
    engine_b.stop()
