"""LLM engine tests: KV-cache correctness + continuous batching."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ray_tpu.models.llama import (
    LlamaConfig,
    forward,
    forward_with_cache,
    init_kv_cache,
    init_params,
)
from ray_tpu.serve.llm import LLMEngine, SamplingParams

# Multi-process / soak tests: excluded from the quick
# tier (pytest -m 'not slow').
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def model():
    cfg = LlamaConfig.debug()
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def naive_greedy(cfg, params, prompt, n_tokens):
    """Generate by re-running the full forward each step (ground truth)."""
    tokens = list(prompt)
    for _ in range(n_tokens):
        logits = forward(params, jnp.asarray([tokens]), cfg)
        tokens.append(int(logits[0, -1].argmax()))
    return tokens[len(prompt):]


def test_cache_prefill_matches_full_forward(model):
    cfg, params = model
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                cfg.vocab_size)
    full = forward(params, tokens, cfg)
    cache = init_kv_cache(cfg, 2, 32)
    cached, _ = forward_with_cache(params, tokens, cfg, cache,
                                   jnp.zeros(2, jnp.int32))
    np.testing.assert_allclose(np.asarray(cached), np.asarray(full),
                               rtol=2e-4, atol=2e-4)


def test_cache_incremental_matches_full(model):
    cfg, params = model
    tokens = jax.random.randint(jax.random.PRNGKey(2), (1, 12), 0,
                                cfg.vocab_size)
    full = forward(params, tokens, cfg)
    cache = init_kv_cache(cfg, 1, 32)
    # Prefill 8, then decode 4 one at a time.
    _, cache = forward_with_cache(params, tokens[:, :8], cfg, cache,
                                  jnp.zeros(1, jnp.int32))
    outs = []
    for i in range(8, 12):
        logits, cache = forward_with_cache(
            params, tokens[:, i:i + 1], cfg, cache,
            jnp.asarray([i], jnp.int32))
        outs.append(logits[:, 0])
    got = jnp.stack(outs, axis=1)
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(full[:, 8:12]),
                               rtol=2e-4, atol=2e-4)


def test_engine_greedy_matches_naive(model):
    cfg, params = model
    prompt = [3, 17, 42, 8]
    expected = naive_greedy(cfg, params, prompt, 8)
    engine = LLMEngine(cfg, params, max_batch_size=2, max_seq_len=64)
    got = engine.generate(prompt, SamplingParams(max_tokens=8))
    engine.stop()
    assert got == expected


def test_engine_concurrent_requests(model):
    cfg, params = model
    engine = LLMEngine(cfg, params, max_batch_size=4, max_seq_len=64)
    prompts = [[1, 2, 3], [9, 8], [5, 5, 5, 5], [7], [11, 13], [2, 4, 6]]
    expected = [naive_greedy(cfg, params, p, 6) for p in prompts]

    import threading

    results = [None] * len(prompts)

    def worker(i):
        results[i] = engine.generate(prompts[i],
                                     SamplingParams(max_tokens=6))

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    engine.stop()
    for got, exp in zip(results, expected):
        assert got == exp


def test_engine_streaming_and_metrics(model):
    cfg, params = model
    engine = LLMEngine(cfg, params, max_batch_size=2, max_seq_len=64)
    stream = engine.generate([4, 2], SamplingParams(max_tokens=5),
                             stream=True)
    tokens = list(stream)
    assert len(tokens) == 5
    m = engine.metrics()
    assert m["active_slots"] == 0 and m["free_slots"] == 2
    engine.stop()
