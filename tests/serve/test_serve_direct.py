"""Replica-direct dispatch + priority shedding + shared membership:

- steady-state HTTP requests skip the router entirely (hop counters +
  per-response ``X-Serve-Path`` prove it), falling back to the routed
  path on cold tables and replica death;
- the two dispatch paths share one per-replica concurrency budget;
- load-shed 503s are accounted at the shed point — route/status
  latency records (what SLO burn reads), the job-tagged request
  counter, and the class-tagged shed counter;
- priority classes shed lowest-first with Retry-After honored;
- replica membership fans out ONCE per process (one long-poll client
  per deployment, shared by every router and the direct table).
"""

import http.client
import json
import threading
import time

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu._private import perf_stats
from ray_tpu._private import tenancy
from ray_tpu._private.config import ray_config


@pytest.fixture
def serve_up():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def _post(port, path, payload=None, headers=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        conn.request("POST", path, body=json.dumps(payload or {}),
                     headers={"Content-Type": "application/json",
                              **(headers or {})})
        resp = conn.getresponse()
        body = resp.read()
        return resp.status, dict(resp.headers), body
    finally:
        conn.close()


def _hops():
    return {hop: perf_stats.counter("serve_hops", {"hop": hop}).value
            for hop in ("router", "direct", "fallback")}


def test_direct_path_skips_router_steady_state(serve_up):
    """After warmup, keep-alive traffic dispatches proxy→replica with
    ZERO router hops — the tentpole's headline property, read from the
    hop counters and every response's X-Serve-Path header."""

    @serve.deployment(num_replicas=2, max_concurrent_queries=8)
    class Echo:
        def __call__(self, payload):
            return {"echo": payload}

    serve.run(Echo.bind(), route_prefix="/echo")
    proxy = serve.start_http_proxy()

    conn = http.client.HTTPConnection("127.0.0.1", proxy.port,
                                      timeout=30)
    try:
        # Warmup: the first requests may route while the membership
        # watch delivers its first snapshot.
        deadline = time.monotonic() + 15
        warmed = False
        while not warmed and time.monotonic() < deadline:
            conn.request("POST", "/echo", body=json.dumps(1),
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            resp.read()
            warmed = resp.headers.get("X-Serve-Path") == "direct"
            if not warmed:
                time.sleep(0.05)
        assert warmed, "direct path never warmed up"

        before = _hops()
        for i in range(30):
            conn.request("POST", "/echo", body=json.dumps(i),
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            body = json.loads(resp.read())
            assert resp.status == 200 and body == {"echo": i}
            assert resp.headers.get("X-Serve-Path") == "direct"
        after = _hops()
    finally:
        conn.close()
    assert after["direct"] - before["direct"] == 30
    assert after["router"] == before["router"], (before, after)
    assert proxy.stats()["direct_served"] >= 30


def test_direct_disabled_routes_everything(serve_up, monkeypatch):
    monkeypatch.setattr(ray_config, "serve_replica_direct", False)

    @serve.deployment
    class Echo:
        def __call__(self, payload):
            return {"echo": payload}

    serve.run(Echo.bind(), route_prefix="/echo")
    proxy = serve.start_http_proxy()
    before = _hops()
    status, headers, _body = _post(proxy.port, "/echo", 1)
    after = _hops()
    assert status == 200
    assert headers.get("X-Serve-Path") == "routed"
    assert after["router"] == before["router"] + 1
    assert after["direct"] == before["direct"]


def test_direct_replica_death_falls_back_exactly_once(serve_up):
    """Kill one replica under a warmed direct table: requests keep
    succeeding (fallback through the routed path, which re-checks
    membership), the dead replica is invalidated, and nothing executes
    twice (execution counts per request id stay <= 1)."""
    counts = {}
    lock = threading.Lock()

    @serve.deployment(num_replicas=2, max_concurrent_queries=8)
    class Count:
        def __call__(self, payload):
            with lock:
                counts[payload] = counts.get(payload, 0) + 1
            return {"id": payload}

    serve.run(Count.bind(), route_prefix="/count")
    proxy = serve.start_http_proxy()
    # Warm the direct table (unique ids: every request executes once).
    deadline = time.monotonic() + 15
    warm = 0
    while time.monotonic() < deadline:
        warm += 1
        _status, headers, _ = _post(proxy.port, "/count", f"warm{warm}")
        if headers.get("X-Serve-Path") == "direct":
            break
        time.sleep(0.05)
    from ray_tpu._private.worker import global_worker

    names = [n for n in global_worker().gcs.list_named_actors()
             if str(n).startswith("SERVE_REPLICA::Count::")]
    assert len(names) == 2
    victim = ray_tpu.get_actor(names[0])
    ray_tpu.kill(victim)
    ok = 0
    for i in range(20):
        status, _headers, _body = _post(proxy.port, "/count", f"r{i}")
        if status == 200:
            ok += 1
    assert ok == 20, f"only {ok}/20 succeeded after replica death"
    with lock:
        over = {k: v for k, v in counts.items() if v > 1}
    assert not over, f"double-dispatched requests: {over}"


def test_shed_503_accounted_at_shed_point(serve_up):
    """A proxy-fast-path 503 (in-flight cap) is visible to per-job
    accounting and SLO burn the moment it happens: the route/status
    latency dist gains a status=503 record, the job-tagged request
    counter ticks, and the class-tagged shed counter ticks."""
    release = threading.Event()

    @serve.deployment(max_concurrent_queries=8)
    class Block:
        def __call__(self, payload):
            release.wait(30)
            return {"ok": True}

    serve.run(Block.bind(), route_prefix="/block")
    proxy = serve.start_http_proxy(max_in_flight=1, queue_timeout_s=1.0)

    def blocker():
        _post(proxy.port, "/block")

    t = threading.Thread(target=blocker)
    t.start()
    try:
        deadline = time.monotonic() + 10
        while proxy.stats()["in_flight"] < 1:
            assert time.monotonic() < deadline
            time.sleep(0.02)
        status, headers, _body = _post(
            proxy.port, "/block", headers={"X-Job-Id": "job-shed"})
        assert status == 503
        assert headers.get("Retry-After") is not None
        # Accounted at the shed point, all three surfaces. (The
        # request-envelope records land one loop tick after the
        # response bytes, so poll briefly.)
        shed = perf_stats.counter(
            "serve_requests_shed",
            {"route": "/block", "job": "job-shed",
             "class": "normal"}).value
        assert shed >= 1
        reqs = perf_stats.counter(
            "serve_requests", {"route": "/block", "job": "job-shed"})
        dist = perf_stats.dist(
            "serve_request_seconds",
            tags={"route": "/block", "status": "503"},
            bounds=perf_stats.SERVE_LATENCY_BOUNDS)
        deadline = time.monotonic() + 5
        while (reqs.value < 1 or dist.total < 1) and \
                time.monotonic() < deadline:
            time.sleep(0.02)
        assert reqs.value >= 1
        assert dist.total >= 1
    finally:
        release.set()
        t.join(timeout=30)


def test_priority_classes_shed_lowest_first(serve_up, monkeypatch):
    """Layered priority admission: with in-flight at half the cap, a
    low-priority request sheds (503 + Retry-After) while normal and
    high still serve; a malformed X-Priority value is just normal."""
    monkeypatch.setattr(ray_config, "serve_priority_shed_fractions",
                        "1.0,1.0,0.5")
    release = threading.Event()
    started = threading.Semaphore(0)

    @serve.deployment(max_concurrent_queries=8)
    class Block:
        def __call__(self, payload):
            if payload == "hold":
                started.release()
                release.wait(30)
            return {"ok": True}

    serve.run(Block.bind(), route_prefix="/p")
    proxy = serve.start_http_proxy(max_in_flight=4)
    holders = [threading.Thread(
        target=lambda: _post(proxy.port, "/p", "hold"))
        for _ in range(2)]
    for t in holders:
        t.start()
    try:
        assert started.acquire(timeout=10)
        assert started.acquire(timeout=10)
        deadline = time.monotonic() + 10
        while proxy.stats()["in_flight"] < 2:
            assert time.monotonic() < deadline
            time.sleep(0.02)
        # in_flight == 2 == 0.5 * max_in_flight: low sheds...
        status, headers, _ = _post(proxy.port, "/p", "x",
                                   headers={"X-Priority": "low"})
        assert status == 503
        assert headers.get("Retry-After") is not None
        # ...normal and high still serve; junk degrades to normal.
        for prio in ("normal", "high", "2junk"):
            status, _h, _b = _post(proxy.port, "/p", "x",
                                   headers={"X-Priority": prio})
            assert status == 200, prio
        shed = perf_stats.counter("serve_priority_shed",
                                  {"class": "low"}).value
        assert shed >= 1
    finally:
        release.set()
        for t in holders:
            t.join(timeout=30)


def test_priority_rate_bucket_sheds_with_headroom(serve_up,
                                                  monkeypatch):
    """A per-class token bucket sheds a class over its rate even when
    the proxy has in-flight headroom, with the bucket's computed
    accrual time on Retry-After."""
    monkeypatch.setattr(ray_config, "serve_priority_rates", "low=1:1")

    @serve.deployment
    class Echo:
        def __call__(self, payload):
            return {"ok": True}

    serve.run(Echo.bind(), route_prefix="/rl")
    proxy = serve.start_http_proxy()
    status, _h, _b = _post(proxy.port, "/rl", 1,
                           headers={"X-Priority": "low"})
    assert status == 200  # burst of 1
    status, headers, _b = _post(proxy.port, "/rl", 2,
                                headers={"X-Priority": "low"})
    assert status == 503
    assert int(headers.get("Retry-After", 0)) >= 1
    # Other classes unaffected.
    status, _h, _b = _post(proxy.port, "/rl", 3)
    assert status == 200


def test_parse_priority_grammar():
    assert tenancy.parse_priority("high") == 0
    assert tenancy.parse_priority("NORMAL") == 1
    assert tenancy.parse_priority("low") == 2
    assert tenancy.parse_priority("0") == 0
    assert tenancy.parse_priority("2") == 2
    assert tenancy.parse_priority("") == 1
    assert tenancy.parse_priority("7") == 1
    assert tenancy.parse_priority("urgent!!") == 1
    assert tenancy.parse_shed_fractions("1.0,0.9,0.5") == (1.0, 0.9, 0.5)
    assert tenancy.parse_shed_fractions("junk") == (1.0, 1.0, 1.0)
    assert tenancy.parse_shed_fractions("0.5") == (0.5, 1.0, 1.0)


def test_membership_fans_out_once_per_process(serve_up):
    """Two handles (four routers/dispatchers worth of subscribers)
    share ONE long-poll client per deployment: membership changes fan
    out once per process, not once per router."""

    @serve.deployment(num_replicas=1)
    class Echo:
        def __call__(self, payload):
            return payload

    handle_a = serve.run(Echo.bind(), route_prefix="/echo")
    handle_b = serve.get_deployment_handle("Echo")
    assert ray_tpu.get(handle_a.remote(1), timeout=30) == 1
    assert ray_tpu.get(handle_b.remote(2), timeout=30) == 2

    poll_threads = [t for t in threading.enumerate()
                    if t.name == "longpoll-replicas::Echo"]
    assert len(poll_threads) == 1, [t.name for t in poll_threads]

    # Both handles see a membership change through the shared watch:
    # scale to 2 and keep serving.
    controller = serve.get_or_create_controller()
    info = ray_tpu.get(
        controller.get_deployment_info.remote("Echo"))
    deploy_info = {"cls": Echo.func_or_class, "init_args": (),
                   "init_kwargs": {}, "num_replicas": 2,
                   "user_config": None, "max_concurrent_queries": 100,
                   "ray_actor_options": None,
                   "autoscaling_config": None,
                   "version": info["version"]}
    ray_tpu.get(controller.deploy.remote("Echo", deploy_info))
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        info = ray_tpu.get(
            controller.get_deployment_info.remote("Echo"))
        if info["num_replicas"] == 2:
            break
        time.sleep(0.05)
    assert info["num_replicas"] == 2
    assert ray_tpu.get(handle_b.remote(3), timeout=30) == 3
