"""Serve controller fault tolerance.

Reference: `serve/_private/storage/kv_store.py:1` (checkpointed target
state) + controller recovery in `serve/controller.py:70` ff. The
controller checkpoints {deployments, routes, replica names} to the GCS
KV on every mutation; replicas are named detached actors. Killing the
controller mid-serving must (a) not interrupt traffic (routers keep the
last replica snapshot), (b) let a replacement controller recover the
same target state and RE-ATTACH the live replicas, and (c) converge
back to HEALTHY."""

import time

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.serve._private.controller import (
    CONTROLLER_NAME,
    get_or_create_controller,
)


@pytest.fixture(autouse=True)
def ray():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def _wait(pred, timeout=20.0, msg=""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.1)
    raise AssertionError(f"timed out: {msg}")


def test_controller_crash_recovers_state_and_replicas():
    @serve.deployment(num_replicas=2, name="survivor")
    class Survivor:
        def __init__(self):
            import uuid

            self.uid = uuid.uuid4().hex

        def __call__(self, x):
            return {"x": x, "uid": self.uid}

    handle = serve.run(Survivor.bind(), route_prefix="/survivor")
    uids_before = {ray_tpu.get(handle.remote(i))["uid"]
                   for i in range(10)}
    assert len(uids_before) == 2  # both replicas answering

    controller = get_or_create_controller()
    routes_before = ray_tpu.get(controller.get_routes.remote())
    assert routes_before.get("/survivor") == "survivor"

    # Kill the controller (not graceful shutdown — no checkpoint wipe).
    ray_tpu.kill(controller)

    # (a) Traffic keeps flowing through the existing handle: the router
    # serves from its last long-poll snapshot; replicas are detached.
    out = ray_tpu.get(handle.remote("during-outage"))
    assert out["x"] == "during-outage"
    assert out["uid"] in uids_before

    # (b) A replacement controller recovers the checkpointed state.
    controller2 = get_or_create_controller()
    assert controller2._actor_id != controller._actor_id
    info = ray_tpu.get(
        controller2.get_deployment_info.remote("survivor"))
    assert info is not None, "deployment lost across controller restart"
    # Live replicas were re-attached, not cold-started: the SAME
    # replica uids keep answering.
    _wait(lambda: ray_tpu.get(controller2.get_deployment_info.remote(
        "survivor"))["status"] == "HEALTHY", msg="recovered HEALTHY")
    routes_after = ray_tpu.get(controller2.get_routes.remote())
    assert routes_after.get("/survivor") == "survivor"

    uids_after = {ray_tpu.get(handle.remote(i))["uid"]
                  for i in range(10)}
    assert uids_after == uids_before, "replicas were restarted, not " \
        "re-attached"

    # (c) The recovered controller still reconciles: scale up works.
    serve.run(Survivor.options(num_replicas=3).bind(),
              route_prefix="/survivor")
    _wait(lambda: serve.status()["survivor"]["num_replicas"] == 3,
          msg="scale-up after recovery")


def test_controller_crash_replica_death_requires_controller():
    """A replica dying while the controller is down stays down until a
    replacement controller reconciles it back — and the replacement
    does exactly that."""

    @serve.deployment(num_replicas=2, name="phoenix")
    def phoenix():
        return "alive"

    handle = serve.run(phoenix.bind())
    # Prime the handle's router while the controller is alive: a router
    # born during a controller outage has no membership source (same as
    # the reference) — FT covers established data paths.
    assert ray_tpu.get(handle.remote()) == "alive"
    controller = get_or_create_controller()
    # find the replica actors through the checkpointed names
    from ray_tpu._private.worker import global_worker

    names = [n for n in global_worker().gcs.list_named_actors()
             if str(n).startswith("SERVE_REPLICA::phoenix::")]
    assert len(names) == 2

    ray_tpu.kill(controller)
    # Kill one replica while there is no controller.
    victim = ray_tpu.get_actor(names[0])
    ray_tpu.kill(victim)

    # The survivor still answers through the handle. With no controller
    # to broadcast membership, requests round-robined onto the dead
    # replica fail (reference semantics during a controller outage) —
    # but retries land on the survivor.
    from ray_tpu.exceptions import ActorDiedError, ActorError

    answered = 0
    for _ in range(6):
        try:
            assert ray_tpu.get(handle.remote()) == "alive"
            answered += 1
        except (ActorDiedError, ActorError):
            pass
    assert answered >= 2, "survivor replica not reachable"

    # Replacement controller re-attaches the survivor and replaces the
    # dead replica to get back to 2.
    controller2 = get_or_create_controller()

    def back_to_two():
        info = ray_tpu.get(
            controller2.get_deployment_info.remote("phoenix"))
        return info and info["num_replicas"] == 2 and \
            info["status"] == "HEALTHY"

    _wait(back_to_two, msg="reconciled back to 2 replicas")
    assert ray_tpu.get(handle.remote()) == "alive"


@pytest.mark.slow
def test_controller_recovery_with_replicas_on_other_node():
    """Cluster mode: replicas live in a separate NODE process; the
    controller dies and its replacement must recover them through the
    cluster-wide named-actor directory + the head's KV checkpoint."""
    from ray_tpu.cluster_utils import Cluster

    ray_tpu.shutdown()
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 1})
    cluster.add_node(num_cpus=2)
    try:
        @serve.deployment(num_replicas=2, name="xnode",
                          ray_actor_options={"num_cpus": 1})
        class Echo:
            def __init__(self):
                import os

                self.pid = os.getpid()

            def __call__(self, x):
                return (self.pid, x)

        handle = serve.run(Echo.bind())
        pids = {ray_tpu.get(handle.remote(i), timeout=30)[0]
                for i in range(12)}
        assert len(pids) == 2

        controller = get_or_create_controller()
        ray_tpu.kill(controller)
        controller2 = get_or_create_controller()
        _wait(lambda: (ray_tpu.get(controller2.get_deployment_info
                                   .remote("xnode")) or {})
              .get("status") == "HEALTHY", timeout=30,
              msg="cluster recovery HEALTHY")
        # Same replica processes keep answering — re-attached, not
        # restarted.
        pids_after = {ray_tpu.get(handle.remote(i), timeout=30)[0]
                      for i in range(12)}
        assert pids_after == pids
    finally:
        serve.shutdown()
        cluster.shutdown()


def test_controller_restart_in_place_recovers():
    """The max_restarts=-1 path: the controller actor restarts IN PLACE
    (same actor id), re-runs __init__, and recovers from the KV
    checkpoint without anyone calling get_or_create_controller."""

    @serve.deployment(num_replicas=1, name="steady")
    def steady():
        return "ok"

    handle = serve.run(steady.bind())
    assert ray_tpu.get(handle.remote()) == "ok"
    controller = get_or_create_controller()
    ray_tpu.kill(controller, no_restart=False)  # crash, not teardown

    def recovered():
        try:
            info = ray_tpu.get(
                controller.get_deployment_info.remote("steady"),
                timeout=5)
            return info is not None and info["status"] == "HEALTHY"
        except Exception:
            return False

    _wait(recovered, timeout=30, msg="in-place restart recovery")
    assert ray_tpu.get(handle.remote()) == "ok"


def test_graceful_shutdown_wipes_checkpoint():
    @serve.deployment(name="ephemeral")
    def f():
        return 1

    serve.run(f.bind())
    serve.shutdown()
    # A fresh controller after graceful shutdown must NOT resurrect
    # the deployment.
    controller = get_or_create_controller()
    assert ray_tpu.get(controller.list_deployments.remote()) == []
