"""Serve resilience: a replica's node dies; the controller reconciles
and requests keep succeeding (reference: deployment_state replica FSM +
chaos serve tests)."""

import time

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.cluster_utils import Cluster

# Multi-process / soak tests: excluded from the quick
# tier (pytest -m 'not slow').
pytestmark = pytest.mark.slow


@pytest.fixture
def fast_health(monkeypatch):
    from ray_tpu._private.config import ray_config

    monkeypatch.setattr(ray_config, "health_check_period_s", 0.2)
    monkeypatch.setattr(ray_config, "health_check_failure_threshold", 2)
    yield


def test_serve_survives_replica_node_death(fast_health):
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 1})
    node_id = cluster.add_node(num_cpus=2)
    try:
        @serve.deployment(num_replicas=2,
                          ray_actor_options={"num_cpus": 1})
        class Echo:
            def __call__(self, x):
                import os

                return (os.getpid(), x)

        handle = serve.run(Echo.bind())
        pids = {ray_tpu.get(handle.remote(i), timeout=30)[0]
                for i in range(12)}
        assert len(pids) == 2, f"replicas not spread: {pids}"

        cluster.kill_node(node_id)
        # Requests must keep succeeding through reconciliation
        # (transient failures tolerated while the dead replica drains).
        deadline = time.monotonic() + 45
        ok = 0
        while time.monotonic() < deadline and ok < 10:
            try:
                ray_tpu.get(handle.remote(1), timeout=10)
                ok += 1
            except Exception:
                time.sleep(0.3)
        assert ok >= 10
    finally:
        serve.shutdown()
        cluster.shutdown()


def test_proxy_fleet_survives_proxy_node_death(fast_health):
    """A proxy-actor's node dies mid-traffic: requests keep succeeding
    through surviving proxies, and the dead proxy is restarted on a
    surviving node (actor restart budget) and serves again — the
    reference's http_state proxy-fleet management under node failure."""
    import json
    import urllib.request

    def post(url, payload):
        req = urllib.request.Request(
            url, data=json.dumps({"payload": payload}).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                return json.loads(resp.read())
        except urllib.error.HTTPError as e:
            raise AssertionError(
                f"HTTP {e.code} from {url}: {e.read()[:400]}")

    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 2})
    victim = cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)
    try:
        @serve.deployment(num_replicas=1)
        class Echo:
            def __call__(self, body):
                # the proxy passes the parsed JSON body as the argument
                return {"v": body["payload"]}

        serve.run(Echo.bind(), route_prefix="/echo")
        fleet = serve.start_proxy_fleet(num_proxies=3)
        assert len(fleet) == 3
        for _a, (host, port) in fleet:
            assert post(f"http://{host}:{port}/echo", 7)["v"] == 7

        # Kill the node that actually hosts a proxy (never a proxy-less
        # node — that would make the restart assertion vacuous).
        head = cluster.head
        victim_node = victim_addr = None
        survivors = []
        for actor, addr in fleet:
            nid = head.actor_nodes.get(actor._actor_id.binary())
            if nid is not None and victim_node is None:
                victim_node, victim_addr = nid, addr
            else:
                survivors.append(addr)
        assert victim_node is not None, "SPREAD placed no proxy on a node"
        cluster.remove_node(victim_node, graceful=False)

        # Surviving proxies keep serving immediately.
        for host, port in survivors[:2]:
            assert post(f"http://{host}:{port}/echo", 9)["v"] == 9

        # The dead proxy actor restarts elsewhere (max_restarts default)
        # and its NEW address serves; poll via the actor handle.
        deadline = time.monotonic() + 30
        recovered = False
        for actor, addr in fleet:
            if addr != victim_addr:
                continue
            while time.monotonic() < deadline and not recovered:
                try:
                    new_addr = ray_tpu.get(actor.address.remote(),
                                           timeout=10)
                    recovered = post(
                        f"http://{new_addr[0]}:{new_addr[1]}/echo",
                        11)["v"] == 11
                except Exception:
                    time.sleep(0.5)
        assert recovered, "killed proxy never came back"
        for actor, _addr in fleet:
            try:
                ray_tpu.get(actor.shutdown.remote(), timeout=10)
            except Exception:
                pass
    finally:
        serve.shutdown()
        cluster.shutdown()
