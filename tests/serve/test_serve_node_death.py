"""Serve resilience: a replica's node dies; the controller reconciles
and requests keep succeeding (reference: deployment_state replica FSM +
chaos serve tests)."""

import time

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.cluster_utils import Cluster

# Multi-process / soak tests: excluded from the quick
# tier (pytest -m 'not slow').
pytestmark = pytest.mark.slow


@pytest.fixture
def fast_health(monkeypatch):
    from ray_tpu._private.config import ray_config

    monkeypatch.setattr(ray_config, "health_check_period_s", 0.2)
    monkeypatch.setattr(ray_config, "health_check_failure_threshold", 2)
    yield


def test_serve_survives_replica_node_death(fast_health):
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 1})
    node_id = cluster.add_node(num_cpus=2)
    try:
        @serve.deployment(num_replicas=2,
                          ray_actor_options={"num_cpus": 1})
        class Echo:
            def __call__(self, x):
                import os

                return (os.getpid(), x)

        handle = serve.run(Echo.bind())
        pids = {ray_tpu.get(handle.remote(i), timeout=30)[0]
                for i in range(12)}
        assert len(pids) == 2, f"replicas not spread: {pids}"

        cluster.kill_node(node_id)
        # Requests must keep succeeding through reconciliation
        # (transient failures tolerated while the dead replica drains).
        deadline = time.monotonic() + 45
        ok = 0
        while time.monotonic() < deadline and ok < 10:
            try:
                ray_tpu.get(handle.remote(1), timeout=10)
                ok += 1
            except Exception:
                time.sleep(0.3)
        assert ok >= 10
    finally:
        serve.shutdown()
        cluster.shutdown()
