"""Serve streaming: generator deployments stream chunks to Python callers
and over HTTP as server-sent events, with the first chunk arriving before
the last is produced.
"""

import http.client
import json
import time

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture
def serve_up():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def test_python_caller_iter_stream(serve_up):
    @serve.deployment
    class Streamer:
        def __call__(self, request):
            def gen():
                for i in range(5):
                    yield {"i": i}
            return gen()

    handle = serve.run(Streamer.bind(), route_prefix="/s1")
    result = ray_tpu.get(handle.remote({"n": 5}), timeout=60)
    assert serve.is_stream(result)
    chunks = list(serve.iter_stream(result))
    assert [c["i"] for c in chunks] == [0, 1, 2, 3, 4]


def test_stream_error_propagates(serve_up):
    @serve.deployment
    class Bad:
        def __call__(self, request):
            def gen():
                yield {"ok": 1}
                raise ValueError("mid-stream boom")
            return gen()

    handle = serve.run(Bad.bind(), route_prefix="/s2")
    result = ray_tpu.get(handle.remote({}), timeout=60)
    it = serve.iter_stream(result)
    assert next(it)["ok"] == 1
    with pytest.raises(RuntimeError, match="mid-stream boom"):
        list(it)


def test_http_sse_streams_incrementally(serve_up):
    """Chunks arrive over HTTP while the generator is still producing —
    the first data line lands well before the slow tail finishes."""

    @serve.deployment
    class SlowStreamer:
        def __call__(self, request):
            def gen():
                for i in range(4):
                    yield {"i": i}
                    time.sleep(0.4)
            return gen()

    serve.run(SlowStreamer.bind(), route_prefix="/slow")
    proxy = serve.start_http_proxy()
    conn = http.client.HTTPConnection(proxy.host, proxy.port, timeout=30)
    t0 = time.perf_counter()
    conn.request("POST", "/slow", body=json.dumps({}),
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.headers.get("Content-Type") == "text/event-stream"

    first_at = None
    items = []
    buf = b""
    while True:
        chunk = resp.read1(65536)
        if not chunk:
            break
        buf += chunk
        while b"\n\n" in buf:
            line, buf = buf.split(b"\n\n", 1)
            if not line.startswith(b"data: "):
                continue
            payload = line[len(b"data: "):]
            if payload == b"[DONE]":
                break
            if first_at is None:
                first_at = time.perf_counter() - t0
            items.append(json.loads(payload))
    conn.close()
    assert [c["i"] for c in items] == [0, 1, 2, 3]
    # 4 chunks at 0.4s spacing = ~1.6s total; the first arrived early.
    assert first_at is not None and first_at < 1.0, first_at
