"""Serve streaming: generator deployments stream chunks to Python callers
and over HTTP as server-sent events, with the first chunk arriving before
the last is produced.
"""

import http.client
import json
import time

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture
def serve_up():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def test_python_caller_iter_stream(serve_up):
    @serve.deployment
    class Streamer:
        def __call__(self, request):
            def gen():
                for i in range(5):
                    yield {"i": i}
            return gen()

    handle = serve.run(Streamer.bind(), route_prefix="/s1")
    result = ray_tpu.get(handle.remote({"n": 5}), timeout=60)
    assert serve.is_stream(result)
    chunks = list(serve.iter_stream(result))
    assert [c["i"] for c in chunks] == [0, 1, 2, 3, 4]


def test_stream_error_propagates(serve_up):
    @serve.deployment
    class Bad:
        def __call__(self, request):
            def gen():
                yield {"ok": 1}
                raise ValueError("mid-stream boom")
            return gen()

    handle = serve.run(Bad.bind(), route_prefix="/s2")
    result = ray_tpu.get(handle.remote({}), timeout=60)
    it = serve.iter_stream(result)
    assert next(it)["ok"] == 1
    with pytest.raises(RuntimeError, match="mid-stream boom"):
        list(it)


def test_async_deployment_unary_and_stream(serve_up):
    """Async deployments run on the replica's persistent loop: an async
    unary method resolves normally, an async-generator result streams
    like a sync generator — to Python callers and over HTTP SSE."""

    @serve.deployment
    class AsyncMixed:
        async def __call__(self, request):
            if isinstance(request, dict) and request.get("stream"):
                async def agen():
                    for i in range(4):
                        yield {"i": i}
                return agen()
            return {"unary": request}

    handle = serve.run(AsyncMixed.bind(), route_prefix="/amixed")

    out = ray_tpu.get(handle.remote({"x": 1}), timeout=60)
    assert out == {"unary": {"x": 1}}

    result = ray_tpu.get(handle.remote({"stream": True}), timeout=60)
    assert serve.is_stream(result)
    chunks = list(serve.iter_stream(result))
    assert [c["i"] for c in chunks] == [0, 1, 2, 3]

    proxy = serve.start_http_proxy()
    conn = http.client.HTTPConnection(proxy.host, proxy.port, timeout=30)
    conn.request("POST", "/amixed", body=json.dumps({"stream": True}),
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.headers.get("Content-Type") == "text/event-stream"
    body = b""
    while True:
        chunk = resp.read1(65536)
        if not chunk:
            break
        body += chunk
        if b"[DONE]" in body:
            break
    conn.close()
    assert body.count(b"data: ") == 5  # 4 chunks + [DONE]


def test_aiter_stream_async_consumer(serve_up):
    """serve.aiter_stream: the event-loop counterpart of iter_stream
    (what the asyncio proxy uses) yields the same chunks."""
    import asyncio

    @serve.deployment
    class Streamer:
        def __call__(self, request):
            def gen():
                for i in range(5):
                    yield {"i": i}
            return gen()

    handle = serve.run(Streamer.bind(), route_prefix="/as1")
    result = ray_tpu.get(handle.remote({}), timeout=60)
    assert serve.is_stream(result)

    async def consume():
        return [c async for c in serve.aiter_stream(result)]

    chunks = asyncio.run(consume())
    assert [c["i"] for c in chunks] == [0, 1, 2, 3, 4]


def test_http_sse_streams_incrementally(serve_up):
    """Chunks arrive over HTTP while the generator is still producing —
    the first data line lands well before the slow tail finishes."""

    @serve.deployment
    class SlowStreamer:
        def __call__(self, request):
            def gen():
                for i in range(4):
                    yield {"i": i}
                    time.sleep(0.4)
            return gen()

    serve.run(SlowStreamer.bind(), route_prefix="/slow")
    proxy = serve.start_http_proxy()
    conn = http.client.HTTPConnection(proxy.host, proxy.port, timeout=30)
    t0 = time.perf_counter()
    conn.request("POST", "/slow", body=json.dumps({}),
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.headers.get("Content-Type") == "text/event-stream"

    first_at = None
    items = []
    buf = b""
    while True:
        chunk = resp.read1(65536)
        if not chunk:
            break
        buf += chunk
        while b"\n\n" in buf:
            line, buf = buf.split(b"\n\n", 1)
            if not line.startswith(b"data: "):
                continue
            payload = line[len(b"data: "):]
            if payload == b"[DONE]":
                break
            if first_at is None:
                first_at = time.perf_counter() - t0
            items.append(json.loads(payload))
    conn.close()
    assert [c["i"] for c in items] == [0, 1, 2, 3]
    # 4 chunks at 0.4s spacing = ~1.6s total; the first arrived early.
    assert first_at is not None and first_at < 1.0, first_at
