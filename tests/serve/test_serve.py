"""Serve tests: deploy, handles, scaling, updates, batching, HTTP."""

import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(autouse=True)
def ray():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def test_function_deployment():
    @serve.deployment
    def echo(x):
        return {"echo": x}

    handle = serve.run(echo.bind())
    out = ray_tpu.get(handle.remote("hi"))
    assert out == {"echo": "hi"}


def test_class_deployment_with_state():
    @serve.deployment(num_replicas=1)
    class Counter:
        def __init__(self, start):
            self.n = start

        def __call__(self):
            self.n += 1
            return self.n

        def value(self):
            return self.n

    handle = serve.run(Counter.bind(10))
    assert ray_tpu.get(handle.remote()) == 11
    assert ray_tpu.get(handle.remote()) == 12
    assert ray_tpu.get(handle.value.remote()) == 12


def test_multiple_replicas_round_robin():
    @serve.deployment(num_replicas=3)
    class Who:
        def __init__(self):
            # Replica identity = the instance, not the serving thread:
            # pooled multi-slot actors construct and serve on shared
            # executor threads, so thread names no longer distinguish
            # replicas.
            import uuid

            self.me = uuid.uuid4().hex

        def __call__(self):
            return self.me

    handle = serve.run(Who.bind())
    names = {ray_tpu.get(handle.remote()) for _ in range(12)}
    assert len(names) == 3


def test_scale_up_down():
    @serve.deployment(num_replicas=1, name="scaler")
    def f():
        return 1

    serve.run(f.bind())
    info = serve.status()["scaler"]
    assert info["num_replicas"] == 1
    serve.run(f.options(num_replicas=3).bind())
    info = serve.status()["scaler"]
    assert info["num_replicas"] == 3


def test_rolling_update_version_change():
    @serve.deployment(name="versioned", version="v1")
    class V:
        def __call__(self):
            return "v1"

    h = serve.run(V.bind())
    assert ray_tpu.get(h.remote()) == "v1"

    @serve.deployment(name="versioned", version="v2")
    class V2:
        def __call__(self):
            return "v2"

    h2 = serve.run(V2.bind())
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if ray_tpu.get(h2.remote()) == "v2":
            break
        time.sleep(0.05)
    assert ray_tpu.get(h2.remote()) == "v2"


def test_user_config_reconfigure():
    @serve.deployment(user_config={"threshold": 5})
    class Cfg:
        def __init__(self):
            self.threshold = None

        def reconfigure(self, config):
            self.threshold = config["threshold"]

        def __call__(self):
            return self.threshold

    h = serve.run(Cfg.bind())
    assert ray_tpu.get(h.remote()) == 5


def test_batching():
    calls = []

    @serve.deployment
    class Batched:
        @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.05)
        def handle(self, items):
            calls.append(len(items))
            return [i * 2 for i in items]

        def __call__(self, x):
            return self.handle(x)

    h = serve.run(Batched.bind())
    refs = [h.remote(i) for i in range(8)]
    out = ray_tpu.get(refs)
    assert sorted(out) == [i * 2 for i in range(8)]
    assert max(calls) > 1  # at least some batching happened


def test_batch_aio_from_event_loop():
    """@serve.batch .aio: N awaiters on ONE event loop coalesce into a
    batch — the wakeup is delivered to the loop instead of blocking it
    (async deployments couldn't use the sync wrapper: every concurrent
    caller would deadlock the loop on Future.result)."""
    import asyncio

    calls = []

    @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.05)
    def double(items):
        calls.append(len(items))
        return [i * 2 for i in items]

    async def main():
        return await asyncio.gather(
            *[double.aio(i) for i in range(8)])

    out = asyncio.run(main())
    assert out == [i * 2 for i in range(8)]
    assert max(calls) > 1


def test_batch_aio_on_method_keeps_instance_binding():
    """`await self.method.aio(item)` from an async handler: the batch
    wrapper is a descriptor, so the instance rides into the batcher
    (a plain function attribute would drop `self` and the batched call
    would blow up with a missing-argument TypeError)."""
    import asyncio

    calls = []

    class Model:
        def __init__(self, scale):
            self.scale = scale

        @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.05)
        def infer(self, items):
            calls.append(len(items))
            return [i * self.scale for i in items]

    m = Model(3)
    assert m.infer(2) == 6  # sync path still bound

    async def main():
        return await asyncio.gather(*[m.infer.aio(i) for i in range(8)])

    out = asyncio.run(main())
    assert out == [i * 3 for i in range(8)]
    assert max(calls) > 1

    # Two instances never share a batch.
    m2 = Model(10)
    assert m2.infer(2) == 20
    assert m.infer(2) == 6


def test_http_proxy():
    @serve.deployment(route_prefix="/api")
    def api(payload=None):
        return {"got": payload}

    serve.run(api.bind(), route_prefix="/api")
    proxy = serve.start_http_proxy()
    url = f"http://{proxy.host}:{proxy.port}/api"
    req = urllib.request.Request(
        url, data=json.dumps({"a": 1}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=10) as resp:
        body = json.loads(resp.read())
    assert body == {"got": {"a": 1}}


def test_delete_deployment():
    @serve.deployment(name="gone")
    def f():
        return 1

    serve.run(f.bind())
    assert "gone" in serve.status()
    serve.delete("gone")
    assert "gone" not in serve.status()
