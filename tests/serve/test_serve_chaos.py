"""Chaos under load: kill one proxy AND one replica mid-load.

The acceptance contract (ISSUE 15):

- requests drain with bounded p99 — nobody waits out a queue/result
  deadline while the fleet reconverges;
- ZERO double-dispatch: every request that got a 200 executed exactly
  once, and no request executed more than once (the proxy's
  fallback-on-ActorDiedError retry is only taken for provably
  never-executed calls);
- ``/api/healthz`` NAMES the dead components while degraded
  (``serve_replica_dead: ...``, ``serve_proxy_dead: ...``) and then
  recovers to ok once the controller replaces the replica and the
  fleet supervisor restarts the proxy on its original port.
"""

import json
import socket
import threading
import time

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu._private import health
from ray_tpu._private.config import ray_config

# In-process replicas share this module's globals: per-request-id
# execution counts are the double-dispatch witness.
EXEC_COUNTS = {}
EXEC_LOCK = threading.Lock()


@pytest.fixture
def fast_chaos(monkeypatch):
    monkeypatch.setattr(ray_config, "serve_replica_health_period_s", 0.2)
    monkeypatch.setattr(ray_config, "serve_proxy_supervise_period_s",
                        0.3)
    yield


@pytest.fixture
def serve_up(fast_chaos):
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    EXEC_COUNTS.clear()
    yield
    serve.shutdown()
    ray_tpu.shutdown()


@serve.deployment(num_replicas=3, max_concurrent_queries=8)
class Chaos:
    def __call__(self, payload):
        rid = payload["id"]
        with EXEC_LOCK:
            EXEC_COUNTS[rid] = EXEC_COUNTS.get(rid, 0) + 1
        time.sleep(0.002)
        return {"id": rid}


def _request_bytes(rid):
    body = json.dumps({"id": rid}).encode()
    return (b"POST /chaos HTTP/1.1\r\nHost: t\r\n"
            b"Content-Type: application/json\r\nContent-Length: "
            + str(len(body)).encode() + b"\r\n\r\n" + body)


def _read_response(sock, buf):
    while b"\r\n\r\n" not in buf:
        chunk = sock.recv(65536)
        if not chunk:
            raise ConnectionError("server closed")
        buf += chunk
    head, buf = buf.split(b"\r\n\r\n", 1)
    status = int(head.split(b" ", 2)[1])
    clen = 0
    for ln in head.split(b"\r\n")[1:]:
        if ln.lower().startswith(b"content-length:"):
            clen = int(ln.split(b":", 1)[1])
    while len(buf) < clen:
        chunk = sock.recv(65536)
        if not chunk:
            raise ConnectionError("closed mid-body")
        buf += chunk
    return status, buf[clen:]


class _Worker(threading.Thread):
    """One keep-alive load client pinned to one proxy port; on a
    transport error it reconnects (the proxy restarts on the SAME
    port) and moves on to a FRESH request id — a request whose
    response was lost is never resent, so its execution count stays
    <= 1 by construction (the double-dispatch witness must come from
    the SERVER side, not client retries)."""

    def __init__(self, name, port, stop):
        super().__init__(name=name, daemon=True)
        self.port = port
        self.stop_evt = stop
        self.latencies = []
        self.statuses = {}
        self.ok_ids = []
        self.lost = 0
        self.seq = 0

    def run(self):
        sock = None
        buf = b""
        while not self.stop_evt.is_set():
            rid = f"{self.name}-{self.seq}"
            self.seq += 1
            t0 = time.perf_counter()
            try:
                if sock is None:
                    sock = socket.create_connection(
                        ("127.0.0.1", self.port), timeout=10)
                    sock.setsockopt(socket.IPPROTO_TCP,
                                    socket.TCP_NODELAY, 1)
                    buf = b""
                sock.sendall(_request_bytes(rid))
                status, buf = _read_response(sock, buf)
            except (OSError, ConnectionError):
                self.lost += 1
                try:
                    if sock is not None:
                        sock.close()
                except OSError:
                    pass
                sock = None
                time.sleep(0.05)
                continue
            self.latencies.append(time.perf_counter() - t0)
            self.statuses[status] = self.statuses.get(status, 0) + 1
            if status == 200:
                self.ok_ids.append(rid)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass


def _percentile(sorted_vals, q):
    import math

    return sorted_vals[min(len(sorted_vals) - 1,
                           max(0, math.ceil(len(sorted_vals) * q) - 1))]


def test_hung_replica_struck_out_and_replaced(serve_up, monkeypatch):
    """A WEDGED (alive but deadlocked) replica — not just a dead one —
    is detected by the ping-timeout strike path
    (serve_replica_health_timeout_s), named in healthz, killed, and
    replaced; traffic recovers. A busy replica serving its FIFO'd ping
    within one item's time never strikes out."""
    monkeypatch.setattr(ray_config, "serve_replica_health_timeout_s",
                        0.3)
    wedge = threading.Event()

    @serve.deployment(num_replicas=1, max_concurrent_queries=1,
                      name="Wedgeable")
    class Wedgeable:
        def __call__(self, payload):
            if payload == "wedge":
                wedge.wait(20)  # deadlock stand-in: pings queue behind
            return {"ok": payload}

    import ray_tpu as rt
    from ray_tpu import serve as serve_mod

    handle = serve_mod.run(Wedgeable.bind(), route_prefix="/wedge")
    assert rt.get(handle.remote("a"), timeout=30)["ok"] == "a"

    wedger = threading.Thread(
        target=lambda: rt.get(handle.remote("wedge"), timeout=60),
        daemon=True)
    wedger.start()
    try:
        # Strikes accumulate (0.2s period, 0.3s timeout, 2 failures):
        # detection + replacement within a few seconds.
        deadline = time.monotonic() + 15
        seen = False
        while time.monotonic() < deadline and not seen:
            seen = any("serve_replica_dead" in r and "Wedgeable" in r
                       and "unresponsive" in r
                       for r in health.provider_reasons())
            time.sleep(0.02)
        assert seen, "wedged replica never struck out"
        # The replacement serves (poll: it must construct first and
        # the handle may briefly retry the broadcast-removed victim).
        deadline = time.monotonic() + 20
        ok = False
        while time.monotonic() < deadline and not ok:
            try:
                ok = rt.get(handle.remote("b"),
                            timeout=10)["ok"] == "b"
            except Exception:
                time.sleep(0.1)
        assert ok, "replacement replica never served"
    finally:
        wedge.set()
        wedger.join(timeout=30)


def test_saturated_replica_is_not_struck_out(serve_up, monkeypatch):
    """The kill-loop guard: a SATURATED replica — health ping FIFO'd
    behind a backlog deeper than its execution slots, but completing
    requests continuously — must never strike out. Only a replica
    making NO progress since the ping was sent is 'unresponsive'."""
    monkeypatch.setattr(ray_config, "serve_replica_health_timeout_s",
                        0.3)

    @serve.deployment(num_replicas=1, max_concurrent_queries=8,
                      name="Busy")
    class Busy:
        def __call__(self, payload):
            time.sleep(0.15)
            return {"ok": payload}

    import ray_tpu as rt
    from ray_tpu import serve as serve_mod

    handle = serve_mod.run(Busy.bind(), route_prefix="/busy")
    from ray_tpu._private.worker import global_worker

    orig = {n for n in global_worker().gcs.list_named_actors()
            if str(n).startswith("SERVE_REPLICA::Busy::")}
    # Sustained depth: 6 concurrent callers x 0.15s against ONE
    # execution slot stream keeps the ping parked well past the 0.3s
    # timeout for ~2.5s (>> period 0.2 x failures 2).
    stop = threading.Event()
    errors = []

    def pound():
        while not stop.is_set():
            try:
                rt.get(handle.remote(1), timeout=30)
            except Exception as e:  # noqa: BLE001
                errors.append(e)
                return

    threads = [threading.Thread(target=pound) for _ in range(6)]
    for t in threads:
        t.start()
    time.sleep(2.5)
    stop.set()
    for t in threads:
        t.join(timeout=30)
    assert not errors, errors[0]
    now = {n for n in global_worker().gcs.list_named_actors()
           if str(n).startswith("SERVE_REPLICA::Busy::")}
    assert now == orig, f"saturated replica was replaced: {orig} -> {now}"
    assert not any("Busy" in r for r in health.provider_reasons())


def test_chaos_kill_proxy_and_replica_mid_load(serve_up):
    serve.run(Chaos.bind(), route_prefix="/chaos")
    fleet = serve.ProxyFleet(num_proxies=2, queue_timeout_s=5.0)
    try:
        ports = [port for _host, port in fleet.addresses()]
        stop = threading.Event()
        workers = [_Worker(f"w{i}", ports[i % len(ports)], stop)
                   for i in range(6)]
        for w in workers:
            w.start()

        # Warm: all workers serving.
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and any(
                not w.latencies for w in workers):
            time.sleep(0.05)
        assert all(w.latencies for w in workers), "load never warmed"

        # -- chaos: kill one replica and one proxy mid-load ----------
        from ray_tpu._private.worker import global_worker

        names = [n for n in global_worker().gcs.list_named_actors()
                 if str(n).startswith("SERVE_REPLICA::Chaos::")]
        assert len(names) == 3
        victim_replica = ray_tpu.get_actor(names[0])
        victim_proxy = fleet.actors()[1]
        ray_tpu.kill(victim_replica)
        ray_tpu.kill(victim_proxy)

        # healthz must NAME the dead components while degraded. Poll
        # fast — supervision replaces them within a couple seconds.
        seen_replica = seen_proxy = False
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and not (
                seen_replica and seen_proxy):
            reasons = health.provider_reasons()
            seen_replica = seen_replica or any(
                "serve_replica_dead" in r and "Chaos" in r
                for r in reasons)
            seen_proxy = seen_proxy or any(
                "serve_proxy_dead" in r and str(ports[1]) in r
                for r in reasons)
            time.sleep(0.01)
        assert seen_replica, "healthz never named the dead replica"
        assert seen_proxy, "healthz never named the dead proxy"

        # The provider reasons flow into the real /api/healthz payload:
        # while any serve component is dead the cluster verdict is
        # degraded with the component named.
        verdict = health.evaluate_health()
        if health.provider_reasons():  # still inside the window
            assert verdict["status"] == "degraded"
            assert any("serve_" in r for r in verdict["reasons"])

        # ...and then RECOVER: reasons drain once the replica is
        # replaced and the proxy restarted on its original port.
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and health.provider_reasons():
            time.sleep(0.05)
        assert health.provider_reasons() == [], (
            f"healthz stuck degraded: {health.provider_reasons()}")
        # The serve components are out of the healthz verdict too (the
        # overall status may still reflect unrelated load signals on a
        # busy CI box, so assert only the serve_* reasons drained).
        assert not any("serve_" in r
                       for r in health.evaluate_health()["reasons"])

        # Load keeps draining through recovery for a beat.
        time.sleep(1.0)
        stop.set()
        for w in workers:
            w.join(timeout=30)

        # -- assertions ---------------------------------------------
        all_lat = sorted(sum((w.latencies for w in workers), []))
        ok = sum(w.statuses.get(200, 0) for w in workers)
        lost = sum(w.lost for w in workers)
        non200 = {s: sum(w.statuses.get(s, 0) for w in workers)
                  for s in {st for w in workers for st in w.statuses}
                  if s != 200}
        assert ok >= 200, (ok, non200, lost)
        # Bounded p99: nobody waited out the 5s queue timeout, let
        # alone the 60s result deadline.
        p99 = _percentile(all_lat, 0.99)
        assert p99 < 3.0, f"p99 {p99:.2f}s unbounded under chaos " \
                          f"(statuses {non200}, lost {lost})"
        # Zero double-dispatch: every 200 executed exactly once, and
        # NOTHING executed twice (lost/shed requests executed <= 1).
        with EXEC_LOCK:
            over = {k: v for k, v in EXEC_COUNTS.items() if v > 1}
            counts = dict(EXEC_COUNTS)
        assert not over, f"double-executed requests: {over}"
        for w in workers:
            for rid in w.ok_ids:
                assert counts.get(rid) == 1, (rid, counts.get(rid))
        # The killed proxy's port answers again (restarted in place).
        status, _hdrs, _body = None, None, None
        sock = socket.create_connection(("127.0.0.1", ports[1]),
                                        timeout=10)
        try:
            sock.sendall(_request_bytes("post-recovery"))
            status, _ = _read_response(sock, b"")
        finally:
            sock.close()
        assert status == 200
        stats = fleet.stats()
        assert stats["restarts"] >= 1
        assert stats.get("direct_served", 0) > 0  # fast path was live
    finally:
        fleet.shutdown()
