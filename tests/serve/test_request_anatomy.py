"""Request anatomy (PR 18): end-to-end critical-path attribution on the
real LLM serving path, plus the affinity hit/miss counters.

The flagship demo: a cache-MISS request through the proxy → replica →
LLM engine names ``llm.prefill`` as its dominant stage; the cache-HIT
request that follows (same shared prompt head, served from the prefix
cache) does not. A prefill-weighted LLM subclass makes the anatomy
deterministic on CPU — sleeping proportionally to the tokens actually
prefilled is exactly what a real transformer's prefill cost does.

Kept tier-1-sized: one tiny 1-layer model, two requests, one proxy.
"""

import http.client
import json
import time
import urllib.request

import pytest

import jax
import jax.numpy as jnp

import ray_tpu
from ray_tpu import serve
from ray_tpu._private import critical_path, perf_stats
from ray_tpu._private.config import ray_config
from ray_tpu.models.llama import LlamaConfig, init_params
from ray_tpu.serve import llm as llm_mod
from ray_tpu.serve.llm import LLMDeployment, LLMEngine

_TINY = LlamaConfig(vocab_size=64, dim=16, n_layers=1, n_heads=2,
                    n_kv_heads=2, hidden_dim=32, max_seq_len=32,
                    dtype=jnp.float32, remat=False)


class _PrefillWeightedEngine(LLMEngine):
    """LLMEngine with a model-realistic cost profile on CPU: prefill
    pays per token actually prefilled (so a prefix-cache hit skips
    most of it), decode pays a fixed per-step cost."""

    def _run_prefill(self, tokens, slot, length, start, bucket):
        time.sleep(0.025 * int(length))
        return super()._run_prefill(tokens, slot, length, start, bucket)

    def _run_decode(self, last, lengths, temps, topks):
        time.sleep(0.03)
        return super()._run_decode(last, lengths, temps, topks)


@pytest.fixture
def llm_up(monkeypatch):
    # Replicas run in-process under the local backend, so patching the
    # module's engine class reshapes every replica this test deploys.
    monkeypatch.setattr(llm_mod, "LLMEngine", _PrefillWeightedEngine)
    monkeypatch.setattr(ray_config, "llm_prefix_cache", True)
    monkeypatch.setattr(ray_config, "llm_kv_block_tokens", 4)
    monkeypatch.setattr(ray_config, "llm_prefix_shm_tier", False)
    # The prefill sleeps stretch warmup past the default supervision
    # window on a loaded box; this test asserts attribution, not
    # failure detection.
    monkeypatch.setattr(ray_config, "serve_replica_health_timeout_s",
                        30.0)
    monkeypatch.setattr(ray_config, "serve_replica_health_failures", 20)
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def _sse_drain(resp):
    n = 0
    buf = b""
    while True:
        chunk = resp.read1(65536)
        if not chunk:
            break
        buf += chunk
        done = False
        while b"\n\n" in buf:
            line, buf = buf.split(b"\n\n", 1)
            if not line.startswith(b"data: "):
                continue
            if line[len(b"data: "):] == b"[DONE]":
                done = True
                break
            n += 1
        if done:
            break
    return n


def _stage_sum(entry, stage):
    return sum(s["dur_s"] for s in entry["stages"]
               if s["stage"] == stage)


def test_cache_miss_names_prefill_dominant_and_traces_chain(llm_up):
    """The attribution demo + the /api/traces span-chain contract in
    one serve session (model warmup is the expensive part)."""
    params = init_params(_TINY, jax.random.PRNGKey(0))
    serve.run(
        serve.deployment(LLMDeployment).bind(
            _TINY, lambda: params, max_batch_size=2, max_seq_len=32,
            warmup_max_prompt_len=16),
        route_prefix="/llm")
    proxy = serve.start_http_proxy()

    shared = list(range(1, 13))  # 12 tokens = 3 full 4-token blocks
    conn = http.client.HTTPConnection(proxy.host, proxy.port,
                                      timeout=60)
    # Absorb replica warm-up with a throwaway request (disjoint 2-token
    # prompt: no shared-prefix blocks enter the cache). Without it the
    # first timed request queues behind warm-up and — correctly! —
    # attributes those seconds to sched.queue instead of prefill.
    conn.request("POST", "/llm",
                 body=json.dumps({"prompt_ids": [40, 41],
                                  "max_tokens": 1, "stream": True}),
                 headers={"Content-Type": "application/json"})
    warm = conn.getresponse()
    assert warm.status == 200
    _sse_drain(warm)
    warm.read()
    for trace_id, tail in (("anatomy-miss", [20, 21]),
                           ("anatomy-hit", [30, 31])):
        conn.request(
            "POST", "/llm",
            body=json.dumps({"prompt_ids": shared + tail,
                             "max_tokens": 4, "stream": True}),
            headers={"Content-Type": "application/json",
                     "X-Trace-Id": trace_id})
        resp = conn.getresponse()
        assert resp.status == 200
        assert _sse_drain(resp) == 4
        resp.read()
    conn.close()

    # The proxy's request envelope closes the waterfall moments after
    # the client drains the stream; poll briefly for both.
    deadline = time.monotonic() + 10
    wf = {}
    while time.monotonic() < deadline:
        wf = {e["trace_id"]: e
              for e in critical_path.finished_waterfalls()}
        if {"anatomy-miss", "anatomy-hit"} <= set(wf):
            break
        time.sleep(0.05)
    assert {"anatomy-miss", "anatomy-hit"} <= set(wf), list(wf)
    miss, hit = wf["anatomy-miss"], wf["anatomy-hit"]

    # The demo: the cold request's time went to prefill; the
    # prefix-cache hit skipped the shared head, so prefill no longer
    # dominates it.
    assert miss["dominant_stage"] == "llm.prefill", miss
    assert hit["dominant_stage"] != "llm.prefill", hit
    assert _stage_sum(hit, "llm.prefill") < \
        _stage_sum(miss, "llm.prefill")

    # The attribution vector reached the fast-path metric under the
    # route tag (what ray_tpu_request_stage_seconds{route,stage}
    # exports).
    vecs = critical_path.attribution_vectors()
    assert vecs["/llm"]["llm.prefill"]["count"] >= 2
    assert vecs["/llm"]["llm.decode"]["count"] >= 2

    # /api/traces: the proxy→replica→prefill chain shares ONE traceId
    # (the supplied one), task spans and synthetic stage spans alike —
    # the TTFT-end-to-end stitching the ISSUE names.
    from ray_tpu.dashboard import shutdown_dashboard, start_dashboard

    server = start_dashboard(port=0)
    try:
        base = f"http://{server.host}:{server.port}"
        with urllib.request.urlopen(f"{base}/api/traces",
                                    timeout=10) as resp:
            envelope = json.loads(resp.read())
        spans = envelope["resourceSpans"][0]["scopeSpans"][0]["spans"]
        mine = [s for s in spans if s["traceId"] == "anatomy-miss"]
        names = {s["name"] for s in mine}
        assert {"stage.proxy.dispatch", "stage.replica.execute",
                "stage.llm.prefill"} <= names, sorted(names)
        # At least one REAL task span (the replica call) rides the
        # same trace id as the synthetic stage spans.
        assert any(not s["spanId"].startswith("stage:")
                   for s in mine), mine
    finally:
        shutdown_dashboard()


class _FakeReplica:
    def __init__(self, name):
        self._actor_name = name


def test_affinity_hit_miss_counters():
    """ReplicaDirectTable.acquire increments serve_affinity_hits when
    an affinity-scored request lands on its best cache-affine replica,
    serve_affinity_misses when it spills or finds no capacity."""
    from ray_tpu._private.kv_cache import chain_keys
    from ray_tpu.serve._private.membership import ReplicaDirectTable

    table = ReplicaDirectTable(cap=1)
    a, b = _FakeReplica("a"), _FakeReplica("b")
    assert table.update(1, [a, b])
    tokens = list(range(8))  # 2 full 4-token blocks
    table.set_digests({"a": {
        "seed": "s", "block_tokens": 4, "block_bytes": 64,
        "keys": list(chain_keys(tokens, 4, "s"))}})

    def counts():
        return (perf_stats.counter("serve_affinity_hits").value,
                perf_stats.counter("serve_affinity_misses").value)

    h0, m0 = counts()
    # Best-scored replica has capacity: a hit.
    tok = table.acquire(affinity_tokens=tokens)
    assert tok is not None and tok.replica is a
    assert counts() == (h0 + 1, m0)
    # Best at cap: the claim spills to the unaffine replica — a miss.
    tok2 = table.acquire(affinity_tokens=tokens)
    assert tok2 is not None and tok2.replica is b
    assert counts() == (h0 + 1, m0 + 1)
    # Everyone at cap: no token, still a miss the hit-rate panel sees.
    assert table.acquire(affinity_tokens=tokens) is None
    assert counts() == (h0 + 1, m0 + 2)
    # No affinity hint: neither counter moves (round-robin contract).
    table.release(tok)
    assert table.acquire() is not None
    assert counts() == (h0 + 1, m0 + 2)
