"""Serve declarative config: schemas, apply_config, REST, CLI."""

import json
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.serve.schema import (
    DeploymentSchema,
    ServeApplicationSchema,
    ServeDeploySchema,
    apply_config,
    import_target,
    status_schema,
)


@pytest.fixture(autouse=True)
def ray():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def test_schema_validation():
    with pytest.raises(ValueError, match="unknown deployment"):
        DeploymentSchema.from_dict({"name": "x", "replicas": 3})
    with pytest.raises(ValueError, match="requires 'name'"):
        DeploymentSchema.from_dict({"num_replicas": 2})
    with pytest.raises(ValueError, match="requires 'import_path'"):
        ServeApplicationSchema.from_dict({"name": "a"})
    with pytest.raises(ValueError, match="non-empty"):
        ServeDeploySchema.from_dict({"applications": []})
    s = ServeDeploySchema.from_dict({"applications": [
        {"import_path": "m:app", "deployments": [
            {"name": "d", "num_replicas": 3}]}]})
    assert s.to_dict()["applications"][0]["deployments"][0][
        "num_replicas"] == 3


def test_import_target():
    app = import_target("tests.serve.sample_app:app")
    assert isinstance(app, serve.Application)
    with pytest.raises(ValueError, match="module:attribute"):
        import_target("no_colon_here")


def test_apply_config_with_overrides():
    handles = apply_config({
        "applications": [{
            "name": "calc",
            "import_path": "tests.serve.sample_app:app",
            "deployments": [
                {"name": "adder", "user_config": None},
                {"name": "Doubler", "num_replicas": 2},
            ],
        }],
    })
    assert ray_tpu.get(handles["calc"].remote(20)) == 41
    st = status_schema()
    assert st["Doubler"]["status"] == "HEALTHY"
    assert st["Doubler"]["num_replicas"] == 2
    assert st["adder"]["status"] == "HEALTHY"


def test_rest_put_and_get():
    from ray_tpu.dashboard import shutdown_dashboard, start_dashboard

    try:
        server = start_dashboard(port=0)
        base = f"http://{server.host}:{server.port}"
        config = {"applications": [{
            "name": "calc",
            "import_path": "tests.serve.sample_app:app",
        }]}
        req = urllib.request.Request(
            f"{base}/api/serve/applications/", method="PUT",
            data=json.dumps(config).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as resp:
            assert resp.status == 200

        with urllib.request.urlopen(
                f"{base}/api/serve/applications/", timeout=30) as resp:
            body = json.loads(resp.read())
        assert body["adder"]["status"] == "HEALTHY"

        # invalid config -> 400
        req = urllib.request.Request(
            f"{base}/api/serve/applications/", method="PUT",
            data=b'{"applications": []}')
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=30)
        assert ei.value.code == 400
    finally:
        shutdown_dashboard()


def test_cli_serve_deploy_and_status(tmp_path, capsys):
    import yaml

    from ray_tpu.scripts.cli import main

    cfg_file = tmp_path / "serve.yaml"
    cfg_file.write_text(yaml.safe_dump({
        "applications": [{
            "name": "calc",
            "import_path": "tests.serve.sample_app:app",
            "deployments": [{"name": "Doubler", "num_replicas": 2}],
        }],
    }))
    main(["serve", "deploy", str(cfg_file)])
    out = capsys.readouterr().out
    assert "deployed 1 application" in out
    main(["serve", "status"])
    out = capsys.readouterr().out
    assert "HEALTHY" in out
    main(["serve", "shutdown"])
    out = capsys.readouterr().out
    assert "shut down" in out
