"""Tier-1 LLM serving smoke: two shared-prefix requests through the
REAL proxy -> replica path on a tiny CPU model prove, on every CI run,
that (a) SSE token streaming works end-to-end, (b) the second request's
shared prompt head HITS the prefix cache (the PR 16 tentpole is live in
the product path, not just in unit tests), and (c) greedy decoding is
deterministic across the cache hit.

Kept under the tier-1 budget by construction: one 1-layer 16-dim model,
a 5-bucket warmup ladder, and exactly three requests.
"""

import http.client
import json

import pytest

import jax

import ray_tpu
from ray_tpu import serve
from ray_tpu._private import perf_stats
from ray_tpu._private.config import ray_config
from ray_tpu.models.llama import LlamaConfig, init_params
from ray_tpu.serve.llm import LLMDeployment

import jax.numpy as jnp

_TINY = LlamaConfig(vocab_size=64, dim=16, n_layers=1, n_heads=2,
                    n_kv_heads=2, hidden_dim=32, max_seq_len=32,
                    dtype=jnp.float32, remat=False)


@pytest.fixture
def serve_up(monkeypatch):
    monkeypatch.setattr(ray_config, "llm_prefix_cache", True)
    monkeypatch.setattr(ray_config, "llm_kv_block_tokens", 4)
    monkeypatch.setattr(ray_config, "llm_prefix_shm_tier", False)
    # On a loaded CI box the replica's warmup compile can outlast the
    # default ~4s health window and get the replica struck mid-warmup
    # ("actor died: killed via kill()" → 500); widen supervision — this
    # test asserts the cache + streaming path, not failure detection.
    monkeypatch.setattr(ray_config, "serve_replica_health_timeout_s",
                        30.0)
    monkeypatch.setattr(ray_config, "serve_replica_health_failures", 20)
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def _sse_tokens(resp):
    toks = []
    buf = b""
    while True:
        chunk = resp.read1(65536)
        if not chunk:
            break
        buf += chunk
        done = False
        while b"\n\n" in buf:
            line, buf = buf.split(b"\n\n", 1)
            if not line.startswith(b"data: "):
                continue
            payload = line[len(b"data: "):]
            if payload == b"[DONE]":
                done = True
                break
            toks.append(json.loads(payload)["token"])
        if done:
            break
    return toks


def _hits() -> int:
    return perf_stats.counter("llm_kv_cache_hits").value


def test_llm_sse_shared_prefix_hits_cache_via_proxy(serve_up):
    params = init_params(_TINY, jax.random.PRNGKey(0))
    serve.run(
        serve.deployment(LLMDeployment).bind(
            _TINY, lambda: params, max_batch_size=2, max_seq_len=32,
            warmup_max_prompt_len=16),
        route_prefix="/llm")
    proxy = serve.start_http_proxy()

    shared = list(range(1, 13))  # 12 tokens = 3 full 4-token blocks
    hits0 = _hits()
    conn = http.client.HTTPConnection(proxy.host, proxy.port, timeout=60)
    streams = []
    for tail in ([20, 21], [30, 31]):
        conn.request(
            "POST", "/llm",
            body=json.dumps({"prompt_ids": shared + tail,
                             "max_tokens": 4, "stream": True}),
            headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 200
        assert resp.headers.get("Content-Type") == "text/event-stream"
        toks = _sse_tokens(resp)
        resp.read()  # drain the chunk terminator, keep-alive intact
        assert len(toks) == 4
        streams.append(toks)
    # Request 2 shared request 1's 3-block prompt head: the prefix
    # cache must have served it (through the real replica, not a local
    # engine) — the counter is process-global, so the delta is the
    # witness.
    assert _hits() - hits0 >= 3, (hits0, _hits())
    # Determinism across the hit: replaying request 2 byte-identically
    # must reproduce its tokens (now fully cache-served).
    conn.request(
        "POST", "/llm",
        body=json.dumps({"prompt_ids": shared + [30, 31],
                         "max_tokens": 4, "stream": True}),
        headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert _sse_tokens(resp) == streams[1]
    resp.read()
    conn.close()
