"""1F1B pipeline-parallel training schedule.

The decisive property: the pipelined loss and ALL gradients (stage
params, head params, pipeline input) exactly match a non-pipelined
reference computation, across stage counts and microbatch counts; and
the schedule's memory/tick structure matches the 1F1B bounds (stash
constant in M, ticks M + 2(S-1))."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ray_tpu.parallel import pipeline_train_1f1b, schedule_info

D = 8


def _stage_fn(params, x):
    # two tanh layers per stage, stacked on the leading axis
    def body(x, w):
        return jnp.tanh(x @ w), None

    x, _ = jax.lax.scan(body, x, params)
    return x


def _head_loss(hp, y, target):
    pred = y @ hp["w"]
    return jnp.mean((pred - target) ** 2)


def _make_inputs(S, M, mb=4, seed=0):
    rng = np.random.RandomState(seed)
    stage_params = jnp.asarray(
        rng.randn(S, 2, D, D).astype(np.float32) * 0.5)
    head = {"w": jnp.asarray(rng.randn(D, D).astype(np.float32) * 0.5)}
    x_mb = jnp.asarray(rng.randn(M, mb, D).astype(np.float32))
    tgt = jnp.asarray(rng.randn(M, mb, D).astype(np.float32))
    return stage_params, head, x_mb, tgt


def _reference(stage_params, head, x_mb, tgt):
    S = stage_params.shape[0]

    def loss_fn(sp, hp, x_mb):
        def one(x, t):
            for si in range(S):
                x = _stage_fn(sp[si], x)
            return _head_loss(hp, x, t)

        return jnp.mean(jax.vmap(one)(x_mb, tgt))

    loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1, 2))(
        stage_params, head, x_mb)
    return loss, *grads


def _mesh(S):
    devs = np.array(jax.devices()[:S])
    return Mesh(devs, ("pipe",))


@pytest.mark.parametrize("S,M", [(2, 4), (2, 9), (4, 8), (4, 5)])
def test_1f1b_matches_reference(S, M):
    stage_params, head, x_mb, tgt = _make_inputs(S, M)
    ref_loss, ref_dsp, ref_dh, ref_dx = _reference(
        stage_params, head, x_mb, tgt)
    loss, dsp, dh, dx = pipeline_train_1f1b(
        _stage_fn, _head_loss, stage_params, head, x_mb, tgt,
        mesh=_mesh(S))
    np.testing.assert_allclose(np.asarray(loss), np.asarray(ref_loss),
                               rtol=2e-5)
    np.testing.assert_allclose(np.asarray(dsp), np.asarray(ref_dsp),
                               rtol=2e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(dh["w"]),
                               np.asarray(ref_dh["w"]),
                               rtol=2e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(ref_dx),
                               rtol=2e-4, atol=1e-6)


def test_microbatch_count_invariance():
    """Same data split into different microbatch counts gives the same
    total gradient (the schedule must not leak state across
    microbatches)."""
    S = 2
    stage_params, head, _, _ = _make_inputs(S, 1)
    rng = np.random.RandomState(7)
    data = jnp.asarray(rng.randn(16, D).astype(np.float32))
    tgt = jnp.asarray(rng.randn(16, D).astype(np.float32))
    mesh = _mesh(S)
    outs = []
    for M in (2, 4, 8):
        x_mb = data.reshape(M, 16 // M, D)
        t_mb = tgt.reshape(M, 16 // M, D)
        loss, dsp, dh, _ = pipeline_train_1f1b(
            _stage_fn, _head_loss, stage_params, head, x_mb, t_mb,
            mesh=mesh)
        # per-microbatch mean losses average to the same total only
        # when microbatches are equal-sized (they are here)
        outs.append((float(loss), np.asarray(dsp)))
    for loss, dsp in outs[1:]:
        assert abs(loss - outs[0][0]) < 1e-5
        np.testing.assert_allclose(dsp, outs[0][1], rtol=2e-4,
                                   atol=1e-6)


def test_schedule_bounds():
    info = schedule_info(4, 16)
    assert info["ticks"] == 16 + 2 * 3
    assert info["stash_slots"] == 7        # constant in M:
    assert schedule_info(4, 64)["stash_slots"] == 7
    assert schedule_info(4, 256)["stash_slots"] == 7
    # bubble shrinks toward zero with M
    assert schedule_info(4, 64)["bubble_fraction"] < 0.09
    # GPipe-through-autodiff would stash M microbatches; 1F1B is O(S).
    assert schedule_info(4, 256)["stash_slots"] < 256


def test_1f1b_llama_stages():
    """Real model: llama blocks staged over pp=2 — loss and stage grads
    match the unpipelined model."""
    from ray_tpu.models import llama

    cfg = llama.LlamaConfig.debug()
    cfg = type(cfg)(**{**cfg.__dict__, "n_layers": 4,
                       "remat": False, "dtype": jnp.float32})
    S, M, B, T = 2, 4, 1, 16
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (M * B, T)),
                         jnp.int32)

    from ray_tpu.parallel.pipeline import llama_pp_parts

    stage_params, head_params, stage_fn, head_loss_fn, embed_fn = \
        llama_pp_parts(cfg, params, n_stages=S)

    x_flat = embed_fn(params["embed"], tokens)
    x_mb = x_flat.reshape(M, B, T, cfg.dim)
    tgt_mb = tokens.reshape(M, B, T)

    loss, dsp, dh, dx = pipeline_train_1f1b(
        stage_fn, head_loss_fn, stage_params, head_params, x_mb,
        tgt_mb, mesh=_mesh(S))

    # Unpipelined reference: same stages composed sequentially.
    def ref_loss_fn(sp, hp, x_mb):
        def one(x, t):
            for si in range(S):
                x = stage_fn(jax.tree.map(lambda a: a[si], sp), x)
            return head_loss_fn(hp, x, t)

        return jnp.mean(jax.vmap(one)(x_mb, tgt_mb))

    ref_loss, (ref_dsp, ref_dh) = jax.value_and_grad(
        ref_loss_fn, argnums=(0, 1))(stage_params, head_params, x_mb)
    np.testing.assert_allclose(np.asarray(loss), np.asarray(ref_loss),
                               rtol=1e-4)
    for a, b in zip(jax.tree.leaves(dsp), jax.tree.leaves(ref_dsp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=1e-5)
    for a, b in zip(jax.tree.leaves(dh), jax.tree.leaves(ref_dh)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=1e-5)
    assert np.isfinite(np.asarray(dx)).all()
