"""Parallel-layer tests on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.parallel import (
    DEFAULT_RULES,
    MeshConfig,
    create_mesh,
    logical_to_mesh_axes,
    named_sharding,
    pipeline_apply,
    ring_attention,
    shard_pytree,
    ulysses_attention,
)
from ray_tpu.parallel.ring_attention import reference_attention
from jax.sharding import PartitionSpec as P


def test_mesh_config_auto_fill():
    cfg = MeshConfig(data=-1, tensor=2)
    assert cfg.shape(8) == (4, 1, 1, 1, 1, 2)
    with pytest.raises(ValueError):
        MeshConfig(data=3, tensor=2).shape(8)


def test_create_mesh_axes():
    mesh = create_mesh(MeshConfig(data=2, fsdp=2, tensor=2))
    assert mesh.shape == {"data": 2, "fsdp": 2, "expert": 1, "pipe": 1,
                          "seq": 1, "tensor": 2}


def test_logical_rules():
    assert logical_to_mesh_axes(("batch", "seq", "embed")) == P(
        ("data", "fsdp"), "seq", "fsdp")
    assert logical_to_mesh_axes((None, "mlp")) == P(None, "tensor")


def test_shard_pytree():
    mesh = create_mesh(MeshConfig(data=2, fsdp=2, tensor=2))
    params = {"w": np.ones((8, 16), np.float32), "b": np.zeros(16, np.float32)}
    logical = {"w": ("embed", "mlp"), "b": ("mlp",)}
    sharded = shard_pytree(params, mesh, logical)
    assert sharded["w"].sharding.spec == P("fsdp", "tensor")
    np.testing.assert_allclose(np.asarray(sharded["w"]), params["w"])


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_reference(causal):
    mesh = create_mesh(MeshConfig(data=1, seq=4, tensor=2))
    b, s, h, d = 2, 32, 4, 16
    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, s, h, d), jnp.float32)
    v = jax.random.normal(kv, (b, s, h, d), jnp.float32)
    expected = reference_attention(q, k, v, causal=causal)
    got = ring_attention(q, k, v, mesh=mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_attention_matches_reference(causal):
    mesh = create_mesh(MeshConfig(data=2, seq=4))
    b, s, h, d = 2, 32, 4, 16
    key = jax.random.PRNGKey(1)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, s, h, d), jnp.float32)
    v = jax.random.normal(kv, (b, s, h, d), jnp.float32)
    expected = reference_attention(q, k, v, causal=causal)
    got = ulysses_attention(q, k, v, mesh=mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_jit_grad():
    mesh = create_mesh(MeshConfig(data=1, seq=8))

    def loss(q, k, v):
        return ring_attention(q, k, v, mesh=mesh).sum()

    b, s, h, d = 1, 16, 2, 8
    q = jnp.ones((b, s, h, d)) * 0.1
    k = jnp.ones((b, s, h, d)) * 0.2
    v = jnp.ones((b, s, h, d)) * 0.3
    g = jax.jit(jax.grad(loss))(q, k, v)
    assert g.shape == q.shape
    assert bool(jnp.all(jnp.isfinite(g)))


def test_pipeline_matches_sequential():
    mesh = create_mesh(MeshConfig(data=2, pipe=4))
    n_stages, n_mb, mb, dim = 4, 8, 2, 16
    key = jax.random.PRNGKey(2)
    ws = jax.random.normal(key, (n_stages, dim, dim)) / np.sqrt(dim)
    x = jax.random.normal(jax.random.PRNGKey(3), (n_mb, mb, dim))

    def stage_fn(w, h):
        return jnp.tanh(h @ w)

    got = pipeline_apply(stage_fn, ws, x, mesh=mesh)

    expected = x
    for i in range(n_stages):
        expected = jax.vmap(lambda h: stage_fn(ws[i], h))(expected)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=1e-5, atol=1e-6)


def test_collectives_roundtrip():
    from ray_tpu.parallel import collectives as col

    mesh = create_mesh(MeshConfig(data=8))

    def body(x):
        s = col.allreduce(x, "data")
        g = col.allgather(x, "data")
        b = col.broadcast(x, "data", root=3)
        return s, g, b

    x = jnp.arange(8.0).reshape(8, 1)
    from ray_tpu.parallel.collectives import shard_map

    fn = shard_map(body, mesh=mesh, in_specs=P("data"),
                   out_specs=(P("data"), P("data"), P("data")),
                   check_vma=False)
    s, g, b = fn(x)
    np.testing.assert_allclose(np.asarray(s).ravel(), [28.0] * 8)
    np.testing.assert_allclose(np.asarray(g).ravel(),
                               np.tile(np.arange(8.0), 8))
    np.testing.assert_allclose(np.asarray(b).ravel(), [3.0] * 8)
