"""Scheduler-scale machinery: compact queued headers, the dep-park
table's exactly-once handoff, lock-partitioned head tables, pooled
actor serving, and the WFQ x compact-queue contract.

The ordering-sensitive pieces (FIFO byte-identity with enforcement
off, charge tokens riding a quota-parked header exactly once) pin the
ISSUE 13 acceptance criteria; the dep_sweep raymc scenario proves the
DepTable claim protocol exhaustively — these tests cover the product
wiring around it.
"""

import queue as _queue
import threading
import time
from types import SimpleNamespace

import pytest

import ray_tpu
from ray_tpu._private.config import ray_config
from ray_tpu._private.sched_state import (DepTable, PendingCounter,
                                          ShardedTable)
from ray_tpu._private.task_spec import (QueuedTaskHeader,
                                        DefaultSchedulingStrategy,
                                        TaskKind, intern_template)
from ray_tpu._private.ids import TaskID


def _header(job_id="", args=(), n_cpus=0.5):
    tpl = intern_template(
        kind=TaskKind.NORMAL_TASK, func=lambda: None, name="t",
        num_returns=1, resources={"CPU": n_cpus},
        scheduling_strategy=DefaultSchedulingStrategy())
    h = QueuedTaskHeader(tpl, TaskID.from_random(), tuple(args), {},
                         job_id=job_id)
    h.assign_return_ids()
    return h


# -- QueuedTaskHeader --------------------------------------------------------


def test_header_materializes_to_equivalent_spec():
    h = _header(job_id="jobX", args=(1, 2))
    h.max_retries = 7
    h.attempt = 2
    spec = h.materialize()
    assert spec.task_id == h.task_id
    assert spec.args == (1, 2)
    assert spec.job_id == "jobX"
    assert spec.max_retries == 7 and spec.attempt == 2
    assert spec.return_ids == h.return_ids
    assert spec.template_id == h.template_id
    assert spec.resources == h.resources


def test_header_quota_tokens_transfer_exactly_once():
    from ray_tpu._private.tenancy import QuotaLedger

    old_enf, old_q = ray_config.tenancy_enforcement, ray_config.job_quotas
    ray_config.tenancy_enforcement = True
    ray_config.job_quotas = "jobQ=cpus:2,queued:10"
    try:
        ledger = QuotaLedger()
        h = _header(job_id="jobQ", n_cpus=1.0)
        assert ledger.note_queued(h) is None
        assert ledger.try_acquire_cpu(h)
        assert ledger.usage("jobQ")["cpu_milli"] == 1000
        assert ledger.usage("jobQ")["queued"] == 1
        spec = h.materialize()  # tokens MOVE to the spec
        assert getattr(h, "_quota_cpu", None) is None
        ledger.note_dequeued(spec)
        ledger.release_cpu(spec)
        assert ledger.usage("jobQ")["cpu_milli"] == 0
        assert ledger.usage("jobQ")["queued"] == 0
        # Idempotent: a second release via either form is a no-op.
        ledger.release_cpu(spec)
        ledger.release_cpu(h)
        assert ledger.usage("jobQ")["cpu_milli"] == 0
    finally:
        ray_config.tenancy_enforcement = old_enf
        ray_config.job_quotas = old_q


def test_quota_parked_header_materializes_on_drain():
    """A header parked at its job's CPU quota is drained by
    take_dispatchable with the charge token riding it — and the token
    survives materialization exactly once (the ISSUE's WFQ x compact
    checklist item)."""
    from ray_tpu._private.tenancy import QuotaLedger

    old_enf, old_q = ray_config.tenancy_enforcement, ray_config.job_quotas
    ray_config.tenancy_enforcement = True
    ray_config.job_quotas = "jobP=cpus:1"
    try:
        ledger = QuotaLedger()
        first = _header(job_id="jobP", n_cpus=1.0)
        assert ledger.note_queued(first) is None
        assert ledger.try_acquire_cpu(first)
        parked = _header(job_id="jobP", n_cpus=1.0)
        assert ledger.note_queued(parked) is None
        assert not ledger.try_acquire_cpu(parked)
        ledger.park(parked)
        assert ledger.take_dispatchable() == []  # job still at cap
        ledger.release_cpu(first.materialize())  # charge rode the spec
        out = ledger.take_dispatchable()
        assert out == [parked]
        assert getattr(parked, "_quota_cpu", None) is not None
        spec = parked.materialize()
        assert getattr(parked, "_quota_cpu", None) is None
        ledger.release_cpu(spec)
        assert ledger.usage("jobP")["cpu_milli"] == 0
    finally:
        ray_config.tenancy_enforcement = old_enf
        ray_config.job_quotas = old_q


# -- WFQ x compact queue -----------------------------------------------------


def test_fair_queue_fifo_byte_identical_with_enforcement_off():
    """Enforcement off: FairTaskQueue over mixed headers/specs pops in
    EXACTLY the put order — indistinguishable from the queue.Queue it
    replaced (acceptance: enforcement-off scheduling order provably
    unchanged)."""
    from ray_tpu._private.tenancy import FairTaskQueue

    assert not ray_config.tenancy_enforcement
    fq = FairTaskQueue()
    baseline = _queue.Queue()
    items = []
    for i in range(200):
        item = _header(job_id=f"job{i % 7}") if i % 3 \
            else SimpleNamespace(job_id=f"job{i % 5}", i=i)
        items.append(item)
        fq.put(item)
        baseline.put(item)
    popped = [fq.get_nowait() for _ in range(len(items))]
    expected = [baseline.get_nowait() for _ in range(len(items))]
    assert [id(x) for x in popped] == [id(x) for x in expected]
    with pytest.raises(_queue.Empty):
        fq.get_nowait()


def test_fair_queue_wfq_bounded_with_headers():
    """Enforcement on: header items class by job_id and the WFQ bypass
    bound holds (a backlogged class is never starved past the
    virtual-time law)."""
    from ray_tpu._private.tenancy import FairTaskQueue

    fq = FairTaskQueue(weights={"a": 1.0, "b": 1.0})
    for _ in range(10):
        fq.put(_header(job_id="a"))
    for _ in range(10):
        fq.put(_header(job_id="b"))
    order = [fq.get_nowait().job_id for _ in range(20)]
    assert sorted(order) == ["a"] * 10 + ["b"] * 10
    # Equal weights: serves alternate once both are backlogged.
    assert fq.max_bypass <= 2
    assert order != ["a"] * 10 + ["b"] * 10  # not plain FIFO


# -- DepTable ----------------------------------------------------------------


def test_dep_table_ready_and_sweep_exactly_once():
    t = DepTable()
    a, b = SimpleNamespace(name="A"), SimpleNamespace(name="B")
    t.park(b"A", a, ["d1"])
    t.park(b"B", b, ["d1", "d2"])
    assert t.waiting_count() == 2
    ready = t.dep_ready("d1")
    assert ready == [a]  # B still waits on d2
    swept = t.sweep(lambda item: True)
    assert swept == [b]
    assert t.waiting_count() == 0
    assert t.parked_entries() == 0  # d2's stale entry purged
    assert t.dep_ready("d2") == []  # loser of the race gets nothing


def test_dep_table_sweep_is_selective():
    t = DepTable()
    mine = SimpleNamespace(actor="x")
    other = SimpleNamespace(actor="y")
    t.park(b"m", mine, ["d"])
    t.park(b"o", other, ["d"])
    assert t.sweep(lambda item: item.actor == "x") == [mine]
    assert t.dep_ready("d") == [other]


def test_dep_table_concurrent_ready_vs_sweep_smoke():
    """Thread-level smoke over the exactly-once claim (the raymc
    dep_sweep scenario explores this space exhaustively)."""
    for _ in range(50):
        t = DepTable()
        items = [SimpleNamespace(i=i) for i in range(6)]
        for i, item in enumerate(items):
            t.park(str(i).encode(), item, ["d1", "d2"])
        got: list = []
        lock = threading.Lock()

        def claim(result):
            with lock:
                got.extend(result)

        threads = [
            threading.Thread(
                target=lambda: claim(t.dep_ready("d1"))),
            threading.Thread(
                target=lambda: claim(t.dep_ready("d2"))),
            threading.Thread(
                target=lambda: claim(t.sweep(lambda item: True))),
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join(5)
        assert len(got) == len(set(id(x) for x in got))
        assert len(got) + t.waiting_count() == len(items)
        if t.waiting_count() == 0:
            assert t.parked_entries() == 0


# -- ShardedTable / PendingCounter -------------------------------------------


def test_sharded_table_basics():
    t = ShardedTable(8)
    t[b"k1"] = ("addr", 1)
    assert b"k1" in t and t[b"k1"] == ("addr", 1)
    assert t.get(b"nope") is None
    assert t.pop(b"k1") == ("addr", 1)
    assert t.pop(b"k1", "dflt") == "dflt"
    for i in range(100):
        t[f"k{i}".encode()] = i
    assert len(t) == 100
    assert sorted(v for _, v in t.items()) == list(range(100))
    assert sorted(t.values()) == list(range(100))


def test_sharded_table_concurrent_smoke():
    t = ShardedTable(4)

    def writer(base):
        for i in range(500):
            key = f"{base}-{i}".encode()
            t[key] = i
            assert t.pop(key) == i

    threads = [threading.Thread(target=writer, args=(b,))
               for b in range(4)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(10)
    assert len(t) == 0


def test_pending_counter_parity():
    c = PendingCounter()
    c.add({"CPU": 500})
    c.add({"CPU": 500, "TPU": 1000})
    assert c.count() == 2 and c.count_approx == 2
    assert c.demand_milli() == {"CPU": 1000, "TPU": 1000}
    c.remove({"CPU": 500, "TPU": 1000})
    c.remove({"CPU": 500})
    assert c.count() == 0 and c.demand_milli() == {}


# -- product wiring (runtime) ------------------------------------------------


@pytest.fixture
def fresh_runtime():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield ray_tpu._private.worker.global_worker()
    ray_tpu.shutdown()


def test_backlogged_submissions_queue_as_headers(fresh_runtime):
    """Dep-blocked submissions park header-only; queue_depths /
    pending_demand_milli / quota queued counts see them exactly like
    full specs (the under-count checklist item)."""
    w = fresh_runtime
    backend = w.backend
    gate = threading.Event()

    @ray_tpu.remote(num_cpus=1)
    def blocker():
        gate.wait(30)
        return 0

    @ray_tpu.remote(num_cpus=0.5)
    def after(x, i):
        return i

    dep = blocker.remote()
    refs = [after.remote(dep, i) for i in range(40)]
    deadline = time.monotonic() + 10
    while backend._deps.waiting_count() < 40 and \
            time.monotonic() < deadline:
        time.sleep(0.01)
    depths = backend.queue_depths()
    assert depths["waiting_for_deps"] == 40
    # The parked items really are compact headers, not full specs.
    with backend._deps._lock:
        parked_types = {type(item).__name__
                        for entries in backend._deps._by_dep.values()
                        for _k, item in entries}
    assert parked_types == {"QueuedTaskHeader"}
    gate.set()
    assert ray_tpu.get(refs, timeout=60) == list(range(40))
    assert backend._deps.waiting_count() == 0
    # Once runnable-but-unfit work exists, demand accounting must see
    # header work identically (0.5 CPU each, 4 CPUs total).
    gate2 = threading.Event()

    @ray_tpu.remote(num_cpus=1)
    def hold():
        gate2.wait(30)
        return 1

    holders = [hold.remote() for _ in range(4)]
    more = [after.remote(0, i) for i in range(10)]
    deadline = time.monotonic() + 10
    while backend.backlog_count() < 10 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert backend.pending_demand_milli().get("CPU", 0) == 5000
    gate2.set()
    assert ray_tpu.get(more, timeout=60) == list(range(10))
    assert ray_tpu.get(holders, timeout=60) == [1] * 4


def test_compact_off_matches_on_results(fresh_runtime):
    @ray_tpu.remote(num_cpus=0.1)
    def sq(x):
        return x * x

    old = ray_config.sched_compact_queue
    try:
        ray_config.sched_compact_queue = False
        off = ray_tpu.get([sq.remote(i) for i in range(50)], timeout=60)
        ray_config.sched_compact_queue = True
        on = ray_tpu.get([sq.remote(i) for i in range(50)], timeout=60)
        assert off == on == [i * i for i in range(50)]
    finally:
        ray_config.sched_compact_queue = old


def test_cancel_and_retry_with_compact_queue(fresh_runtime):
    w = fresh_runtime
    gate = threading.Event()

    @ray_tpu.remote(num_cpus=4)
    def hold():
        gate.wait(30)
        return 1

    attempts = []

    @ray_tpu.remote(num_cpus=1, max_retries=2, retry_exceptions=True)
    def flaky(dep):
        attempts.append(1)
        if len(attempts) < 3:
            raise ValueError("transient")
        return "ok"

    holder = hold.remote()
    # Queue a task behind the resource hold, then cancel it while
    # it is still header-queued.
    queued = ray_tpu.remote(num_cpus=2)(lambda: 9).remote()
    ray_tpu.cancel(queued)
    gate.set()
    assert ray_tpu.get(holder, timeout=30) == 1
    from ray_tpu.exceptions import TaskCancelledError

    with pytest.raises(TaskCancelledError):
        ray_tpu.get(queued, timeout=30)
    assert ray_tpu.get(flaky.remote(holder), timeout=60) == "ok"
    assert len(attempts) == 3
    assert w.backend.backlog_count() == 0


def test_pool_actors_have_no_dedicated_threads(fresh_runtime):
    @ray_tpu.remote(num_cpus=0.01)
    class P:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

    @ray_tpu.remote(num_cpus=0.01, max_concurrency=2)
    class Multi:
        def ping(self):
            return 1

    @ray_tpu.remote(num_cpus=0.01)
    class Async:
        async def ping(self):
            return 1

    actors = [P.remote() for _ in range(20)]
    assert ray_tpu.get([a.bump.remote() for a in actors],
                       timeout=60) == [1] * 20
    multi = Multi.remote()
    assert ray_tpu.get(multi.ping.remote(), timeout=30) == 1
    an = Async.remote()
    assert ray_tpu.get(an.ping.remote(), timeout=30) == 1
    backend = ray_tpu._private.worker.global_worker().backend
    pool_actors = [a for a in backend._actors.values() if a.pool_mode]
    dedicated = [a for a in backend._actors.values() if not a.pool_mode]
    assert len(pool_actors) == 21 and not any(
        a._threads for a in pool_actors)
    # Sync max_concurrency>1 actors pool too (multi-slot: up to
    # max_concurrency concurrent drain passes, zero standing threads);
    # async actors keep the dedicated-thread path (they own an event
    # loop). (Poll: start() appends to _threads after the first thread
    # may already serve.)
    multi_actor = next(a for a in pool_actors if a.max_slots == 2)
    assert multi_actor.max_slots == 2
    assert len(dedicated) == 1 and dedicated[0].is_async
    deadline = time.monotonic() + 5
    while not dedicated[0]._threads and time.monotonic() < deadline:
        time.sleep(0.01)
    assert dedicated[0]._threads
    # Kill fails pending work and frees the mailbox.
    ray_tpu.kill(actors[0])
    from ray_tpu.exceptions import ActorDiedError

    with pytest.raises(ActorDiedError):
        ray_tpu.get(actors[0].bump.remote(), timeout=30)


def test_pool_actor_ordering_under_burst(fresh_runtime):
    @ray_tpu.remote(num_cpus=0.01)
    class Seq:
        def __init__(self):
            self.log = []

        def add(self, i):
            self.log.append(i)
            return i

        def read(self):
            return list(self.log)

    s = Seq.remote()
    refs = [s.add.remote(i) for i in range(300)]
    assert ray_tpu.get(refs, timeout=60) == list(range(300))
    assert ray_tpu.get(s.read.remote(), timeout=30) == list(range(300))


def test_pool_multislot_actor_slot_accounting(fresh_runtime):
    """Multi-slot pooled actors (serve-replica shape): a sync
    max_concurrency=4 actor runs on the executor pool with SLOT
    accounting — true concurrency reaches the slot count under a
    concurrent-call burst (not 1, not unbounded), ``_active_count``
    never exceeds ``max_slots`` at any observed instant, everything
    drains back to zero, and the actor owns no standing threads."""
    import threading as _threading

    @ray_tpu.remote(num_cpus=0.01, max_concurrency=4)
    class Gate:
        def __init__(self):
            self._lock = _threading.Lock()
            self.now = 0
            self.peak = 0

        def call(self, hold_s):
            with self._lock:
                self.now += 1
                self.peak = max(self.peak, self.now)
            time.sleep(hold_s)
            with self._lock:
                self.now -= 1
            return 1

        def peak_now(self):
            return (self.peak, self.now)

    g = Gate.remote()
    refs = [g.call.remote(0.15) for _ in range(12)]
    backend = fresh_runtime.backend
    deadline = time.monotonic() + 10
    actor = None
    while actor is None and time.monotonic() < deadline:
        actor = next((a for a in backend._actors.values()
                      if a.max_slots == 4), None)
        time.sleep(0.005)
    assert actor is not None and actor.pool_mode
    peak_active = 0
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        with actor.mb_lock:
            peak_active = max(peak_active, actor._active_count)
            assert actor._active_count <= actor.max_slots
        done, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=0)
        if len(done) == len(refs):
            break
        time.sleep(0.005)
    assert sum(ray_tpu.get(refs, timeout=60)) == 12
    peak, now = ray_tpu.get(g.peak_now.remote(), timeout=30)
    assert now == 0
    assert 2 <= peak <= 4, peak  # true parallelism, bounded by slots
    assert peak_active >= 2, peak_active
    assert not actor._threads  # zero standing threads: pool-served
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        with actor.mb_lock:
            if actor._active_count == 0:
                break
        time.sleep(0.01)
    with actor.mb_lock:
        assert actor._active_count == 0  # every activation retired


def test_exec_submit_reenqueue_accounting(fresh_runtime):
    """_exec_submit(spawn=False) must report whether the enqueue was
    accounted: at idle==0 the drain continuation rides the CALLING
    thread, so the caller skips its post-serve idle credit (regression:
    the unaccounted item plus the unconditional +1 minted a phantom
    idle credit per re-enqueued drain slice, inflating _exec_idle past
    the real thread count and defeating the fast-dispatch gate)."""
    w = fresh_runtime

    @ray_tpu.remote(num_cpus=0.01)
    class A:
        def ping(self):
            return 1

    a = A.remote()
    assert ray_tpu.get(a.ping.remote(), timeout=30) == 1
    backend = w.backend
    actor = next(x for x in backend._actors.values() if x.pool_mode)
    # Quiesce: the executor that served ping parks with an idle credit.
    deadline = time.monotonic() + 5
    while backend._exec_idle == 0 and time.monotonic() < deadline:
        time.sleep(0.01)
    with backend._exec_lock:
        assert backend._exec_idle >= 1
    # An idle promise is available: the enqueue consumes it (accounted).
    assert backend._exec_submit(("actor", actor), spawn=False) is True
    # The parked thread no-op-drains the stale activation and restores
    # its credit; wait so the forced-idle==0 probe below is exact.
    deadline = time.monotonic() + 5
    while backend._exec_idle == 0 and time.monotonic() < deadline:
        time.sleep(0.01)
    # idle==0 (forced): nothing is promised to the item — unaccounted,
    # the caller must skip its own +1.
    with backend._exec_lock:
        saved = backend._exec_idle
        backend._exec_idle = 0
    try:
        assert backend._exec_submit(("actor", actor),
                                    spawn=False) is False
    finally:
        with backend._exec_lock:
            backend._exec_idle += saved


def test_pool_actor_restart_keeps_mailbox(fresh_runtime):
    @ray_tpu.remote(num_cpus=0.01, max_restarts=1)
    class R:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

    r = R.remote()
    assert ray_tpu.get(r.bump.remote(), timeout=30) == 1
    ray_tpu.kill(r, no_restart=False)
    # Replacement re-runs the constructor; counts restart from 1.
    assert ray_tpu.get(r.bump.remote(), timeout=30) == 1


def test_sched_metrics_registered(fresh_runtime):
    """The new ray_tpu_sched_* series exist and count (folded into
    runtime metrics via perf_stats like every fast-path stat)."""
    from ray_tpu._private import perf_stats

    gate = threading.Event()

    @ray_tpu.remote(num_cpus=4)
    def holdall():
        gate.wait(30)
        return 1

    @ray_tpu.remote(num_cpus=1)
    def queued(i):
        return i

    base = perf_stats.counter("sched_headers_queued").value
    h = holdall.remote()
    # >= 64 headers so the 1/32-sampled materialization distribution
    # is guaranteed at least one recorded sample.
    refs = [queued.remote(i) for i in range(80)]
    gate.set()
    ray_tpu.get(refs + [h], timeout=60)
    assert perf_stats.counter("sched_headers_queued").value > base
    assert perf_stats.counter("sched_queued_header_bytes").value > 0
    assert perf_stats.latency("sched_materialize_seconds").total > 0
    # Lease-cache counters exist (counted on the cluster path).
    perf_stats.counter("sched_lease_cache_hit")
    perf_stats.counter("sched_lease_cache_miss")
    perf_stats.counter("sched_spillbacks")


def test_spillback_falls_back_to_calm_held_lease():
    """When the spill grant fails (every node already leased or full)
    but a held lease sits on a below-threshold node, submissions must
    redirect there instead of piling onto the over-backlog node
    (min(in_flight) keeps picking the overloaded lease because a deep
    node queue acks frames fast). Also pins the grant-scan backoff: a
    denied spill is stamped against the node's report, and the stamped
    window skips the O(nodes) grant scan but still takes the cheap
    fallback."""
    from ray_tpu._private import perf_stats
    from ray_tpu._private.task_spec import TaskSpec  # noqa: F401
    from ray_tpu.cluster_utils import (ClusterBackendMixin, ClusterHead,
                                       _NodeRecord)

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=1)
    w = ray_tpu._private.worker.global_worker()
    head = ClusterHead(w, start_server=False)
    for nid in ("hot", "calm"):
        head.nodes[nid] = _NodeRecord(nid, ("127.0.0.1", 0),
                                      {"CPU": 4.0})
    head.nodes["hot"].backlog = ray_config.sched_spillback_backlog + 50

    mixin = ClusterBackendMixin.__new__(ClusterBackendMixin)
    mixin.head = head
    mixin.local_backend = w.backend
    mixin._lease_locks = [threading.Lock()]
    sent = []
    mixin._lease_send = lambda lease, spec: sent.append(lease) or True

    spec = _header(n_cpus=1.0).materialize()
    key = mixin._shape_key(spec)
    now = time.monotonic()
    hot = {"node_id": "hot", "pipe": SimpleNamespace(in_flight=0),
           "slots": 4, "last_used": now, "address": ("127.0.0.1", 0),
           "job": ""}
    calm = {"node_id": "calm", "pipe": SimpleNamespace(in_flight=1),
            "slots": 4, "last_used": now, "address": ("127.0.0.1", 0),
            "job": ""}
    mixin._leases = {key: [hot, calm]}

    sb0 = perf_stats.counter("sched_spillbacks").value
    hit0 = perf_stats.counter("sched_lease_cache_hit").value
    # First submission: grant scan runs (both nodes excluded -> None),
    # the hot lease is stamped, and the calm lease wins.
    assert mixin._lease_submit(spec, None) is True
    assert sent[-1] is calm
    assert hot["spill_denied_at"] == head.nodes["hot"].last_report
    assert perf_stats.counter("sched_spillbacks").value == sb0 + 1
    # Second submission inside the backoff window: no grant scan (the
    # submission counts as a cache HIT) but still redirected.
    assert mixin._lease_submit(spec, None) is True
    assert sent[-1] is calm
    assert perf_stats.counter("sched_spillbacks").value == sb0 + 2
    assert perf_stats.counter("sched_lease_cache_hit").value == hit0 + 1
    ray_tpu.shutdown()


def test_dep_parked_demand_charged_and_released(fresh_runtime):
    """Dep-parked work charges an incremental demand counter at park
    and releases it at claim — head placement of lifetime-pinned
    creations reserves against it (a dep-blocked burst is invisible to
    the backlog counter until the deps resolve, by which time
    over-landed creations park forever)."""
    w = fresh_runtime
    backend = w.backend
    gate = threading.Event()

    @ray_tpu.remote(num_cpus=1)
    def dep():
        gate.wait(30)
        return 1

    @ray_tpu.remote(num_cpus=2)
    def blocked(d):
        return d

    d = dep.remote()
    b = blocked.remote(d)
    deadline = time.monotonic() + 5
    while backend.dep_parked_demand_milli().get("CPU", 0) != 2000 \
            and time.monotonic() < deadline:
        time.sleep(0.01)
    assert backend.dep_parked_demand_milli().get("CPU", 0) == 2000
    gate.set()
    assert ray_tpu.get(b, timeout=30) == 1
    assert backend.dep_parked_demand_milli().get("CPU", 0) == 0


def test_local_fits_reserves_dep_parked_only_for_creations():
    from ray_tpu.cluster_utils import ClusterBackendMixin

    mixin = ClusterBackendMixin.__new__(ClusterBackendMixin)
    mixin.local_backend = SimpleNamespace(
        resources=SimpleNamespace(_cond=threading.Condition(),
                                  _available={"CPU": 1000}),
        pending_demand_milli=lambda: {},
        dep_parked_demand_milli=lambda: {"CPU": 1000})
    # Plain-task check ignores dep-parked demand (tasks queue+release).
    assert mixin._local_fits_now({"CPU": 1000}) is True
    # Creation placement reserves for it.
    assert mixin._local_fits_now({"CPU": 1000},
                                 reserve_dep_parked=True) is False


def test_creation_never_parks_on_full_head():
    """A creation that cannot construct NOW on the head must queue
    cluster-wide, not land in the head's local backlog (regression: the
    head-local fallback admitted creations against local TOTAL — task
    semantics — so a burst arriving while remote reports were stale
    parked creations behind lifetime-pinned actor CPUs forever while a
    remote node freed up; found by the flood-then-actors verify
    drive). The gate must be registered before queueing so concurrent
    method calls park instead of failing 'unknown actor'."""
    from ray_tpu._private.resources import ResourceSet
    from ray_tpu._private.task_spec import intern_template as it
    from ray_tpu._private.ids import ActorID
    from ray_tpu.cluster_utils import (ClusterBackendMixin, ClusterHead,
                                       _NodeRecord)

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=1)
    w = ray_tpu._private.worker.global_worker()
    head = ClusterHead(w, start_server=False)
    # One remote node: total CPU 2 but pushed availability reads 0
    # (stale report — its tasks just finished).
    rec = _NodeRecord("n1", ("127.0.0.1", 0), {"CPU": 2.0})
    rec.available = {"CPU": 0.0}
    head.nodes["n1"] = rec

    mixin = ClusterBackendMixin.__new__(ClusterBackendMixin)
    mixin.head = head
    mixin.local_backend = w.backend
    routed = []
    mixin._queue_for_cluster = \
        lambda spec, request: routed.append(("queue", spec))
    # Patch the backend submit (the atomic check-and-claim in
    # _submit_local_if_fits calls it directly, not _submit_local).
    w.backend.submit = lambda spec: routed.append(("local", spec))

    def creation():
        tpl = it(kind=TaskKind.ACTOR_CREATION, func=object, name="A",
                 num_returns=1, resources={"CPU": 1.0},
                 scheduling_strategy=DefaultSchedulingStrategy())
        spec = tpl.make_spec(TaskID.from_random(), (), {},
                             actor_id=ActorID.from_random())
        spec.assign_return_ids()
        return spec

    # Local CPU free: the creation lands locally (local-first pack).
    mixin.submit(creation())
    assert routed[-1][0] == "local"
    # Local CPU lifetime-pinned: the creation must QUEUE, and the gate
    # must exist so concurrent calls park rather than "unknown actor".
    w.backend.resources = ResourceSet({"CPU": 0.0})
    spec = creation()
    mixin.submit(spec)
    assert routed[-1][0] == "queue", routed[-1]
    assert head.actor_gate.state(spec.actor_id.binary()) is not None
    ray_tpu.shutdown()


def test_creation_reservation_gates_choose_node():
    """In-flight actor creations charge a head-side placement
    reservation (stale pushed views + lifetime CPU pinning: an
    unreserved burst packs one node with actors that can never start —
    found by the PR 13 verify drive, multiprocess regression in
    test_cluster). Unit-level: reserve at record_inflight, subtract in
    _choose_node, release at clear_inflight."""
    from ray_tpu._private.resources import ResourceSet
    from ray_tpu._private.task_spec import (TaskSpec,
                                            intern_template as it)
    from ray_tpu.cluster_utils import (ClusterBackendMixin, ClusterHead,
                                       _NodeRecord)

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=1)
    w = ray_tpu._private.worker.global_worker()
    head = ClusterHead(w, start_server=False)
    for nid in ("nA", "nB"):
        head.nodes[nid] = _NodeRecord(nid, ("127.0.0.1", 0),
                                      {"CPU": 4.0})

    def creation(i):
        tpl = it(kind=TaskKind.ACTOR_CREATION, func=object, name="A",
                 num_returns=1, resources={"CPU": 1.0},
                 scheduling_strategy=DefaultSchedulingStrategy())
        from ray_tpu._private.ids import ActorID
        spec = tpl.make_spec(TaskID.from_random(), (), {},
                             actor_id=ActorID.from_random())
        spec.assign_return_ids()
        return spec

    mixin = ClusterBackendMixin.__new__(ClusterBackendMixin)
    mixin.head = head
    mixin.local_backend = w.backend
    # Fill the local backend so _choose_node must go remote.
    w.backend.resources = ResourceSet({"CPU": 0.0})

    placed = {"nA": 0, "nB": 0}
    specs = []
    for i in range(8):
        target = mixin._choose_node(creation(0))
        assert target is not None, (placed, "burst bounced at 8 <= 8")
        spec = creation(i)
        head.record_inflight(spec, target.node_id)
        specs.append((spec, target.node_id))
        placed[target.node_id] += 1
    # 8 one-CPU creations over two 4-CPU nodes: exactly 4 + 4.
    assert placed == {"nA": 4, "nB": 4}, placed
    # The 9th has nowhere to go until something releases.
    assert mixin._choose_node(creation(9)) is None
    for spec, nid in specs:
        head.clear_inflight(spec)
    assert all(not r.reserved_milli for r in head.nodes.values())
    assert mixin._choose_node(creation(10)) is not None
    ray_tpu.shutdown()
