"""rayspec's own regression suite: the checker demonstrably catches
seeded non-linearizable histories (and 1-minimizes them), the recorder
pairs concurrent invocation/response events correctly, and every
catalog-registered decision core passes its conformance suite — driven
concurrently against the REAL core, coverage by construction via
parametrization over ``SPEC_CATALOG`` itself (the other half of the R9
contract).

The two ISSUE-pinned seeded violations live here: a monkeypatched
QuotaLedger double-release and the pre-fix FT-gap-(a) double-execution
history, each flagged with a VERIFIED 1-minimal counterexample and an
emitted raysan Schedule script.
"""

import os
import sys
import threading
from types import SimpleNamespace

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
if REPO_ROOT not in sys.path:  # `tools` must resolve from the repo root
    sys.path.insert(0, REPO_ROOT)

from ray_tpu._private import sanitize_hooks  # noqa: E402
from ray_tpu._private.actor_gate import ActorRestartGate  # noqa: E402
from ray_tpu._private.config import ray_config  # noqa: E402
from ray_tpu._private.ids import ActorID, TaskID  # noqa: E402
from ray_tpu._private.kv_cache import (PrefixCache,  # noqa: E402
                                       chain_keys)
from ray_tpu._private.memory_store import MemoryStore  # noqa: E402
from ray_tpu._private.sched_state import (DepTable,  # noqa: E402
                                          ShardedTable)
from ray_tpu._private.task_spec import TaskKind, TaskSpec  # noqa: E402
from ray_tpu._private.tenancy import (FairTaskQueue,  # noqa: E402
                                      QuotaLedger)
from ray_tpu.cluster_utils import ClusterHead, _NodeRecord  # noqa: E402

from tools.rayspec.check import (check_events, linearize,  # noqa: E402
                                 schedule_script)
from tools.rayspec.conformance import check_conformance  # noqa: E402
from tools.rayspec.history import OpEvent, Recorder  # noqa: E402
from tools.rayspec.specs import (ANY, SPEC_CATALOG,  # noqa: E402
                                 AtomicRegisterSpec, FifoQueueSpec,
                                 ShardedTableSpec)


def ev(op, args, result, inv, ret, thread="t", point=None):
    return OpEvent(point=point or f"spec.x.{op}", op=op, args=args,
                   result=result, invoked=inv, returned=ret,
                   thread=thread)


# ---------------------------------------------------------------------------
# checker fixtures: classic histories
# ---------------------------------------------------------------------------


def test_atomic_register_concurrent_history_linearizable():
    # w(1) done; r->1 overlaps w(2); r->2 strictly after: linearizable.
    h = [ev("write", (1,), None, 0, 1),
         ev("read", (), 1, 2, 5, "a"),
         ev("write", (2,), None, 3, 4, "b"),
         ev("read", (), 2, 6, 7)]
    (out,) = check_events(h, AtomicRegisterSpec())
    assert out.status == "ok"


def test_atomic_register_stale_read_flagged_and_minimized():
    # r->2 strictly after w(1) with no w(2) anywhere: impossible.
    h = [ev("write", (1,), None, 0, 1), ev("read", (), 2, 2, 3)]
    (out,) = check_events(h, AtomicRegisterSpec())
    assert out.status == "violation"
    # 1-minimal: the read of a never-written value alone already fails.
    assert [e.op for e in out.minimal] == ["read"]
    assert out.minimal_verified


def test_fifo_queue_reorder_flagged_overlap_ok():
    seq = [ev("enq", (1,), None, 0, 1), ev("enq", (2,), None, 2, 3),
           ev("deq", (), 2, 4, 5), ev("deq", (), 1, 6, 7)]
    (out,) = check_events(seq, FifoQueueSpec())
    assert out.status == "violation"
    # The same delivery order is FINE when the enqueues overlapped —
    # either enq may linearize first.
    lap = [ev("enq", (1,), None, 0, 3), ev("enq", (2,), None, 1, 2, "b"),
           ev("deq", (), 2, 4, 5), ev("deq", (), 1, 6, 7)]
    (out,) = check_events(lap, FifoQueueSpec())
    assert out.status == "ok"


def test_pending_invocation_may_or_may_not_take_effect():
    # A pending enq's item may be observed by a completed deq...
    h = [ev("enq", ("x",), None, 0, None), ev("deq", (), "x", 1, 2, "b")]
    (out,) = check_events(h, FifoQueueSpec())
    assert out.status == "ok"
    # ...and a pending enq that was never observed is fine too.
    h = [ev("enq", ("x",), None, 0, None), ev("deq", (), None, 1, 2, "b")]
    (out,) = check_events(h, FifoQueueSpec())
    assert out.status == "ok"


def test_partition_by_key_still_catches_per_key_violation():
    """The compositionality rule: checking per key must still catch a
    violation CONFINED to one key while other keys' (interleaved)
    subhistories are clean."""
    spec = ShardedTableSpec()
    pt = "spec.table."
    h = [
        ev("set", ("k1", "v1"), None, 0, 1, point=pt + "set"),
        ev("set", ("k2", "v2"), None, 2, 3, point=pt + "set"),
        # k2 reads its own value back: fine.
        ev("get", ("k2",), "v2", 4, 5, point=pt + "get"),
        # k1 reads a value NEVER written to k1, strictly after the set:
        # no linearization explains it.
        ev("get", ("k1",), "v2", 6, 7, point=pt + "get"),
    ]
    outs = {o.key: o for o in check_events(h, spec)}
    assert outs["k2"].status == "ok"
    assert outs["k1"].status == "violation"
    # 1-minimal needs BOTH ops: an absent-key get matches anything (the
    # tap does not capture the caller's default), so the set is what
    # pins the cell to "v1" and makes the stray read impossible.
    assert [e.op for e in outs["k1"].minimal] == ["set", "get"]
    assert outs["k1"].minimal_verified


def test_ddmin_minimal_subhistory_is_one_minimal():
    """Dropping ANY single event from the emitted minimal sub-history
    loses the violation — 1-minimality, checked directly."""
    spec = FifoQueueSpec()
    noise = [ev("enq", (i,), None, i * 2 + 10, i * 2 + 11)
             for i in range(4)]
    bad = [ev("enq", ("a",), None, 0, 1), ev("enq", ("b",), None, 2, 3),
           ev("deq", (), "b", 4, 5), ev("deq", (), "a", 6, 7)]
    (out,) = check_events(bad + noise, FifoQueueSpec())
    assert out.status == "violation" and out.minimal_verified
    for i in range(len(out.minimal)):
        candidate = out.minimal[:i] + out.minimal[i + 1:]
        status, _ = linearize(candidate, spec)
        assert status == "ok", (
            f"minimal sub-history is not 1-minimal: dropping event {i} "
            f"({out.minimal[i].op}) still fails")


def test_bounded_search_falls_back_to_undecided():
    # A wide all-overlapping write burst under a tiny budget: the
    # checker must give up with 'undecided', never a false verdict.
    n = 12
    h = [ev("write", (i,), None, i, 100 + i, f"t{i}") for i in range(n)]
    h.append(ev("read", (), 0, 200, 201))
    status, explored = linearize(h, AtomicRegisterSpec(), max_configs=5)
    assert status == "undecided" and explored >= 5


def test_schedule_script_emission_keys():
    h = [ev("enq", (1,), None, 0, 1, point="spec.wfq.put"),
         ev("enq", (2,), None, 2, 3, point="spec.wfq.put"),
         ev("deq", (), 1, 4, 5, point="spec.wfq.pop")]
    assert schedule_script(h) == ["spec.wfq.put", "spec.wfq.put#2",
                                  "spec.wfq.pop"]


def test_emitted_script_gates_spec_points_under_recorder():
    """The triage recipe end-to-end: with a Recorder installed, spec
    taps forward their call phase into the raysan Schedule seam, so an
    emitted script really gates the op-entry order."""
    from tools.raysan.sched import Schedule

    order = ["spec.wfq.put", "spec.wfq.put#2"]
    q = FairTaskQueue(weights={"": 1.0})
    done = []
    with Recorder():
        sched = Schedule(order=order, timeout_s=5.0)
        with sched:
            def put(tag):
                q.put(SimpleNamespace(job_id="", tag=tag))
                done.append(tag)
            t1 = threading.Thread(target=put, args=("a",))
            t2 = threading.Thread(target=put, args=("b",))
            t1.start(); t1.join(5)
            t2.start(); t2.join(5)
        assert sched.completed
    assert sorted(done) == ["a", "b"]


# ---------------------------------------------------------------------------
# recorder units
# ---------------------------------------------------------------------------


def test_recorder_pairs_calls_and_rets_per_thread():
    with Recorder() as rec:
        core = object()
        sanitize_hooks.spec_op("spec.wfq.put", "call", core, "a")
        sanitize_hooks.spec_op("spec.wfq.put", "ret", core, None)
        sanitize_hooks.spec_op("spec.wfq.pop", "call", core, None)
        # pop never returns: stays pending
    events = rec.events_for(core)
    assert [(e.op, e.returned is None) for e in events] == \
        [("put", False), ("pop", True)]
    assert events[0].invoked < events[0].returned < events[1].invoked


def test_recorder_partitions_by_instance_and_overflows_flagged():
    a, b = object(), object()
    with Recorder(max_events=3) as rec:
        for core in (a, b, a):
            sanitize_hooks.spec_op("spec.wfq.put", "call", core, None)
        # 4th event (any) overflows: recording stops, flag set.
        sanitize_hooks.spec_op("spec.wfq.put", "call", b, None)
    assert len(rec.events_for(a)) == 2
    assert len(rec.events_for(b)) == 1
    assert rec.overflowed


def test_recorder_chains_with_previous_hook():
    seen = []
    sanitize_hooks.install_spec_op(
        lambda name, phase, obj, payload: seen.append((name, phase)))
    try:
        with Recorder() as rec:
            sanitize_hooks.spec_op("spec.wfq.put", "call", rec, None)
            sanitize_hooks.spec_op("spec.wfq.put", "ret", rec, None)
        assert len(rec.events_for(rec)) == 1
        assert seen == [("spec.wfq.put", "call"), ("spec.wfq.put", "ret")]
        assert sanitize_hooks._spec_op is not None  # outer restored
    finally:
        sanitize_hooks.install_spec_op(None)


def test_uninstalled_taps_are_noops():
    assert sanitize_hooks._spec_op is None
    sanitize_hooks.spec_op("spec.wfq.put", "call", object(), None)
    assert not sanitize_hooks.spec_recording()


# ---------------------------------------------------------------------------
# per-core conformance suites (coverage by construction: every catalog
# entry must have a drive registered here)
# ---------------------------------------------------------------------------


def _drive_quota_ledger(rec):
    """Concurrent admit/charge/release churn plus the LEASE slots the
    PR 13 lease-cache/spillback path acquires and retires per
    (job, shape) channel — the ledger side of that path is the
    lease_acquire/lease_release law under concurrency."""
    old_enf, old_q = ray_config.tenancy_enforcement, ray_config.job_quotas
    ray_config.tenancy_enforcement = True
    ray_config.job_quotas = "a=cpus:1,queued:2,leases:2;b=cpus:2"
    try:
        led = QuotaLedger()

        def spec_of(job):
            return SimpleNamespace(job_id=job, resources={"CPU": 0.5},
                                   attempt=0)

        def churn(job):
            for _ in range(6):
                s = spec_of(job)
                led.note_queued(s)
                if led.try_acquire_cpu(s):
                    led.release_cpu(s)
                led.note_dequeued(s)
                if led.try_acquire_lease(job):
                    led.release_lease(job)

        ts = [threading.Thread(target=churn, args=(j,))
              for j in ("a", "a", "b")]
        [t.start() for t in ts]
        [t.join() for t in ts]
        led.take_dispatchable()
        return led
    finally:
        ray_config.tenancy_enforcement = old_enf
        ray_config.job_quotas = old_q


def _drive_dep_table(rec):
    dt = DepTable()
    items = {k: SimpleNamespace(name=k) for k in ("A", "B", "C")}
    dt.park(b"A", items["A"], [b"d1"])
    dt.park(b"B", items["B"], [b"d1", b"d2"])
    dt.park(b"C", items["C"], [b"d2"])
    ts = [threading.Thread(target=dt.dep_ready, args=(d,))
          for d in (b"d1", b"d2")]
    ts.append(threading.Thread(
        target=lambda: dt.sweep(lambda it: it is items["C"])))
    [t.start() for t in ts]
    [t.join() for t in ts]
    return dt


def _drive_actor_gate(rec):
    gate = ActorRestartGate()
    gate.register(b"a1", 2)
    gate.register(b"a2", 0)
    call = SimpleNamespace(
        actor_id=SimpleNamespace(binary=lambda: b"a1"),
        max_retries=1, attempt=0, describe=lambda: "A.f")

    def deaths():
        gate.begin_restart(b"a1", "n1 died")
        gate.ready(b"a1")
        gate.begin_restart(b"a2", "n1 died")  # budget 0 -> tombstone

    def calls():
        gate.route_call(call, dispatch=None, park=lambda s: None,
                        fail=lambda s, m, d: None)
        gate.recover_call(call, resubmit=lambda s: None,
                          fail=lambda s, m, d: None)

    ts = [threading.Thread(target=deaths),
          threading.Thread(target=calls)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    return gate


def _drive_sharded_table(rec):
    """Mixed per-key churn shaped like the head's hot tables under the
    PR 13 scheduler (inflight record/clear via set/pop, directory
    setdefault/contains probes, spillback-style re-reads)."""
    st = ShardedTable(8)

    def worker(i):
        key = f"task-{i}"
        st[key] = ("n1", i)
        assert st.get(key) == ("n1", i)
        st.setdefault(key, ("n9", -1))
        assert key in st
        if i % 2:
            st.pop(key)
        else:
            st[key] = ("n2", i)

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(10)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    return st


def _drive_fair_task_queue(rec):
    # Equal weights: the catalog's spec factory models the default
    # weight; a weighted queue needs a matching
    # FairTaskQueueSpec(weights=...) — covered separately below.
    q = FairTaskQueue(weights={"a": 1.0, "b": 1.0})
    items = [SimpleNamespace(job_id=j, tag=f"{j}{i}")
             for j in ("a", "b") for i in range(4)]
    got = []

    def producer():
        for item in items:
            q.put(item)

    def consumer():
        import queue as _q

        for _ in range(len(items)):
            try:
                got.append(q.get(timeout=2))
            except _q.Empty:
                return

    ts = [threading.Thread(target=producer),
          threading.Thread(target=consumer)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    return q


def _drive_exactly_once_call(rec):
    head, worker, _submitted = _make_head()
    creation = _creation_spec(max_restarts=1)
    head.record_lineage(creation)
    head.set_actor_node(creation.actor_id.binary(), "n1")
    call = _call_spec(creation, max_task_retries=1)
    head.record_lineage(call)
    head.record_inflight(call, "n1")
    head._report_objects([call.return_ids[0].binary()],
                         head.nodes["n1"].address)
    return head


def _drive_kv_cache(rec):
    """Concurrent lookup/pin/release racing admit and pressure evict
    on a capacity so tight every admission must evict — the
    pinned-never-evicted and charge-conservation laws under exactly
    the contention the LLM engine's prefill path produces."""
    cache = PrefixCache(capacity_bytes=300, block_tokens=4)
    chains = {j: chain_keys([b + 100 * i for b in range(8)], 4, "m")
              for i, j in enumerate(("a", "b", "c"))}

    def churn(job):
        for _ in range(4):
            created, _ev = cache.admit(chains[job], job, 100)
            hit = cache.lookup(chains[job], job)
            if hit:
                cache.pin(hit)
                cache.release(hit)
            cache.release(hit)
            cache.release(created)
            cache.evict(100)

    ts = [threading.Thread(target=churn, args=(j,))
          for j in ("a", "b", "c")]
    [t.start() for t in ts]
    [t.join() for t in ts]
    return cache


CORE_DRIVES = {
    "quota_ledger": _drive_quota_ledger,
    "dep_table": _drive_dep_table,
    "actor_gate": _drive_actor_gate,
    "sharded_table": _drive_sharded_table,
    "fair_task_queue": _drive_fair_task_queue,
    "exactly_once_call": _drive_exactly_once_call,
    "kv_cache": _drive_kv_cache,
}


def test_every_catalog_entry_has_a_conformance_drive():
    assert set(CORE_DRIVES) == set(SPEC_CATALOG), (
        "every SPEC_CATALOG entry needs a conformance drive here "
        "(and vice versa) — this is the R9 contract's testing half")


def test_weighted_wfq_history_checks_under_matching_spec():
    """Non-default weights: the spec instance must carry the queue's
    weights (the catalog factory models the default); with them, a
    weighted real queue's history linearizes — and the same history
    FAILS under a deliberately wrong weight map, proving the
    virtual-time law (not just FIFO-per-class) is what's checked."""
    from tools.rayspec.specs import FairTaskQueueSpec

    weights = {"a": 4.0, "b": 1.0}
    with Recorder() as rec:
        q = FairTaskQueue(weights=weights)
        for j, i in [("a", 0), ("b", 0), ("a", 1), ("a", 2),
                     ("b", 1), ("a", 3)]:
            q.put(SimpleNamespace(job_id=j, tag=f"{j}{i}"))
        for _ in range(6):
            q.get_nowait()
    raw = rec.events_for(q)
    spec = FairTaskQueueSpec(weights=weights)
    events, _ = spec.adapt(raw)
    assert all(o.status == "ok" for o in check_events(events, spec))
    wrong = FairTaskQueueSpec(weights={"a": 1.0, "b": 4.0})
    events, _ = wrong.adapt(raw)
    assert any(o.status == "violation"
               for o in check_events(events, wrong))


def test_conformance_binds_live_queue_weights():
    """Review regression: the catalog factory cannot know a queue's
    weight map — conformance must BIND it from the live core, or a
    weighted queue's correct picks read as WFQ violations."""
    weights = {"a": 4.0, "b": 1.0}
    with Recorder() as rec:
        q = FairTaskQueue(weights=weights)
        for j, i in [("a", 0), ("b", 0), ("a", 1), ("a", 2),
                     ("b", 1), ("a", 3)]:
            q.put(SimpleNamespace(job_id=j, tag=f"{j}{i}"))
        for _ in range(4):
            q.get_nowait()
    assert check_conformance(rec.events_for(q),
                             SPEC_CATALOG["fair_task_queue"], q) is None


@pytest.mark.parametrize("name", sorted(SPEC_CATALOG))
def test_core_conformance(name):
    """Drive the REAL core concurrently under the recorder; the
    history must linearize against the spec, and (where a live
    abstraction exists) the end state must be spec-reachable."""
    entry = SPEC_CATALOG[name]
    with Recorder() as rec:
        core = CORE_DRIVES[name](rec)
    raw = rec.events_for(core)
    assert raw, f"drive for {name} recorded nothing"
    spec = entry.factory()
    events, _tokens = spec.adapt(raw)
    outcomes = check_events(events, spec)
    assert outcomes and all(o.status == "ok" for o in outcomes), [
        (o.key, o.status, o.message) for o in outcomes
        if o.status != "ok"]
    if entry.supports_conformance:
        assert check_conformance(raw, entry, core) is None


# ---------------------------------------------------------------------------
# seeded violations (the ISSUE's acceptance pair)
# ---------------------------------------------------------------------------


def test_seeded_quota_ledger_double_release_flagged():
    """Monkeypatched bug: release_cpu forgets to clear the charge
    token, so a spec releases twice. The ledger spec calls the second
    release ILLEGAL (usage would go negative) — no linearization
    survives — with a verified 1-minimal counterexample and a replay
    script."""
    old_enf, old_q = ray_config.tenancy_enforcement, ray_config.job_quotas
    ray_config.tenancy_enforcement = True
    ray_config.job_quotas = "a=cpus:1"
    try:
        with Recorder() as rec:
            led = QuotaLedger()
            s = SimpleNamespace(job_id="a", resources={"CPU": 1.0},
                                attempt=0)
            assert led.try_acquire_cpu(s)
            token = s._quota_cpu
            led.release_cpu(s)
            s._quota_cpu = token  # the seeded bug: token not cleared
            led.release_cpu(s)
        entry = SPEC_CATALOG["quota_ledger"]
        spec = entry.factory()
        events, _ = spec.adapt(rec.events_for(led))
        (out,) = check_events(events, spec)
        assert out.status == "violation"
        assert [e.op for e in out.minimal] == ["release"]
        assert out.minimal_verified
        assert out.schedule_order == ["spec.quota.release"]
    finally:
        ray_config.tenancy_enforcement = old_enf
        ray_config.job_quotas = old_q


def _make_head():
    worker = SimpleNamespace(memory_store=MemoryStore(), shm_plane=None,
                             gcs=None, backend=None)
    head = ClusterHead(worker, start_server=False)
    submitted = []
    worker.backend = SimpleNamespace(submit=submitted.append)
    head.nodes["n1"] = _NodeRecord("n1", ("127.0.0.1", 7191), {"CPU": 2})
    return head, worker, submitted


def _creation_spec(max_restarts=0):
    spec = TaskSpec(task_id=TaskID.from_random(),
                    kind=TaskKind.ACTOR_CREATION, func=object,
                    args=(), kwargs={}, name="A.__init__",
                    actor_id=ActorID.from_random(),
                    max_restarts=max_restarts)
    spec.assign_return_ids()
    return spec


def _call_spec(creation, max_task_retries=0):
    spec = TaskSpec(task_id=TaskID.from_random(),
                    kind=TaskKind.ACTOR_TASK, func="f", args=(),
                    kwargs={}, name="A.f", actor_id=creation.actor_id,
                    max_retries=max_task_retries)
    spec.assign_return_ids()
    return spec


def _gap_a_history(monkeypatch, prefix_behavior: bool):
    """Drive the FT-gap-(a) interleaving against a real head; with
    ``prefix_behavior`` the dedupe + dead-node-report guard are
    disabled (the PRE-fix code paths)."""
    if prefix_behavior:
        monkeypatch.setattr(ClusterHead, "_call_output_applied",
                            lambda self, spec: False)
        monkeypatch.setattr(ClusterHead, "_addr_dead",
                            lambda self, addr: False)
    with Recorder() as rec:
        head, worker, _submitted = _make_head()
        creation = _creation_spec(max_restarts=1)
        head.record_lineage(creation)
        head.set_actor_node(creation.actor_id.binary(), "n1")
        call = _call_spec(creation, max_task_retries=1)
        head.record_lineage(call)
        head.record_inflight(call, "n1")
        dead_addr = head.nodes["n1"].address
        head.mark_node_dead("n1", reason="chaos kill")
        if call.attempt:  # the replay dispatched to a replacement
            head.nodes["n2"] = _NodeRecord("n2", ("127.0.0.1", 7192),
                                           {"CPU": 2})
            head.record_inflight(call, "n2")
        oid = call.return_ids[0].binary()
        # Execution #1's output REPORT, in flight at node death, lands.
        head._report_objects([oid], dead_addr)
        # The replay's execution reports from the replacement.
        if call.attempt:
            head._report_objects([oid], ("127.0.0.1", 7192))
    entry = SPEC_CATALOG["exactly_once_call"]
    spec = entry.factory()
    events, _ = spec.adapt(rec.events_for(head))
    return check_events(events, spec)


def test_prefix_gap_a_double_execution_history_flagged(monkeypatch):
    outcomes = _gap_a_history(monkeypatch, prefix_behavior=True)
    bad = [o for o in outcomes if o.status == "violation"]
    assert bad, "pre-fix double execution was NOT flagged"
    (out,) = bad
    assert [e.op for e in out.minimal] == ["apply", "apply"]
    assert out.minimal_verified
    assert out.schedule_order == ["spec.call.apply", "spec.call.apply#2"]


def test_fixed_gap_a_history_clean(monkeypatch):
    outcomes = _gap_a_history(monkeypatch, prefix_behavior=False)
    assert all(o.status == "ok" for o in outcomes), [
        (o.key, o.status, o.message) for o in outcomes]


# ---------------------------------------------------------------------------
# deterministic report artifacts (tools/reporting.py)
# ---------------------------------------------------------------------------


def test_report_artifact_is_deterministic_modulo_volatile(tmp_path):
    from tools.reporting import (render_deterministic, split_volatile,
                                 write_report_artifact)

    a = {"pass": True, "elapsed_s": 1.23,
         "scenarios": [{"name": "x", "elapsed_s": 4.5, "count": 7}]}
    b = {"pass": True, "elapsed_s": 9.87,
         "scenarios": [{"name": "x", "elapsed_s": 0.1, "count": 7}]}
    assert render_deterministic(a, ("elapsed_s",)) == \
        render_deterministic(b, ("elapsed_s",))
    # But a REAL difference still shows.
    c = {**a, "pass": False}
    assert render_deterministic(a, ("elapsed_s",)) != \
        render_deterministic(c, ("elapsed_s",))
    # The sidecar keeps the real values, path-addressed.
    _norm, timings = split_volatile(a, ("elapsed_s",))
    assert timings == {"elapsed_s": 1.23,
                       "scenarios[0].elapsed_s": 4.5}
    # write_report_artifact: artifact + sidecar land; artifact bytes
    # identical across the two volatile-differing runs.
    p1, p2 = tmp_path / "r1.json", tmp_path / "r2.json"
    assert write_report_artifact(str(p1), a)
    assert write_report_artifact(str(p2), b)
    assert p1.read_bytes() == p2.read_bytes()
    assert (tmp_path / "r1.json.timing.json").exists()
