"""Per-rule raylint fixtures: each rule demonstrably catches its seeded
violation (positive), stays quiet on the compliant twin (negative), and
honors a justified inline suppression (suppressed).

These are the analyzer's own regression tests — `test_raylint.py` only
proves the tree is clean, which would also be true of an analyzer that
checks nothing.
"""

import os
import sys
import textwrap

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
if REPO_ROOT not in sys.path:  # `tools` must resolve from the repo root
    sys.path.insert(0, REPO_ROOT)

from tools.raylint.core import analyze_source  # noqa: E402
from tools.raylint.rules import select_rules  # noqa: E402


def lint(src, rule_ids, module="ray_tpu.fixture_mod", relpath=None):
    return analyze_source(textwrap.dedent(src), select_rules(rule_ids),
                          module=module, relpath=relpath)


def active(violations):
    return [v for v in violations if not v.suppressed]


# ---------------------------------------------------------------------------
# R1 async-blocking
# ---------------------------------------------------------------------------


def test_r1_flags_time_sleep_in_coroutine():
    vs = active(lint("""
        import time


        async def handler():
            time.sleep(0.1)
    """, ["R1"]))
    assert len(vs) == 1 and vs[0].rule == "R1"
    assert "time.sleep" in vs[0].message
    assert vs[0].line == 6


def test_r1_flags_lock_future_and_queue_on_loop():
    vs = active(lint("""
        async def handler(self, fut, q):
            with self._lock:
                pass
            fut.result()
            q.get()
    """, ["R1"]))
    msgs = "\n".join(v.message for v in vs)
    assert len(vs) == 3
    assert "self._lock" in msgs and "fut.result" in msgs \
        and "q.get" in msgs


def test_r1_negative_awaited_and_nested_sync_def():
    vs = active(lint("""
        import asyncio
        import time


        async def handler(loop):
            await asyncio.sleep(0.1)

            def blocking_helper():  # runs in an executor, not the loop
                time.sleep(1.0)

            await loop.run_in_executor(None, blocking_helper)
    """, ["R1"]))
    assert vs == []


def test_r1_suppressed_with_justification():
    vs = lint("""
        import time


        async def handler():
            time.sleep(0.1)  # raylint: disable=R1 -- startup-only path, loop not yet serving
    """, ["R1"])
    assert len(vs) == 1 and vs[0].suppressed
    assert vs[0].justification.startswith("startup-only")
    assert active(vs) == []


# ---------------------------------------------------------------------------
# R2 lock discipline
# ---------------------------------------------------------------------------

LOCK_ORDER_CYCLE = """
    import threading


    class Store:
        def __init__(self):
            self._meta_lock = threading.Lock()
            self._data_lock = threading.Lock()

        def read(self):
            with self._meta_lock:
                with self._data_lock:
                    return 1

        def write(self):
            with self._data_lock:
                with self._meta_lock:
                    return 2
"""


def test_r2_lock_order_cycle_fixture():
    vs = active(lint(LOCK_ORDER_CYCLE, ["R2"]))
    cycles = [v for v in vs if "lock-order cycle" in v.message]
    assert len(cycles) == 1
    assert "_meta_lock" in cycles[0].message
    assert "_data_lock" in cycles[0].message


def test_r2_consistent_order_is_clean():
    vs = active(lint("""
        import threading


        class Store:
            def __init__(self):
                self._meta_lock = threading.Lock()
                self._data_lock = threading.Lock()

            def read(self):
                with self._meta_lock:
                    with self._data_lock:
                        return 1

            def write(self):
                with self._meta_lock:
                    with self._data_lock:
                        return 2
    """, ["R2"]))
    assert vs == []


def test_r2_blocking_rpc_under_lock_direct_and_transitive():
    vs = active(lint("""
        import threading


        class Client:
            def __init__(self):
                self._lock = threading.Lock()

            def _push(self, sock, payload):
                sock.sendall(payload)

            def direct(self, sock, payload):
                with self._lock:
                    sock.sendall(payload)

            def transitive(self, sock, payload):
                with self._lock:
                    self._push(sock, payload)
    """, ["R2"]))
    assert len(vs) == 2
    direct = [v for v in vs if "blocking call `sock.sendall`" in v.message]
    trans = [v for v in vs if "call to `_push` which blocks" in v.message]
    assert len(direct) == 1 and len(trans) == 1


def test_r2_remote_submission_and_callback_under_lock():
    vs = active(lint("""
        import threading


        class Dispatcher:
            def __init__(self):
                self._lock = threading.Lock()

            def dispatch(self, replica, on_done):
                with self._lock:
                    ref = replica.handle.remote()
                    on_done(ref)
    """, ["R2"]))
    msgs = "\n".join(v.message for v in vs)
    assert ".remote()` submission" in msgs
    assert "user callback `on_done`" in msgs


def test_r2_condvar_own_lock_wait_is_clean():
    vs = active(lint("""
        import threading


        class WaitGroup:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Condition(self._lock)

            def wait(self):
                with self._cond:
                    while not self._ready():
                        self._cond.wait()

            def _ready(self):
                return True
    """, ["R2"]))
    assert vs == []


def test_r2_suppressed_with_justification():
    vs = lint("""
        import threading


        class Client:
            def __init__(self):
                self._lock = threading.Lock()

            def call(self, sock, payload):
                with self._lock:
                    sock.sendall(payload)  # raylint: disable=R2 -- the lock IS the per-socket framing discipline
    """, ["R2"])
    assert len(vs) == 1 and vs[0].suppressed
    assert active(vs) == []


# ---------------------------------------------------------------------------
# R3 layering
# ---------------------------------------------------------------------------


def test_r3_core_importing_library_flagged():
    vs = active(lint("""
        from ray_tpu.serve.llm import LLMEngine
    """, ["R3"], module="ray_tpu._private.metrics_exporter",
        relpath="ray_tpu/_private/metrics_exporter.py"))
    assert len(vs) == 1
    assert "imports library package `ray_tpu.serve`" in vs[0].message


def test_r3_cross_package_private_import_and_attr_read():
    vs = active(lint("""
        from ray_tpu.serve._private.router import Router
        from ray_tpu._private import task_events

        buffered = task_events._max
    """, ["R3"], module="ray_tpu.tune.trainable",
        relpath="ray_tpu/tune/trainable.py"))
    msgs = "\n".join(v.message for v in vs)
    assert "private namespace" in msgs
    assert "task_events._max" in msgs


def test_r3_own_package_private_use_is_clean():
    vs = active(lint("""
        from ray_tpu.serve._private.router import Router
    """, ["R3"], module="ray_tpu.serve.api",
        relpath="ray_tpu/serve/api.py"))
    assert vs == []


def test_r3_library_importing_core_public_is_clean():
    vs = active(lint("""
        from ray_tpu.util.metrics import Gauge
    """, ["R3"], module="ray_tpu.serve.llm",
        relpath="ray_tpu/serve/llm.py"))
    assert vs == []


def test_r3_suppressed_with_justification():
    vs = lint("""
        from ray_tpu.serve._private.router import Router  # raylint: disable=R3 -- test-only shim, removed with the next router API rev
    """, ["R3"], module="ray_tpu.tune.trainable",
        relpath="ray_tpu/tune/trainable.py")
    assert len(vs) == 1 and vs[0].suppressed
    assert active(vs) == []


# ---------------------------------------------------------------------------
# R4 resource lifecycle
# ---------------------------------------------------------------------------


def test_r4_thread_attr_without_teardown():
    vs = active(lint("""
        import threading


        class Pump:
            def __init__(self):
                self._thread = threading.Thread(target=self._loop)

            def _loop(self):
                pass
    """, ["R4"]))
    assert len(vs) == 1
    assert "no teardown method" in vs[0].message


def test_r4_thread_attr_with_teardown_is_clean():
    vs = active(lint("""
        import threading


        class Pump:
            def __init__(self):
                self._thread = threading.Thread(target=self._loop)

            def _loop(self):
                pass

            def stop(self):
                self._thread.join()
    """, ["R4"]))
    assert vs == []


def test_r4_group_commit_close_without_flush():
    vs = active(lint("""
        class Writer:
            def flush(self):
                pass

            def close(self):
                self._conn = None
    """, ["R4"]))
    assert len(vs) == 1
    assert "without flush()/commit()" in vs[0].message


def test_r4_group_commit_close_with_flush_is_clean():
    vs = active(lint("""
        class Writer:
            def flush(self):
                pass

            def close(self):
                self.flush()
                self._conn = None
    """, ["R4"]))
    assert vs == []


def test_r4_unclosed_socket_and_nondaemon_thread():
    vs = active(lint("""
        import socket
        import threading


        def probe(addr):
            sock = socket.create_connection(addr)
            sock.sendall(b"ping")

        def fire_and_forget(fn):
            threading.Thread(target=fn).start()
    """, ["R4"]))
    msgs = "\n".join(v.message for v in vs)
    assert "`sock` is never closed" in msgs
    assert "non-daemon fire-and-forget Thread" in msgs


def test_r4_socket_closed_or_returned_is_clean():
    vs = active(lint("""
        import socket


        def probe(addr):
            sock = socket.create_connection(addr)
            try:
                sock.sendall(b"ping")
            finally:
                sock.close()

        def connect(addr):
            sock = socket.create_connection(addr)
            return sock
    """, ["R4"]))
    assert vs == []


def test_r4_suppressed_with_justification():
    vs = lint("""
        import threading


        class Pump:
            def __init__(self):
                self._thread = threading.Thread(target=self._loop, daemon=True)  # raylint: disable=R4 -- process-lifetime pump, dies with the interpreter by design
    """, ["R4"])
    assert len(vs) == 1 and vs[0].suppressed
    assert active(vs) == []


# ---------------------------------------------------------------------------
# R5 wire hygiene
# ---------------------------------------------------------------------------

WIRE_KW = dict(module="ray_tpu._private.wire",
               relpath="ray_tpu/_private/wire.py")


def test_r5_unregistered_frame_flagged():
    vs = active(lint("""
        class TaskCall:
            task_id: bytes
            depth: int
    """, ["R5"], **WIRE_KW))
    assert len(vs) == 1
    assert "not registered with @message" in vs[0].message


def test_r5_registered_frame_with_scalar_fields_is_clean():
    vs = active(lint("""
        @message("TaskCall", version=1)
        class TaskCall:
            task_id: bytes
            depth: int
    """, ["R5"], **WIRE_KW))
    assert vs == []


def test_r5_duplicate_name_bad_version_and_rich_field():
    vs = active(lint("""
        @message("Frame", version=1)
        class A:
            x: int


        @message("Frame", version=VERSION)
        class B:
            ref: ObjectRef
    """, ["R5"], **WIRE_KW))
    msgs = "\n".join(v.message for v in vs)
    assert "duplicate wire name 'Frame'" in msgs
    assert "version must be a literal int" in msgs
    assert "unsupported wire field type `ObjectRef`" in msgs


def test_r5_to_dict_without_from_dict_any_module():
    vs = active(lint("""
        class TaskEvent:
            def to_dict(self):
                return {}
    """, ["R5"]))
    assert len(vs) == 1
    assert "to_dict without from_dict" in vs[0].message


def test_r5_matched_pair_with_classmethod_is_clean():
    vs = active(lint("""
        class TaskEvent:
            def to_dict(self):
                return {}

            @classmethod
            def from_dict(cls, d):
                return cls()
    """, ["R5"]))
    assert vs == []


def test_r5_instance_method_from_dict_flagged():
    vs = active(lint("""
        class TaskEvent:
            def to_dict(self):
                return {}

            def from_dict(self, d):
                return TaskEvent()
    """, ["R5"]))
    assert len(vs) == 1
    assert "classmethod/staticmethod" in vs[0].message


# ---------------------------------------------------------------------------
# R6 unused imports
# ---------------------------------------------------------------------------


def test_r6_unused_import_flagged_used_and_noqa_clean():
    vs = active(lint("""
        import os
        import sys
        from typing import Dict  # noqa: F401  (re-export)


        def f():
            return sys.platform
    """, ["R6"]))
    assert len(vs) == 1
    assert "`os`" in vs[0].message


def test_r6_init_py_reexports_skipped():
    vs = active(lint("""
        from ray_tpu.serve.api import deployment
    """, ["R6"], module="ray_tpu.serve",
        relpath="ray_tpu/serve/__init__.py"))
    assert vs == []


def test_r6_string_annotation_counts_as_use():
    vs = active(lint("""
        from typing import Optional


        def f(x: "Optional") -> None:
            return None
    """, ["R6"]))
    assert vs == []


# ---------------------------------------------------------------------------
# R0 meta rule: suppressions must be justified
# ---------------------------------------------------------------------------


def test_r0_bare_suppression_fails_and_does_not_suppress():
    vs = lint("""
        import time


        async def handler():
            time.sleep(0.1)  # raylint: disable=R1
    """, ["R1"])
    act = active(vs)
    rules = sorted(v.rule for v in act)
    assert rules == ["R0", "R1"], (
        "a bare disable must both fail R0 and leave the original "
        "violation active")


def test_suppression_only_covers_named_rules():
    vs = lint("""
        import time


        async def handler():
            time.sleep(0.1)  # raylint: disable=R2 -- wrong rule named
    """, ["R1"])
    act = active(vs)
    assert [v.rule for v in act] == ["R1"]


# ---------------------------------------------------------------------------
# R7 ambient-state hygiene
# ---------------------------------------------------------------------------


def test_r7_discarded_ambient_token_flagged():
    vs = active(lint("""
        from ray_tpu._private.task_spec import set_ambient_job_id


        def tag(job):
            set_ambient_job_id(job)
    """, ["R7"]))
    assert len(vs) == 1 and vs[0].rule == "R7"
    assert "discards the restore token" in vs[0].message


def test_r7_captured_token_without_finally_restore_flagged():
    vs = active(lint("""
        from ray_tpu._private.task_spec import set_ambient_job_id


        def tag(job):
            prev = set_ambient_job_id(job)
            do_work()
            set_ambient_job_id(prev)  # restore NOT in a finally
    """, ["R7"]))
    # The restore outside a finally is itself a discarded-token set,
    # and the guarded set never restores on the exception path.
    assert vs and all(v.rule == "R7" for v in vs)
    msgs = "\n".join(v.message for v in vs)
    assert "never restored" in msgs or "discards" in msgs


def test_r7_token_try_finally_pattern_clean():
    vs = active(lint("""
        from ray_tpu._private.task_spec import (set_ambient_job_id,
                                                set_ambient_trace_parent)


        def tag(job, trace):
            prev = set_ambient_job_id(job) if job is not None else None
            tp = set_ambient_trace_parent(trace)
            try:
                return do_work()
            finally:
                set_ambient_job_id(prev)
                if trace is not None:
                    set_ambient_trace_parent(tp)
    """, ["R7"]))
    assert vs == []


def test_r7_nested_try_finally_restore_is_seen():
    """The restore may live in an inner try/finally — containment must
    follow real finally scoping, not flat tree order."""
    vs = active(lint("""
        from ray_tpu._private.task_spec import set_ambient_job_id


        def tag(job):
            prev = set_ambient_job_id(job)
            try:
                before()
                try:
                    return do_work()
                finally:
                    set_ambient_job_id(prev)
            finally:
                after()
    """, ["R7"]))
    assert vs == []


def test_r7_grow_only_registry_flagged_and_reset_api_clean():
    grow_only = """
        _REGISTRY = {}


        def register(name, value):
            _REGISTRY[name] = value
    """
    vs = active(lint(grow_only, ["R7"]))
    assert len(vs) == 1 and "only ever grows" in vs[0].message

    with_removal = grow_only + """

        def unregister(name):
            _REGISTRY.pop(name, None)
    """
    assert active(lint(with_removal, ["R7"])) == []

    # A reset-NAMED function referencing the registry also counts,
    # even when it mutates entries in place (the perf_stats.reset
    # shape).
    with_reset = grow_only + """

        def reset():
            for k in _REGISTRY:
                _REGISTRY[k] = None
    """
    assert active(lint(with_reset, ["R7"])) == []


def test_r7_import_time_memo_table_and_slot_box_clean():
    vs = active(lint("""
        _TABLE = []
        for _i in range(256):
            _TABLE.append(_i * 31)

        _BOX = [None]


        def set_box(v):
            _BOX[0] = v


        def lookup(i):
            return _TABLE[i & 0xFF]
    """, ["R7"]))
    assert vs == []


def test_r7_suppressed_with_justification():
    vs = lint("""
        _CATALOG = {}  # raylint: disable=R7 -- append-only by contract


        def register(name, cls):
            _CATALOG[name] = cls
    """, ["R7"])
    assert active(vs) == []
    assert len([v for v in vs if v.suppressed]) == 1


# ---------------------------------------------------------------------------
# stale-suppression audit
# ---------------------------------------------------------------------------


def test_stale_suppression_flagged_and_live_one_not():
    import textwrap

    from tools.raylint.core import (FileInfo, run_rules,
                                    stale_suppressions)
    from tools.raylint.rules import select_rules

    src = textwrap.dedent("""
        import time


        async def live():
            time.sleep(0.1)  # raylint: disable=R1 -- still fires here


        def stale():
            return 1  # raylint: disable=R1 -- nothing fires here
    """)
    fi = FileInfo(path="fixture.py", relpath="fixture.py",
                  module="fixture", source=src)
    violations = run_rules([fi], select_rules(["R1"]))
    stale = stale_suppressions([fi], violations)
    assert len(stale) == 1
    assert stale[0].line == 10 and stale[0].rule == "R1"
    assert "stale" in stale[0].message


def test_analyze_reports_stale_only_for_rules_it_ran(tmp_path):
    """A rule the analyzer did not run cannot prove its suppressions
    stale — `--rule R6` must not call an R1 suppression dead."""
    from tools.raylint.core import analyze
    from tools.raylint.rules import select_rules

    f = tmp_path / "mod.py"
    f.write_text("def f():\n"
                 "    return 1  # raylint: disable=R1 -- was blocking\n")
    report = analyze([str(f)], rules=select_rules(["R6"]),
                     root=str(tmp_path))
    assert report.stale == []
    report = analyze([str(f)], rules=select_rules(["R1"]),
                     root=str(tmp_path))
    assert [v.line for v in report.stale] == [2]


# ---------------------------------------------------------------------------
# R8 yield-point hygiene
# ---------------------------------------------------------------------------


def test_r8_registered_literal_points_clean():
    vs = active(lint("""
        from ray_tpu._private import sanitize_hooks


        def handoff():
            sanitize_hooks.sched_point("router.handoff")
            sanitize_hooks.crash_point("gcs.commit.before")
    """, ["R8"]))
    assert vs == []


def test_r8_unregistered_name_flagged():
    vs = active(lint("""
        from ray_tpu._private import sanitize_hooks


        def handoff():
            sanitize_hooks.sched_point("router.handofff")
    """, ["R8"]))
    assert len(vs) == 1 and vs[0].rule == "R8"
    assert "not in the registered point catalog" in vs[0].message
    assert "silently never gates" in vs[0].message


def test_r8_computed_name_flagged():
    vs = active(lint("""
        from ray_tpu._private import sanitize_hooks


        def cross(which):
            sanitize_hooks.sched_point(f"router.{which}")
    """, ["R8"]))
    assert len(vs) == 1
    assert "must be a literal string" in vs[0].message


def test_r8_wrong_hook_kind_gets_a_hint():
    vs = active(lint("""
        from ray_tpu._private import sanitize_hooks


        def commit():
            sanitize_hooks.sched_point("gcs.commit.before")
    """, ["R8"]))
    assert len(vs) == 1
    assert "wrong hook?" in vs[0].message


def test_r8_bare_imported_name_form_is_checked():
    vs = active(lint("""
        from ray_tpu._private.sanitize_hooks import sched_point


        def cross():
            sched_point("totally.bogus")
    """, ["R8"]))
    assert len(vs) == 1 and "not in the registered" in vs[0].message


def test_r8_missing_argument_flagged():
    vs = active(lint("""
        from ray_tpu._private import sanitize_hooks


        def cross():
            sanitize_hooks.sched_point()
    """, ["R8"]))
    assert len(vs) == 1 and "without a point name" in vs[0].message


def test_r8_tools_and_tests_exempt():
    # The scheduler side of the seam crosses synthetic/test-local
    # names by design (mc.start.*, router.buggy_gap) — only ray_tpu
    # product files are held to the registry.
    vs = active(lint("""
        from ray_tpu._private import sanitize_hooks


        def drive(role):
            sanitize_hooks.sched_point(f"mc.start.{role}")
            sanitize_hooks.sched_point("router.buggy_gap")
    """, ["R8"], module="tools.raymc.fixture",
        relpath="tools/raymc/fixture.py"))
    assert vs == []


def test_r8_suppression_with_justification_honored():
    vs = lint("""
        from ray_tpu._private import sanitize_hooks


        def cross():
            sanitize_hooks.sched_point("experimental.point")  # raylint: disable=R8 -- staged rollout: registered in the next PR alongside its raymc scenario
    """, ["R8"])
    assert all(v.suppressed for v in vs if v.rule == "R8")


def test_r8_aliased_imports_still_checked():
    # `as` renames must not smuggle a typo'd point past the rule.
    vs = active(lint("""
        from ray_tpu._private import sanitize_hooks as sh
        from ray_tpu._private.sanitize_hooks import sched_point as sp


        def cross():
            sh.sched_point("router.handofff")
            sp("also.bogus")
    """, ["R8"]))
    assert len(vs) == 2, vs
    assert all("not in the registered" in v.message for v in vs)


# ---------------------------------------------------------------------------
# R9 spec-coverage
# ---------------------------------------------------------------------------


def test_r9_registered_literal_taps_clean():
    vs = active(lint("""
        from ray_tpu._private import sanitize_hooks


        class Core:
            def charge(self, job):
                sanitize_hooks.spec_op("spec.quota.charge", "call",
                                       self, (job, 1, 2))
                sanitize_hooks.spec_op("spec.quota.charge", "ret",
                                       self, True)
    """, ["R9"]))
    assert vs == []


def test_r9_unregistered_point_flagged():
    vs = active(lint("""
        from ray_tpu._private import sanitize_hooks


        class Core:
            def charge(self):
                sanitize_hooks.spec_op("spec.quota.chargee", "call",
                                       self)
    """, ["R9"]))
    assert len(vs) == 1 and vs[0].rule == "R9"
    assert "not in sanitize_hooks.SPEC_POINTS" in vs[0].message


def test_r9_computed_point_and_phase_flagged():
    vs = active(lint("""
        from ray_tpu._private import sanitize_hooks


        class Core:
            def op(self, which, phase):
                sanitize_hooks.spec_op(f"spec.quota.{which}", "call",
                                       self)
                sanitize_hooks.spec_op("spec.quota.charge", phase,
                                       self)
    """, ["R9"]))
    msgs = "\n".join(v.message for v in vs)
    assert len(vs) == 2, vs
    assert "must be a literal string" in msgs
    assert "invocation/response pairing" in msgs


def test_r9_tap_without_catalog_entry_flagged():
    from tools.raylint.core import analyze_source
    from tools.raylint.rules.r9_spec_coverage import SpecCoverageRule
    import textwrap

    rule = SpecCoverageRule(
        registry=frozenset({"spec.orphan.op"}), prefixes={})
    vs = [v for v in analyze_source(textwrap.dedent("""
        from ray_tpu._private import sanitize_hooks


        class Core:
            def op(self):
                sanitize_hooks.spec_op("spec.orphan.op", "call", self)
    """), [rule], module="ray_tpu.fixture_mod") if not v.suppressed]
    assert len(vs) == 1
    assert "no rayspec SPEC_CATALOG entry" in vs[0].message


def test_r9_tools_and_tests_exempt():
    vs = active(lint("""
        from ray_tpu._private import sanitize_hooks


        def drive(core):
            sanitize_hooks.spec_op("totally.bogus", "call", core)
    """, ["R9"], module="tools.rayspec.fixture",
        relpath="tools/rayspec/fixture.py"))
    assert vs == []


def test_r9_suppression_with_justification_honored():
    vs = lint("""
        from ray_tpu._private import sanitize_hooks


        class Core:
            def op(self):
                sanitize_hooks.spec_op("spec.future.op", "call", self)  # raylint: disable=R9 -- staged rollout: registered next PR with its spec
    """, ["R9"])
    assert all(v.suppressed for v in vs if v.rule == "R9")


def test_r9_cross_file_coverage_halves():
    """Finalize half: a catalog entry with no product tap and a
    registry point never crossed both anchor findings on the registry
    module — and only when that module is in the linted set."""
    from tools.raylint.core import FileInfo, run_rules
    from tools.raylint.rules.r9_spec_coverage import SpecCoverageRule

    registry_src = "SPEC_POINTS = frozenset()\n"
    product_src = (
        "from ray_tpu._private import sanitize_hooks\n\n\n"
        "class Core:\n"
        "    def op(self):\n"
        "        sanitize_hooks.spec_op('spec.quota.charge', 'call',"
        " self)\n")
    registry_fi = FileInfo(
        path="ray_tpu/_private/sanitize_hooks.py",
        relpath="ray_tpu/_private/sanitize_hooks.py",
        module="ray_tpu._private.sanitize_hooks", source=registry_src)
    product_fi = FileInfo(
        path="ray_tpu/_private/core.py",
        relpath="ray_tpu/_private/core.py",
        module="ray_tpu._private.core", source=product_src)
    rule = SpecCoverageRule(
        registry=frozenset({"spec.quota.charge", "spec.dead.point"}),
        prefixes={"spec.quota.": "quota_ledger",
                  "spec.ghost.": "ghost_core"})
    vs = [v for v in run_rules([registry_fi, product_fi], [rule])
          if not v.suppressed]
    msgs = "\n".join(v.message for v in vs)
    assert "ghost_core" in msgs and "no product spec_op tap" in msgs
    assert "'spec.dead.point' is never crossed" in msgs
    assert all(v.path.endswith("sanitize_hooks.py") for v in vs)
    # Without the registry module in the set, the cross-file half
    # stays quiet (partial lints must not produce spurious findings).
    vs = [v for v in run_rules([product_fi], [rule])
          if not v.suppressed]
    assert vs == []


# ---------------------------------------------------------------------------
# R10 length-before-allocation
# ---------------------------------------------------------------------------


def test_r10_unguarded_exact_read_flagged():
    vs = active(lint("""
        import struct

        _LEN = struct.Struct("!I")


        def recv_msg(sock):
            (length,) = _LEN.unpack(_recv_exact(sock, 4))
            return _recv_exact(sock, length)
    """, ["R10"]))
    assert len(vs) == 1 and vs[0].rule == "R10"
    assert "decoded off the wire" in vs[0].message
    assert "a peer controls this allocation" in vs[0].message


def test_r10_indexed_unpack_flagged():
    # The canonical one-liner idiom binds through a Subscript, not the
    # bare Call — the taint must see through the [0].
    vs = active(lint("""
        import struct


        def read_frame(sock):
            n = struct.unpack("!I", _recv_exact(sock, 4))[0]
            return _recv_exact(sock, n)
    """, ["R10"]))
    assert len(vs) == 1 and vs[0].rule == "R10"
    assert "`n`" in vs[0].message


def test_r10_guarded_read_clean():
    vs = active(lint("""
        import struct

        _LEN = struct.Struct("!I")


        def recv_msg(sock, cap):
            (length,) = _LEN.unpack(_recv_exact(sock, 4))
            if length > cap:
                raise ValueError("frame too large")
            return _recv_exact(sock, length)
    """, ["R10"]))
    assert vs == []


def test_r10_from_bytes_into_read_flagged():
    vs = active(lint("""
        def read_record(f):
            n = int.from_bytes(f.read(8), "big")
            return f.read(n)
    """, ["R10"]))
    assert len(vs) == 1
    assert "`read()`" in vs[0].message


def test_r10_multiplied_allocation_flagged():
    vs = active(lint("""
        import struct


        def slab(sock):
            count, = struct.unpack("!I", sock.recv(4))
            return bytearray(count * 8)
    """, ["R10"]))
    assert len(vs) == 1
    assert "multiplied allocation" in vs[0].message


def test_r10_bytearray_after_compare_clean():
    vs = active(lint("""
        def read_record(f, limit):
            n = int.from_bytes(f.read(8), "big")
            if n >= limit:
                raise ValueError("record too large")
            return bytearray(n)
    """, ["R10"]))
    assert vs == []


def test_r10_outside_package_exempt():
    # Same unguarded source, but in tools/ (fi.package is None): the
    # rule only patrols product code.
    vs = active(lint("""
        import struct


        def recv_msg(sock):
            (length,) = struct.unpack("!I", sock.recv(4))
            return sock.recv(length)
    """, ["R10"], module="tools.fixture_mod",
        relpath="tools/fixture_mod.py"))
    assert vs == []


def test_r10_suppression_with_justification_honored():
    vs = lint("""
        import struct


        def recv_msg(sock):
            (length,) = struct.unpack("!I", sock.recv(4))
            return sock.recv(length)  # raylint: disable=R10 -- trusted local pipe, bounded by the writer
    """, ["R10"])
    assert len(vs) == 1 and vs[0].suppressed
