"""Worker process isolation: a crashing task/actor cannot take down the
node. Reference: raylet WorkerPool (`src/ray/raylet/worker_pool.h:156`) —
forked workers execute tasks; worker death is a task failure, not a node
failure.
"""

import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.exceptions import WorkerCrashedError

# Multi-process / soak tests: excluded from the quick
# tier (pytest -m 'not slow').
pytestmark = pytest.mark.slow


@pytest.fixture
def ray_local():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_isolated_task_runs_out_of_process(ray_local):
    @ray_tpu.remote(isolate_process=True)
    def whoami():
        return os.getpid()

    pid = ray_tpu.get(whoami.remote(), timeout=60)
    assert pid != os.getpid()


def test_isolated_task_crash_is_task_error_not_node_death(ray_local):
    @ray_tpu.remote(isolate_process=True, max_retries=0)
    def die():
        os._exit(1)

    @ray_tpu.remote
    def alive():
        return "still here"

    with pytest.raises(Exception) as ei:
        ray_tpu.get(die.remote(), timeout=60)
    assert "worker process" in str(ei.value).lower() or \
        isinstance(ei.value, WorkerCrashedError)
    # The node survived: plain tasks still run.
    assert ray_tpu.get(alive.remote(), timeout=60) == "still here"
    # And so do further isolated tasks (the pool replaced the worker).
    @ray_tpu.remote(isolate_process=True)
    def ok():
        return 7

    assert ray_tpu.get(ok.remote(), timeout=60) == 7


def test_isolated_task_roundtrips_numpy(ray_local):
    @ray_tpu.remote(isolate_process=True)
    def make(n):
        return np.arange(n, dtype=np.float32)

    out = ray_tpu.get(make.remote(4096), timeout=60)
    assert out.shape == (4096,) and out[-1] == 4095.0


def test_isolated_task_exception_propagates(ray_local):
    @ray_tpu.remote(isolate_process=True, max_retries=0)
    def boom():
        raise ValueError("inner detail")

    with pytest.raises(ValueError, match="inner detail"):
        ray_tpu.get(boom.remote(), timeout=60)


def test_isolated_actor_state_and_crash_restart(ray_local):
    @ray_tpu.remote(isolate_process=True, max_restarts=1)
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

        def pid(self):
            return os.getpid()

        def crash(self):
            os._exit(1)

    c = Counter.remote()
    assert ray_tpu.get(c.incr.remote(), timeout=60) == 1
    assert ray_tpu.get(c.incr.remote(), timeout=60) == 2
    pid1 = ray_tpu.get(c.pid.remote(), timeout=60)
    assert pid1 != os.getpid()

    with pytest.raises(Exception):
        ray_tpu.get(c.crash.remote(), timeout=60)

    # Restarted in a fresh process with fresh state.
    def restarted():
        try:
            return ray_tpu.get(c.incr.remote(), timeout=5) == 1
        except Exception:
            return False

    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if restarted():
            break
        time.sleep(0.2)
    else:
        raise AssertionError("actor did not restart after worker crash")
    pid2 = ray_tpu.get(c.pid.remote(), timeout=60)
    assert pid2 != pid1


def test_isolated_actor_without_budget_dies(ray_local):
    @ray_tpu.remote(isolate_process=True)
    class A:
        def crash(self):
            os._exit(1)

        def f(self):
            return 1

    a = A.remote()
    assert ray_tpu.get(a.f.remote(), timeout=60) == 1
    with pytest.raises(Exception):
        ray_tpu.get(a.crash.remote(), timeout=60)
    with pytest.raises(Exception):
        ray_tpu.get(a.f.remote(), timeout=30)


def test_isolated_actor_call_replays_with_retry_budget(ray_local,
                                                       tmp_path):
    """Restart-window mailbox contract: the call EXECUTING when the
    worker crashes replays on the replacement iff it carries
    max_task_retries budget — and returns the retried result."""
    marker = str(tmp_path / "crashed-once")

    @ray_tpu.remote(isolate_process=True, max_restarts=1,
                    max_task_retries=1)
    class FlakyOnce:
        def work(self, marker):
            if not os.path.exists(marker):
                open(marker, "w").close()
                os._exit(1)  # first attempt: worker dies mid-call
            return "retried-ok"

    actor = FlakyOnce.remote()
    assert ray_tpu.get(actor.work.remote(marker),
                       timeout=120) == "retried-ok"


def test_isolated_actor_call_without_budget_rejects_naming_it(
        ray_local):
    from ray_tpu.exceptions import ActorUnavailableError

    @ray_tpu.remote(isolate_process=True, max_restarts=1)
    class Crasher:  # max_task_retries=0
        def crash(self):
            os._exit(1)

        def f(self):
            return "alive"

    actor = Crasher.remote()
    with pytest.raises(ActorUnavailableError) as ei:
        ray_tpu.get(actor.crash.remote(), timeout=120)
    assert "max_task_retries" in str(ei.value)
    # The actor itself restarted (budget 1) and keeps serving.
    assert ray_tpu.get(actor.f.remote(), timeout=120) == "alive"


def test_isolation_in_cluster_node_survives(tmp_path):
    """A crashing isolated task on a cluster node leaves the node alive."""
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(head_node_args={"num_cpus": 1})
    try:
        node = cluster.add_node(num_cpus=2)

        @ray_tpu.remote(num_cpus=2, isolate_process=True, max_retries=0)
        def die():
            os._exit(1)

        @ray_tpu.remote(num_cpus=2)
        def where():
            return os.getpid()

        with pytest.raises(Exception):
            ray_tpu.get(die.remote(), timeout=60)
        assert cluster.head.nodes[node].alive
        pid = ray_tpu.get(where.remote(), timeout=60)
        assert pid != os.getpid()  # node still executing work
    finally:
        cluster.shutdown()
