"""raymc validation: the checker earns its keep the same way raysan
did — against the repo's own historical races, REVERTED under
monkeypatch. The bar is strictly higher than the raysan replay suite:
there, the racy interleaving is hand-scripted; here raymc must
*discover* it from nothing but the yield-point map and an invariant,
then hand back a minimized Schedule script that replays it
deterministically.

Also covers: explorer determinism and prefix replay, crash-branch
exploration and budgets, sleep-set pruning, ddmin minimizer units, the
crash-fault durability property (clean exhaustively; lost-fsync bug
discovered — slow-marked), bounded exactly-once/long-poll checks, and
the CLI's exit-code contract.
"""

import json

import pytest

from ray_tpu._private import sanitize_hooks
from ray_tpu._private.gcs_storage import SqliteStoreClient
from ray_tpu._private.rpc import PipelinedClient
from ray_tpu.serve._private.router import Router

from tests.core.test_concurrency_races import (_buggy_close,
                                               _buggy_try_assign)
from tools.raymc import (ExplorerConfig, Invariant, Liveness, Scenario,
                         check)
from tools.raymc.explorer import Decision, Execution, ExecutionResult
from tools.raymc.minimize import minimize_decisions
from tools.raymc.scenarios import (ExactlyOnceResubmitScenario,
                                   GroupCommitDurabilityScenario,
                                   LongPollRecoveryScenario,
                                   PipelinedCloseScenario,
                                   RouterCapScenario)
from tools.raysan.sched import Schedule


def _cfg(**kw):
    kw.setdefault("max_schedules", 400)
    kw.setdefault("time_budget_s", 60.0)
    return ExplorerConfig(**kw)


# -- explorer mechanics on a toy scenario ------------------------------------


class _LostUpdate(Scenario):
    """Textbook read-modify-write race: both increments read before
    either writes. The explorer must both FIND the racy interleaving
    and prove the clean ones clean."""

    name = "toy_lost_update"
    points = ("toy.rmw",)
    max_steps = 12

    def setup(self):
        self.v = 0

    def actions(self):
        def inc():
            tmp = self.v
            sanitize_hooks.sched_point("toy.rmw")
            self.v = tmp + 1
        return [("inc-a", inc), ("inc-b", inc)]

    def liveness(self):
        return [Liveness("no-lost-update", lambda s: s.v == 2,
                         timeout_s=0.2,
                         description="both increments landed")]


class _AtomicUpdate(_LostUpdate):
    """The fixed twin: read happens after the yield point, inside one
    uninterrupted segment — no schedule can lose an update."""

    name = "toy_atomic_update"

    def actions(self):
        def inc():
            sanitize_hooks.sched_point("toy.rmw")
            self.v = self.v + 1
        return [("inc-a", inc), ("inc-b", inc)]


@pytest.mark.mc_harness
def test_toy_race_found_minimized_and_replayable():
    result = check(_LostUpdate, _cfg())
    assert result.findings, "the lost-update race was not discovered"
    f = result.findings[0]
    assert f.prop == "no-lost-update"
    ce = f.counterexample
    assert ce is not None and ce.verified_replays is True
    # And the emitted script replays it through plain raysan Schedule
    # machinery, outside the explorer:
    scn = _LostUpdate()
    msgs = scn.replay_under_schedule(
        Schedule(order=ce.schedule_order, timeout_s=3.0))
    assert any(m.startswith("no-lost-update") for m in msgs), msgs


@pytest.mark.mc_harness
def test_toy_clean_twin_passes_exhaustively():
    result = check(_AtomicUpdate, _cfg())
    assert not result.findings
    assert result.exhausted, (
        "the atomic twin's tiny space must drain exhaustively: "
        f"{result.to_dict()}")


@pytest.mark.mc_harness
def test_default_policy_and_prefix_replay_are_deterministic():
    r1 = Execution(_AtomicUpdate(), [], _cfg()).run()
    r2 = Execution(_AtomicUpdate(), [], _cfg()).run()
    assert [s.chosen for s in r1.steps] == [s.chosen for s in r2.steps]
    # Prefix replay: feeding a run's own decisions back reproduces it.
    decisions = [s.chosen for s in r1.steps]
    r3 = Execution(_AtomicUpdate(), decisions, _cfg()).run()
    assert r3.status == "ok"
    assert [s.chosen for s in r3.steps] == decisions


class _CrashToy(Scenario):
    name = "toy_crash"
    crash_points = ("mc.env.boom",)
    crash_budget = 1
    max_steps = 8
    observed_crashes = None  # set by the test via subclass

    def setup(self):
        pass

    def actions(self):
        return [("env",
                 lambda: sanitize_hooks.crash_point("mc.env.boom"))]

    def on_crash(self, point):
        type(self).observed_crashes.append(point)


@pytest.mark.mc_harness
def test_crash_branching_explores_both_worlds_within_budget():
    crashes = []

    class Probe(_CrashToy):
        observed_crashes = crashes

    result = check(Probe, _cfg())
    # Exactly two schedules: the fault-free one and the injected death
    # (budget 1 forbids a second kill from branching further).
    assert result.executions == 2, result.to_dict()
    assert result.exhausted
    assert crashes == ["mc.env.boom"]


class _TwoDomains(Scenario):
    """Two threads touching disjoint state, points in disjoint declared
    domains: sleep sets must prune the commuting reorder."""

    name = "toy_domains"
    points = ("xdom.p", "ydom.q")
    max_steps = 8

    def setup(self):
        self.a = self.b = 0

    def conflict_key(self, point):
        if point.startswith(("xdom.", "ydom.")):
            return point.split(".", 1)[0]
        return super().conflict_key(point)

    def actions(self):
        def ax():
            sanitize_hooks.sched_point("xdom.p")
            self.a += 1

        def by():
            sanitize_hooks.sched_point("ydom.q")
            self.b += 1
        return [("ax", ax), ("by", by)]

    def invariants(self):
        return [Invariant("domains-sane",
                          lambda s: s.a <= 1 and s.b <= 1)]


@pytest.mark.mc_harness
def test_sleep_sets_prune_commuting_reorderings():
    pruned_cfg = _cfg(dpor=True)
    full_cfg = _cfg(dpor=False)
    with_dpor = check(_TwoDomains, pruned_cfg)
    without = check(_TwoDomains, full_cfg)
    assert not with_dpor.findings and not without.findings
    assert with_dpor.exhausted and without.exhausted
    assert with_dpor.pruned > 0
    assert with_dpor.executions < without.executions, (
        f"DPOR explored {with_dpor.executions} vs "
        f"{without.executions} unpruned")


@pytest.mark.mc_harness
def test_minimizer_ddmin_unit_is_one_minimal():
    """Pure ddmin unit: a fake run that fails iff BOTH load-bearing
    decisions survive must shrink arbitrary noise down to exactly that
    pair, order preserved."""
    load_bearing = [Decision("a", "p.x", 1, False),
                    Decision("b", "p.y", 1, True)]
    noise = [Decision(f"n{i}", "p.z", 1, False) for i in range(6)]
    decisions = [noise[0], load_bearing[0], *noise[1:4],
                 load_bearing[1], *noise[4:]]

    def fake_run(prefix):
        hit = all(d in prefix for d in load_bearing)
        return ExecutionResult(
            status="violation" if hit else "ok", steps=[],
            crossings=[], pending=[],
            violations=["prop: boom"] if hit else [])

    minimal, res = minimize_decisions(fake_run, decisions, {"prop"})
    assert minimal == load_bearing
    assert res.status == "violation"


# -- the acceptance bar: historical fixes reverted, DISCOVERED ---------------


def test_raymc_discovers_reverted_router_handoff_and_replays_10_of_10(
        ray_start_regular, monkeypatch):
    """Fix reverted (PR 4's reserved→in-flight gap): raymc finds the
    cap oversubscription with NO schedule given — just the yield-point
    map and the invariant — and the minimized counterexample replays
    deterministically, ten for ten, through plain raysan Schedule."""
    monkeypatch.setattr(Router, "_try_assign", _buggy_try_assign)
    result = check(RouterCapScenario, _cfg())
    assert result.findings, (
        "raymc failed to rediscover the historical router handoff race")
    f = result.findings[0]
    assert f.prop == "router-cap"
    ce = f.counterexample
    assert ce is not None and ce.verified_replays is True
    # Canonical, minimal: the two dispatch windows plus bracket gates.
    assert len(ce.schedule_order) <= 8, ce.schedule_order
    assert not ce.crash_at
    for attempt in range(10):
        scn = RouterCapScenario()
        msgs = scn.replay_under_schedule(
            Schedule(order=ce.schedule_order, timeout_s=3.0))
        assert any(m.startswith("router-cap") for m in msgs), (
            f"replay {attempt + 1}/10 did not reproduce: {msgs}\n"
            f"script: {ce.schedule_order}")


def test_router_cap_clean_with_fix_exhaustive(ray_start_regular):
    result = check(RouterCapScenario, _cfg())
    assert not result.findings, [f.render() for f in result.findings]
    assert result.exhausted, result.to_dict()


def test_raymc_discovers_reverted_pipelined_close(ray_start_regular,
                                                  monkeypatch):
    """Fix reverted (close set ``_closed`` before the flush): raymc
    finds the orphan-sweep of an about-to-be-acked request without a
    script, and the counterexample replays."""
    monkeypatch.setattr(PipelinedClient, "close", _buggy_close)
    result = check(PipelinedCloseScenario, _cfg(time_budget_s=90))
    assert result.findings, (
        "raymc failed to rediscover the close-before-flush orphan "
        "sweep")
    props = {f.prop for f in result.findings}
    assert "close-no-orphan" in props
    f = [x for x in result.findings if x.prop == "close-no-orphan"][0]
    assert f.counterexample is not None
    assert f.counterexample.verified_replays is True
    for _ in range(2):
        scn = PipelinedCloseScenario()
        msgs = scn.replay_under_schedule(
            Schedule(order=f.counterexample.schedule_order,
                     timeout_s=5.0))
        assert any(m.startswith("close-no-orphan") for m in msgs), msgs


def test_pipelined_close_clean_with_fix(ray_start_regular):
    result = check(PipelinedCloseScenario, _cfg(time_budget_s=90))
    assert not result.findings, [f.render() for f in result.findings]
    assert result.exhausted, result.to_dict()


# -- crash-fault properties --------------------------------------------------


def test_gcs_durability_clean_exhaustive():
    """Real group commit survives EVERY bounded interleaving and crash
    placement: acked writes durable, uncommitted writes dead."""
    result = check(GroupCommitDurabilityScenario, _cfg())
    assert not result.findings, [f.render() for f in result.findings]
    assert result.exhausted, (
        f"the small-scope durability check must drain exhaustively: "
        f"{result.to_dict()}")


@pytest.mark.slow
def test_gcs_discovers_lost_fsync_bug(monkeypatch):
    """Inject the classic lost-fsync bug (dirty flag cleared, COMMIT
    skipped): crash exploration must find the acked-write loss and
    emit a replayable crash counterexample."""

    def buggy_flush(self):
        with self._lock:
            sanitize_hooks.crash_point("gcs.commit.before")
            sanitize_hooks.crash_point("gcs.commit.after")
            self._dirty.clear()

    monkeypatch.setattr(SqliteStoreClient, "flush", buggy_flush)
    result = check(GroupCommitDurabilityScenario,
                   _cfg(max_schedules=600, time_budget_s=150))
    assert result.findings, "lost-fsync bug not discovered"
    f = result.findings[0]
    assert f.prop == "gcs-durability"
    assert f.counterexample is not None
    assert f.counterexample.crash_at, (
        "the counterexample must pin the injected death to a crossing")
    assert f.counterexample.verified_replays is True


def test_exactly_once_resubmit_holds_under_connection_death():
    kills = []

    class Probe(ExactlyOnceResubmitScenario):
        def on_crash(self, point):
            kills.append(point)
            super().on_crash(point)

    result = check(Probe, _cfg(max_schedules=10, time_budget_s=60))
    assert not result.findings, [f.render() for f in result.findings]
    assert kills, "no explored schedule injected the connection death"


def test_longpoll_membership_converges_across_controller_restart(
        ray_start_regular):
    kills = []

    class Probe(LongPollRecoveryScenario):
        def on_crash(self, point):
            kills.append(point)
            super().on_crash(point)

    result = check(Probe, _cfg(max_schedules=10, time_budget_s=60))
    assert not result.findings, [f.render() for f in result.findings]
    assert kills, "no explored schedule killed the controller"


# -- CLI contract ------------------------------------------------------------


@pytest.mark.mc_harness
def test_cli_list_and_unknown_scenario(capsys):
    from tools.raymc.__main__ import main

    assert main(["--list"]) == 0
    out = capsys.readouterr().out
    assert "router_cap" in out and "gcs_durability" in out
    assert main(["--scenario", "no_such_thing"]) == 2


@pytest.mark.mc_harness
def test_cli_reports_findings_with_exit_1(tmp_path, capsys,
                                          monkeypatch):
    from tools.raymc import scenarios as scenarios_mod
    from tools.raymc.__main__ import main

    monkeypatch.setitem(scenarios_mod.SCENARIOS, "toy_lost_update",
                        _LostUpdate)
    report_path = tmp_path / "report.json"
    rc = main(["--scenario", "toy_lost_update", "--report", "json",
               "--report-file", str(report_path)])
    assert rc == 1
    report = json.loads(report_path.read_text())
    assert report["pass"] is False
    [scenario] = report["scenarios"]
    assert scenario["scenario"] == "toy_lost_update"
    assert scenario["findings"], scenario
    ce = scenario["findings"][0]["counterexample"]
    assert ce["schedule_order"], "report must carry the replay script"
    # stdout carried the JSON report too
    assert '"pass": false' in capsys.readouterr().out


@pytest.mark.mc_harness
def test_cli_clean_scenario_exit_0(tmp_path):
    from tools.raymc.__main__ import main

    report_path = tmp_path / "report.json"
    rc = main(["--scenario", "gcs_durability", "--report", "json",
               "--report-file", str(report_path)])
    assert rc == 0
    report = json.loads(report_path.read_text())
    assert report["pass"] is True
    assert report["scenarios"][0]["exhausted"] is True
