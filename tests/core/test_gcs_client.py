"""Typed GCS accessor client from a NODE process + events + dashboard
log/event modules (reference gcs_client.h:61, dashboard log/event
modules, util/event.h)."""

import json
import urllib.request

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster

pytestmark = pytest.mark.slow  # multi-process cluster


def test_gcs_client_accessors_from_node_process():
    cluster = Cluster(head_node_args={"num_cpus": 1})
    try:
        cluster.add_node(num_cpus=4)
        ray_tpu.get(ray_tpu.put(1))  # settle

        # seed state: kv + a named actor
        @ray_tpu.remote
        class Named:
            def ping(self):
                return 1

        a = Named.options(name="gcs_probe").remote()
        ray_tpu.get(a.ping.remote())

        address = cluster.address

        @ray_tpu.remote(num_cpus=2)
        def probe(addr):
            from ray_tpu._private.gcs_client import GcsClient

            gcs = GcsClient(addr)
            gcs.kv.put(b"k1", b"v1")
            assert gcs.kv.get(b"k1") == b"v1"
            assert b"k1" in gcs.kv.keys(b"k")
            gcs.kv.delete(b"k1")
            assert gcs.kv.get(b"k1") is None
            nodes = gcs.nodes.alive()
            named = gcs.actors.list_named()
            events = gcs.events.list()
            return (len(nodes), [str(n) for n in named],
                    [e["message"] for e in events])

        n_nodes, named, events = ray_tpu.get(probe.remote(address),
                                             timeout=120)
        assert n_nodes >= 1
        assert any("gcs_probe" in n for n in named), named
        assert any("joined" in m for m in events), events
        ray_tpu.kill(a)
    finally:
        cluster.shutdown()


def test_dashboard_logs_and_events_routes():
    from ray_tpu.dashboard import start_dashboard

    cluster = Cluster(head_node_args={"num_cpus": 1})
    dash = None
    try:
        cluster.add_node(num_cpus=2)

        @ray_tpu.remote(num_cpus=2)
        def speak():
            print("dashboard-sees-this")
            return 1

        assert ray_tpu.get(speak.remote()) == 1
        dash = start_dashboard(port=0)
        base = f"http://127.0.0.1:{dash.port}"

        logs = json.load(urllib.request.urlopen(f"{base}/api/logs",
                                                timeout=30))
        assert "node-1" in logs
        import time
        deadline = time.monotonic() + 20
        tail = ""
        while time.monotonic() < deadline:
            detail = json.load(urllib.request.urlopen(
                f"{base}/api/logs/node-1", timeout=30))
            tail = detail.get("tail", "")
            if "dashboard-sees-this" in tail:
                break
            time.sleep(0.5)
        assert "dashboard-sees-this" in tail

        events = json.load(urllib.request.urlopen(f"{base}/api/events",
                                                  timeout=30))
        assert any(e["source"] == "node" and "joined" in e["message"]
                   for e in events)
    finally:
        if dash is not None:
            from ray_tpu.dashboard import shutdown_dashboard

            shutdown_dashboard()  # clears the module singleton too
        cluster.shutdown()


def test_events_forward_from_node_and_pg_table_plain():
    from ray_tpu._private.gcs_client import GcsClient
    from ray_tpu.util.placement_group import (placement_group,
                                              remove_placement_group)

    cluster = Cluster(head_node_args={"num_cpus": 1})
    try:
        cluster.add_node(num_cpus=4)
        pg = placement_group([{"CPU": 0.1}] * 2, strategy="PACK")
        ray_tpu.get(pg.ready(), timeout=60)

        @ray_tpu.remote(num_cpus=2)
        def emit():
            from ray_tpu._private.events import record_event

            record_event("test-src", "hello-from-node")
            return 1

        assert ray_tpu.get(emit.remote(), timeout=60) == 1

        gcs = GcsClient(cluster.address)
        # forwarded node-process event is visible at the head
        import time
        deadline = time.monotonic() + 20
        msgs = []
        while time.monotonic() < deadline:
            msgs = [e["message"] for e in gcs.events.list()]
            if "hello-from-node" in msgs:
                break
            time.sleep(0.2)
        assert "hello-from-node" in msgs, msgs
        # pg table decodes into plain data (no runtime side effects)
        table = gcs.placement_groups.table()
        assert isinstance(table, dict) and table
        import json as _json
        _json.dumps(table)  # strictly plain
        remove_placement_group(pg)
    finally:
        cluster.shutdown()


def test_rpc_handler_stats_surface():
    """Per-handler control-plane latency stats (instrumented_io_context
    event-stats role) are recorded and served by the dashboard."""
    cluster = Cluster(head_node_args={"num_cpus": 1})
    try:
        cluster.add_node(num_cpus=4)

        @ray_tpu.remote(num_cpus=2)
        def work(i):
            return i

        assert ray_tpu.get([work.remote(i) for i in range(10)],
                           timeout=60) == list(range(10))
        stats = cluster.head.server.handler_stats()
        assert "report_objects" in stats, stats.keys()
        row = stats["report_objects"]
        # Output reports BATCH across tasks (round-5 reporter thread):
        # 10 results arrive in a handful of calls, not one per task.
        assert row["calls"] >= 1
        assert row["mean_ms"] >= 0 and row["max_ms"] >= row["mean_ms"]
        assert row["errors"] == 0
    finally:
        cluster.shutdown()
