"""Native shm object store: Python client tests (incl. cross-process)."""

import os
import subprocess
import sys

import numpy as np
import pytest

from ray_tpu._private.shm_store import ShmObjectStore


@pytest.fixture
def store():
    s = ShmObjectStore(name="/raytpu_pytest_store", capacity=16 * 2**20,
                       max_objects=128)
    yield s
    s.destroy()


def _oid(n: int) -> bytes:
    return n.to_bytes(4, "little") + b"\0" * 16


def test_bytes_roundtrip(store):
    assert store.put_bytes(_oid(1), b"hello world")
    view = store.get_bytes(_oid(1))
    assert bytes(view) == b"hello world"
    store.release(_oid(1))
    assert store.contains(_oid(1))
    assert store.delete(_oid(1))
    assert not store.contains(_oid(1))


def test_numpy_zero_copy(store):
    arr = np.arange(10000, dtype=np.float32).reshape(100, 100)
    assert store.put_numpy(_oid(2), arr)
    out = store.get_numpy(_oid(2))
    np.testing.assert_array_equal(out, arr)
    assert not out.flags.writeable
    # Zero-copy: the array's buffer lives in the shared map, not a copy.
    assert out.base is not None
    store.release(_oid(2))


def test_eviction_under_pressure(store):
    big = np.zeros(2 * 2**20, np.uint8)  # 2MB each into a 16MB store
    for i in range(10):
        assert store.put_numpy(_oid(100 + i), big)
    st = store.stats()
    assert st["evictions"] > 0
    # Most recent objects survive.
    assert store.contains(_oid(109))


def test_duplicate_create_fails(store):
    assert store.put_bytes(_oid(3), b"x")
    assert not store.put_bytes(_oid(3), b"y")


def test_cross_process_access(store):
    arr = np.arange(256, dtype=np.int64)
    assert store.put_numpy(_oid(7), arr)
    code = """
import numpy as np
from ray_tpu._private.shm_store import ShmObjectStore
s = ShmObjectStore(name="/raytpu_pytest_store", create=False)
oid = (7).to_bytes(4, "little") + b"\\0" * 16
out = s.get_numpy(oid)
assert out is not None and out.sum() == %d, out
s.release(oid)
s.put_bytes((8).to_bytes(4, "little") + b"\\0" * 16, b"from-child")
s.close()
print("child-ok")
""" % int(arr.sum())
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=60)
    assert "child-ok" in out.stdout, out.stderr
    # Parent sees the child's object.
    view = store.get_bytes(_oid(8))
    assert bytes(view) == b"from-child"
    store.release(_oid(8))


def test_publish_vs_close_stress():
    """Regression: `contains()`/`put_bytes` racing `close()` on another
    thread used to dereference the freed C handle (segfault at
    publish-vs-teardown). The op gate must turn late calls into benign
    misses and make close() wait for in-flight ones."""
    import threading

    for round_ in range(8):
        s = ShmObjectStore(name=f"/raytpu_pytest_gate{round_}",
                           capacity=8 * 2**20, max_objects=64)
        stop = threading.Event()
        errs = []

        def publisher():
            i = 0
            try:
                while not stop.is_set():
                    oid = _oid(1000 + (i % 32))
                    s.put_bytes(oid, b"p" * 512)
                    s.contains(oid)
                    s.object_size(oid)
                    s.delete(oid)
                    i += 1
            except BaseException as e:  # noqa: BLE001 - record, don't die
                errs.append(e)

        threads = [threading.Thread(target=publisher) for _ in range(4)]
        for t in threads:
            t.start()
        # Close while publishers are mid-flight — the old code
        # segfaulted here (no Python exception to catch: the process
        # died). Surviving the loop IS the assertion.
        s.close()
        stop.set()
        for t in threads:
            t.join(timeout=10.0)
        assert not errs, errs
        # Post-close calls are benign misses, not crashes.
        assert s.contains(_oid(1)) is False
        assert s.put_bytes(_oid(1), b"x") is False
        assert s.get_bytes(_oid(1)) is None
        assert s.refcount(_oid(1)) == -1
        try:
            s._lib.shm_store_destroy(s.name.encode())
        except Exception:
            pass
