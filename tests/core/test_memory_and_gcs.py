"""Memory monitor / worker killing + GCS storage backends.

Reference: `src/ray/common/memory_monitor.h:52`,
`worker_killing_policy_retriable_fifo.h`,
`gcs/store_client/{in_memory,redis}_store_client.h` (SQLite plays the
durable Redis role here).
"""

import time

import pytest

import ray_tpu
from ray_tpu._private.config import ray_config
from ray_tpu.exceptions import WorkerCrashedError

# Multi-process / soak tests: excluded from the quick
# tier (pytest -m 'not slow').
pytestmark = pytest.mark.slow


@pytest.fixture
def ray_local():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_memory_monitor_kills_newest_retriable_first(ray_local, monkeypatch):
    from ray_tpu._private.memory_monitor import MemoryMonitor

    backend = ray_tpu._private.worker.global_worker().backend

    @ray_tpu.remote(isolate_process=True, max_retries=0)
    def hog():
        time.sleep(30)
        return "survived"

    ref = hog.remote()
    # Wait until the worker registers as active.
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        pool = backend._worker_pool
        if pool is not None and pool.active:
            break
        time.sleep(0.05)
    assert backend._worker_pool.active

    monitor = MemoryMonitor(backend, usage_fn=lambda: 0.99)
    assert monitor.kill_one(0.99)  # policy found and killed a worker

    with pytest.raises(Exception) as ei:
        ray_tpu.get(ref, timeout=30)
    assert "worker process" in str(ei.value).lower()


def test_memory_monitor_retries_retriable_task(ray_local):
    from ray_tpu._private.memory_monitor import MemoryMonitor

    backend = ray_tpu._private.worker.global_worker().backend

    @ray_tpu.remote(isolate_process=True, max_retries=2,
                    retry_exceptions=[WorkerCrashedError])
    def flaky(marker_dir):
        import os
        import time as _t

        path = os.path.join(marker_dir, f"a{os.getpid()}")
        open(path, "w").close()
        if len(os.listdir(marker_dir)) < 2:
            _t.sleep(20)  # first attempt: park until the monitor kills us
        return len(os.listdir(marker_dir))

    import tempfile

    import os

    with tempfile.TemporaryDirectory() as d:
        ref = flaky.remote(d)
        monitor = MemoryMonitor(backend, usage_fn=lambda: 0.99)
        deadline = time.monotonic() + 20
        killed = False
        while time.monotonic() < deadline and not killed:
            pool = backend._worker_pool
            # Only kill once the first attempt has provably started (its
            # marker exists) — killing during worker startup would leave
            # the retry seeing a single marker.
            if pool is not None and pool.active and os.listdir(d):
                killed = monitor.kill_one(0.99)
            time.sleep(0.05)
        assert killed
        # The retry (fresh worker) sees 2 markers and returns.
        assert ray_tpu.get(ref, timeout=60) == 2


def test_system_memory_usage_readable():
    from ray_tpu._private.memory_monitor import (
        system_memory_usage_fraction,
    )

    usage = system_memory_usage_fraction()
    assert 0.0 < usage < 1.0


def test_gcs_storage_in_memory_and_sqlite(tmp_path):
    from ray_tpu._private.gcs_storage import (
        InMemoryStoreClient,
        SqliteStoreClient,
    )

    for store in (InMemoryStoreClient(),
                  SqliteStoreClient(str(tmp_path / "gcs.db"))):
        store.put("actors", b"a1", b"v1")
        store.put("actors", b"a2", b"v2")
        store.put("actors", b"a1", b"v1b")  # overwrite
        assert store.get("actors", b"a1") == b"v1b"
        assert store.get("jobs", b"a1") is None
        assert sorted(store.keys("actors")) == [b"a1", b"a2"]
        store.delete("actors", b"a2")
        assert store.get("actors", b"a2") is None
        store.close()


def test_kv_survives_head_restart(tmp_path, monkeypatch):
    """With a configured gcs_storage_path, internal KV outlives the
    worker process (the reference's Redis-backed GCS FT contract)."""
    monkeypatch.setattr(ray_config, "gcs_storage_path",
                        str(tmp_path / "gcs.db"))
    ray_tpu.shutdown()
    w = ray_tpu.init(num_cpus=1)
    w.gcs.kv_put(b"jobkey", b"payload", namespace=b"jobs")
    w.gcs.kv_put(b"other", b"x")
    ray_tpu.shutdown()

    w2 = ray_tpu.init(num_cpus=1)  # "restarted head"
    assert w2.gcs.kv_get(b"jobkey", namespace=b"jobs") == b"payload"
    assert w2.gcs.kv_get(b"other") == b"x"
    w2.gcs.kv_del(b"other")
    ray_tpu.shutdown()

    w3 = ray_tpu.init(num_cpus=1)
    assert w3.gcs.kv_get(b"other") is None
    ray_tpu.shutdown()
