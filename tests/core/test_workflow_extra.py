"""Round-3 workflow surface: management actor, events, cancel, true
resume from stored DAG, per-step retry/catch options."""

import threading
import time

import pytest

import ray_tpu
from ray_tpu import workflow


@pytest.fixture(autouse=True)
def ray():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


@ray_tpu.remote
def _double(x):
    return 2 * x


@ray_tpu.remote
def _add(a, b):
    return a + b


def test_resume_from_stored_dag(tmp_path):
    """resume() needs no DAG from the caller — it reloads the stored one."""
    workflow.init(str(tmp_path))
    flaky_calls = {"n": 0}
    marker = tmp_path / "fail_once"
    marker.write_text("x")

    @ray_tpu.remote
    def flaky(x):
        import os

        if os.path.exists(str(marker)):
            os.remove(str(marker))
            raise RuntimeError("boom")
        return x + 1

    dag = _double.bind(flaky.bind(10))
    with pytest.raises(Exception):
        workflow.run(dag, workflow_id="wfr")
    assert workflow.get_status("wfr") == "FAILED"
    # New "driver": no DAG in hand.
    out = workflow.resume("wfr")
    assert out == 22
    assert workflow.get_status("wfr") == "SUCCESSFUL"


def test_step_retry_and_catch(tmp_path):
    workflow.init(str(tmp_path))
    cnt = tmp_path / "attempts"
    cnt.write_text("0")

    @ray_tpu.remote
    def fails_twice():
        n = int(cnt.read_text())
        cnt.write_text(str(n + 1))
        if n < 2:
            raise RuntimeError(f"attempt {n}")
        return "ok"

    node = workflow.with_options(fails_twice.bind(), max_retries=3)
    assert workflow.run(node, workflow_id="wf-retry") == "ok"
    assert int(cnt.read_text()) == 3

    @ray_tpu.remote
    def always_fails():
        raise ValueError("nope")

    node = workflow.with_options(always_fails.bind(),
                                 catch_exceptions=True)
    result, err = workflow.run(node, workflow_id="wf-catch")
    assert result is None
    assert isinstance(err, Exception)
    assert workflow.get_status("wf-catch") == "SUCCESSFUL"


def test_event_trigger_unblocks(tmp_path):
    workflow.init(str(tmp_path))
    ev = workflow.wait_for_event("approval", timeout_s=10)
    dag = _add.bind(ev, 5)

    done = {}

    def runner():
        done["out"] = workflow.run(dag, workflow_id="wf-ev", dag_input=None)

    t = threading.Thread(target=runner)
    t.start()
    time.sleep(0.2)
    assert workflow.get_status("wf-ev") == "RUNNING"
    workflow.trigger_event("wf-ev", "approval", 37)
    t.join(timeout=15)
    assert done.get("out") == 42
    # Resume does not re-wait: the event step is durable.
    assert workflow.resume("wf-ev") == 42


def test_timer_listener(tmp_path):
    workflow.init(str(tmp_path))
    fire_at = time.time() + 0.3
    node = workflow.wait_for_event(workflow.TimerListener(fire_at))
    t0 = time.time()
    workflow.run(node, workflow_id="wf-timer")
    assert time.time() - t0 >= 0.25


def test_cancel_stops_at_step_boundary(tmp_path):
    workflow.init(str(tmp_path))

    @ray_tpu.remote
    def slow(x):
        time.sleep(0.3)
        return x

    # chain of slow steps; cancel lands between them
    dag = slow.bind(slow.bind(slow.bind(slow.bind(1))))
    err = {}

    def runner():
        try:
            workflow.run(dag, workflow_id="wf-cancel")
        except workflow.WorkflowCancelledError as e:
            err["e"] = e

    t = threading.Thread(target=runner)
    t.start()
    time.sleep(0.35)
    workflow.cancel("wf-cancel")
    t.join(timeout=10)
    assert "e" in err
    assert workflow.get_status("wf-cancel") == "CANCELED"
    # resume clears the flag and finishes
    assert workflow.resume("wf-cancel") == 1


def test_management_actor(tmp_path):
    workflow.init(str(tmp_path))
    mgr = workflow.get_management_actor(str(tmp_path))
    dag = _double.bind(21)
    out = ray_tpu.get(mgr.run_async.remote(dag, "wf-mgr", None))
    assert out == 42
    listing = dict(ray_tpu.get(mgr.list_all.remote()))
    assert listing.get("wf-mgr") == "SUCCESSFUL"
    assert ray_tpu.get(mgr.get_status.remote("wf-mgr")) == "SUCCESSFUL"
    # second lookup returns the same named actor
    again = workflow.get_management_actor()
    assert ray_tpu.get(again.get_status.remote("wf-mgr")) == "SUCCESSFUL"
