"""Shared-memory object plane: zero-copy cross-process objects.

Verifies the VERDICT round-1 item "wire the C++ store into the runtime":
large task outputs and puts travel through the native shm segment
(`src/object_store/store.cc`), and readers on the same host get numpy
views over shared memory — no pickle of the payload on the RPC plane.
"""

import numpy as np
import pytest


@pytest.fixture
def shm_cluster():
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(head_node_args={"num_cpus": 1},
                      shm_capacity=512 * 2**20)
    if cluster.shm_plane is None:
        cluster.shutdown()
        pytest.skip("shm store unavailable")
    yield cluster
    cluster.shutdown()
    ray_tpu.shutdown()


def test_large_put_lands_in_shm(shm_cluster):
    import ray_tpu

    arr = np.arange(1_000_000, dtype=np.float64)  # 8 MB
    ref = ray_tpu.put(arr)
    stats = shm_cluster.shm_plane.stats()
    assert stats["num_sealed"] >= 1
    assert shm_cluster.shm_plane.contains(ref.id)
    out = ray_tpu.get(ref)
    np.testing.assert_array_equal(out, arr)


def test_small_put_stays_on_heap(shm_cluster):
    import ray_tpu

    before = shm_cluster.shm_plane.stats()["num_sealed"]
    ref = ray_tpu.put({"tiny": 1})
    assert shm_cluster.shm_plane.stats()["num_sealed"] == before
    assert ray_tpu.get(ref) == {"tiny": 1}


def test_remote_large_output_read_zero_copy(shm_cluster):
    """A 100MB array produced on a worker node is read by the driver as a
    zero-copy view over the shared segment."""
    import ray_tpu

    shm_cluster.add_node(num_cpus=2)

    @ray_tpu.remote(num_cpus=2)
    def produce():
        # 100 MB; deterministic content for verification.
        return np.arange(13_107_200, dtype=np.float64)

    ref = produce.remote()
    out = ray_tpu.get(ref)
    assert out.nbytes == 104_857_600
    assert out[0] == 0 and out[-1] == 13_107_199
    # Zero-copy: the array does not own its data; it views the mapped
    # shm segment, so no pickle of the payload happened on the driver.
    assert not out.flags["OWNDATA"]
    assert not out.flags["WRITEABLE"]
    assert shm_cluster.shm_plane.contains(ref.id)


def test_driver_large_arg_readable_on_node(shm_cluster):
    """Driver-side put travels to the node through shm, not pickle RPC."""
    import ray_tpu

    shm_cluster.add_node(num_cpus=2)
    arr = np.full(2_000_000, 7.5)  # 16 MB
    ref = ray_tpu.put(arr)

    @ray_tpu.remote(num_cpus=2)
    def consume(x):
        return float(x.sum()), bool(x.flags["OWNDATA"])

    total, owns = ray_tpu.get(consume.remote(ref))
    assert total == 7.5 * 2_000_000
    assert not owns, "node received a heap copy, not a shm view"


def test_transfer_plane_cross_segment(shm_cluster):
    """A node simulating a remote host (own shm segment) produces a
    large object; the driver pulls it through the native chunked
    transfer server (C++ plane), not pickle RPC."""
    import ray_tpu

    shm_cluster.add_node(num_cpus=2, simulate_remote_host=True)

    @ray_tpu.remote(num_cpus=2)
    def produce():
        return np.arange(4_000_000, dtype=np.float64)  # 32 MB

    ref = produce.remote()
    out = ray_tpu.get(ref)
    assert out[0] == 0 and out[-1] == 3_999_999
    assert not out.flags["OWNDATA"], "expected zero-copy view after pull"
    # The object was pulled into the driver's own segment.
    assert shm_cluster.shm_plane.contains(ref.id)


def test_composite_value_with_arrays(shm_cluster):
    import ray_tpu

    shm_cluster.add_node(num_cpus=2)
    payload = {"w": np.ones((512, 512)), "step": 3,
               "names": ["a", "b"]}
    ref = ray_tpu.put(payload)

    @ray_tpu.remote(num_cpus=2)
    def check(d):
        return float(d["w"].sum()), d["step"], d["names"]

    s, step, names = ray_tpu.get(check.remote(ref))
    assert s == 512 * 512 and step == 3 and names == ["a", "b"]
