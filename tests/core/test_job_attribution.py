"""Per-job attribution plane + SLO/overload health signals.

Reference roles: the state API's JobID slicing (tasks/actors/objects
attributable to the submitting job) and the dashboard agent's per-node
psutil/health reporting, unified here with the SLO burn-rate and
overload verdict surface (`/api/healthz`).
"""

import json
import time

import pytest

import ray_tpu
from ray_tpu._private.config import ray_config
from ray_tpu._private.task_spec import set_ambient_job_id
from ray_tpu.experimental import state


@pytest.fixture
def ray_local():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_ambient_job_tag_propagation(ray_local):
    """One tag set at the entry point flows through .remote() chains,
    actor calls, and ray.put; clearing the ambient stops the flow."""

    @ray_tpu.remote
    def child(x):
        return x + 1

    @ray_tpu.remote
    def parent(x):
        # In-task submission: inherits the submitting task's tag even
        # though this executor thread never saw set_ambient_job_id.
        return ray_tpu.get(child.remote(x)) + 10

    @ray_tpu.remote
    class Acc:
        def add(self, x):
            return x

    prev = set_ambient_job_id("tenant-a")
    try:
        assert ray_tpu.get(parent.remote(1)) == 12
        acc = Acc.remote()
        assert ray_tpu.get(acc.add.remote(5)) == 5
        obj = ray_tpu.put({"owned": True})
    finally:
        set_ambient_job_id(prev)

    # Untagged control submitted AFTER the ambient scope closed.
    assert ray_tpu.get(parent.remote(2)) == 13
    untagged_obj = ray_tpu.put({"owned": False})  # held: stays resident

    rows = state.list_tasks()
    tagged = [r for r in rows if r["job_id"] == "tenant-a"]
    names = {r["name"].rsplit(".", 1)[-1] for r in tagged}
    # parent, child, actor creation (__init__), and the actor method
    # all tagged.
    assert {"parent", "child", "__init__", "add"} <= names
    # The control run is NOT tagged: exactly one parent+child pair each.
    assert sum(1 for r in tagged if r["name"].endswith(".parent")) == 1
    assert sum(1 for r in rows
               if r["name"].endswith(".parent") and not r["job_id"]) == 1

    # job_summary separates the tenant from untagged work.
    summary = state.job_summary()
    assert summary["tenant-a"]["tasks"]["FINISHED"] >= 4
    assert summary["tenant-a"]["cpu_seconds"] >= 0.0
    # The put (and task returns) are accounted to the job.
    assert summary["tenant-a"]["objects"] >= 1
    assert summary["tenant-a"]["object_store_bytes"] >= 0
    assert "" in summary  # untagged rollup keeps cluster totals whole
    # Untagged RESIDENT objects (the held driver put above; freed refs
    # drop out of the store) are accounted under "" too — per-job rows
    # sum to the store's real footprint.
    assert summary[""]["objects"] >= 1
    del untagged_obj

    # timeline(job_id=...) filters to the job, and events carry the tag
    # in args.job.
    events = ray_tpu.timeline(job_id="tenant-a")
    assert events
    assert all(ev["args"].get("job") == "tenant-a" for ev in events)
    all_events = ray_tpu.timeline()
    assert len(all_events) > len(events)


def test_job_tag_env_default(ray_local, monkeypatch):
    """RAY_TPU_JOB_ID (the env channel job_submission sets for
    entrypoint subprocesses) becomes the process-default tag."""
    from ray_tpu._private import task_spec

    monkeypatch.setenv("RAY_TPU_JOB_ID", "raysubmit_envjob")
    monkeypatch.setattr(task_spec, "_default_job_id", None)

    @ray_tpu.remote
    def f():
        return 1

    assert ray_tpu.get(f.remote()) == 1
    rows = [r for r in state.list_tasks()
            if r["name"].endswith(".f")]
    assert rows and all(r["job_id"] == "raysubmit_envjob" for r in rows)
    monkeypatch.setattr(task_spec, "_default_job_id", None)


def test_slo_tracker_burn_rates(ray_local):
    """Multi-window burn rates from the cumulative route latency
    dists: a route serving over its SLO target burns error budget at
    bad_fraction/budget; one serving under it reads 0."""
    from ray_tpu._private import perf_stats
    from ray_tpu._private.health import SloTracker

    route = "/slo-burn-test"
    old_targets = ray_config.serve_slo_targets
    # 50ms target, 90% objective -> 10% error budget.
    ray_config.serve_slo_targets = f"{route}=0.05:0.9"
    try:
        stat = perf_stats.dist(
            "serve_request_seconds",
            tags={"route": route, "status": "200"},
            bounds=perf_stats.SERVE_LATENCY_BOUNDS)
        tracker = SloTracker()
        for _ in range(10):
            stat.record(0.001)  # good
        tracker.sample(now=1000.0)
        for _ in range(10):
            stat.record(0.2)  # bad (over the 50ms target)
        tracker.sample(now=1010.0)

        burn = tracker.burn_rates(now=1010.0)[route]
        # Window diff: 10 requests, all bad -> bad_fraction 1.0, over a
        # 0.1 budget = 10x burn, in both windows (the long window falls
        # back to the oldest snapshot on a young tracker).
        assert burn["short"] == pytest.approx(10.0)
        assert burn["long"] == pytest.approx(10.0)

        # Quiet period: a later sample window with no traffic burns 0.
        tracker.sample(now=1050.0)
        burn = tracker.burn_rates(now=1050.0)[route]
        assert burn["short"] == 0.0
    finally:
        ray_config.serve_slo_targets = old_targets


def test_slo_fast_5xx_counts_as_bad(ray_local):
    """Server errors burn budget at any latency: the proxy's own
    load-shed 503s complete in ~1ms, and if their bucket made them
    'good' the burn signal would read healthy exactly when shedding
    should be driving it."""
    from ray_tpu._private import perf_stats
    from ray_tpu._private.health import SloTracker

    route = "/slo-5xx-test"
    old_targets = ray_config.serve_slo_targets
    ray_config.serve_slo_targets = f"{route}=0.05:0.9"
    try:
        shed = perf_stats.dist(
            "serve_request_seconds",
            tags={"route": route, "status": "503"},
            bounds=perf_stats.SERVE_LATENCY_BOUNDS)
        tracker = SloTracker()
        tracker.sample(now=1000.0)
        for _ in range(10):
            shed.record(0.001)  # fast, but an error
        tracker.sample(now=1010.0)
        burn = tracker.burn_rates(now=1010.0)[route]
        # All 10 bad over a 0.1 budget -> 10x burn.
        assert burn["short"] == pytest.approx(10.0)
    finally:
        ray_config.serve_slo_targets = old_targets
        # The 5xx records and the global tracker's history are rolled
        # back by conftest's autouse `_global_state_baseline` fixture
        # (the structural fix for the order-dependent healthz flake
        # this test used to guard against by hand), and the ambient
        # sanitizer (`--sanitize=ambient`) verifies nothing escapes.


def test_parse_slo_targets_malformed():
    from ray_tpu._private.health import parse_slo_targets

    old = ray_config.serve_slo_targets
    ray_config.serve_slo_targets = \
        "/a=0.25:0.999, /b=0.1, garbage, /c=xyz, =0.3"
    try:
        targets = parse_slo_targets()
        assert targets["/a"] == (0.25, 0.999)
        assert targets["/b"] == (
            0.1, ray_config.serve_slo_default_objective)
        assert "/c" not in targets and "garbage" not in targets
    finally:
        ray_config.serve_slo_targets = old


def test_evaluate_signals_reasons():
    """Each overload signal produces a degraded verdict whose reason
    names the signal (the load-shedding / autoscaling contract)."""
    from ray_tpu._private.health import evaluate_signals

    ok = evaluate_signals({
        "memory_pressure": 0.2, "sched_backlog": 3,
        "loop_lag": {"http_proxy": 0.001}, "slo_burn": {"/r": 0.5}})
    assert ok["status"] == "ok" and not ok["reasons"]

    cases = [
        ({"memory_pressure": 0.99}, "memory_pressure"),
        ({"sched_backlog": ray_config.health_backlog_threshold + 1},
         "sched_backlog"),
        ({"loop_lag": {"replica:d": 10.0}}, "event_loop_lag"),
        ({"slo_burn": {"/chat": 100.0}}, "slo_burn"),
    ]
    for sig, signal_name in cases:
        verdict = evaluate_signals(sig)
        assert verdict["status"] == "degraded"
        assert any(r.startswith(signal_name) for r in verdict["reasons"]), \
            (signal_name, verdict["reasons"])


def test_healthz_flips_degraded_on_backlog_and_recovers(ray_local):
    """A flood of queued submits trips the scheduler-backlog signal;
    /api/healthz (evaluate_health) goes degraded with a reason naming
    it, and recovers once the backlog drains."""
    from ray_tpu._private.health import evaluate_health

    @ray_tpu.remote
    def slow(i):
        time.sleep(0.2)
        return i

    old = ray_config.health_backlog_threshold
    ray_config.health_backlog_threshold = 10
    try:
        refs = [slow.remote(i) for i in range(80)]
        verdict = evaluate_health()
        assert verdict["status"] == "degraded"
        assert any(r.startswith("sched_backlog") for r in
                   verdict["reasons"]), verdict["reasons"]

        ray_tpu.get(refs)
        verdict = evaluate_health()
        assert verdict["status"] == "ok", verdict["reasons"]
        assert verdict["head"]["signals"]["sched_backlog"] == 0
    finally:
        ray_config.health_backlog_threshold = old


def test_health_metrics_exported(ray_local):
    """collect_runtime_metrics folds the health + node-stat gauges into
    the registry: node_* psutil samples, memory pressure, and the
    scheduler queue-depth gauges all reach /api/metrics."""
    from ray_tpu._private.runtime_metrics import collect_runtime_metrics
    from ray_tpu.util.metrics import render_prometheus, snapshot_registry

    @ray_tpu.remote
    def f():
        return 1

    ray_tpu.get([f.remote() for _ in range(4)])
    collect_runtime_metrics()
    snap = snapshot_registry()
    for name in ("ray_tpu_node_cpu_percent", "ray_tpu_node_cpu_count",
                 "ray_tpu_node_mem_total_bytes",
                 "ray_tpu_node_mem_percent", "ray_tpu_node_load_1m",
                 "ray_tpu_memory_pressure", "ray_tpu_sched_backlog",
                 "ray_tpu_sched_parked_for_resources",
                 "ray_tpu_sched_waiting_for_deps"):
        assert name in snap, name
    pressure = snap["ray_tpu_memory_pressure"]["series"][0][1]
    assert 0.0 < pressure <= 1.0
    # Renders as valid exposition text.
    text = render_prometheus([(snap, None)])
    assert "ray_tpu_node_cpu_percent" in text


def test_stale_loop_lag_clears_from_gauge_and_verdict(ray_local):
    """A component whose lag sampler died (stopped proxy, retired
    replica) must read 0 in the exported gauge — the shipped gauge is
    what per-node healthz verdicts use, and a frozen above-threshold
    sample would pin the node degraded forever."""
    from ray_tpu._private import health
    from ray_tpu._private.runtime_metrics import collect_runtime_metrics
    from ray_tpu.util.metrics import snapshot_registry

    def lag_series():
        snap = snapshot_registry()
        out = {}
        for tags, v in (snap.get("ray_tpu_event_loop_lag_last_seconds")
                        or {}).get("series") or []:
            out[dict(tags).get("component", "")] = v
        return out

    health.note_loop_lag("testcomp", 1.5)
    collect_runtime_metrics()
    assert lag_series()["testcomp"] == 1.5
    # Verdict side sees it too (above the 0.25s threshold).
    verdict = health.evaluate_signals(
        {"loop_lag": health.recent_loop_lag()})
    assert any("testcomp" in r for r in verdict["reasons"])

    # Sampler dies: the sample ages past recent_loop_lag's window, the
    # gauge snaps to 0 (not its last value), the verdict recovers.
    with health._LAG_LOCK:
        health._LAST_LAG["testcomp"] = (time.time() - 60, 1.5)
    collect_runtime_metrics()
    assert lag_series()["testcomp"] == 0.0
    verdict = health.evaluate_signals(
        {"loop_lag": health.recent_loop_lag()})
    assert not any("testcomp" in r for r in verdict["reasons"])
    with health._LAG_LOCK:
        health._LAST_LAG.pop("testcomp", None)


def test_superseded_sampler_stops_writing(ray_local):
    """Installing a sampler for a component a second time (replica
    redeploy) invalidates the first: the orphaned loop's idle ~0
    readings must not last-write-wins mask the live loop's lag."""
    import asyncio
    import threading as _threading

    from ray_tpu._private import health

    def start_loop():
        loop = asyncio.new_event_loop()
        _threading.Thread(target=loop.run_forever, daemon=True).start()
        return loop

    old_period = ray_config.loop_lag_sample_period_s
    ray_config.loop_lag_sample_period_s = 0.05
    loop_a = start_loop()
    loop_b = start_loop()
    try:
        fut_a = health.install_loop_lag_sampler(loop_a, "replica:dup")
        fut_b = health.install_loop_lag_sampler(loop_b, "replica:dup")
        assert fut_a is not None and fut_b is not None
        # The superseded sampler notices on its next tick and exits.
        fut_a.result(timeout=5)
        # The live one keeps sampling.
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and \
                "replica:dup" not in health.recent_loop_lag():
            time.sleep(0.02)
        assert "replica:dup" in health.recent_loop_lag()
        assert not fut_b.done()
    finally:
        ray_config.loop_lag_sample_period_s = old_period
        for loop in (loop_a, loop_b):
            loop.call_soon_threadsafe(loop.stop)
        with health._LAG_LOCK:
            health._LAST_LAG.pop("replica:dup", None)
            health._SAMPLER_TOKENS.pop("replica:dup", None)


def test_replica_samplers_distinct_keys_and_retire(ray_local):
    """Two replicas of ONE deployment in one process must get distinct
    lag-sampler components (under a shared key the second install's
    supersede token stops the first replica's sampler — leaving a loop
    unmonitored), and shutdown retires a replica's component
    immediately instead of leaving an idle-~0 series behind."""
    from ray_tpu._private import health
    from ray_tpu.serve._private.replica import ServeReplica

    class Echo:
        def __call__(self, v):
            return v

    old_period = ray_config.loop_lag_sample_period_s
    ray_config.loop_lag_sample_period_s = 0.05
    r1 = r2 = None
    try:
        r1 = ServeReplica._cls("dup-dep", Echo, (), {})
        r2 = ServeReplica._cls("dup-dep", Echo, (), {})
        r1._ensure_loop()
        r2._ensure_loop()
        c1, c2 = r1._loop_lag_component, r2._loop_lag_component
        assert c1 and c2 and c1 != c2
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            seen = health.recent_loop_lag()
            if c1 in seen and c2 in seen:
                break
            time.sleep(0.02)
        seen = health.recent_loop_lag()
        assert c1 in seen and c2 in seen
        # Orderly teardown retires r1's component; r2 keeps sampling.
        assert r1.prepare_for_shutdown() is True
        assert c1 not in health.recent_loop_lag()
        with health._LAG_LOCK:
            assert c1 not in health._SAMPLER_TOKENS
        time.sleep(0.1)
        assert c2 in health.recent_loop_lag()
    finally:
        ray_config.loop_lag_sample_period_s = old_period
        for r in (r1, r2):
            if r is not None:
                r.prepare_for_shutdown()


def test_memory_kill_records_task_event(ray_local):
    """An OOM kill decision lands in the task-event plane (synthetic
    MEMORY_KILLED event naming the victim and usage) so it shows up in
    timeline()/state views, tagged with the victim's job."""
    from ray_tpu._private.memory_monitor import MemoryMonitor
    from ray_tpu._private.task_spec import TaskKind, TaskSpec
    from ray_tpu._private.ids import TaskID

    w = ray_tpu._private.worker.global_worker()
    monitor = MemoryMonitor(w.backend)
    victim = TaskSpec(task_id=TaskID.from_random(),
                      kind=TaskKind.NORMAL_TASK, func=None, args=(),
                      kwargs={}, name="victim.task",
                      job_id="tenant-oom")
    monitor._record_kill_event(4242, victim, 0.97)

    ev = next(e for e in w.task_events.snapshot()
              if e.state == "MEMORY_KILLED")
    assert ev.job_id == "tenant-oom"
    assert victim.task_id.hex() in ev.error
    assert "0.97" in ev.error
    # And it appears in the chrome-trace timeline under the job filter.
    events = ray_tpu.timeline(job_id="tenant-oom")
    assert any(e["name"] == "memory_monitor.kill_worker"
               for e in events)


def test_job_summary_endpoint_and_cli(ray_local):
    """The dashboard serves /api/job_summary and /api/healthz; the CLI
    `jobs` / `health` commands print the same payloads."""
    import urllib.request

    from ray_tpu.dashboard import DashboardServer

    @ray_tpu.remote
    def g():
        return 1

    prev = set_ambient_job_id("tenant-ui")
    try:
        ray_tpu.get([g.remote() for _ in range(3)])
    finally:
        set_ambient_job_id(prev)

    server = DashboardServer(host="127.0.0.1", port=0)
    host, port = server.host, server.port
    try:
        with urllib.request.urlopen(
                f"http://{host}:{port}/api/job_summary") as resp:
            summary = json.loads(resp.read())
        assert summary["tenant-ui"]["tasks"]["FINISHED"] == 3

        with urllib.request.urlopen(
                f"http://{host}:{port}/api/healthz") as resp:
            verdict = json.loads(resp.read())
        assert verdict["status"] in ("ok", "degraded")
        assert "head" in verdict and "reasons" in verdict
        assert "signals" in verdict["head"]
    finally:
        server.shutdown()


def test_two_job_enforcement_caps_flood_protects_serve(monkeypatch):
    """The adversarial two-job scenario in ENFORCE mode (the PR 6
    variant below remains the enforcement-off, observe-only control):
    with `tenancy_enforcement` on and a quota on the flood job, the
    flood runs at most its CPU-slot share (its overflow parks behind
    its own limit / rejects typed), and the serve job's X-Job-Id
    traffic is never shed by the flood's pressure — every request
    lands 200 while the flood is at full push."""
    import http.client

    from ray_tpu import serve
    from ray_tpu._private import perf_stats
    from ray_tpu.exceptions import JobQuotaExceededError
    from ray_tpu.util.metrics import render_prometheus, \
        snapshot_registry

    monkeypatch.setattr(ray_config, "tenancy_enforcement", True)
    monkeypatch.setattr(ray_config, "job_quotas",
                        "job-flood=cpus:1,queued:15")
    monkeypatch.setattr(ray_config, "job_weights",
                        "job-serve=8,job-flood=1")
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    try:
        @serve.deployment
        class Api:
            def __call__(self, request):
                return {"out": 42}

        serve.run(Api.bind(), route_prefix="/api")
        proxy = serve.start_http_proxy()

        @ray_tpu.remote(num_cpus=1)
        def flood():
            time.sleep(0.15)
            return 1

        prev = set_ambient_job_id("job-flood")
        try:
            flood_refs = [flood.remote() for _ in range(30)]
        finally:
            set_ambient_job_id(prev)

        # While the flood is at full push, the serve tenant's requests
        # ALL land — none shed by the flood's queue pressure.
        conn = http.client.HTTPConnection(proxy.host, proxy.port,
                                          timeout=30)
        for _ in range(8):
            conn.request("POST", "/api", body=json.dumps({}),
                         headers={"Content-Type": "application/json",
                                  "X-Job-Id": "job-serve"})
            resp = conn.getresponse()
            assert resp.status == 200, resp.status
            assert json.loads(resp.read()) == {"out": 42}
        conn.close()
        assert proxy.stats()["shed_503"] == 0

        # The flood never held more than its cpus:1 quota of the 4
        # CPUs, the admitted work completed, and the overflow failed
        # TYPED (not silently queued forever).
        w = ray_tpu._private.worker.global_worker()
        assert w.backend.quota_ledger.usage(
            "job-flood")["peak_cpu_milli"] <= 1000
        ok = rejected = 0
        for ref in flood_refs:
            try:
                ray_tpu.get(ref, timeout=60)
                ok += 1
            except JobQuotaExceededError as e:
                assert "job-flood" in str(e)
                rejected += 1
        assert ok >= 15 and rejected >= 1, (ok, rejected)
        # Rejections are metered under the flood's own tag and reach
        # the exposition as ray_tpu_job_quota_* series.
        assert perf_stats.counter("job_quota_rejections",
                                  {"job": "job-flood"}).value >= 1
        from ray_tpu._private.runtime_metrics import \
            collect_runtime_metrics

        collect_runtime_metrics()
        text = render_prometheus([(snapshot_registry(), None)])
        assert 'ray_tpu_job_quota_rejections_total{job="job-flood"}' \
            in text
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
        ray_tpu.shutdown()


def test_two_job_cluster_attribution_and_health():
    """The adversarial two-job scenario on a two-node cluster — the
    ENFORCEMENT-OFF control for the enforce-mode test above: a
    flooding job (parked submits pinned to node 1) and a
    latency-sensitive serve job, concurrently. Every task event /
    metric series carries the right job tag, job_summary() separates
    the tenants, the cluster healthz verdict degrades with a reason
    naming the overloaded signal while the flood is queued (the flood
    genuinely floods — nothing caps it), and recovers after it
    drains."""
    from ray_tpu import serve
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu._private.health import evaluate_health
    from ray_tpu._private.obs_plane import export_cluster_prometheus
    from ray_tpu._private.task_spec import NodeAffinitySchedulingStrategy

    ray_tpu.shutdown()
    cluster = Cluster(head_node_args={"num_cpus": 4})
    old_threshold = ray_config.health_backlog_threshold
    try:
        n1 = cluster.add_node(num_cpus=2)

        # -- the latency-sensitive job: a serve deployment whose
        # handler fans into a task; requests tagged via X-Job-Id.
        @serve.deployment
        class Api:
            def __call__(self, request):
                @ray_tpu.remote
                def nested(x):
                    return x * 2

                return {"out": ray_tpu.get(nested.remote(21))}

        serve.run(Api.bind(), route_prefix="/api")
        proxy = serve.start_http_proxy()

        # -- the flooding job: CPU-holding sleeps pinned to node 1 (a
        # blocking ray get would RELEASE its CPU — the nested-get
        # deadlock guard — and drain the queue), so 2 run while ~38
        # park in node 1's scheduler backlog, which the health plane
        # reads out of the node's shipped snapshot; the flood then
        # drains on its own and the verdict must recover.
        @ray_tpu.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(
            node_id=n1))
        def flood():
            time.sleep(0.5)
            return 1

        prev = set_ambient_job_id("job-flood")
        try:
            flood_refs = [flood.remote() for _ in range(40)]
        finally:
            set_ambient_job_id(prev)

        # Node 1's shipped snapshot carries its backlog gauge; the
        # driver-side verdict (driver-process thresholds) names it.
        ray_config.health_backlog_threshold = 10
        deadline = time.monotonic() + 60
        verdict = None
        while time.monotonic() < deadline:
            verdict = evaluate_health(cluster.driver_worker)
            if verdict["status"] == "degraded" and any(
                    "sched_backlog" in r for r in verdict["reasons"]):
                break
            time.sleep(0.3)
        assert verdict is not None and verdict["status"] == "degraded", \
            verdict
        assert any("sched_backlog" in r for r in verdict["reasons"]), \
            verdict["reasons"]

        # While flooded, the latency job is served and tagged end to
        # end: header echo + replica-submitted task attribution.
        import http.client

        conn = http.client.HTTPConnection(proxy.host, proxy.port,
                                          timeout=30)
        for _ in range(3):
            conn.request("POST", "/api", body=json.dumps({}),
                         headers={"Content-Type": "application/json",
                                  "X-Job-Id": "job-serve"})
            resp = conn.getresponse()
            assert resp.status == 200
            assert resp.headers.get("X-Job-Id") == "job-serve"
            assert json.loads(resp.read()) == {"out": 42}
        conn.close()

        # Attribution is fully separated in the cluster-wide state
        # view: flood tasks (node-executed, header wire path) are all
        # job-flood; the serve chain (replica call + nested task) is
        # all job-serve. Shipping is periodic — poll for the flood
        # tasks' arrival from node 1.
        deadline = time.monotonic() + 60
        flood_rows = serve_rows = []
        while time.monotonic() < deadline:
            rows = state.list_tasks()
            flood_rows = [r for r in rows
                          if r["name"].endswith(".flood")]
            serve_rows = [r for r in rows
                          if "nested" in r["name"]
                          or "handle_request" in r["name"]]
            if len(flood_rows) >= 40 and len(serve_rows) >= 4:
                break
            time.sleep(0.3)
        assert len(flood_rows) >= 40
        assert all(r["job_id"] == "job-flood" for r in flood_rows)
        assert serve_rows and all(
            r["job_id"] == "job-serve" for r in serve_rows), \
            [(r["name"], r["job_id"]) for r in serve_rows]

        # job_summary separates the tenants.
        summary = state.job_summary()
        assert summary["job-flood"]["tasks"]
        assert "job-serve" in summary
        assert summary["job-serve"]["serve_requests"].get("/api") == 3
        assert "/api" not in summary["job-flood"]["serve_requests"]

        # The merged exposition carries job-tagged series and the per-
        # request counter under the serve job's tag.
        text = export_cluster_prometheus(cluster.driver_worker)
        assert 'ray_tpu_job_tasks{job="job-flood"' in text
        assert 'job="job-serve"' in text
        assert "ray_tpu_serve_requests_total" in text
        # Satellite: node psutil gauges reach the exposition, node-
        # tagged for the worker node's shipped snapshot.
        assert "ray_tpu_node_cpu_percent" in text
        assert f'ray_tpu_node_cpu_percent{{node="{n1}"}}' in text

        # Timeline filtered by job: only the flood's events.
        flood_tl = ray_tpu.timeline(job_id="job-flood")
        assert flood_tl and all(
            ev["args"].get("job") == "job-flood" for ev in flood_tl)

        # The flood drains; the verdict recovers.
        assert ray_tpu.get(flood_refs, timeout=120) == [1] * 40
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            verdict = evaluate_health(cluster.driver_worker)
            if verdict["status"] == "ok":
                break
            time.sleep(0.3)
        assert verdict["status"] == "ok", verdict["reasons"]
    finally:
        ray_config.health_backlog_threshold = old_threshold
        try:
            serve.shutdown()
        except Exception:
            pass
        cluster.shutdown()
