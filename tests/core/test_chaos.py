"""Chaos soak: a NodeKiller randomly kill -9s worker nodes (undetected
until the health checker notices) while a task workload runs; retriable
work must all complete correctly.

Reference: `python/ray/_private/test_utils.py:1347` NodeKillerActor +
`release/nightly_tests/chaos_test/test_chaos_basic.py` — killing raylets
at intervals during a workload, asserting completion.
"""

import random
import threading
import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster

# Multi-process / soak tests: excluded from the quick
# tier (pytest -m 'not slow').
pytestmark = pytest.mark.slow


@pytest.fixture
def fast_health(monkeypatch):
    from ray_tpu._private.config import ray_config

    monkeypatch.setattr(ray_config, "health_check_period_s", 0.2)
    monkeypatch.setattr(ray_config, "health_check_failure_threshold", 2)
    yield ray_config


class NodeKiller:
    """Kills random live worker nodes at intervals, replacing each so
    capacity survives the soak (the reference chaos fixture's shape)."""

    def __init__(self, cluster: Cluster, period_s: float = 1.0,
                 seed: int = 0):
        self.cluster = cluster
        self.period_s = period_s
        self.rng = random.Random(seed)
        self.kills = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def start(self):
        self._thread.start()

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=30)

    def _loop(self):
        while not self._stop.wait(self.period_s):
            victims = [nid for nid, proc in
                       list(self.cluster._procs.items())
                       if proc.poll() is None]
            if not victims:
                continue
            victim = self.rng.choice(victims)
            self.cluster.kill_node(victim)
            self.kills += 1
            try:  # replace capacity so the workload can finish
                self.cluster.add_node(num_cpus=2, wait=True)
            except Exception:
                pass


def test_chaos_tasks_survive_random_node_kills(fast_health):
    cluster = Cluster(head_node_args={"num_cpus": 1})
    try:
        for _ in range(2):
            cluster.add_node(num_cpus=2)

        @ray_tpu.remote(num_cpus=2, max_retries=10)
        def work(i):
            time.sleep(0.2)
            return i * i

        killer = NodeKiller(cluster, period_s=1.2)
        killer.start()
        try:
            results = []
            for wave in range(4):
                refs = [work.remote(i) for i in
                        range(wave * 8, wave * 8 + 8)]
                results.extend(ray_tpu.get(refs, timeout=180))
        finally:
            killer.stop()
        assert sorted(results) == sorted(i * i for i in range(32))
        assert killer.kills >= 1, "chaos never struck; soak too short"
    finally:
        cluster.shutdown()


def test_chaos_actor_state_survives_with_restarts(fast_health):
    cluster = Cluster(head_node_args={"num_cpus": 1})
    try:
        cluster.add_node(num_cpus=2)
        cluster.add_node(num_cpus=2)

        @ray_tpu.remote(num_cpus=2, max_restarts=8, max_task_retries=8)
        class Counter:
            def __init__(self):
                self.n = 0

            def inc(self):
                self.n += 1
                return self.n

        c = Counter.remote()
        assert ray_tpu.get(c.inc.remote(), timeout=60) == 1

        killer = NodeKiller(cluster, period_s=1.0, seed=7)
        killer.start()
        try:
            values = []
            for _ in range(12):
                try:
                    values.append(ray_tpu.get(c.inc.remote(),
                                              timeout=120))
                except Exception:
                    # A call can legitimately fail mid-restart; the
                    # actor itself must come back for later calls.
                    time.sleep(0.5)
        finally:
            killer.stop()
        # The actor survived the soak: late calls succeed, and the
        # counter kept increasing within each incarnation.
        final = ray_tpu.get(c.inc.remote(), timeout=120)
        assert final >= 1
        assert len(values) >= 4, values
    finally:
        cluster.shutdown()
