"""Cross-process actor semantics: handles work from ANY process and
named actors are a cluster-wide registry (reference: direct actor
transport + GcsActorManager named actors)."""

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster

pytestmark = pytest.mark.slow  # multi-process cluster


def test_node_task_calls_actor_on_other_process():
    cluster = Cluster(head_node_args={"num_cpus": 1})
    try:
        cluster.add_node(num_cpus=4)
        cluster.add_node(num_cpus=4)

        @ray_tpu.remote
        class Counter:
            def __init__(self):
                self.n = 0

            def inc(self, k=1):
                self.n += k
                return self.n

        c = Counter.remote()  # lands on the driver
        assert ray_tpu.get(c.inc.remote()) == 1

        @ray_tpu.remote(num_cpus=2)
        def bump_from_node(handle, k):
            # routed via the head; result fetched on demand here
            return ray_tpu.get(handle.inc.remote(k), timeout=60)

        outs = ray_tpu.get([bump_from_node.remote(c, 10),
                            bump_from_node.remote(c, 100)], timeout=120)
        assert sorted(outs) == [11, 111] or sorted(outs) == [101, 111]
        assert ray_tpu.get(c.inc.remote()) == 112
        ray_tpu.kill(c)
    finally:
        cluster.shutdown()


def test_named_actor_resolves_from_node_process():
    cluster = Cluster(head_node_args={"num_cpus": 1})
    try:
        cluster.add_node(num_cpus=4)

        @ray_tpu.remote
        class Store:
            def __init__(self):
                self.v = {}

            def put(self, k, v):
                self.v[k] = v
                return True

            def get(self, k):
                return self.v.get(k)

        s = Store.options(name="global_store").remote()
        assert ray_tpu.get(s.put.remote("a", 41))

        @ray_tpu.remote(num_cpus=2)
        def use_named():
            h = ray_tpu.get_actor("global_store")
            ray_tpu.get(h.put.remote("b", 42), timeout=60)
            return ray_tpu.get(h.get.remote("a"), timeout=60)

        assert ray_tpu.get(use_named.remote(), timeout=120) == 41
        assert ray_tpu.get(s.get.remote("b")) == 42

        # registration FROM a node is visible at the driver
        @ray_tpu.remote(num_cpus=2)
        def register_one():
            @ray_tpu.remote
            class NodeLocal:
                def ping(self):
                    return "pong"

            NodeLocal.options(name="from_node").remote()
            return True

        assert ray_tpu.get(register_one.remote(), timeout=120)
        h = ray_tpu.get_actor("from_node")
        assert ray_tpu.get(h.ping.remote(), timeout=60) == "pong"
        ray_tpu.kill(s)
    finally:
        cluster.shutdown()
