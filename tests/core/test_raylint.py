"""Tier-1 self-enforcement: raylint over all of ``ray_tpu/`` is clean.

This test IS the CI gate for the concurrency/invariant rules: every
future PR runs it via the ordinary test suite, so a new event-loop
stall, lock-order cycle, layering inversion, leaked resource, or
one-way wire frame fails tier-1 with a pointed message — no extra CI
infrastructure. It also pins the analyzer's cost (< 10 s over the whole
tree) so the gate stays cheap forever.
"""

import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
if REPO_ROOT not in sys.path:  # `tools` must resolve from the repo root
    sys.path.insert(0, REPO_ROOT)

from tools.raylint.core import analyze  # noqa: E402


_REPORT = None


def _run_full():
    # One full analysis shared by every assertion in this module: the
    # 10-second budget below is per-run, not per-test. tools/raysan,
    # tools/raymc and tools/rayspec are linted alongside the runtime —
    # the sanitizer, model-checker and spec-checker layers enforce
    # concurrency invariants, so they hold themselves to the same
    # rules.
    global _REPORT
    if _REPORT is None:
        _REPORT = analyze([os.path.join(REPO_ROOT, "ray_tpu"),
                           os.path.join(REPO_ROOT, "tools", "raysan"),
                           os.path.join(REPO_ROOT, "tools", "raymc"),
                           os.path.join(REPO_ROOT, "tools", "rayspec")],
                          root=REPO_ROOT)
    return _REPORT


def test_codebase_has_zero_unsuppressed_violations():
    report = _run_full()
    assert report.files_checked > 100, (
        "raylint saw suspiciously few files — collection is broken, "
        "which would make this gate vacuous")
    assert not report.active, (
        "raylint found unsuppressed violations (fix them, or suppress "
        "deliberate ones with `# raylint: disable=<rule> -- <reason>`):\n"
        + "\n".join(v.render() for v in report.active))


def test_every_suppression_carries_a_justification():
    report = _run_full()
    # By construction an unjustified suppression does not suppress (the
    # violation stays active AND an R0 meta violation fires), so this
    # is mostly belt-and-braces — but it documents the contract.
    assert report.suppressed, (
        "expected at least the known deliberate suppressions; an empty "
        "set here means suppression matching silently broke")
    for v in report.suppressed:
        assert v.justification, f"suppressed without justification: " \
                                f"{v.render()}"
    assert not [v for v in report.active if v.rule == "R0"], (
        "bare `# raylint: disable` without `-- <reason>` found")


def test_no_stale_suppressions():
    """Every disable comment still earns its keep: a suppression whose
    line no longer triggers the named rule is dead weight that would
    silently mask a NEW violation if the code regresses — the
    `--show-suppressed` audit is enforced here so the set can only
    shrink deliberately."""
    report = _run_full()
    assert not report.stale, (
        "stale suppressions found (the named rule no longer fires on "
        "that line — delete the disable comment):\n"
        + "\n".join(f"{v.path}:{v.line}: {v.rule}" for v in report.stale))


def test_full_run_stays_under_ten_seconds():
    report = _run_full()
    assert report.elapsed_s < 10.0, (
        f"raylint took {report.elapsed_s:.1f}s over ray_tpu/ — the "
        f"tier-1 gate must stay cheap; profile the offending rule "
        f"(each Rule.finalize must stay near-linear in files)")


def test_cli_exit_code_contract(tmp_path):
    """0 clean / 1 violations / 2 usage error — on tiny fixtures, so
    the contract is pinned without re-linting the whole tree."""
    from tools.raylint.__main__ import main

    clean = tmp_path / "clean.py"
    clean.write_text("import os\n\n\ndef f():\n    return os.getpid()\n")
    dirty = tmp_path / "dirty.py"
    dirty.write_text("import os\n\n\ndef f():\n    return 1\n")

    assert main([str(clean)]) == 0
    assert main([str(dirty)]) == 1
    assert main([str(dirty), "--rule", "R999"]) == 2
    assert main([str(tmp_path / "missing.py")]) == 2
    assert main(["--list-rules"]) == 0


def test_cli_json_and_rule_filter(tmp_path, capsys):
    import json

    from tools.raylint.__main__ import main

    dirty = tmp_path / "dirty.py"
    dirty.write_text("import os\n\n\ndef f():\n    return 1\n")

    rc = main([str(dirty), "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["files_checked"] == 1
    assert [v["rule"] for v in out["violations"]] == ["R6"]

    # Filtered to an unrelated rule, the same file is clean.
    assert main([str(dirty), "--rule", "R1"]) == 0
    capsys.readouterr()
