"""Lease-based decentralized dispatch.

Reference: `core_worker/transport/direct_task_transport.h:75,211`
(lease + pipelining: one scheduling decision per task shape, then tasks
stream to the leased node without per-task round trips) and
`lease_policy.h:56` (locality-aware lease targeting). Backlog rides the
node resource reports (raylet backlog reporting role).
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster

pytestmark = pytest.mark.slow


def test_lease_pipelines_and_returns_on_idle():
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 1})
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)
    try:
        @ray_tpu.remote(num_cpus=1)
        def sq(x):
            return x * x

        # 1-CPU tasks exceed the head: leases form, results are right.
        refs = [sq.remote(i) for i in range(300)]
        assert ray_tpu.get(refs, timeout=120) == [i * i
                                                 for i in range(300)]
        backend = ray_tpu._private.worker.global_worker().backend
        with backend._lease_lock:
            held = {l["node_id"] for ls in backend._leases.values()
                    for l in ls}
        assert held, "no leases were granted for the fan-out"
        # After the idle window the next submission prunes them (lease
        # return on idle).
        time.sleep(backend._LEASE_IDLE_S + 0.5)
        ray_tpu.get(sq.remote(7), timeout=30)
        with backend._lease_lock:
            held_after = {l["node_id"] for ls in backend._leases.values()
                          for l in ls}
        # A fresh lease may exist from the probe task; the point is the
        # OLD saturated set did not persist unexpired.
        assert len(held_after) <= len(held)
    finally:
        cluster.shutdown()


def test_locality_aware_lease_targets_arg_holder():
    """A task whose object arg lives on node X gets leased to node X
    (reference lease_policy.h:56), instead of whichever node is
    emptiest."""
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
    )

    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 1})
    n1 = cluster.add_node(num_cpus=2)
    n2 = cluster.add_node(num_cpus=2)
    try:
        @ray_tpu.remote(num_cpus=1)
        def produce():
            return np.arange(1000)

        # Pin the producer (and thus the object's primary copy) to n1.
        blob = produce.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                node_id=n1)).remote()
        _, not_ready = ray_tpu.wait([blob], timeout=30)
        assert not not_ready

        @ray_tpu.remote(num_cpus=1)
        def consume(arr):
            return int(arr.sum())

        # Saturate the head so consumers take the lease path; their arg
        # lives on n1, so the lease must target n1.
        @ray_tpu.remote(num_cpus=1)
        def hog():
            time.sleep(3.0)

        hog_ref = hog.remote()
        time.sleep(0.2)
        refs = [consume.remote(blob) for _ in range(4)]
        assert set(ray_tpu.get(refs, timeout=60)) == {499500}
        backend = ray_tpu._private.worker.global_worker().backend
        with backend._lease_lock:
            nodes = {l["node_id"] for ls in backend._leases.values()
                     for l in ls}
        assert n1 in nodes, (nodes, n1, n2)
        ray_tpu.get(hog_ref, timeout=30)
    finally:
        cluster.shutdown()


def test_backlog_reported_to_head():
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 1})
    nid = cluster.add_node(num_cpus=1)
    try:
        @ray_tpu.remote(num_cpus=1)
        def slow():
            time.sleep(0.4)
            return 1

        refs = [slow.remote() for _ in range(8)]
        deadline = time.monotonic() + 10
        saw_backlog = False
        while time.monotonic() < deadline and not saw_backlog:
            rec = cluster.head.nodes.get(nid)
            if rec is not None and rec.backlog > 0:
                saw_backlog = True
            time.sleep(0.05)
        assert saw_backlog, "node backlog never surfaced at the head"
        assert sum(ray_tpu.get(refs, timeout=60)) == 8
    finally:
        cluster.shutdown()


def test_leased_task_with_driver_local_args():
    """Leased dispatch must publish (and for big args push) the
    driver's local objects so the node's dep fetch finds them."""
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 1})
    cluster.add_node(num_cpus=2)
    try:
        big = ray_tpu.put(np.arange(1_000_000, dtype=np.float64))

        @ray_tpu.remote(num_cpus=1)
        def total(a, b):
            return float(a.sum()) + b

        refs = [total.remote(big, i) for i in range(8)]
        expect = float(np.arange(1_000_000, dtype=np.float64).sum())
        assert ray_tpu.get(refs, timeout=60) == [expect + i
                                                 for i in range(8)]
    finally:
        cluster.shutdown()


def test_push_path_to_simulated_remote_node():
    """A big driver arg is PUSHED to a node on its OWN segment
    (push_manager role): the consuming task still sees it, and the
    node's transfer stats show inbound bytes without a pull request
    from the node side having raced it."""
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 1})
    cluster.add_node(num_cpus=2, simulate_remote_host=True)
    try:
        data = np.ones(2_000_000, dtype=np.float64)  # 16 MB > push min
        big = ray_tpu.put(data)

        @ray_tpu.remote(num_cpus=2)  # only fits the remote node
        def consume(a):
            return float(a.sum())

        assert ray_tpu.get(consume.remote(big),
                           timeout=60) == 2_000_000.0
        # The push really happened (not just the dep-fetch fallback):
        # the dispatch recorded a successful (node, oid) push.
        backend = ray_tpu._private.worker.global_worker().backend
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not backend._pushed:
            time.sleep(0.05)
        assert any(oid == big.id.binary()
                   for _, oid in backend._pushed), backend._pushed
    finally:
        cluster.shutdown()


def test_striped_pull_and_push_shm_api():
    """Direct store-level drive of the new transfer surfaces."""
    import os

    from ray_tpu._private.shm_store import ShmObjectStore

    a = ShmObjectStore(name=f"/lease_xa_{os.getpid()}", create=True,
                       capacity=256 << 20)
    b = ShmObjectStore(name=f"/lease_xb_{os.getpid()}", create=True,
                       capacity=256 << 20)
    try:
        port = a.start_transfer_server()
        port_b = b.start_transfer_server()
        oid = b"x" * 20
        payload = np.random.RandomState(0).bytes(32 << 20)
        assert a.put_bytes(oid, payload)
        assert a.object_size(oid) == len(payload)
        # striped pull b <- a
        rc = b.pull_from_striped(oid, "127.0.0.1", port, streams=3,
                                 allow_local=False)
        assert rc == 0
        got = b.get_bytes(oid)
        assert got is not None and bytes(got) == payload
        b.release(oid)
        # push a -> b of a second object
        oid2 = b"y" * 20
        assert a.put_bytes(oid2, payload[: 8 << 20])
        assert a.push_to(oid2, "127.0.0.1", port_b) == 0
        got2 = b.get_bytes(oid2)
        assert got2 is not None and bytes(got2) == payload[: 8 << 20]
        b.release(oid2)
        # re-push: remote already has it
        assert a.push_to(oid2, "127.0.0.1", port_b) == -5
    finally:
        a.destroy()
        b.destroy()


def test_striped_pull_source_death_then_repull_from_other_holder():
    """Degradation path: the source node dies MID-STRIPE during a
    striped parallel pull; the pull fails cleanly (no partial object
    left behind) and a re-pull from another holder of the same object
    completes with correct bytes — the pull_manager's
    retry-on-another-location contract."""
    import os
    import threading

    from ray_tpu._private.shm_store import ShmObjectStore

    pid = os.getpid()
    src = ShmObjectStore(name=f"/stripe_src_{pid}", create=True,
                         capacity=512 << 20)
    alt = ShmObjectStore(name=f"/stripe_alt_{pid}", create=True,
                         capacity=512 << 20)
    dst = ShmObjectStore(name=f"/stripe_dst_{pid}", create=True,
                         capacity=512 << 20)
    try:
        oid = b"z" * 20
        payload = np.random.RandomState(7).bytes(96 << 20)
        assert src.put_bytes(oid, payload)
        assert alt.put_bytes(oid, payload)
        src_port = src.start_transfer_server()
        alt_port = alt.start_transfer_server()

        def kill_src_mid_transfer():
            # Wait until bytes are actually moving (mid-stripe), then
            # yank the source's transfer server.
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if src.transfer_stats().get("bytes_sent", 0) > 0:
                    break
                time.sleep(0.0005)
            src.stop_transfer_server()

        killer = threading.Thread(target=kill_src_mid_transfer)
        killer.start()
        rc = dst.pull_from_striped(oid, "127.0.0.1", src_port,
                                   streams=4, allow_local=False)
        killer.join(timeout=30)
        if rc == 0:
            # The whole object raced past the kill on this host: the
            # degradation path wasn't exercised, so force it — drop the
            # object and pull from the now-dead source.
            dst.release(oid)
            rc = dst.pull_from_striped(oid, "127.0.0.1", src_port,
                                       streams=4, allow_local=False)
        assert rc < 0 and rc != -5, f"pull from dead source gave {rc}"
        # Clean failure: no partial/corrupt object left in the dest.
        assert dst.get_bytes(oid) is None

        # Re-pull from the other holder completes with correct bytes.
        rc = dst.pull_from_striped(oid, "127.0.0.1", alt_port,
                                   streams=4, allow_local=False)
        assert rc == 0, rc
        got = dst.get_bytes(oid)
        assert got is not None and bytes(got) == payload
        dst.release(oid)
    finally:
        src.destroy()
        alt.destroy()
        dst.destroy()


def test_pipelined_client_error_feedback():
    """Failure replies on the pipelined channel surface through the
    error callback with the request id; successful ones don't."""
    from ray_tpu._private.rpc import PipelinedClient, RpcServer

    seen = []
    hits = []
    server = RpcServer({
        "ok": lambda **kw: hits.append(kw) or True,
        "boom": lambda **kw: (_ for _ in ()).throw(
            RuntimeError("kapow")),
    })
    try:
        client = PipelinedClient(
            server.address,
            on_error=lambda tag, msg, rid, lost: seen.append(
                (tag, msg, lost)))
        for i in range(20):
            client.send("ok", tag=i, x=i)
        client.send("boom", tag="bad")
        client.send("ok", tag=99, x=99)
        assert client.flush(timeout=10)
        assert len(hits) == 21
        assert len(seen) == 1
        tag, msg, lost = seen[0]
        assert tag == "bad" and "kapow" in msg and lost is False
        client.close()
    finally:
        server.shutdown()
