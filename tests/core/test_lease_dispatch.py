"""Lease-based decentralized dispatch.

Reference: `core_worker/transport/direct_task_transport.h:75,211`
(lease + pipelining: one scheduling decision per task shape, then tasks
stream to the leased node without per-task round trips) and
`lease_policy.h:56` (locality-aware lease targeting). Backlog rides the
node resource reports (raylet backlog reporting role).
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster

pytestmark = pytest.mark.slow


def test_lease_pipelines_and_returns_on_idle():
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 1})
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)
    try:
        @ray_tpu.remote(num_cpus=1)
        def sq(x):
            return x * x

        # 1-CPU tasks exceed the head: leases form, results are right.
        refs = [sq.remote(i) for i in range(300)]
        assert ray_tpu.get(refs, timeout=120) == [i * i
                                                 for i in range(300)]
        backend = ray_tpu._private.worker.global_worker().backend
        with backend._lease_lock:
            held = {l["node_id"] for ls in backend._leases.values()
                    for l in ls}
        assert held, "no leases were granted for the fan-out"
        # After the idle window the next submission prunes them (lease
        # return on idle).
        time.sleep(backend._LEASE_IDLE_S + 0.5)
        ray_tpu.get(sq.remote(7), timeout=30)
        with backend._lease_lock:
            held_after = {l["node_id"] for ls in backend._leases.values()
                          for l in ls}
        # A fresh lease may exist from the probe task; the point is the
        # OLD saturated set did not persist unexpired.
        assert len(held_after) <= len(held)
    finally:
        cluster.shutdown()


def test_locality_aware_lease_targets_arg_holder():
    """A task whose object arg lives on node X gets leased to node X
    (reference lease_policy.h:56), instead of whichever node is
    emptiest."""
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
    )

    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 1})
    n1 = cluster.add_node(num_cpus=2)
    n2 = cluster.add_node(num_cpus=2)
    try:
        @ray_tpu.remote(num_cpus=1)
        def produce():
            return np.arange(1000)

        # Pin the producer (and thus the object's primary copy) to n1.
        blob = produce.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                node_id=n1)).remote()
        _, not_ready = ray_tpu.wait([blob], timeout=30)
        assert not not_ready

        @ray_tpu.remote(num_cpus=1)
        def consume(arr):
            return int(arr.sum())

        # Saturate the head so consumers take the lease path; their arg
        # lives on n1, so the lease must target n1.
        @ray_tpu.remote(num_cpus=1)
        def hog():
            time.sleep(3.0)

        hog_ref = hog.remote()
        time.sleep(0.2)
        refs = [consume.remote(blob) for _ in range(4)]
        assert set(ray_tpu.get(refs, timeout=60)) == {499500}
        backend = ray_tpu._private.worker.global_worker().backend
        with backend._lease_lock:
            nodes = {l["node_id"] for ls in backend._leases.values()
                     for l in ls}
        assert n1 in nodes, (nodes, n1, n2)
        ray_tpu.get(hog_ref, timeout=30)
    finally:
        cluster.shutdown()


def test_backlog_reported_to_head():
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 1})
    nid = cluster.add_node(num_cpus=1)
    try:
        @ray_tpu.remote(num_cpus=1)
        def slow():
            time.sleep(0.4)
            return 1

        refs = [slow.remote() for _ in range(8)]
        deadline = time.monotonic() + 10
        saw_backlog = False
        while time.monotonic() < deadline and not saw_backlog:
            rec = cluster.head.nodes.get(nid)
            if rec is not None and rec.backlog > 0:
                saw_backlog = True
            time.sleep(0.05)
        assert saw_backlog, "node backlog never surfaced at the head"
        assert sum(ray_tpu.get(refs, timeout=60)) == 8
    finally:
        cluster.shutdown()


def test_pipelined_client_error_feedback():
    """Failure replies on the pipelined channel surface through the
    error callback with the request id; successful ones don't."""
    from ray_tpu._private.rpc import PipelinedClient, RpcServer

    seen = []
    hits = []
    server = RpcServer({
        "ok": lambda **kw: hits.append(kw) or True,
        "boom": lambda **kw: (_ for _ in ()).throw(
            RuntimeError("kapow")),
    })
    try:
        client = PipelinedClient(
            server.address,
            on_error=lambda tag, msg, rid, lost: seen.append(
                (tag, msg, lost)))
        for i in range(20):
            client.send("ok", tag=i, x=i)
        client.send("boom", tag="bad")
        client.send("ok", tag=99, x=99)
        assert client.flush(timeout=10)
        assert len(hits) == 21
        assert len(seen) == 1
        tag, msg, lost = seen[0]
        assert tag == "bad" and "kapow" in msg and lost is False
        client.close()
    finally:
        server.shutdown()
