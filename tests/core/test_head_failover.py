"""Head (GCS) failover with a LIVE cluster.

Reference: GCS restart + `node_manager.proto:356` RayletNotifyGCSRestart
+ `gcs_failover_worker_reconnect_timeout` (`ray_config_def.h:62`). With
SQLite persistence configured, the head is torn down and recreated on
the same address under live node processes and running actors:

- nodes re-register through their report loop (report returns False for
  an unknown node -> re-register + re-publish hosted actors and owned
  objects);
- KV / named-actor / placement-group tables reload from storage;
- actors keep their in-memory state (the node processes never died);
- pre-restart object refs stay fetchable; new work schedules normally.

Semantics documented on `Cluster.restart_head`.
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster

pytestmark = pytest.mark.slow


@pytest.fixture
def durable_gcs(tmp_path, monkeypatch):
    from ray_tpu._private.config import ray_config

    monkeypatch.setattr(ray_config, "gcs_storage_path",
                        str(tmp_path / "gcs.sqlite"))
    monkeypatch.setattr(ray_config, "health_check_period_s", 0.3)
    yield


def _wait(pred, timeout=20.0, msg=""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            if pred():
                return
        except Exception:
            pass
        time.sleep(0.1)
    raise AssertionError(f"timed out: {msg}")


def test_head_failover_live_nodes(durable_gcs):
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 1})
    n1 = cluster.add_node(num_cpus=2)
    n2 = cluster.add_node(num_cpus=2)
    try:
        @ray_tpu.remote(num_cpus=1)
        class Counter:
            def __init__(self):
                self.n = 0

            def incr(self):
                self.n += 1
                return self.n

        counter = Counter.options(name="survivor-counter",
                                  lifetime="detached").remote()
        for _ in range(5):
            assert ray_tpu.get(counter.incr.remote(), timeout=30) >= 1

        @ray_tpu.remote(num_cpus=1)
        def make_blob():
            return np.arange(4096, dtype=np.float64)

        blob_ref = make_blob.remote()
        np.testing.assert_array_equal(
            ray_tpu.get(blob_ref, timeout=30),
            np.arange(4096, dtype=np.float64))

        from ray_tpu._private.worker import global_worker

        global_worker().gcs.kv_put(b"ft-key", b"ft-value")

        from ray_tpu.util.placement_group import placement_group

        pg = placement_group([{"CPU": 1}], strategy="PACK", name="ft-pg")
        pg.wait(timeout=20)

        # ---- failover ----
        cluster.restart_head()

        # Nodes re-register within the report window.
        _wait(lambda: sum(n["Alive"] for n in cluster.nodes()) >= 2,
              msg="nodes re-registered")

        # Durable tables recovered.
        assert global_worker().gcs.kv_get(b"ft-key") == b"ft-value"
        table = global_worker().gcs.placement_group_table()
        assert any(getattr(p, "name", "") == "ft-pg"
                   for p in table.values())

        # Named actor resolves AND kept its in-memory state (the node
        # process never died; the handle re-routes through the new
        # head's directory repopulated by the node's re-report).
        again = ray_tpu.get_actor("survivor-counter")
        _wait(lambda: ray_tpu.get(again.incr.remote(), timeout=10) == 6,
              msg="actor state preserved across head restart")

        # Pre-restart object refs stay fetchable (owned copy re-reported
        # by its node).
        np.testing.assert_array_equal(
            ray_tpu.get(make_blob.remote(), timeout=30),
            np.arange(4096, dtype=np.float64))

        # Release the recovered PG's bundle first (its reserved CPU plus
        # the counter actor could otherwise leave no node with 2 free
        # CPUs) — removal through the RECOVERED table is part of the
        # failover contract.
        from ray_tpu.util.placement_group import remove_placement_group

        recovered_pg = next(p for p in table.values()
                            if getattr(p, "name", "") == "ft-pg")
        remove_placement_group(recovered_pg)

        # New work schedules on the re-registered nodes: 2-CPU tasks
        # cannot fit the 1-CPU head, and they overlap, so both node
        # processes must serve.
        @ray_tpu.remote(num_cpus=2)
        def whoami():
            import os
            import time as _t

            _t.sleep(0.5)
            return os.getpid()

        import os

        pids = set(ray_tpu.get([whoami.remote() for _ in range(4)],
                               timeout=30))
        assert pids and os.getpid() not in pids, \
            f"2-CPU work must run on re-registered nodes: {pids}"
    finally:
        cluster.shutdown()


def test_head_failover_inflight_task(durable_gcs):
    """A task RUNNING on a node while the head restarts completes, its
    output is re-reported after re-registration, and the caller's get
    resolves — no spurious error."""
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 1})
    cluster.add_node(num_cpus=2)
    try:
        @ray_tpu.remote(num_cpus=1)
        def slow():
            import time as _t

            _t.sleep(3.0)
            return "made-it"

        ref = slow.remote()
        time.sleep(0.5)  # ensure it is dispatched and running
        cluster.restart_head()
        assert ray_tpu.get(ref, timeout=45) == "made-it"
    finally:
        cluster.shutdown()


def test_head_hard_crash_failover(tmp_path, monkeypatch):
    """Acceptance: crash-mode failover (NO flush_storage) recovers all
    group-committed state, loses AT MOST the open commit window, and
    live nodes re-register without driver intervention."""
    from ray_tpu._private.config import ray_config

    monkeypatch.setattr(ray_config, "gcs_storage_path",
                        str(tmp_path / "gcs.sqlite"))
    monkeypatch.setattr(ray_config, "health_check_period_s", 0.3)
    # A wide, test-controlled commit window: what rides it when the
    # head dies is exactly what the contract allows to be lost.
    monkeypatch.setattr(ray_config, "gcs_commit_interval_s", 30.0)
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 1})
    cluster.add_node(num_cpus=2)
    try:
        from ray_tpu._private.worker import global_worker

        gcs = global_worker().gcs
        gcs.kv_put(b"acked-key", b"durable")
        gcs.flush_storage()  # acked durable: must survive the crash
        gcs.kv_put(b"window-key", b"riding")  # un-acked: may be lost

        cluster.restart_head(mode="crash")

        # Acked-durable state survived; the window write did NOT
        # resurrect (it was never made durable, and a crash recovers
        # only from disk).
        assert global_worker().gcs.kv_get(b"acked-key") == b"durable"
        assert global_worker().gcs.kv_get(b"window-key") is None

        # Live nodes re-register through report-returns-False, with no
        # driver involvement.
        _wait(lambda: sum(n["Alive"] for n in cluster.nodes()) >= 1,
              msg="node re-registered after hard crash")

        # And the cluster schedules new work end to end.
        @ray_tpu.remote(num_cpus=2)
        def on_node():
            import os

            return os.getpid()

        import os

        assert ray_tpu.get(on_node.remote(), timeout=60) != os.getpid()
    finally:
        cluster.shutdown()


def test_head_hard_crash_inflight_task_rides_fetch_retry(tmp_path,
                                                         monkeypatch):
    """A task RUNNING on a node while the head hard-crashes completes;
    its caller rides the fetch-retry window to the result."""
    from ray_tpu._private.config import ray_config

    monkeypatch.setattr(ray_config, "gcs_storage_path",
                        str(tmp_path / "gcs.sqlite"))
    monkeypatch.setattr(ray_config, "health_check_period_s", 0.3)
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 1})
    cluster.add_node(num_cpus=2)
    try:
        @ray_tpu.remote(num_cpus=1)
        def slow():
            import time as _t

            _t.sleep(3.0)
            return "made-it"

        ref = slow.remote()
        time.sleep(0.5)  # dispatched and running
        cluster.restart_head(mode="crash")
        assert ray_tpu.get(ref, timeout=45) == "made-it"
    finally:
        cluster.shutdown()


def test_restart_budget_survives_head_failover(durable_gcs):
    """ROADMAP FT gap (c): consumed actor-restart budgets must survive
    head failover. A max_restarts=1 actor that already spent its one
    restart re-reports into the FRESH head's gate with the consumed
    count (riding the node's re-register report), so its next node
    death TOMBSTONES it — a reset budget would let it restart forever,
    one head failover at a time."""
    from ray_tpu._private.actor_gate import ActorRestartState
    from ray_tpu._private.task_spec import NodeAffinitySchedulingStrategy
    from ray_tpu.exceptions import ActorDiedError

    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 1})
    n1 = cluster.add_node(num_cpus=2)
    n2 = cluster.add_node(num_cpus=2)
    assert n2
    try:
        # 2 CPUs: can never land on the 1-CPU head, so both the first
        # placement and the restart live on NODES — the re-register
        # report is the only channel the consumed count can ride.
        @ray_tpu.remote(num_cpus=2, max_restarts=1, max_task_retries=2,
                        scheduling_strategy=NodeAffinitySchedulingStrategy(
                            node_id=n1, soft=True))
        class Counter:
            def __init__(self):
                self.n = 0

            def bump(self):
                self.n += 1
                return self.n

        counter = Counter.remote()
        aid = counter._actor_id.binary()
        assert ray_tpu.get(counter.bump.remote(), timeout=30) == 1

        # First death: the ONE restart is consumed; the replacement
        # constructs on the surviving node.
        cluster.remove_node(n1, graceful=False)
        _wait(lambda: ray_tpu.get(counter.bump.remote(),
                                  timeout=10) >= 1,
              msg="actor restarted after first node death")
        assert cluster.head.actor_gate.restarts_left(aid) == 0

        # ---- hard-crash head failover ----
        cluster.restart_head(mode="crash")
        _wait(lambda: cluster.head.actor_gate.state(aid)
              == ActorRestartState.ALIVE,
              msg="node re-reported the actor into the fresh gate")
        # THE regression: the fresh gate carries the CONSUMED budget
        # (a reset gate would read 1 restart left again).
        assert cluster.head.actor_gate.restarts_left(aid) == 0, \
            "consumed restart budget reset across head failover"
        assert ray_tpu.get(counter.bump.remote(), timeout=30) >= 1

        # Second death: budget exhausted — tombstone, never another
        # restart. Calls fail FAST with a cause naming the budget.
        home = cluster.head.actor_nodes.get(aid)
        assert home == n2, home
        cluster.remove_node(n2, graceful=False)
        _wait(lambda: cluster.head.actor_gate.state(aid)
              == ActorRestartState.DEAD,
              msg="budget-exhausted actor tombstoned after failover")
        with pytest.raises(ActorDiedError, match="exhausted"):
            ray_tpu.get(counter.bump.remote(), timeout=30)
    finally:
        cluster.shutdown()


def test_head_failover_without_durable_storage(tmp_path, monkeypatch):
    """Without gcs_storage_path the tables start empty after restart —
    nodes still re-register and NEW work proceeds (the non-FT
    deployment's documented behavior)."""
    from ray_tpu._private.config import ray_config

    monkeypatch.setattr(ray_config, "health_check_period_s", 0.3)
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 1})
    cluster.add_node(num_cpus=2)
    try:
        from ray_tpu._private.worker import global_worker

        global_worker().gcs.kv_put(b"volatile", b"1")
        cluster.restart_head()
        _wait(lambda: sum(n["Alive"] for n in cluster.nodes()) >= 1,
              msg="node re-registered")
        assert global_worker().gcs.kv_get(b"volatile") is None

        @ray_tpu.remote(num_cpus=1)
        def ping():
            return "pong"

        assert ray_tpu.get(ping.remote(), timeout=30) == "pong"
    finally:
        cluster.shutdown()
